"""Rapids — the Lisp-ish dataframe expression language (water/rapids/).

Reference: water/rapids/Rapids.java (parser), Session.java (temp-frame
ref-counting per client session), ast/AstExec.java (apply), ast/prims/** (207
primitive ASTs: operators, reducers, mungers incl. merge/sort/groupby, math,
string, time ops). Python/R clients compile every dataframe expression to this
grammar and POST it to /99/Rapids — implementing the same grammar here is what
makes the client surface work.

Grammar (Rapids.java:24-38):
  expr  := (op args…) | number | "str" | 'str' | id | %id | [num…] | {args . body}
Assignments: (tmp= key expr), (rm key).

TPU-native evaluation: element-wise ops and reducers run as fused jits over
the sharded column arrays; order-based mungers (sort/merge/group-by) factorize
keys on the controller and use device segment ops where profitable, host
numpy otherwise. Strings are host-side (see frame.py design note).
"""

from __future__ import annotations

import math
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_CAT, T_NUM, T_STR, T_TIME
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.parallel import mrtask as _mrt


# ===========================================================================
# Parser (Rapids.java)
class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def peek(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self):
        c = self.peek()
        if c == "(":
            return self._list(")", "(")
        if c == "[":
            return self._numlist()
        if c == "{":
            return self._fun()
        if c in "\"'":
            return self._string(c)
        return self._token()

    def _list(self, close, open_):
        self.i += 1
        out = []
        while self.peek() != close:
            if not self.peek():
                raise ValueError("unterminated expression")
            out.append(self.parse())
        self.i += 1
        return out

    def _numlist(self):
        self.i += 1
        out = []
        while self.peek() != "]":
            if not self.peek():
                raise ValueError("unterminated [...] list")
            if self.peek() in "\"'":
                # h2o string lists use the same bracket syntax, e.g.
                # (countmatches col ["o"]); _token() cannot consume a
                # quote char so it must parse as a string here
                out.append(self._string(self.peek())[1])
                continue
            tok = self._token()
            if isinstance(tok, str) and ":" in tok:   # a:b span
                a, b = tok.split(":")
                out.append(("span", float(a), float(b)))
            else:
                out.append(tok)
        self.i += 1
        return ("numlist", out)

    def _fun(self):
        self.i += 1
        parts = []
        while self.peek() != "}":
            parts.append(self.parse())
        self.i += 1
        # {arg1 arg2 . body}
        if "." in parts:
            dot = parts.index(".")
            return ("lambda", parts[:dot], parts[dot + 1])
        return ("lambda", parts[:-1], parts[-1])

    def _string(self, q):
        self.i += 1
        out = []
        while self.i < len(self.s) and self.s[self.i] != q:
            ch = self.s[self.i]
            if ch == "\\":
                self.i += 1
                if self.i >= len(self.s):
                    break
                ch = self.s[self.i]
            out.append(ch)
            self.i += 1
        if self.i >= len(self.s):
            raise ValueError("unterminated string literal")
        self.i += 1
        return ("str", "".join(out))

    def _token(self):
        self.peek()
        start = self.i
        while self.i < len(self.s) and not self.s[self.i].isspace() \
                and self.s[self.i] not in "()[]{}\"'":
            self.i += 1
        tok = self.s[start:self.i]
        if tok in ("True", "TRUE", "true"):
            return 1.0
        if tok in ("False", "FALSE", "false"):
            return 0.0
        if tok in ("NA", "NaN", "nan"):
            return float("nan")
        if tok.startswith("#"):          # classic grammar number prefix
            try:
                return float(tok[1:])
            except ValueError:
                pass
        if tok.startswith("%") and len(tok) > 1 and \
                re.fullmatch(r"[A-Za-z_][A-Za-z0-9_.\-]*", tok[1:]):
            return tok[1:]       # classic %id prefix ('%/%' stays an op)
        try:
            return float(tok)
        except ValueError:
            return tok


def parse(expr: str):
    return _Parser(expr).parse()


# ===========================================================================
class Session:
    """Per-client session: tracks temp frames for GC (rapids/Session.java)."""

    def __init__(self, session_id: str = "default"):
        self.id = session_id
        self.tmps: set = set()

    def register(self, key: str):
        self.tmps.add(key)

    def end(self):
        for k in self.tmps:
            DKV.remove(k)
        self.tmps.clear()


_default_session = Session()


# ===========================================================================
# Evaluation
class Env:
    def __init__(self, session: Session):
        self.session = session
        self.locals: dict = {}


def rapids_exec(expr: str, session: Optional[Session] = None):
    """Rapids.exec: parse + evaluate; returns float | str | Frame | list."""
    session = session or _default_session
    ast = parse(expr)
    return _eval(ast, Env(session))


def _eval(ast, env: Env):
    if isinstance(ast, float):
        return ast
    if isinstance(ast, tuple):
        if ast[0] == "str":
            return ast[1]
        if ast[0] == "numlist":
            return _expand_numlist(ast[1])
        if ast[0] == "lambda":
            return ast
        if ast[0] == "span":
            return list(np.arange(ast[1], ast[2] + 1))
    if isinstance(ast, str):
        if ast in env.locals:
            return env.locals[ast]
        obj = DKV.get(ast)
        if obj is not None:
            return obj
        return ast  # bare symbol (e.g. column name)
    if isinstance(ast, list):
        op = ast[0]
        if isinstance(op, (tuple, list)):
            op = _eval(op, env)
        if isinstance(op, tuple) and op[0] == "lambda":
            return _apply_lambda(op, [_eval(a, env) for a in ast[1:]], env)
        fn = PRIMS.get(op)
        if fn is None:
            raise ValueError(f"unknown Rapids op: {op!r}")
        return fn(ast[1:], env)
    raise ValueError(f"cannot evaluate {ast!r}")


def _expand_numlist(items):
    out = []
    for it in items:
        if isinstance(it, tuple) and it[0] == "span":
            out.extend(np.arange(it[1], it[2] + 1).tolist())
        else:
            out.append(it)
    return out


def _apply_lambda(lam, args, env: Env):
    _, params, body = lam
    sub = Env(env.session)
    sub.locals = dict(env.locals)
    for p, a in zip(params, args):
        sub.locals[p] = a
    return _eval(body, sub)


# ===========================================================================
# helpers
def _as_frame(v) -> Frame:
    if isinstance(v, Frame):
        return v
    if isinstance(v, (int, float)):
        return Frame(["C1"], [Vec.from_numpy(np.array([float(v)]))])
    raise TypeError(f"expected frame, got {type(v)}")


def _numeric_cols(f: Frame):
    return [n for n, v in zip(f.names, f.vecs) if v.type != T_STR]


def _col_np(f: Frame, j=0) -> np.ndarray:
    return f.vecs[j].to_numpy()


def _new_frame(names, arrays, types=None, domains=None) -> Frame:
    vecs = []
    for i, a in enumerate(arrays):
        t = (types or {}).get(i) if isinstance(types, dict) else None
        d = (domains or {}).get(i) if isinstance(domains, dict) else None
        if a.dtype == object:
            vecs.append(Vec.from_numpy(a, type=t or T_STR))
        elif d is not None:
            mask = np.isnan(a)
            vecs.append(Vec._from_floats(np.where(mask, 0, a), mask, T_CAT,
                                         np.asarray(d, object)))
        else:
            vecs.append(Vec.from_numpy(a))
    return Frame(list(names), vecs)


def _broadcast_op(args, env, fn, str_ok=False):
    """Element-wise binary op over frame/scalar combinations — fused jit."""
    a = _eval(args[0], env)
    b = _eval(args[1], env)
    fa, fb = isinstance(a, Frame), isinstance(b, Frame)
    if not fa and not fb:
        return float(fn(jnp.float32(a), jnp.float32(b)))
    base = a if fa else b
    names = base.names

    def get(x):
        if isinstance(x, Frame):
            return x.matrix(_numeric_cols(x))
        return jnp.float32(x)

    A, B = get(a), get(b)
    out = _mrt.cached_jit(fn)(A, B)
    out_np = np.asarray(out, np.float64)[: base.nrows]
    return _new_frame(names, [out_np[:, j] for j in range(out_np.shape[1])])


def _unary_op(args, env, fn):
    a = _eval(args[0], env)
    if not isinstance(a, Frame):
        return float(fn(jnp.float32(a)))
    A = a.matrix(_numeric_cols(a))
    out = np.asarray(_mrt.cached_jit(fn)(A), np.float64)[: a.nrows]
    return _new_frame(a.names, [out[:, j] for j in range(out.shape[1])])


def _reduce_op(args, env, fn, na_rm_idx=None):
    """Whole-frame reducer via one fused jit (NaN-aware)."""
    a = _eval(args[0], env)
    na_rm = bool(_eval(args[na_rm_idx], env)) if na_rm_idx is not None and \
        len(args) > na_rm_idx else True
    A = a.matrix(_numeric_cols(a))
    n = a.nrows

    def red(A):
        idx = jnp.arange(A.shape[0])[:, None]
        live = idx < n
        return fn(A, live)

    # cached_jit resolves fn's identity down to its code object, so the
    # per-call reducer lambdas each keep one resident program per shape
    return float(_mrt.cached_jit(red)(A))


# ===========================================================================
# Primitive registry  (ast/prims/**)
PRIMS: dict = {}


def prim(*names):
    def deco(fn):
        for n in names:
            PRIMS[n] = fn
        return fn
    return deco


# ---- operators (prims/operators) ------------------------------------------
@prim("+")
def _add(a, e): return _broadcast_op(a, e, lambda x, y: x + y)


@prim("-")
def _sub(a, e): return _broadcast_op(a, e, lambda x, y: x - y)


@prim("*")
def _mul(a, e): return _broadcast_op(a, e, lambda x, y: x * y)


@prim("/")
def _div(a, e): return _broadcast_op(a, e, lambda x, y: x / y)


@prim("^", "**")
def _pow(a, e): return _broadcast_op(a, e, lambda x, y: jnp.power(x, y))


@prim("%", "mod")
def _mod(a, e): return _broadcast_op(a, e, lambda x, y: jnp.mod(x, y))


@prim("intDiv", "%/%")
def _intdiv(a, e): return _broadcast_op(a, e, lambda x, y: jnp.floor_divide(x, y))


def _cmp(fn):
    return lambda a, e: _broadcast_op(a, e,
                                      lambda x, y: fn(x, y).astype(jnp.float32))


PRIMS["=="] = _cmp(lambda x, y: x == y)
PRIMS["!="] = _cmp(lambda x, y: x != y)
PRIMS[">"] = _cmp(lambda x, y: x > y)
PRIMS[">="] = _cmp(lambda x, y: x >= y)
PRIMS["<"] = _cmp(lambda x, y: x < y)
PRIMS["<="] = _cmp(lambda x, y: x <= y)
PRIMS["&"] = _cmp(lambda x, y: (x != 0) & (y != 0))
PRIMS["|"] = _cmp(lambda x, y: (x != 0) | (y != 0))
PRIMS["&&"] = PRIMS["&"]
PRIMS["||"] = PRIMS["|"]


@prim("!", "not")
def _not(a, e):
    return _unary_op(a, e, lambda x: (x == 0).astype(jnp.float32))


# ---- math (prims/math) -----------------------------------------------------
_MATH = {
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sqrt": jnp.sqrt, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "floor": jnp.floor, "ceiling": jnp.ceil, "trunc": jnp.trunc,
    "sign": jnp.sign, "gamma": jax.scipy.special.gammaln,
}
for name, f in _MATH.items():
    PRIMS[name] = (lambda ff: lambda a, e: _unary_op(a, e, ff))(f)


@prim("round")
def _round(a, e):
    digits = int(_eval(a[1], e)) if len(a) > 1 else 0
    m = 10.0 ** digits
    return _unary_op(a[:1], e, lambda x: jnp.round(x * m) / m)


@prim("signif")
def _signif(a, e):
    digits = int(_eval(a[1], e)) if len(a) > 1 else 6

    def f(x):
        mag = jnp.power(10.0, digits - 1 - jnp.floor(jnp.log10(jnp.abs(x))))
        return jnp.where(x == 0, 0.0, jnp.round(x * mag) / mag)
    return _unary_op(a[:1], e, f)


# ---- reducers (prims/reducers) --------------------------------------------
@prim("sum")
def _sum(a, e):
    return _reduce_op(a, e, lambda A, live: jnp.where(
        jnp.isnan(A) | ~live, 0.0, A).sum())


@prim("mean")
def _mean(a, e):
    def f(A, live):
        ok = ~jnp.isnan(A) & live
        return jnp.where(ok, A, 0.0).sum() / jnp.maximum(ok.sum(), 1)
    return _reduce_op(a, e, f)


@prim("min")
def _min(a, e):
    return _reduce_op(a, e, lambda A, live: jnp.where(
        jnp.isnan(A) | ~live, jnp.inf, A).min())


@prim("max")
def _max(a, e):
    return _reduce_op(a, e, lambda A, live: jnp.where(
        jnp.isnan(A) | ~live, -jnp.inf, A).max())


@prim("sd")
def _sd(a, e):
    f = _eval(a[0], e)
    return float(f.vecs[0].sigma())


@prim("var")
def _var(a, e):
    f = _eval(a[0], e)
    return float(f.vecs[0].sigma()) ** 2


@prim("median")
def _median(a, e):
    f = _eval(a[0], e)
    return float(np.nanmedian(_col_np(f)))


@prim("prod")
def _prod(a, e):
    return _reduce_op(a, e, lambda A, live: jnp.where(
        jnp.isnan(A) | ~live, 1.0, A).prod())


@prim("all")
def _all(a, e):
    return _reduce_op(a, e, lambda A, live: (
        jnp.where(live, A != 0, True)).all().astype(jnp.float32))


@prim("any")
def _any(a, e):
    return _reduce_op(a, e, lambda A, live: (
        jnp.where(live, A != 0, False)).any().astype(jnp.float32))


@prim("cumsum", "cumprod", "cummin", "cummax")
def _cumulative(a, e):
    raise NotImplementedError  # replaced below per-op


def _make_cum(npfn):
    def f(a, e):
        fr = _eval(a[0], e)
        col = _col_np(fr)
        return _new_frame(fr.names[:1], [npfn(col)])
    return f


PRIMS["cumsum"] = _make_cum(np.cumsum)
PRIMS["cumprod"] = _make_cum(np.cumprod)
PRIMS["cummin"] = _make_cum(np.minimum.accumulate)
PRIMS["cummax"] = _make_cum(np.maximum.accumulate)


# ---- frame structure (prims/mungers) ---------------------------------------
@prim("nrow")
def _nrow(a, e): return float(_eval(a[0], e).nrows)


@prim("ncol")
def _ncol(a, e): return float(_eval(a[0], e).ncols)


@prim("colnames", "names")
def _colnames(a, e): return list(_eval(a[0], e).names)


@prim("cols", "cols_py")
def _cols(a, e):
    f = _eval(a[0], e)
    sel = _eval(a[1], e)
    if isinstance(sel, str):
        return f[[sel]]
    if isinstance(sel, float):
        sel = [sel]
    if isinstance(sel, list):
        if sel and isinstance(sel[0], str):
            return f[[s for s in sel]]
        idx = [int(s) for s in sel]
        if idx and idx[0] < 0:   # negative = drop
            keep = [i for i in range(f.ncols) if -(i + 1) not in idx and i not in [-(j + 1) for j in idx]]
            keep = [i for i in range(f.ncols) if i not in [-j - 1 for j in idx]]
            return f[keep]
        return f[idx]
    raise ValueError(sel)


@prim("rows")
def _rows(a, e):
    f = _eval(a[0], e)
    sel = _eval(a[1], e)
    if isinstance(sel, Frame):  # boolean mask frame
        mask = _col_np(sel) != 0
        idx = np.nonzero(mask[: f.nrows])[0]
    elif isinstance(sel, list):
        idx = np.array([int(s) for s in sel])
        if len(idx) and idx[0] < 0:
            drop = set((-idx - 1).tolist())
            idx = np.array([i for i in range(f.nrows) if i not in drop])
    else:
        idx = np.array([int(sel)])
    return _take_rows(f, idx)


def _take_rows(f: Frame, idx: np.ndarray) -> Frame:
    names, vecs = [], []
    for c, v in zip(f.names, f.vecs):
        if v.type == T_STR:
            vecs.append(Vec.from_numpy(v.host_data[idx], type=T_STR))
        else:
            col = v.to_numpy()[idx]
            mask = np.isnan(col)
            vecs.append(Vec._from_floats(np.where(mask, 0, col), mask,
                                         v.type, v.domain))
        names.append(c)
    return Frame(names, vecs)


@prim("cbind")
def _cbind(a, e):
    frames = [_as_frame(_eval(x, e)) for x in a]
    names, vecs = [], []
    seen = set()
    for f in frames:
        for n, v in zip(f.names, f.vecs):
            nn = n
            k = 0
            while nn in seen:
                k += 1
                nn = f"{n}{k}"
            seen.add(nn)
            names.append(nn)
            vecs.append(v)
    return Frame(names, vecs)


@prim("rbind")
def _rbind(a, e):
    frames = [_as_frame(_eval(x, e)) for x in a]
    base = frames[0]
    names, vecs = [], []
    for j, c in enumerate(base.names):
        vts = [f.vecs[j] for f in frames]
        if vts[0].type == T_STR:
            data = np.concatenate([v.host_data for v in vts])
            vecs.append(Vec.from_numpy(data, type=T_STR))
        elif vts[0].type == T_CAT:
            # merge domains (ParseDataset categorical merge)
            dom = sorted({l for v in vts for l in (v.levels() or [])})
            lut = {l: i for i, l in enumerate(dom)}
            cols = []
            for v in vts:
                c_np = v.to_numpy()
                vdom = v.levels()
                cols.append(np.array([np.nan if math.isnan(x)
                                      else lut[vdom[int(x)]] for x in c_np]))
            col = np.concatenate(cols)
            mask = np.isnan(col)
            vecs.append(Vec._from_floats(np.where(mask, 0, col), mask, T_CAT,
                                         np.asarray(dom, object)))
        else:
            col = np.concatenate([v.to_numpy() for v in vts])
            mask = np.isnan(col)
            vecs.append(Vec._from_floats(np.where(mask, 0, col), mask,
                                         vts[0].type))
        names.append(c)
    return Frame(names, vecs)


@prim("setnames", "colnames=")
def _setnames(a, e):
    f = _eval(a[0], e)
    idx = _eval(a[1], e)
    names = _eval(a[2], e)
    if not isinstance(idx, list):
        idx = [idx]
    if not isinstance(names, list):
        names = [names]
    for i, n in zip(idx, names):
        f.names[int(i)] = n if isinstance(n, str) else str(n)
    f._matrix_cache.clear()
    return f


@prim("tmp=")
def _assign(a, e):
    key = a[0]
    val = _eval(a[1], e)
    if isinstance(val, Frame):
        if val.key and DKV.get(val.key) is val:
            # identity-returning prims (as.factor on an already-enum col,
            # …) hand back the SOURCE frame: alias with a fresh handle
            # instead of stealing its key (which silently dropped the
            # source binding)
            val = Frame(list(val.names), list(val.vecs))
        else:
            DKV.remove(val.key)
        val.key = key
    DKV.put(key, val)
    e.session.register(key)
    return val


@prim("rm")
def _rm(a, e):
    DKV.remove(a[0] if isinstance(a[0], str) else _eval(a[0], e))
    return 0.0


@prim(":=")
def _colassign(a, e):
    """(:= frame rhs col_idx row_idx) — update columns in place."""
    f = _eval(a[0], e)
    rhs = _eval(a[1], e)
    cols = _eval(a[2], e)
    if isinstance(cols, float):
        cols = [cols]
    for k, ci in enumerate(int(c) for c in cols):
        if ci >= f.ncols:
            name = f"C{ci+1}"
        else:
            name = f.names[ci]
        if isinstance(rhs, Frame):
            f[name] = rhs.vecs[min(k, rhs.ncols - 1)]
        else:
            f[name] = np.full(f.nrows, float(rhs))
    return f


@prim("is.na")
def _isna(a, e):
    return _unary_op(a, e, lambda x: jnp.isnan(x).astype(jnp.float32))


@prim("ifelse")
def _ifelse(a, e):
    def f(c, x, y):
        return jnp.where(c != 0, x, y)
    c = _eval(a[0], e)
    x = _eval(a[1], e)
    y = _eval(a[2], e)
    if not isinstance(c, Frame):
        return x if c else y
    C = c.matrix(_numeric_cols(c))
    X = x.matrix(_numeric_cols(x)) if isinstance(x, Frame) else jnp.float32(x)
    Y = y.matrix(_numeric_cols(y)) if isinstance(y, Frame) else jnp.float32(y)
    out = np.asarray(_mrt.cached_jit(f)(C, X, Y), np.float64)[: c.nrows]
    return _new_frame(c.names, [out[:, j] for j in range(out.shape[1])])


@prim("h2o.which")
def _which(a, e):
    f = _eval(a[0], e)
    idx = np.nonzero(_col_np(f) != 0)[0].astype(np.float64)
    return _new_frame(["which"], [idx])


@prim("na.omit")
def _naomit(a, e):
    f = _eval(a[0], e)
    m = f.to_numpy()
    keep = ~np.isnan(m).any(axis=1)
    return _take_rows(f, np.nonzero(keep)[0])


@prim("unique")
def _unique(a, e):
    f = _eval(a[0], e)
    v = f.vecs[0]
    col = _col_np(f)
    u = np.unique(col[~np.isnan(col)])
    if v.type == T_CAT:
        dom = v.levels()
        mask = np.zeros(len(u), bool)
        return _new_frame(f.names[:1], [u], domains={0: dom})
    return _new_frame(f.names[:1], [u])


@prim("table")
def _table(a, e):
    f = _eval(a[0], e)
    col = _col_np(f)
    v = f.vecs[0]
    vals, cnts = np.unique(col[~np.isnan(col)], return_counts=True)
    if v.type == T_CAT:
        dom = v.levels()
        labels = np.array([dom[int(x)] for x in vals], object)
        return _new_frame([f.names[0], "Count"],
                          [labels, cnts.astype(np.float64)])
    return _new_frame([f.names[0], "Count"],
                      [vals, cnts.astype(np.float64)])


# ---- type coercion ---------------------------------------------------------
@prim("as.factor", "asfactor")
def _asfactor(a, e):
    f = _eval(a[0], e)
    v = f.vecs[0]
    if v.type == T_CAT:
        return f
    col = v.to_numpy()
    if v.type == T_STR:
        return _new_frame(f.names[:1], [v.host_data])  # re-ingest as enum
    mask = np.isnan(col)
    uniq = np.unique(col[~mask])
    lut = {x: i for i, x in enumerate(uniq)}
    codes = np.array([np.nan if m else lut[x] for x, m in zip(col, mask)])
    dom = [("%g" % x) for x in uniq]
    return _new_frame(f.names[:1], [codes], domains={0: dom})


@prim("as.numeric", "asnumeric")
def _asnumeric(a, e):
    f = _eval(a[0], e)
    v = f.vecs[0]
    if v.type == T_CAT:
        col = v.to_numpy()
        dom = v.levels()
        try:
            vals = np.array([float(d) for d in dom])
            out = np.array([np.nan if math.isnan(c) else vals[int(c)]
                            for c in col])
        except ValueError:
            out = col
        return _new_frame(f.names[:1], [out])
    return _new_frame(f.names[:1], [v.to_numpy()])


@prim("as.character", "ascharacter")
def _aschar(a, e):
    f = _eval(a[0], e)
    v = f.vecs[0]
    if v.type == T_CAT:
        dom = v.levels()
        col = v.to_numpy()
        out = np.array([None if math.isnan(c) else dom[int(c)] for c in col],
                       object)
    else:
        out = np.array(["%g" % x if not math.isnan(x) else None
                        for x in v.to_numpy()], object)
    return _new_frame(f.names[:1], [out])


@prim("levels")
def _levels(a, e):
    f = _eval(a[0], e)
    return f.vecs[0].levels() or []


# ---- sort / merge / group-by (prims/mungers radix family) ------------------
@prim("sort")
def _sort(a, e):
    f = _eval(a[0], e)
    by = _eval(a[1], e)
    asc = _eval(a[2], e) if len(a) > 2 else [1.0] * 99
    if not isinstance(by, list):
        by = [by]
    cols = [int(b) if isinstance(b, float) else f.col_idx(b) for b in by]
    ascending = [bool(asc[k]) if isinstance(asc, list) and k < len(asc)
                 else True for k in range(len(cols))]
    if all(f.vecs[ci].type != T_STR for ci in cols):
        # device radix path (water/rapids/RadixOrder.java analog)
        from h2o3_tpu.ops import device_sort as DS
        return DS.sort_frame(f, cols, ascending)
    keys = []
    for k, ci in enumerate(reversed(cols)):
        colv = f.vecs[ci].to_numpy()
        keys.append(colv if ascending[len(cols) - 1 - k] else -colv)
    order = np.lexsort(keys)
    return _take_rows(f, order)


@prim("merge")
def _merge(a, e):
    """(merge left right all_left all_right by_left by_right method)"""
    lf = _eval(a[0], e)
    rf = _eval(a[1], e)
    all_l = bool(_eval(a[2], e)) if len(a) > 2 else False
    all_r = bool(_eval(a[3], e)) if len(a) > 3 else False
    by_l = _eval(a[4], e) if len(a) > 4 else []
    by_r = _eval(a[5], e) if len(a) > 5 else []
    if not by_l:
        common = [c for c in lf.names if c in rf.names]
        by_l = [lf.col_idx(c) for c in common]
        by_r = [rf.col_idx(c) for c in common]
    by_l = [int(x) for x in (by_l if isinstance(by_l, list) else [by_l])]
    by_r = [int(x) for x in (by_r if isinstance(by_r, list) else [by_r])]
    keys_numeric = all(lf.vecs[i].type != T_STR for i in by_l) and \
        all(rf.vecs[i].type != T_STR for i in by_r)
    if keys_numeric and not all_r:
        # device sort-merge join (water/rapids/Merge.java analog);
        # right/outer joins + degenerate shapes use the host fallback
        from h2o3_tpu.ops import device_sort as DS
        out = DS.merge_frames(lf, rf, by_l, by_r, all_l=all_l)
        if out is not None:
            return out
    ldf = lf.as_data_frame()
    rdf = rf.as_data_frame()
    lkeys = [lf.names[i] for i in by_l]
    rkeys = [rf.names[i] for i in by_r]
    how = "outer" if (all_l and all_r) else \
        "left" if all_l else "right" if all_r else "inner"
    out = ldf.merge(rdf, left_on=lkeys, right_on=rkeys, how=how)
    return Frame.from_pandas(out)


@prim("GB", "group_by")
def _groupby(a, e):
    """(GB frame [by…] agg_col agg_fn na_handling …) — AstGroup."""
    f = _eval(a[0], e)
    by = _eval(a[1], e)
    by = [int(b) for b in (by if isinstance(by, list) else [by])]
    aggs = []
    i = 2
    rest = a[2:]
    while i + 2 < len(a) + 1 and i + 2 <= len(a):
        fn_name = _eval(a[i], e)
        col = int(_eval(a[i + 1], e))
        na = _eval(a[i + 2], e) if i + 2 < len(a) else "rm"
        aggs.append((fn_name, col, na))
        i += 3
    device_ok = all(f.vecs[j].type != T_STR for j in by) and \
        all(fn in ("sum", "mean", "min", "max", "var", "sd", "nrow",
                   "count") and f.vecs[cj].type != T_STR
            for fn, cj, _na in aggs)
    if device_ok and by:
        from h2o3_tpu.ops import device_sort as DS
        got = DS.group_by_device(f, by, [(fn, cj) for fn, cj, _ in aggs])
        if got is not None:
            names2, cols2, doms2 = got
            return _new_frame(names2, cols2, domains=doms2)
    key_cols = [f.vecs[j].to_numpy() for j in by]
    key_tup = list(zip(*key_cols)) if key_cols else []
    uniq = sorted(set(key_tup))
    index = {k: i for i, k in enumerate(uniq)}
    gid = np.array([index[k] for k in key_tup])
    out_names = [f.names[j] for j in by]
    out_cols = []
    for kd, j in enumerate(by):
        vals = np.array([u[kd] for u in uniq])
        out_cols.append(vals)
    fns = {"sum": np.nansum, "mean": np.nanmean, "min": np.nanmin,
           "max": np.nanmax, "sd": lambda x: np.nanstd(x, ddof=1),
           "var": lambda x: np.nanvar(x, ddof=1), "median": np.nanmedian,
           "nrow": len, "count": len, "mode": lambda x: float(
               np.bincount(x[~np.isnan(x)].astype(int)).argmax())}
    for fn_name, cj, _na in aggs:
        colv = f.vecs[cj].to_numpy()
        fn = fns[fn_name]
        vals = np.array([fn(colv[gid == g]) for g in range(len(uniq))],
                        np.float64)
        out_names.append(f"{fn_name}_{f.names[cj]}")
        out_cols.append(vals)
    doms = {}
    for kd, j in enumerate(by):
        if f.vecs[j].type == T_CAT:
            doms[kd] = f.vecs[j].levels()
    return _new_frame(out_names, out_cols, domains=doms)


@prim("quantile")
def _quantile(a, e):
    """(quantile fr probs ["interpolate"|...]) — device histogram-refinement
    quantiles (hex/quantile/Quantile.java path), not a host sort."""
    from h2o3_tpu.models.quantile import quantile as devq
    f = _eval(a[0], e)
    probs = _eval(a[1], e)
    probs = probs if isinstance(probs, list) else [probs]
    method = _eval(a[2], e) if len(a) > 2 else "interpolate"
    cols = _numeric_cols(f)
    out_cols = [np.asarray(probs, np.float64)]
    names = ["Probs"]
    for c in cols:
        col = f.matrix([c])[:, 0]
        out_cols.append(devq(col, probs, combine_method=method))
        names.append(c)
    return _new_frame(names, out_cols)


@prim("h2o.impute")
def _impute(a, e):
    f = _eval(a[0], e)
    col = int(_eval(a[1], e))
    method = _eval(a[2], e) if len(a) > 2 else "mean"
    v = f.vecs[col]
    x = v.to_numpy()
    if method == "median":
        fill = float(np.nanmedian(x))
    elif method == "mode":
        vals, cnt = np.unique(x[~np.isnan(x)], return_counts=True)
        fill = float(vals[cnt.argmax()])
    else:
        fill = float(np.nanmean(x))
    x = np.where(np.isnan(x), fill, x)
    f[f.names[col]] = Vec._from_floats(x, np.zeros(len(x), bool), v.type,
                                       v.domain)
    return f


# ---- string ops (prims/string) --------------------------------------------
def _str_map(args, env, fn):
    from h2o3_tpu.core.frame import StrVec
    f = _eval(args[0], env)
    v = f.vecs[0]
    if isinstance(v, StrVec):
        # device string plane: transform the DICTIONARY (O(unique) host
        # calls), remap codes with one device gather — the n-sized host
        # object array never materializes (CStrChunk MRTask analog)
        return Frame(f.names[:1], [v.map_values(fn)])
    if v.type == T_STR:
        data = v.host_data
        out = np.array([None if s is None else fn(s) for s in data], object)
        return _new_frame(f.names[:1], [out])
    if v.type == T_CAT:
        dom = [fn(d) for d in v.levels()]
        col = v.to_numpy()
        mask = np.isnan(col)
        return _new_frame(f.names[:1], [col], domains={0: dom})
    raise TypeError("string op on numeric column")


@prim("toupper")
def _toupper(a, e): return _str_map(a, e, str.upper)


@prim("tolower")
def _tolower(a, e): return _str_map(a, e, str.lower)


@prim("trim")
def _trim(a, e): return _str_map(a, e, str.strip)


@prim("nchar", "strlen", "length")
def _nchar(a, e):
    from h2o3_tpu.core.frame import StrVec
    f = _eval(a[0], e)
    v = f.vecs[0]
    if isinstance(v, StrVec):
        # per-level length table + one device gather: O(unique) host work
        x = v.per_level_f32(len)[: v.nrows]
        return Frame(f.names[:1], [Vec.from_device_floats(x)])
    if v.type == T_STR:
        out = np.array([np.nan if s is None else float(len(s))
                        for s in v.host_data])
    else:
        dom = v.levels()
        col = v.to_numpy()
        out = np.array([np.nan if math.isnan(c) else float(len(dom[int(c)]))
                        for c in col])
    return _new_frame(f.names[:1], [out])


@prim("replaceall", "gsub")
def _gsub(a, e):
    """(replaceall fr pattern replacement ignore_case) —
    AstReplaceAll.java argument order."""
    pat = _eval(a[1], e)
    rep = _eval(a[2], e)
    ic = bool(_eval(a[3], e)) if len(a) > 3 else False
    flags = re.IGNORECASE if ic else 0
    return _str_map(a[:1], e, lambda s: re.sub(pat, rep, s, flags=flags))


@prim("replacefirst", "sub")
def _sub_str(a, e):
    """(replacefirst fr pattern replacement ignore_case)."""
    pat = _eval(a[1], e)
    rep = _eval(a[2], e)
    ic = bool(_eval(a[3], e)) if len(a) > 3 else False
    flags = re.IGNORECASE if ic else 0
    return _str_map(a[:1], e,
                    lambda s: re.sub(pat, rep, s, count=1, flags=flags))


@prim("substring")
def _substring(a, e):
    f_args = a[:1]
    start = int(_eval(a[1], e))
    end = int(_eval(a[2], e)) if len(a) > 2 else None
    return _str_map(f_args, e, lambda s: s[start:end])


@prim("strsplit")
def _strsplit(a, e):
    from h2o3_tpu.core.frame import StrVec
    f = _eval(a[0], e)
    pat = _eval(a[1], e)
    v = f.vecs[0]
    if isinstance(v, StrVec):
        # split the DICTIONARY once; each output part is a StrVec sharing
        # the row codes (missing parts -> NA via map_values_opt)
        lv_parts = [re.split(pat, s) for s in v.levels_arr]
        width = max((len(p) for p in lv_parts), default=0)
        by_level = {s: p for s, p in zip(v.levels_arr, lv_parts)}
        cols = [v.map_values_opt(
                    lambda s, j=j: (by_level[s][j]
                                    if j < len(by_level[s]) else None))
                for j in range(width)]
        return Frame([f"C{j+1}" for j in range(width)], cols)
    data = v.host_data if v.type == T_STR else np.array(
        [None if math.isnan(c) else v.levels()[int(c)] for c in v.to_numpy()],
        object)
    parts = [re.split(pat, s) if s is not None else [] for s in data]
    width = max((len(p) for p in parts), default=0)
    cols = []
    for j in range(width):
        cols.append(np.array([p[j] if j < len(p) else None for p in parts],
                             object))
    return _new_frame([f"C{j+1}" for j in range(width)], cols)


@prim("countmatches")
def _countmatches(a, e):
    from h2o3_tpu.core.frame import StrVec
    f = _eval(a[0], e)
    pat = _eval(a[1], e)
    pats = pat if isinstance(pat, list) else [pat]
    v = f.vecs[0]
    if isinstance(v, StrVec):
        x = v.per_level_f32(
            lambda s: float(sum(s.count(p) for p in pats)))[: v.nrows]
        return Frame(f.names[:1], [Vec.from_device_floats(x)])
    data = v.host_data if v.type == T_STR else np.array(
        [None if math.isnan(c) else v.levels()[int(c)] for c in v.to_numpy()],
        object)
    out = np.array([np.nan if s is None else
                    float(sum(s.count(p) for p in pats)) for s in data])
    return _new_frame(f.names[:1], [out])


# ---- time ops (prims/time) -------------------------------------------------
def _time_part(args, env, part):
    f = _eval(args[0], env)
    ms = f.vecs[0].to_numpy()
    dt = ms.astype("datetime64[ms]")
    import pandas as pd
    s = pd.Series(dt)
    out = getattr(s.dt, part).to_numpy().astype(np.float64)
    out[np.isnan(ms)] = np.nan
    return _new_frame(f.names[:1], [out])


for _p, _attr in [("year", "year"), ("month", "month"), ("day", "day"),
                  ("hour", "hour"), ("minute", "minute"),
                  ("second", "second"), ("dayOfWeek", "dayofweek"),
                  ("week", "isocalendar")]:
    if _p == "week":
        continue
    PRIMS[_p] = (lambda attr: lambda a, e: _time_part(a, e, attr))(_attr)


# ---- misc ------------------------------------------------------------------
@prim("getrow")
def _getrow(a, e):
    f = _eval(a[0], e)
    return [float(x) for x in f.to_numpy()[0]]


@prim("h2o.runif")
def _runif(a, e):
    f = _eval(a[0], e)
    seed = int(_eval(a[1], e)) if len(a) > 1 else -1
    rng = np.random.default_rng(seed if seed > 0 else None)
    return _new_frame(["rnd"], [rng.random(f.nrows)])


@prim("hist")
def _hist(a, e):
    f = _eval(a[0], e)
    breaks = _eval(a[1], e) if len(a) > 1 else "sturges"
    col = _col_np(f)
    col = col[~np.isnan(col)]
    if isinstance(breaks, list):
        counts, edges = np.histogram(col, bins=np.asarray(breaks))
    elif isinstance(breaks, float):
        counts, edges = np.histogram(col, bins=int(breaks))
    else:
        counts, edges = np.histogram(col, bins="sturges")
    return _new_frame(["breaks", "counts", "mids"],
                      [edges[1:].astype(np.float64),
                       counts.astype(np.float64),
                       ((edges[:-1] + edges[1:]) / 2).astype(np.float64)])


@prim("scale")
def _scale(a, e):
    f = _eval(a[0], e)
    center = _eval(a[1], e) if len(a) > 1 else True
    scale_ = _eval(a[2], e) if len(a) > 2 else True
    A = f.matrix(_numeric_cols(f))
    n = f.nrows

    def sc(A):
        live = jnp.arange(A.shape[0])[:, None] < n
        ok = ~jnp.isnan(A) & live
        cnt = jnp.maximum(ok.sum(0), 1)
        mu = jnp.where(ok, A, 0).sum(0) / cnt
        x = A - (mu if center else 0.0)
        sd = jnp.sqrt(jnp.where(ok, x * x, 0).sum(0) / jnp.maximum(cnt - 1, 1))
        return x / jnp.where(sd > 0, sd, 1.0) if scale_ else x

    out = np.asarray(_mrt.cached_jit(sc)(A), np.float64)[:n]
    return _new_frame(f.names, [out[:, j] for j in range(out.shape[1])])


@prim("apply")
def _apply(a, e):
    f = _eval(a[0], e)
    margin = int(_eval(a[1], e))
    lam = _eval(a[2], e)
    if margin == 2:  # per column
        outs = []
        for j, c in enumerate(f.names):
            sub = f[[c]]
            r = _apply_lambda(lam, [sub], e)
            outs.append(float(r) if not isinstance(r, Frame)
                        else float(_col_np(r)[0]))
            DKV.remove(sub.key)
        return _new_frame(f.names, [np.array([o]) for o in outs])
    # margin == 1: per row — vectorize via matrix when the body allows
    m = f.to_numpy()
    outs = []
    for i in range(f.nrows):
        rowf = _new_frame(f.names, [m[i:i+1, j] for j in range(f.ncols)])
        r = _apply_lambda(lam, [rowf], e)
        outs.append(float(r) if not isinstance(r, Frame)
                    else float(_col_np(r)[0]))
        DKV.remove(rowf.key)
    return _new_frame(["apply"], [np.asarray(outs)])


# ---- tranche 2 of the primitive table (prims_ext registers into PRIMS) ----
from h2o3_tpu.rapids import prims_ext  # noqa: E402,F401  (registration import)
