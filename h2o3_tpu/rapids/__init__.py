from h2o3_tpu.rapids.rapids import rapids_exec, Session
