"""Rapids primitives, tranche 2 — closes the gap to the reference's 207
ASTs (water/rapids/ast/prims/**). Registered into the same PRIMS table.

Groups mirror the reference packages: advmath (AstCor, AstDistance,
AstSkewness, AstKurtosis, AstMad, AstMode, AstKFold*, AstDifLag1,
AstPerfectAUC, AstStratifiedSplit), math (hyperbolic/gamma-family),
mungers (AstCut, AstMelt, AstPivot, AstRelevel, AstRename, AstFillNA,
AstAppend, AstColumnsByType, AstFilterNACols, AstFlatten, AstNaCnt,
AstDropDuplicates, AstTopN, AstRankWithinGroupBy, AstDdply, AstSetDomain,
AstSetLevel, AstNLevels, AstSeq*, AstRepLen, AstWhich*, AstTranspose,
AstSumAxis), string (AstEntropy, AstLStrip, AstRStrip, AstGrep,
AstStrDistance, AstTokenize, AstNumValidSubstrings), time (AstMktime,
AstMoment, AstMillis, AstWeek, AstAsDate, timezone trio), reducers
(NA-counting variants), misc (AstLs, AstComma).

Element-wise math runs as fused jits over the device columns (the same
_unary_op path as tranche 1); order/string/irregular mungers are
host-side, matching the frame design note.
"""

from __future__ import annotations

import functools
import math
import re
from datetime import datetime, timezone

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_CAT, T_NUM, T_STR, T_TIME
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.parallel import mrtask as _mrt
from h2o3_tpu.rapids.rapids import (PRIMS, prim, _eval, _new_frame,
                                    _numeric_cols, _col_np, _unary_op,
                                    _reduce_op)


def _f(x) -> Frame:
    assert isinstance(x, Frame), f"expected frame, got {type(x)}"
    return x


def _col0(fr: Frame) -> np.ndarray:
    return _col_np(fr, 0)[: fr.nrows]


def _mat(fr: Frame) -> np.ndarray:
    cols = _numeric_cols(fr)
    return np.asarray(fr.matrix(cols), np.float64)[: fr.nrows]


# ===========================================================================
# math (prims/math) — the hyperbolic / gamma family
@prim("acosh")
def _acosh(a, e): return _unary_op(a, e, jnp.arccosh)


@prim("asinh")
def _asinh(a, e): return _unary_op(a, e, jnp.arcsinh)


@prim("atanh")
def _atanh(a, e): return _unary_op(a, e, jnp.arctanh)


@prim("cospi")
def _cospi(a, e): return _unary_op(a, e, lambda x: jnp.cos(jnp.pi * x))


@prim("sinpi")
def _sinpi(a, e): return _unary_op(a, e, lambda x: jnp.sin(jnp.pi * x))


@prim("tanpi")
def _tanpi(a, e): return _unary_op(a, e, lambda x: jnp.tan(jnp.pi * x))


@prim("lgamma")
def _lgamma(a, e):
    return _unary_op(a, e, jax.scipy.special.gammaln)


@prim("digamma")
def _digamma(a, e):
    return _unary_op(a, e, jax.scipy.special.digamma)


@prim("trigamma")
def _trigamma(a, e):
    return _unary_op(a, e, lambda x: jax.scipy.special.polygamma(1, x))


# ===========================================================================
# advmath (prims/advmath)
@prim("cor")
def _cor(a, e):
    """(cor fr1 fr2 use method) — AstCor; pearson, 'complete.obs' rows."""
    x = _f(_eval(a[0], e))
    # the y slot must be EVALUATED before deciding whether it is a frame:
    # identifier tokens are plain strings, so testing the raw token made
    # every (cor x y ...) silently compute cor(x, x)
    y = x
    if len(a) > 1:
        cand = _eval(a[1], e)
        if isinstance(cand, Frame):
            y = cand
    X = _mat(x)
    Y = _mat(y)
    ok = ~(np.isnan(X).any(1) | np.isnan(Y).any(1))
    X, Y = X[ok], Y[ok]
    Xc = X - X.mean(0)
    Yc = Y - Y.mean(0)
    num = Xc.T @ Yc
    den = np.sqrt((Xc ** 2).sum(0))[:, None] * np.sqrt((Yc ** 2).sum(0))
    C = num / np.maximum(den, 1e-300)
    if C.size == 1:
        return float(C[0, 0])
    return _new_frame(y.names, [C[:, j] for j in range(C.shape[1])])


@prim("distance")
def _distance(a, e):
    """(distance fr1 fr2 measure) — AstDistance: pairwise rows."""
    x = _mat(_f(_eval(a[0], e)))
    y = _mat(_f(_eval(a[1], e)))
    measure = _eval(a[2], e) if len(a) > 2 else "l2"
    if measure in ("l2", "euclidean"):
        d2 = (x ** 2).sum(1)[:, None] + (y ** 2).sum(1)[None] - 2 * x @ y.T
        D = np.sqrt(np.maximum(d2, 0))
    elif measure in ("l1", "manhattan"):
        D = np.abs(x[:, None, :] - y[None, :, :]).sum(-1)
    else:  # cosine
        nx = np.linalg.norm(x, axis=1, keepdims=True)
        ny = np.linalg.norm(y, axis=1, keepdims=True)
        D = 1 - (x @ y.T) / np.maximum(nx * ny.T, 1e-300)
    return _new_frame([f"C{j+1}" for j in range(D.shape[1])],
                      [D[:, j] for j in range(D.shape[1])])


def _moments(col):
    col = col[~np.isnan(col)]
    n = col.size
    mu = col.mean() if n else np.nan
    sd = col.std(ddof=1) if n > 1 else np.nan
    return col, n, mu, sd


@prim("skewness")
def _skewness(a, e):
    fr = _f(_eval(a[0], e))
    out = []
    for j in range(len(_numeric_cols(fr))):
        col, n, mu, sd = _moments(_mat(fr)[:, j])
        out.append(float((((col - mu) / sd) ** 3).sum() * n
                         / ((n - 1) * (n - 2))) if n > 2 else np.nan)
    return out[0] if len(out) == 1 else out


@prim("kurtosis")
def _kurtosis(a, e):
    fr = _f(_eval(a[0], e))
    out = []
    for j in range(len(_numeric_cols(fr))):
        col, n, mu, sd = _moments(_mat(fr)[:, j])
        out.append(float((((col - mu) / sd) ** 4).mean() * n ** 2
                         * (n + 1) / ((n - 1) * (n - 2) * (n - 3)))
                   if n > 3 else np.nan)
    return out[0] if len(out) == 1 else out


@prim("h2o.mad")
def _mad(a, e):
    col = _col0(_f(_eval(a[0], e)))
    col = col[~np.isnan(col)]
    med = np.median(col)
    return float(1.4826 * np.median(np.abs(col - med)))


@prim("mode")
def _mode(a, e):
    col = _col0(_f(_eval(a[0], e)))
    col = col[~np.isnan(col)]
    vals, cnt = np.unique(col, return_counts=True)
    return float(vals[np.argmax(cnt)])


@prim("difflag1")
def _difflag1(a, e):
    fr = _f(_eval(a[0], e))
    col = _col0(fr)
    out = np.empty_like(col)
    out[0] = np.nan
    out[1:] = col[1:] - col[:-1]
    return _new_frame(fr.names[:1], [out])


@prim("kfold_column")
def _kfold(a, e):
    fr = _f(_eval(a[0], e))
    k = int(_eval(a[1], e))
    seed = int(_eval(a[2], e)) if len(a) > 2 else -1
    rng = np.random.default_rng(seed if seed > 0 else None)
    return _new_frame(["fold"],
                      [rng.integers(0, k, fr.nrows).astype(np.float64)])


@prim("modulo_kfold_column")
def _mod_kfold(a, e):
    fr = _f(_eval(a[0], e))
    k = int(_eval(a[1], e))
    return _new_frame(["fold"],
                      [(np.arange(fr.nrows) % k).astype(np.float64)])


@prim("stratified_kfold_column")
def _strat_kfold(a, e):
    fr = _f(_eval(a[0], e))
    k = int(_eval(a[1], e))
    seed = int(_eval(a[2], e)) if len(a) > 2 else -1
    y = _col0(fr)
    rng = np.random.default_rng(seed if seed > 0 else None)
    fold = np.zeros(fr.nrows, np.float64)
    for lvl in np.unique(y[~np.isnan(y)]):
        idx = np.where(y == lvl)[0]
        rng.shuffle(idx)
        fold[idx] = np.arange(idx.size) % k
    return _new_frame(["fold"], [fold])


@prim("h2o.random_stratified_split")
def _strat_split(a, e):
    fr = _f(_eval(a[0], e))
    ratio = float(_eval(a[1], e))
    seed = int(_eval(a[2], e)) if len(a) > 2 else -1
    y = _col0(fr)
    rng = np.random.default_rng(seed if seed > 0 else None)
    out = np.zeros(fr.nrows, np.float64)
    for lvl in np.unique(y[~np.isnan(y)]):
        idx = np.where(y == lvl)[0]
        rng.shuffle(idx)
        out[idx[: int(round(ratio * idx.size))]] = 1.0
    return _new_frame(["test_train_split"], [out])


@prim("perfectAUC")
def _perfect_auc(a, e):
    p = _col0(_f(_eval(a[0], e)))
    y = _col0(_f(_eval(a[1], e)))
    ok = ~(np.isnan(p) | np.isnan(y))
    p, y = p[ok], y[ok]
    order = np.argsort(p, kind="stable")
    r = np.empty(p.size)
    r[order] = np.arange(1, p.size + 1)
    # midranks for ties
    import scipy.stats as _ss  # noqa — fallback below if absent
    try:
        r = _ss.rankdata(p)
    except Exception:
        pass
    npos = (y == 1).sum()
    nneg = (y == 0).sum()
    return float((r[y == 1].sum() - npos * (npos + 1) / 2)
                 / max(npos * nneg, 1))


# ===========================================================================
# mungers (prims/mungers)
# ---- module-level jitted munger kernels (a fresh closure per call would
# recompile per invocation — same rule as frame._sparse_densify) ----------
@functools.partial(jax.jit, static_argnames=("nb",))
def _cut_kernel(col, br, *, nb):
    codes = jnp.searchsorted(br, col, side="left") - 1
    bad = (codes < 0) | (codes >= nb) | jnp.isnan(col)
    return jnp.where(bad, jnp.nan, codes.astype(jnp.float32))


@functools.partial(jax.jit, static_argnames=("fwd", "maxlen"))
def _fillna_kernel(M, *, fwd, maxlen):
    Mi = M if fwd else M[::-1]

    def step(carry, row):
        last, run = carry
        isna = jnp.isnan(row)
        can = (~jnp.isnan(last)) & (run < maxlen)
        out = jnp.where(isna & can, last, row)
        new_last = jnp.where(isna, last, row)
        new_run = jnp.where(isna, jnp.where(can, run + 1, run),
                            jnp.zeros_like(run))
        return (new_last, new_run), out

    init = (jnp.full(M.shape[1], jnp.nan), jnp.zeros(M.shape[1], jnp.int32))
    _, out = jax.lax.scan(step, init, Mi)
    return out if fwd else out[::-1]


@functools.partial(jax.jit, static_argnames=("nv",))
def _melt_tile(col, *, nv):
    return jnp.tile(col, nv)


@jax.jit
def _uniq_sorted_count(x):
    s = jnp.sort(x)
    newg = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
    return s, newg.sum()


@functools.partial(jax.jit, static_argnames=("ui", "uc"))
def _pivot_fill(uniq_i, iv, inv_c, vv, *, ui, uc):
    inv_i = jnp.searchsorted(uniq_i, iv).astype(jnp.int32)
    out = jnp.full(ui * uc, jnp.nan, jnp.float32)
    return out.at[inv_i * uc + inv_c].set(vv, mode="drop").reshape(ui, uc)


@jax.jit
def _rank_kernel(G, S):
    n = G.shape[0]
    keys = tuple(S[:, k] for k in range(S.shape[1] - 1, -1, -1)) + \
        tuple(G[:, k] for k in range(G.shape[1] - 1, -1, -1))
    order = jnp.lexsort(keys)
    Gs = G[order]
    newg = jnp.concatenate(
        [jnp.ones(1, bool), jnp.any(Gs[1:] != Gs[:-1], axis=1)])
    pos = jnp.arange(n)
    start = jnp.where(newg, pos, 0)
    start = jax.lax.associative_scan(jnp.maximum, start)
    rank_sorted = (pos - start + 1).astype(jnp.float32)
    return jnp.zeros(n, jnp.float32).at[order].set(rank_sorted)


def _dev_frame(names, dev_cols, types=None, domains=None):
    """Frame from device columns (no host round trip — the AstXxx MRTask
    outputs stay in HBM)."""
    from h2o3_tpu.core.frame import Vec as _V
    vecs = []
    for i, col in enumerate(dev_cols):
        t = (types or {}).get(i)
        d = (domains or {}).get(i)
        vecs.append(_V.from_device_floats(
            col, vtype=t or (T_CAT if d is not None else T_NUM),
            domain=d))
    return Frame(list(names), vecs)


@prim("cut")
def _cut(a, e):
    """(cut fr breaks labels include.lowest right digits) — AstCut.
    Device-native: one searchsorted pass; no column readback."""
    fr = _f(_eval(a[0], e))
    breaks = [float(b) for b in _eval(a[1], e)]
    n = fr.nrows
    col = fr.matrix(fr.names[:1])[:n, 0]
    nb = len(breaks) - 1
    br = jnp.asarray(breaks, jnp.float32)
    lab = _eval(a[2], e) if len(a) > 2 else None
    if not isinstance(lab, list) or not lab:
        lab = [f"({breaks[i]},{breaks[i+1]}]" for i in range(nb)]
    return _dev_frame(fr.names[:1], [_cut_kernel(col, br, nb=nb)],
                      domains={0: [str(x) for x in lab]})


@prim("h2o.fillna")
def _fillna(a, e):
    """(h2o.fillna fr method axis maxlen) — AstFillNA (forward/backward).
    Device-native: ONE lax.scan over rows carrying (last value, run
    length) for every column at once — 10M rows never leave HBM."""
    fr = _f(_eval(a[0], e))
    method = str(_eval(a[1], e)) if len(a) > 1 else "forward"
    maxlen = int(_eval(a[3], e)) if len(a) > 3 else 1
    cols = _numeric_cols(fr)
    n = fr.nrows
    M = fr.matrix(cols)[:n]
    fwd = method.lower().startswith("f")
    out = _fillna_kernel(M, fwd=fwd, maxlen=maxlen)
    return _dev_frame(cols, [out[:, j] for j in range(len(cols))])


@prim("append")
def _append(a, e):
    fr = _f(_eval(a[0], e))
    col = _eval(a[1], e)
    name = str(_eval(a[2], e)) if len(a) > 2 else "C1"
    if isinstance(col, Frame):
        v = col.vecs[0]
    else:
        v = Vec.from_numpy(np.full(fr.nrows, float(col)))
    return Frame(fr.names + [name], list(fr.vecs) + [v])


@prim("columnsByType")
def _cols_by_type(a, e):
    fr = _f(_eval(a[0], e))
    want = str(_eval(a[1], e)).lower() if len(a) > 1 else "numeric"
    sel = {"numeric": T_NUM, "categorical": T_CAT, "string": T_STR,
           "time": T_TIME}.get(want, T_NUM)
    idx = [float(j) for j, v in enumerate(fr.vecs) if v.type == sel]
    return _new_frame(["C1"], [np.asarray(idx, np.float64)])


@prim("filterNACols")
def _filter_na_cols(a, e):
    fr = _f(_eval(a[0], e))
    frac = float(_eval(a[1], e)) if len(a) > 1 else 0.1
    keep = []
    for j, v in enumerate(fr.vecs):
        col = v.to_numpy()[: fr.nrows]
        if np.isnan(col).mean() < frac:
            keep.append(float(j))
    return _new_frame(["C1"], [np.asarray(keep, np.float64)])


@prim("flatten")
def _flatten(a, e):
    fr = _f(_eval(a[0], e))
    if fr.nrows == 1 and len(fr.vecs) == 1:
        v = fr.vecs[0]
        x = v.to_numpy()[0]
        if v.type == T_CAT and not np.isnan(x):
            return v.domain[int(x)]
        return float(x)
    return fr


@prim("naCnt")
def _nacnt(a, e):
    fr = _f(_eval(a[0], e))
    return [float(np.isnan(v.to_numpy()[: fr.nrows]).sum())
            for v in fr.vecs]


@prim("dropdup", "drop_duplicates")
def _dropdup(a, e):
    fr = _f(_eval(a[0], e))
    M = _mat(fr)
    _, idx = np.unique(M, axis=0, return_index=True)
    idx = np.sort(idx)
    cols = _numeric_cols(fr)
    return _new_frame(cols, [M[idx, j] for j in range(M.shape[1])])


@prim("topn")
def _topn(a, e):
    """(topn fr col nPercent getBottomN) — AstTopN."""
    fr = _f(_eval(a[0], e))
    cidx = int(_eval(a[1], e))
    pct = float(_eval(a[2], e)) if len(a) > 2 else 10.0
    bottom = bool(_eval(a[3], e)) if len(a) > 3 else False
    col = _col_np(fr, cidx)[: fr.nrows]
    k = max(1, int(round(fr.nrows * pct / 100.0)))
    order = np.argsort(col, kind="stable")
    if not bottom:
        order = order[::-1]
    pick = order[:k]
    return _new_frame(["Row Indices", fr.names[cidx]],
                      [pick.astype(np.float64), col[pick]])


@prim("relevel")
def _relevel(a, e):
    """(relevel col level) — make `level` the first domain value."""
    fr = _f(_eval(a[0], e))
    lvl = str(_eval(a[1], e))
    v = fr.vecs[0]
    dom = list(v.domain)
    assert lvl in dom, f"level {lvl} not in domain"
    new_dom = [lvl] + [d for d in dom if d != lvl]
    remap = np.array([new_dom.index(d) for d in dom], np.float64)
    col = v.to_numpy()[: fr.nrows]
    out = np.where(np.isnan(col), np.nan, remap[np.nan_to_num(col)
                                               .astype(int)])
    return _new_frame(fr.names[:1], [out], domains={0: new_dom})


@prim("relevel.by.freq")
def _relevel_freq(a, e):
    fr = _f(_eval(a[0], e))
    v = fr.vecs[0]
    col = v.to_numpy()[: fr.nrows]
    dom = list(v.domain)
    cnt = np.zeros(len(dom))
    ok = ~np.isnan(col)
    np.add.at(cnt, col[ok].astype(int), 1)
    order = np.argsort(-cnt, kind="stable")
    new_dom = [dom[i] for i in order]
    remap = np.empty(len(dom), np.float64)
    remap[order] = np.arange(len(dom))
    out = np.where(ok, remap[np.nan_to_num(col).astype(int)], np.nan)
    return _new_frame(fr.names[:1], [out], domains={0: new_dom})


@prim("rename")
def _rename(a, e):
    key_old = _eval(a[0], e)
    key_new = str(_eval(a[1], e))
    fr = key_old if isinstance(key_old, Frame) else DKV.get(str(key_old))
    DKV.put(key_new, fr)
    return fr


@prim("setDomain")
def _set_domain(a, e):
    fr = _f(_eval(a[0], e))
    dom = _eval(a[-1], e)
    v = fr.vecs[0]
    col = v.to_numpy()[: fr.nrows]
    return _new_frame(fr.names[:1], [col],
                      domains={0: [str(d) for d in dom]})


@prim("setLevel")
def _set_level(a, e):
    fr = _f(_eval(a[0], e))
    lvl = str(_eval(a[1], e))
    v = fr.vecs[0]
    dom = list(v.domain)
    code = float(dom.index(lvl))
    return _new_frame(fr.names[:1],
                      [np.full(fr.nrows, code)], domains={0: dom})


@prim("nlevels")
def _nlevels(a, e):
    fr = _f(_eval(a[0], e))
    v = fr.vecs[0]
    return float(len(v.domain) if v.type == T_CAT else 0)


@prim("is.factor")
def _is_factor(a, e):
    fr = _eval(a[0], e)
    return bool(isinstance(fr, Frame) and fr.vecs[0].type == T_CAT)


@prim("is.numeric")
def _is_numeric(a, e):
    fr = _eval(a[0], e)
    return bool(isinstance(fr, Frame)
                and fr.vecs[0].type in (T_NUM, T_TIME))


@prim("is.character")
def _is_character(a, e):
    fr = _eval(a[0], e)
    return bool(isinstance(fr, Frame) and fr.vecs[0].type == T_STR)


@prim("any.factor")
def _any_factor(a, e):
    fr = _f(_eval(a[0], e))
    return bool(any(v.type == T_CAT for v in fr.vecs))


@prim("any.na")
def _any_na(a, e):
    fr = _f(_eval(a[0], e))
    return bool(any(np.isnan(v.to_numpy()[: fr.nrows]).any()
                    for v in fr.vecs if v.type != T_STR))


@prim("seq")
def _seq(a, e):
    frm = float(_eval(a[0], e))
    to = float(_eval(a[1], e))
    by = float(_eval(a[2], e)) if len(a) > 2 else 1.0
    vals = np.arange(frm, to + by * 0.5, by, dtype=np.float64)
    return _new_frame(["C1"], [vals])


@prim("seq_len")
def _seq_len(a, e):
    n = int(_eval(a[0], e))
    return _new_frame(["C1"], [np.arange(1, n + 1, dtype=np.float64)])


@prim("rep_len")
def _rep_len(a, e):
    x = _eval(a[0], e)
    n = int(_eval(a[1], e))
    if isinstance(x, Frame):
        col = _col0(x)
        out = np.resize(col, n)
    else:
        out = np.full(n, float(x))
    return _new_frame(["C1"], [out.astype(np.float64)])


@prim("which")
def _which(a, e):
    col = _col0(_f(_eval(a[0], e)))
    idx = np.where(np.nan_to_num(col) != 0)[0]
    return _new_frame(["C1"], [idx.astype(np.float64)])


@prim("which.max")
def _which_max(a, e):
    fr = _f(_eval(a[0], e))
    M = _mat(fr)
    return _new_frame(["which.max"],
                      [np.nanargmax(M, axis=1).astype(np.float64)])


@prim("which.min")
def _which_min(a, e):
    fr = _f(_eval(a[0], e))
    M = _mat(fr)
    return _new_frame(["which.min"],
                      [np.nanargmin(M, axis=1).astype(np.float64)])


@prim("t")
def _transpose(a, e):
    fr = _f(_eval(a[0], e))
    M = _mat(fr).T
    return _new_frame([f"C{j+1}" for j in range(M.shape[1])],
                      [M[:, j] for j in range(M.shape[1])])


@prim("sumaxis")
def _sumaxis(a, e):
    fr = _f(_eval(a[0], e))
    na_rm = bool(_eval(a[1], e)) if len(a) > 1 else True
    axis = int(_eval(a[2], e)) if len(a) > 2 else 0
    M = _mat(fr)
    s = (np.nansum(M, axis=axis) if na_rm else M.sum(axis=axis))
    if axis == 0:
        return _new_frame(_numeric_cols(fr), [np.asarray([v])
                                              for v in s])
    return _new_frame(["sum"], [s])


@prim("melt")
def _melt(a, e):
    """(melt fr id_vars value_vars var_name value_name skipna) — AstMelt."""
    fr = _f(_eval(a[0], e))
    idv = _eval(a[1], e)
    valv = _eval(a[2], e) if len(a) > 2 else None
    var_name = str(_eval(a[3], e)) if len(a) > 3 else "variable"
    value_name = str(_eval(a[4], e)) if len(a) > 4 else "value"
    idv = [fr.names[int(i)] for i in idv] if isinstance(idv, list) else []
    if isinstance(valv, list) and valv:
        valv = [fr.names[int(i)] for i in valv]
    else:
        valv = [c for c in fr.names if c not in idv]
    n = fr.nrows
    nv = len(valv)

    # device-native wide->long: tile/repeat/concat stay in HBM; string id
    # vars (host-resident by design) tile on host
    names = idv + [var_name, value_name]
    out_cols, doms, types = [], {len(idv): valv}, {}
    for i, c in enumerate(idv):
        v = fr.vec(c)
        if v.type == T_STR:
            out_cols.append(np.tile(v.host_data[:n], nv))
            types[i] = T_STR
        else:
            out_cols.append(_melt_tile(fr.matrix([c])[:n, 0], nv=nv))
            if v.domain is not None:
                doms[i] = list(v.domain)
    var = jnp.repeat(jnp.arange(nv, dtype=jnp.float32), n)
    val = jnp.concatenate([fr.matrix([c])[:n, 0] for c in valv])
    out_cols += [var, val]
    if any(isinstance(c, np.ndarray) for c in out_cols):
        # mixed host/device columns: build Vecs individually
        vecs = []
        for i, c in enumerate(out_cols):
            if isinstance(c, np.ndarray):
                vecs.append(Vec.from_numpy(c, type=types.get(i)))
            else:
                from h2o3_tpu.core.frame import Vec as _V
                d = doms.get(i)
                vecs.append(_V.from_device_floats(
                    c, vtype=T_CAT if d is not None else T_NUM, domain=d))
        return Frame(names, vecs)
    return _dev_frame(names, out_cols, domains=doms)


@prim("pivot")
def _pivot(a, e):
    """(pivot fr index column value) — AstPivot. Device-native long->wide:
    the index uniquing is a device sort + boundary flags (only the unique
    COUNT and the small unique-values vector reach the host); the fill is
    one device scatter."""
    fr = _f(_eval(a[0], e))
    index = str(_eval(a[1], e))
    column = str(_eval(a[2], e))
    value = str(_eval(a[3], e))
    n = fr.nrows
    if fr.vec(index).type == T_STR or fr.vec(column).type == T_STR:
        # string keys live on host by design: host fallback
        iv = fr.vec(index).to_numpy()[:n]
        cv = fr.vec(column).to_numpy()[:n]
        vv = fr.vec(value).to_numpy()[:n]
        uniq_i, inv_i = np.unique(iv, return_inverse=True)
        uniq_c, inv_c = np.unique(cv, return_inverse=True)
        out = np.full((uniq_i.size, uniq_c.size), np.nan)
        out[inv_i, inv_c] = vv
        names = [index] + [str(c) for c in uniq_c]
        arrays = [uniq_i if iv.dtype == object
                  else uniq_i.astype(np.float64)] + \
            [out[:, j] for j in range(uniq_c.size)]
        return _new_frame(names, arrays)
    iv = fr.matrix([index])[:n, 0]
    cv = fr.matrix([column])[:n, 0]
    vv = fr.matrix([value])[:n, 0]

    s, cnt = _uniq_sorted_count(iv)
    ui = int(cnt)                              # scalar readback only
    uniq_i = jnp.unique(s, size=ui)            # (ui,) device

    cdom = fr.vec(column).domain
    if cdom is not None and len(cdom):
        uc = len(cdom)
        labels = list(cdom)
        inv_c = jnp.nan_to_num(cv).astype(jnp.int32)
    else:
        sc, ccnt = _uniq_sorted_count(cv)
        uc = int(ccnt)
        uniq_c = jnp.unique(sc, size=uc)
        labels = [str(float(x)) for x in np.asarray(uniq_c)]
        inv_c = jnp.searchsorted(uniq_c, cv).astype(jnp.int32)

    out = _pivot_fill(uniq_i, iv, inv_c, vv, ui=ui, uc=uc)
    names = [index] + labels
    return _dev_frame(names,
                      [uniq_i.astype(jnp.float32)]
                      + [out[:, j] for j in range(uc)])


@prim("rank_within_groupby")
def _rank_within(a, e):
    """(rank_within_groupby fr groupby_cols sort_cols sort_orders new_colname
    sort_cols_sorted) — AstRankWithinGroupBy."""
    fr = _f(_eval(a[0], e))
    gcols = [int(i) for i in _eval(a[1], e)]
    scols = [int(i) for i in _eval(a[2], e)]
    new_col = str(_eval(a[4], e)) if len(a) > 4 else "New_Rank_column"
    n = fr.nrows
    # device-native: ONE lexsort over (group cols, sort cols), ranks from
    # group-boundary flags + cumulative positions, scattered back to the
    # original row order. No per-row host loop; the untouched columns are
    # REUSED (no copy, string columns included) — only the rank is new.
    G = fr.matrix([fr.names[j] for j in gcols])[:n]
    S = fr.matrix([fr.names[j] for j in scols])[:n]
    rank = _rank_kernel(G, S)
    from h2o3_tpu.core.frame import Vec as _V
    return Frame(fr.names + [new_col],
                 list(fr.vecs) + [_V.from_device_floats(rank)])


@prim("ddply")
def _ddply(a, e):
    """(ddply fr [group cols] fun) — per-group lambda apply."""
    from h2o3_tpu.rapids.rapids import _apply_lambda_rows
    fr = _f(_eval(a[0], e))
    gcols = [int(i) for i in _eval(a[1], e)]
    fun = a[2]
    n = fr.nrows
    gkey = np.stack([_col_np(fr, j)[:n] for j in gcols], 1)
    uniq, inv = np.unique(gkey, axis=0, return_inverse=True)
    results = []
    for g in range(uniq.shape[0]):
        mask = inv == g
        sub = _new_frame(fr.names,
                         [v.to_numpy()[:n][mask] for v in fr.vecs])
        val = _eval([fun, sub], e) if callable(fun) else \
            _eval_lambda(fun, sub, e)
        results.append(float(val if not isinstance(val, Frame)
                             else _col0(val)[0]))
    arrays = [uniq[:, k].astype(np.float64)
              for k in range(uniq.shape[1])] + \
        [np.asarray(results, np.float64)]
    names = [fr.names[j] for j in gcols] + ["ddply_C1"]
    return _new_frame(names, arrays)


def _eval_lambda(fun, sub, e):
    """Apply a {args . body} lambda AST to a sub-frame."""
    from h2o3_tpu.rapids.rapids import Env
    assert isinstance(fun, tuple) and fun[0] == "fun", "expected lambda"
    _, params, body = fun
    env2 = Env(e.session)
    env2.locals = dict(getattr(e, "locals", {}))
    env2.locals[params[0]] = sub
    return _eval(body, env2)


# ===========================================================================
# string (prims/string)
def _str_col(fr):
    v = fr.vecs[0]
    if v.type == T_STR:
        return np.asarray(v.host_data, object), None
    assert v.type == T_CAT
    col = v.to_numpy()[: fr.nrows]
    dom = np.asarray(v.domain, object)
    out = np.where(np.isnan(col), None,
                   dom[np.nan_to_num(col).astype(int)])
    return out, list(v.domain)


@prim("lstrip")
def _lstrip(a, e):
    fr = _f(_eval(a[0], e))
    chars = str(_eval(a[1], e)) if len(a) > 1 else None
    s, _ = _str_col(fr)
    out = np.array([x.lstrip(chars) if x is not None else None
                    for x in s], object)
    return _new_frame(fr.names[:1], [out])


@prim("rstrip")
def _rstrip(a, e):
    fr = _f(_eval(a[0], e))
    chars = str(_eval(a[1], e)) if len(a) > 1 else None
    s, _ = _str_col(fr)
    out = np.array([x.rstrip(chars) if x is not None else None
                    for x in s], object)
    return _new_frame(fr.names[:1], [out])


@prim("entropy")
def _entropy(a, e):
    fr = _f(_eval(a[0], e))
    s, _ = _str_col(fr)
    out = np.empty(len(s), np.float64)
    for i, x in enumerate(s):
        if not x:
            out[i] = np.nan if x is None else 0.0
            continue
        _, cnt = np.unique(list(x), return_counts=True)
        p = cnt / cnt.sum()
        out[i] = float(-(p * np.log2(p)).sum())
    return _new_frame(fr.names[:1], [out])


@prim("grep")
def _grep(a, e):
    """(grep fr regex ignore_case invert output_logical) — AstGrep."""
    fr = _f(_eval(a[0], e))
    pattern = str(_eval(a[1], e))
    ignore_case = bool(_eval(a[2], e)) if len(a) > 2 else False
    invert = bool(_eval(a[3], e)) if len(a) > 3 else False
    logical = bool(_eval(a[4], e)) if len(a) > 4 else False
    s, _ = _str_col(fr)
    rx = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    hit = np.array([bool(rx.search(x)) if x is not None else False
                    for x in s])
    if invert:
        hit = ~hit
    if logical:
        return _new_frame(["C1"], [hit.astype(np.float64)])
    return _new_frame(["C1"], [np.where(hit)[0].astype(np.float64)])


@prim("strDistance")
def _str_distance(a, e):
    """(strDistance fr1 fr2 measure compare_empty) — Levenshtein/jaccard."""
    f1 = _f(_eval(a[0], e))
    f2 = _f(_eval(a[1], e))
    measure = str(_eval(a[2], e)) if len(a) > 2 else "lv"
    s1, _ = _str_col(f1)
    s2, _ = _str_col(f2)
    out = np.empty(len(s1), np.float64)
    for i in range(len(s1)):
        x, y = s1[i], s2[i % len(s2)]
        if x is None or y is None:
            out[i] = np.nan
        elif measure in ("lv", "levenshtein"):
            out[i] = _lev(x, y)
        else:  # jaccard over character sets
            sx, sy = set(x), set(y)
            out[i] = 1.0 - len(sx & sy) / max(len(sx | sy), 1)
    return _new_frame(["C1"], [out])


def _lev(x, y):
    m, n = len(x), len(y)
    if m == 0 or n == 0:
        return float(max(m, n))
    prev = list(range(n + 1))
    for i in range(1, m + 1):
        cur = [i] + [0] * n
        for j in range(1, n + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (x[i - 1] != y[j - 1]))
        prev = cur
    return float(prev[n])


@prim("tokenize")
def _tokenize(a, e):
    fr = _f(_eval(a[0], e))
    split = str(_eval(a[1], e)) if len(a) > 1 else "\\s+"
    s, _ = _str_col(fr)
    toks = []
    for x in s:
        if x is not None:
            toks += [t for t in re.split(split, x) if t]
        toks.append(None)          # sentence separator NA row
    return _new_frame(["C1"], [np.asarray(toks, object)])


@prim("num_valid_substrings")
def _num_valid_sub(a, e):
    fr = _f(_eval(a[0], e))
    words_path = _eval(a[1], e)
    words = set()
    try:
        with open(str(words_path)) as fh:
            words = {w.strip() for w in fh}
    except OSError:
        pass
    s, _ = _str_col(fr)
    out = np.empty(len(s), np.float64)
    for i, x in enumerate(s):
        if x is None:
            out[i] = np.nan
            continue
        cnt = 0
        for lo in range(len(x)):
            for hi in range(lo + 1, len(x) + 1):
                if x[lo:hi] in words:
                    cnt += 1
        out[i] = cnt
    return _new_frame(["C1"], [out])


# ===========================================================================
# time (prims/time)
@prim("mktime")
def _mktime(a, e):
    """(mktime year month day hour minute second msec) — ms since epoch.
    month/day are 0-based in the reference (AstMktime)."""
    parts = [_eval(x, e) for x in a]

    def arr(x, default):
        if isinstance(x, Frame):
            return _col0(x)
        return np.asarray([float(x if x is not None else default)])

    cols = [arr(p, 0) for p in parts]
    n = max(len(c) for c in cols)
    cols = [np.resize(c, n) for c in cols]
    while len(cols) < 7:
        cols.append(np.zeros(n))
    out = np.empty(n, np.float64)
    for i in range(n):
        y, mo, d, h, mi, s, ms = (int(c[i]) for c in cols[:7])
        dt = datetime(y, mo + 1, d + 1, h, mi, s, ms * 1000,
                      tzinfo=timezone.utc)
        out[i] = dt.timestamp() * 1000.0
    return _new_frame(["mktime"], [out])


@prim("moment")
def _moment(a, e):
    return _mktime(a, e)


@prim("millis")
def _millis(a, e):
    fr = _f(_eval(a[0], e))
    col = _col0(fr)
    # time columns already carry ms since epoch
    return _new_frame(fr.names[:1], [col * 1.0])


@prim("week")
def _week(a, e):
    fr = _f(_eval(a[0], e))
    col = _col0(fr)
    out = np.array(
        [float(datetime.fromtimestamp(float(x) / 1000.0,
                                      tz=timezone.utc).isocalendar()[1])
         if not np.isnan(x) else np.nan for x in col])
    return _new_frame(fr.names[:1], [out])


@prim("as.Date")
def _as_date(a, e):
    fr = _f(_eval(a[0], e))
    fmt = str(_eval(a[1], e)) if len(a) > 1 else "%Y-%m-%d"
    # translate Java time patterns to strptime
    pyfmt = (fmt.replace("yyyy", "%Y").replace("MM", "%m")
             .replace("dd", "%d").replace("HH", "%H")
             .replace("mm", "%M").replace("ss", "%S"))
    s, _ = _str_col(fr)
    out = np.empty(len(s), np.float64)
    for i, x in enumerate(s):
        try:
            out[i] = datetime.strptime(x, pyfmt) \
                .replace(tzinfo=timezone.utc).timestamp() * 1000.0
        except (TypeError, ValueError):
            out[i] = np.nan
    return _new_frame(fr.names[:1], [out], types={0: T_TIME})


_TZ = ["UTC"]


@prim("getTimeZone")
def _get_tz(a, e):
    return _TZ[0]


@prim("setTimeZone")
def _set_tz(a, e):
    _TZ[0] = str(_eval(a[0], e))
    return _TZ[0]


@prim("listTimeZones")
def _list_tz(a, e):
    import zoneinfo
    zs = sorted(zoneinfo.available_timezones())
    return _new_frame(["Timezones"], [np.asarray(zs, object)])


# ===========================================================================
# reducers (NA-counting variants) + misc
@prim("maxNA")
def _max_na(a, e):
    return _reduce_op(a, e, lambda A, live:
                      jnp.max(jnp.where(live, A, -jnp.inf)))


@prim("minNA")
def _min_na(a, e):
    return _reduce_op(a, e, lambda A, live:
                      jnp.min(jnp.where(live, A, jnp.inf)))


@prim("sumNA")
def _sum_na(a, e):
    return _reduce_op(a, e, lambda A, live:
                      jnp.sum(jnp.where(live, A, 0.0)))


@prim("prod.na")
def _prod_na(a, e):
    return _reduce_op(a, e, lambda A, live:
                      jnp.prod(jnp.where(live, A, 1.0)))


@prim("match")
def _match(a, e):
    """(match fr table nomatch start_index) — AstMatch."""
    fr = _f(_eval(a[0], e))
    table = _eval(a[1], e)
    nomatch = _eval(a[2], e) if len(a) > 2 else float("nan")
    start = int(_eval(a[3], e)) if len(a) > 3 else 1
    v = fr.vecs[0]
    if v.type == T_CAT:
        vals = [str(t) for t in (table if isinstance(table, list)
                                 else [table])]
        lut = {lvl: i for i, lvl in enumerate(v.domain)}
        codes = [lut.get(t, -1) for t in vals]
        col = v.to_numpy()[: fr.nrows]
        out = np.full(fr.nrows, np.nan)
        for rank, c in enumerate(codes):
            if c >= 0:
                out[col == c] = rank + start
    else:
        vals = [float(t) for t in (table if isinstance(table, list)
                                   else [table])]
        col = v.to_numpy()[: fr.nrows]
        out = np.full(fr.nrows, np.nan)
        for rank, t in enumerate(vals):
            out[col == t] = rank + start
    if not (isinstance(nomatch, float) and math.isnan(nomatch)):
        out = np.where(np.isnan(out), float(nomatch), out)
    return _new_frame(fr.names[:1], [out])


@prim("ls")
def _ls(a, e):
    keys = sorted(DKV.keys()) if hasattr(DKV, "keys") else []
    return _new_frame(["key"], [np.asarray(keys, object)])


@prim("comma")
def _comma(a, e):
    out = None
    for x in a:
        out = _eval(x, e)
    return out


# ===========================================================================
# Tranche 3 — final parity prims (ast/prims coverage to the full registry)

PRIMS["%%"] = PRIMS["%"]          # AstMod alias (operators/AstMod.java)
PRIMS[","] = PRIMS["comma"]       # AstComma (operators/AstComma.java)


@prim("none")
def _noop(a, e):
    """AstNoOp (math/AstNoOp.java): identity unary op."""
    return _eval(a[0], e) if a else 0.0


@prim("assign")
def _assign_global(a, e):
    """AstAssign (assign/AstAssign.java): global key <- frame (copy; the
    reference shares Vecs — here frames are immutable columns, so a
    shallow re-key is the same semantics)."""
    key = a[0] if isinstance(a[0], str) else str(_eval(a[0], e))
    src = _eval(a[1], e)
    f = _new_frame(list(src.names),
                   [src.vecs[j].to_numpy()[: src.nrows]
                    for j in range(src.ncols)],
                   types=[v.type for v in src.vecs],
                   domains={j: src.vecs[j].levels()
                            for j in range(src.ncols)
                            if src.vecs[j].type == T_CAT})
    DKV.remove(f.key)
    f.key = key
    DKV.put(key, f)
    e.session.register(key)
    return f


@prim("x")
def _mmult(a, e):
    """AstMMult (matrix/AstMMult.java): (x fr1 fr2) matrix product on MXU."""
    f1 = _eval(a[0], e)
    f2 = _eval(a[1], e)
    A = f1.matrix(_numeric_cols(f1))[: f1.nrows]
    B = f2.matrix(_numeric_cols(f2))[: f2.nrows]
    out = np.asarray(_mrt.cached_jit(jnp.matmul)(A, B), np.float64)
    return _new_frame([f"C{j+1}" for j in range(out.shape[1])],
                      [out[:, j] for j in range(out.shape[1])])


@prim("scale_inplace")
def _scale_inplace(a, e):
    """AstScale.AstScaleInPlace: scale writing back into the source key.

    The target key is the symbol the frame was looked up by (the DKV id in
    the Rapids expression), not the frame's own auto-generated key — they
    differ when a frame is registered under more than one id."""
    f = _eval(a[0], e)
    # target key = the DKV id the frame was looked up by (may differ from
    # f.key when the frame is registered under an alias); a lambda-local
    # binding is NOT a DKV id — fall back to the frame's own key then
    key = a[0] if isinstance(a[0], str) and DKV.get(a[0]) is f else f.key
    out = PRIMS["scale"](a, e)
    DKV.remove(out.key)
    out.key = key
    DKV.put(key, out)
    if f.key != key and DKV.get(f.key) is f:
        # every live id of the frame must see the scaled data (in-place
        # contract): repoint the original registration too
        DKV.put(f.key, out)
    return out


@prim("setproperty")
def _setproperty(a, e):
    """AstSetProperty (misc/AstSetProperty.java): set a runtime property
    (the reference sets Java system properties with the ai.h2o. prefix)."""
    from h2o3_tpu.utils import config as _cfg
    prop = _eval(a[0], e)
    value = _eval(a[1], e)
    _cfg.set_property(str(prop), value)
    return str(value)


@prim("model.reset.threshold")
def _reset_threshold(a, e):
    """AstModelResetThreshold: set a binomial model's decision threshold;
    returns the OLD threshold."""
    m = _eval(a[0], e)
    thr = float(_eval(a[1], e))
    old = getattr(m, "_default_threshold", 0.5)
    m._default_threshold = thr
    DKV.put(m.key, m)
    return float(old)


@prim("segment_models_as_frame")
def _segment_models_as_frame(a, e):
    """AstSegmentModelsAsFrame: one row per segment: segment cols +
    model key + status + error."""
    sm = _eval(a[0], e)
    rows = sm.as_list()
    seg_names = sorted({k for r in rows for k in r["segment"]})
    cols, names = [], []
    for sn in seg_names:
        names.append(sn)
        cols.append(np.asarray([r["segment"].get(sn) for r in rows],
                               object))
    for field in ("model", "status"):
        names.append(field if field != "model" else "model_id")
        cols.append(np.asarray([r.get(field) or "" for r in rows], object))
    names.append("errors")
    cols.append(np.asarray([r.get("error") or "" for r in rows], object))
    types = [T_NUM if np.asarray(c).dtype.kind in "fi" else T_STR
             for c in cols]
    cols = [c if t == T_NUM else np.asarray([str(x) for x in c], object)
            for c, t in zip(cols, types)]
    return _new_frame(names, cols, types=types)


@prim("PermutationVarImp")
def _perm_varimp(a, e):
    """AstPermutationVarImp (models/AstPermutationVarImp.java)."""
    from h2o3_tpu.explain_data import permutation_varimp
    m = _eval(a[0], e)
    fr = _eval(a[1], e)
    metric = str(_eval(a[2], e)) if len(a) > 2 else "AUTO"
    # args 3 (n_samples) is subsampling — full frame used here
    n_repeats = int(_eval(a[4], e)) if len(a) > 4 else 1
    seed = int(_eval(a[6], e)) if len(a) > 6 else 42
    rows = permutation_varimp(m, fr, metric=metric,
                              n_repeats=max(1, n_repeats), seed=seed)
    return _new_frame(
        ["Variable", "Relative Importance", "Scaled Importance",
         "Percentage"],
        [np.asarray([r["variable"] for r in rows], object),
         np.asarray([r["relative_importance"] for r in rows]),
         np.asarray([r["scaled_importance"] for r in rows]),
         np.asarray([r["percentage"] for r in rows])],
        types=[T_STR, T_NUM, T_NUM, T_NUM])


@prim("grouped_permute")
def _grouped_permute(a, e):
    """AstGroupedPermute (mungers/AstGroupedPermute.java): per group-by
    value, cross product of the 'D' rows x 'C' rows of permuteBy (a 2-level
    categorical), amounts summed per distinct permCol id. Output:
    group cols + In, Out, InAmnt, OutAmnt."""
    fr = _eval(a[0], e)
    perm_col = int(_eval(a[1], e))
    gb = _eval(a[2], e)
    gb_cols = [int(g) for g in (gb if isinstance(gb, list) else [gb])]
    permute_by = int(_eval(a[3], e))
    keep_col = int(_eval(a[4], e))
    n = fr.nrows
    gid = fr.vecs[gb_cols[0]].to_numpy()[:n]
    rid = fr.vecs[perm_col].to_numpy()[:n]
    typ_codes = fr.vecs[permute_by].to_numpy()[:n]
    dom = fr.vecs[permute_by].levels() or []
    is_d = np.asarray([dom[int(t)] == "D" if t == t and dom else int(t) == 0
                       for t in typ_codes])
    amt = fr.vecs[keep_col].to_numpy()[:n]
    groups: dict = {}
    for i in range(n):
        g = groups.setdefault(gid[i], [{}, {}])
        side = 0 if is_d[i] else 1
        g[side][rid[i]] = g[side].get(rid[i], 0.0) + float(amt[i])
    out = [[] for _ in range(len(gb_cols) + 4)]
    for g, (dd, cc) in sorted(groups.items()):
        for rd, ad in sorted(dd.items()):
            for rc, ac in sorted(cc.items()):
                out[0].append(g)
                out[-4].append(rd)
                out[-3].append(rc)
                out[-2].append(ad)
                out[-1].append(ac)
    names = [fr.names[g] for g in gb_cols] + \
        ["In", "Out", "InAmnt", "OutAmnt"]
    doms = {0: fr.vecs[gb_cols[0]].levels(),
            len(gb_cols): fr.vecs[perm_col].levels(),
            len(gb_cols) + 1: fr.vecs[perm_col].levels()}
    doms = {k: v for k, v in doms.items() if v}
    return _new_frame(names, [np.asarray(c, np.float64) for c in out],
                      domains=doms)


@prim("isax")
def _isax(a, e):
    """AstIsax (timeseries/AstIsax.java): iSAX 2.0 — rows are time series;
    PAA into numWords segments then symbolize against N(0,1) breakpoints
    up to maxCardinality. Output: iSax_index string + numWords PAA cols."""
    fr = _eval(a[0], e)
    num_words = int(_eval(a[1], e))
    max_card = int(_eval(a[2], e))
    if num_words <= 0 or max_card <= 0:
        raise ValueError("numWords and maxCardinality must be > 0")
    A = fr.matrix(_numeric_cols(fr))[: fr.nrows]

    @jax.jit
    def paa(A):
        nTS, T = A.shape
        # z-normalize each series then piecewise-aggregate into words
        mu = jnp.nanmean(A, axis=1, keepdims=True)
        sd = jnp.nanstd(A, axis=1, keepdims=True)
        Z = (A - mu) / jnp.where(sd > 0, sd, 1.0)
        k = -(-T // num_words)
        pad = jnp.pad(Z, ((0, 0), (0, k * num_words - T)),
                      constant_values=jnp.nan)
        seg = pad.reshape(nTS, num_words, k)
        return jnp.nanmean(seg, axis=2)

    W = np.asarray(paa(A), np.float64)
    # Gaussian breakpoints at cardinality max_card
    from h2o3_tpu.utils.stats import norm_ppf
    card = max(2, min(int(max_card), 64))
    bps = np.asarray([norm_ppf((i + 1) / card) for i in range(card - 1)])
    sym = np.stack([np.searchsorted(bps, W[:, j]) for j in
                    range(num_words)], axis=1)
    idx = np.asarray(["^".join(str(int(s)) for s in row) for row in sym],
                     object)
    names = ["iSax_index"] + [f"c{j}" for j in range(num_words)]
    cols = [idx] + [sym[:, j].astype(np.float64)
                    for j in range(num_words)]
    return _new_frame(names, cols, types=[T_STR] + [T_NUM] * num_words)


@prim("tf-idf")
def _tf_idf(a, e):
    """AstTfIdf (advmath/AstTfIdf.java): (tf-idf frame doc_id_idx text_idx
    preprocess case_sensitive) -> DocID, Word, TF, IDF, TF-IDF."""
    fr = _eval(a[0], e)
    doc_idx = int(_eval(a[1], e))
    txt_idx = int(_eval(a[2], e))
    preprocess = bool(_eval(a[3], e)) if len(a) > 3 else True
    case_sensitive = bool(_eval(a[4], e)) if len(a) > 4 else False
    n = fr.nrows
    docs = fr.vecs[doc_idx].to_numpy()[:n]
    tv = fr.vecs[txt_idx]
    if tv.type == T_STR:
        txt = tv.to_numpy()[:n]
    elif tv.type == T_CAT:
        dom = tv.levels()
        txt = [dom[int(c)] if c == c else None for c in tv.to_numpy()[:n]]
    else:
        raise ValueError("tf-idf text column must be string/categorical")
    pairs = []
    for d, t in zip(docs, txt):
        s = str(t) if t is not None else ""
        if not case_sensitive:
            s = s.lower()
        words = s.split() if preprocess else [s]
        for w in words:
            if w:
                pairs.append((float(d), w))
    if not pairs:
        raise ValueError("Empty input frame provided.")
    tf: dict = {}
    for d, w in pairs:
        tf[(d, w)] = tf.get((d, w), 0) + 1
    n_docs = len(set(d for d, _ in pairs))
    dfreq: dict = {}
    for (d, w) in tf:
        dfreq[w] = dfreq.get(w, 0) + 1
    rows = sorted(tf.items(), key=lambda kv: (kv[0][1], kv[0][0]))
    doc_c = np.asarray([d for (d, w), _ in rows])
    word_c = np.asarray([w for (d, w), _ in rows], object)
    tf_c = np.asarray([c for _, c in rows], np.float64)
    idf_c = np.asarray([math.log((n_docs + 1.0) / (dfreq[w] + 1.0))
                        for (_, w), _ in rows], np.float64)
    return _new_frame(["DocID", "Word", "TF", "IDF", "TF-IDF"],
                      [doc_c, word_c, tf_c, idf_c, tf_c * idf_c],
                      types=[T_NUM, T_STR, T_NUM, T_NUM, T_NUM])


@prim("run_tool")
def _run_tool(a, e):
    """AstRunTool (internal/AstRunTool.java): dispatch to a registered
    maintenance tool by name."""
    from h2o3_tpu.utils.tools import run_tool as _rt
    name = str(_eval(a[0], e))
    args = [_eval(x, e) for x in a[1:]]
    return _rt(name, args)
