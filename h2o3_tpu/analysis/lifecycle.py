"""Flow-sensitive lifecycle rules (R022-R025) over the exception-edge CFG.

The last four review cycles converged on one bug shape: a PAIRED
protocol — reserve→commit/rollback, slot acquire→release,
prepay→adopt/settle, refcount place→free, gauge register→remove —
whose closer is skipped on an exception or early-return path (the
FairGate slot leak, the ParamStore refs=1 permanent HBM leak, the ghost
gauge series, the admission double-count — every one hand-fixed).
R001-R021 are flow-insensitive and cannot see this class. These rules
run the cfg.py exception-edge graph over a declarative PAIR REGISTRY:

  * R022 paired-protocol leak — an opener whose matching closer is NOT
    reached on every CFG path (normal fall-through, early return, and
    the exception edge out of every call/attribute access). `with`
    items and try/finally closers prove closed by construction; a
    helper that closes on EVERY one of its own paths counts as a closer
    at its call sites (interprocedural closure over the dispatch-
    resolved callgraph); a helper that only conditionally closes does
    not — exactly the paths where it doesn't are the leak. Tokens that
    ESCAPE the function (returned, stored on self, captured by a
    closure, handed to a non-closer call) transfer ownership and are
    not flagged here — returns are R024's job, stored/captured tokens
    belong to an object lifecycle the runtime leaktrack sanitizer owns.
    Per-entity gauge series (`.set(..., label=)` with no `.remove(...)`
    anywhere in the module) are the registry's one flow-INsensitive
    pair: a ghost series outlives its entity no matter which path
    registered it.
  * R023 swallowed control-flow exception — a broad `except Exception`
    on a dispatch/serving/replay path whose body neither re-raises nor
    filters the typed control exceptions (RateLimited, QuotaExceeded,
    DeadlineExceeded, EpochChanged, DivergenceError) that MUST
    propagate to produce their status codes. Flagged only where one
    can actually ARRIVE: a call in the try body resolves (through the
    callgraph, transitively) into a function that raises one — a
    heartbeat loop swallowing socket errors owes nothing. A preceding
    typed handler arm counts as the filter.
  * R024 leaked-return protocol — a call to a function that RETURNS an
    open resource (the registry openers, plus any wrapper that returns
    one unclosed) whose result is discarded, or bound by a wrapper
    caller and never closed on some path.
  * R025 export contract for scoring programs — the `_score_with_params`
    family (and the scorer_cache `_build` trace closures) free of host
    callbacks (pure_callback/io_callback/debug.callback), module-level
    device-array constants captured by closure, and
    float(x)/bool(x)/int(x)/`if x:` concretization of traced values
    (function parameters; shape/ndim/dtype/len reads, string-constant
    config dispatch, and jit `static_argnames` are static and exempt).
    Run at zero findings: the static precondition for the jax.export
    portable-artifact item.

All four ride the ONE build_project index (callgraph.check calls
check_project here, after effects.py) and build CFGs lazily, only for
functions that mention a registered opener or closer.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from h2o3_tpu.analysis import callgraph as _cg
from h2o3_tpu.analysis import cfg as _cfg
from h2o3_tpu.analysis.engine import Finding

RULES = {"R022", "R023", "R024", "R025"}

# typed control exceptions that must propagate through dispatch layers
CONTROL_EXCEPTIONS = {"RateLimited", "QuotaExceeded", "DeadlineExceeded",
                      "EpochChanged", "DivergenceError"}

# module prefixes that constitute the dispatch/serving/replay surface
# (R023 scope; replay roots from the callgraph join regardless of path)
_R023_PREFIXES = ("h2o3_tpu/api/", "h2o3_tpu/serving/", "h2o3_tpu/deploy/")


# ---------------------------------------------------------------------------
# pair registry
@dataclass(frozen=True)
class Pair:
    """One paired protocol. Openers/closers match a call site when a
    dispatch-resolved callee qual ends with an entry in *_quals, or the
    textual receiver chain ends with an entry in *_chains (the chain
    fallback catches `_qos.GATE.acquire(...)` singleton sites the
    callgraph's import-alias resolution deliberately punts on)."""
    key: str
    desc: str
    opener_quals: tuple = ()
    opener_chains: tuple = ()
    closer_quals: tuple = ()
    closer_chains: tuple = ()
    token: bool = False        # opener returns a token worth tracking
    falsy_ok: bool = False     # falsy token == nothing acquired (guards
    #                            on the bare token var are acquire tests)
    scoped: bool = False       # request-scoped: the closer lives in the
    #                            request teardown frame — only path-check
    #                            functions that contain a closer themselves


PAIRS = (
    Pair("qos.gate", "FairGate dispatch slot",
         opener_quals=("FairGate.acquire",),
         opener_chains=("GATE.acquire",),
         closer_quals=("FairGate.release",),
         closer_chains=("GATE.release",),
         token=True, falsy_ok=True),
    Pair("qos.job_slot", "concurrent-job quota charge",
         opener_quals=(".acquire_job_slot",),
         opener_chains=(".acquire_job_slot", "acquire_job_slot"),
         closer_quals=(".release_job_slot",),
         closer_chains=(".release_job_slot", "release_job_slot"),
         token=True, falsy_ok=True),
    Pair("qos.prepaid", "prepaid job-slot charge",
         opener_quals=(".prepay_job_slot",),
         opener_chains=(".prepay_job_slot", "prepay_job_slot"),
         closer_quals=(".adopt_prepaid_job_slot",
                       ".settle_prepaid_job_slot"),
         closer_chains=(".adopt_prepaid_job_slot", "adopt_prepaid_job_slot",
                        ".settle_prepaid_job_slot",
                        "settle_prepaid_job_slot"),
         scoped=True),
    Pair("qos.edge_admit", "edge-admission flag",
         opener_quals=(".edge_admit",),
         opener_chains=(".edge_admit", "edge_admit"),
         closer_quals=(".end_request",),
         closer_chains=(".end_request", "end_request"),
         scoped=True),
    Pair("qos.lane", "interactive-lane counter",
         opener_quals=(".note_interactive_start",),
         opener_chains=(".note_interactive_start",
                        "note_interactive_start"),
         closer_quals=(".note_interactive_end",),
         closer_chains=(".note_interactive_end", "note_interactive_end"),
         scoped=True),
    Pair("tiering.reserve", "byte-budget reservation",
         opener_quals=("._try_reserve",),
         opener_chains=("._try_reserve",),
         closer_quals=("._release_reservation",),
         closer_chains=("._release_reservation",),
         token=True, falsy_ok=True),
    Pair("params.refcount", "model-param placement refcount",
         opener_quals=("ParamStore.acquire",),
         opener_chains=("PARAMS.acquire",),
         closer_quals=("ParamStore.release",),
         closer_chains=("PARAMS.release",),
         token=True),
    Pair("usage.request", "usage-attribution request record",
         opener_quals=(".begin_request",),
         opener_chains=(".begin_request", "begin_request"),
         closer_quals=(".finish_request", ".clear_request"),
         closer_chains=(".finish_request", "finish_request",
                        ".clear_request", "clear_request"),
         scoped=True),
)


def _suffix_terms(pair: Pair, closer: bool) -> frozenset:
    """Terminal attr names for the cheap candidate prefilter."""
    src = (pair.closer_quals + pair.closer_chains) if closer \
        else (pair.opener_quals + pair.opener_chains)
    return frozenset(s.rsplit(".", 1)[-1] for s in src)


_PAIR_OPENER_TERMS = {p.key: _suffix_terms(p, False) for p in PAIRS}
_PAIR_CLOSER_TERMS = {p.key: _suffix_terms(p, True) for p in PAIRS}


# ---------------------------------------------------------------------------
# one-pass call index: receiver chains are computed ONCE per call node
# (R022+R024 visits every call per pair, per fixpoint round — recomputing
# _chain dominated the first profile at 6x the whole analyzer budget)
class _Idx:
    def __init__(self, proj):
        self.chain: dict = {}     # call node -> receiver chain
        self.term: dict = {}      # call node -> terminal attr/name
        self.calls: dict = {}     # qual -> [call nodes]
        self.byline: dict = {}    # qual -> {line: {callee qual}}
        self.terms: dict = {}     # qual -> {call terminals}
        self.callees: dict = {}   # qual -> {resolved callee qual}
        for qual, fi in proj.fns.items():
            calls = [n for n in proj.fn_nodes(fi)
                     if isinstance(n, ast.Call)]
            self.calls[qual] = calls
            terms = set()
            for c in calls:
                ch = _cg._chain(c.func)
                self.chain[c] = ch
                t = ch.rsplit(".", 1)[-1] if ch \
                    else (_cg._terminal(c.func) or "")
                self.term[c] = t
                terms.add(t)
            self.terms[qual] = terms
            by: dict = {}
            for q, ln, _h, _b, _s in fi.calls:
                by.setdefault(ln, set()).add(q)
            self.byline[qual] = by
            self.callees[qual] = {c[0] for c in fi.calls}


def _match(idx: _Idx, qual: str, call: ast.Call, quals: tuple,
           chains: tuple) -> bool:
    chain = idx.chain.get(call)
    if chain is None:
        chain = _cg._chain(call.func)
    if chain and any(chain.endswith(c) for c in chains):
        return True
    for q in idx.byline.get(qual, {}).get(call.lineno, ()):
        if any(q.endswith(s) for s in quals):
            return True
    return False


def _stmt_exprs(stmt) -> list:
    """The expressions a CFG block for `stmt` actually EVALUATES — a
    compound statement's block is its header only (an If block must not
    claim the closers buried in its branches, or an else-path leak
    proves closed)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def _calls_under(stmt) -> list:
    return [n for e in _stmt_exprs(stmt) for n in ast.walk(e)
            if isinstance(n, ast.Call)]


def _enclosing_stmt(mod, node):
    """Nearest ancestor that is a statement (the CFG's block unit)."""
    parents = mod.parents()
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = parents.get(cur)
    return cur


def _inside_withitem(mod, node) -> bool:
    parents = mod.parents()
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        parent = parents.get(cur)
        if isinstance(parent, ast.withitem) \
                and parent.context_expr is cur:
            return True
        cur = parent
    return False


# ---------------------------------------------------------------------------
# interprocedural closers: helpers that close on EVERY path
def _stmt_closes(idx, qual, stmt, pair: Pair, extra: set) -> bool:
    for c in _calls_under(stmt):
        if _match(idx, qual, c, pair.closer_quals, pair.closer_chains):
            return True
        for q in idx.byline.get(qual, {}).get(c.lineno, ()):
            if q in extra:
                return True
    return False


def _always_closers(proj, idx: _Idx, pair: Pair) -> set:
    """Quals of functions that reach a closer for `pair` on every path
    from entry to either exit — calling one IS closing (fixpoint, so a
    helper calling an always-closing helper qualifies too). A function
    that closes only on SOME paths never enters this set: at its call
    sites the pair stays open on exactly the paths it misses."""
    cterms = _PAIR_CLOSER_TERMS[pair.key]
    out: set = set()
    changed = True
    guard = 0
    while changed and guard < 6:
        changed = False
        guard += 1
        for qual, fi in proj.fns.items():
            if qual in out:
                continue
            if not (idx.terms.get(qual, frozenset()) & cterms
                    or idx.callees.get(qual, frozenset()) & out):
                continue
            g = _cfg.get(fi.mod.mod, fi.node)
            closing = {b.bid for b in g.blocks.values()
                       if b.stmt is not None
                       and _stmt_closes(idx, qual, b.stmt, pair, out)}
            if closing and g.escape_path([g.entry], closing) is None:
                out.add(qual)
                changed = True
    return out


# ---------------------------------------------------------------------------
# R022 core: per-site path proof
def _token_name(stmt):
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name):
        return stmt.targets[0].id
    return None


def _token_escapes(fi, proj, idx, stmt, name: str, pair: Pair,
                   extra: set) -> str:
    """How the token leaves this function's custody, or "" when it
    stays local. Returned / stored / closure-captured / passed-to-a-
    non-closer tokens transfer ownership — the path proof would be
    meaningless here."""
    qual = fi.qual
    for n in proj.fn_nodes(fi):
        if isinstance(n, ast.Return) and n.value is not None:
            if any(isinstance(s, ast.Name) and s.id == name
                   for s in ast.walk(n.value)):
                return "returned"
        elif isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if n is stmt:
                continue
            tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
            v = getattr(n, "value", None)
            if v is not None and any(
                    isinstance(s, ast.Name) and s.id == name
                    for s in ast.walk(v)):
                for t in tgts:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        return "stored"
        elif isinstance(n, ast.Call):
            if _match(idx, qual, n, pair.closer_quals, pair.closer_chains):
                continue
            if any(q in extra
                   for q in idx.byline.get(qual, {}).get(n.lineno, ())):
                continue
            args = list(n.args) + [kw.value for kw in n.keywords]
            if any(isinstance(a, ast.Name) and a.id == name
                   for a in args):
                return "passed on"
        elif isinstance(n, (ast.Yield, ast.YieldFrom)) \
                and getattr(n, "value", None) is not None:
            if any(isinstance(s, ast.Name) and s.id == name
                   for s in ast.walk(n.value)):
                return "yielded"
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and n is not fi.node:
            # captured by a nested closure (the Job worker-thread shape:
            # the closure releases on its own schedule)
            if any(isinstance(s, ast.Name) and s.id == name
                   and isinstance(s.ctx, ast.Load)
                   for s in ast.walk(n)):
                return "captured by a closure"
    return ""


def _acquired_branch_starts(g, stmt, call):
    """Branch-sensitive start set when the opener call sits in an If
    test: `if self._try_reserve(n):` opens the then-branch only,
    `if not self._try_reserve(n):` opens the else/fall-through."""
    bids = g.stmt_blocks.get(id(stmt), ())
    starts = []
    for bid in bids:
        norm = g.norm_succs(bid)
        if len(norm) < 2:
            starts.extend(norm)
            continue
        test = stmt.test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and call in set(ast.walk(test.operand)):
            starts.append(norm[1])
        elif test is call:
            starts.append(norm[0])
        else:
            starts.extend(norm)      # composite test: both branches
    return starts


def _token_guard_skips(g, token: str) -> frozenset:
    """Edges to prune for falsy_ok tokens: at an If testing the bare
    token (`if tok:` / `if not tok:` / `is None` checks), the branch
    where nothing was acquired owes no closer."""
    skips = set()
    for b in g.blocks.values():
        if not isinstance(b.stmt, ast.If):
            continue
        t = b.stmt.test
        unacquired = None       # which norm succ index needs no closer
        if isinstance(t, ast.Name) and t.id == token:
            unacquired = 1
        elif isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not) \
                and isinstance(t.operand, ast.Name) \
                and t.operand.id == token:
            unacquired = 0
        elif isinstance(t, ast.Compare) and len(t.ops) == 1 \
                and isinstance(t.left, ast.Name) and t.left.id == token \
                and isinstance(t.comparators[0], ast.Constant) \
                and t.comparators[0].value is None:
            unacquired = 0 if isinstance(t.ops[0], ast.Is) else 1
        if unacquired is None:
            continue
        norm = g.norm_succs(b.bid)
        if len(norm) >= 2:
            skips.add((b.bid, norm[unacquired]))
    return frozenset(skips)


def _escape_with_skips(g, starts, closing, skips):
    if not skips:
        return g.escape_path(starts, closing)
    seen: set = set()
    work = [(b, 0) for b in starts]
    leak = None
    while work:
        bid, via = work.pop()
        if bid == _cfg.EXIT:
            if via == 0:
                return ("return", 0)
            leak = leak or ("return", via)
            continue
        if bid == _cfg.RAISE:
            leak = leak or ("raise", via)
            continue
        if bid in closing or bid in seen:
            continue
        seen.add(bid)
        blk = g.blocks[bid]
        for nxt, kind in blk.succs:
            if (bid, nxt) in skips:
                continue
            work.append((nxt, via if (kind == "norm" or via)
                         else blk.line))
    return leak


def _closing_bids(g, idx, qual, pair: Pair, extra: set) -> set:
    return {b.bid for b in g.blocks.values()
            if b.stmt is not None
            and _stmt_closes(idx, qual, b.stmt, pair, extra)}


def _class_sibling_closes(fi, proj, idx, pair: Pair) -> bool:
    """Opener in one method, closer in another of the same class — the
    __enter__/__exit__ lifecycle-class shape. The pairing is an object-
    lifetime property the runtime leaktrack sanitizer owns."""
    if not fi.cls:
        return False
    ci = fi.mod.classes.get(fi.cls)
    if ci is None:
        return False
    cterms = _PAIR_CLOSER_TERMS[pair.key]
    for mqual in ci.methods.values():
        if mqual == fi.qual:
            continue
        if not (idx.terms.get(mqual, frozenset()) & cterms):
            continue
        for n in idx.calls.get(mqual, ()):
            if _match(idx, mqual, n, pair.closer_quals,
                      pair.closer_chains):
                return True
    return False


def _check_r022_r024(proj, idx: _Idx) -> list:
    findings = []
    extra_closers = {p.key: _always_closers(proj, idx, p) for p in PAIRS}
    returners: dict = {}          # qual -> pair (functions returning an
    #                               open token)

    def opener_sites(fi):
        """[(pair, call, via_returner)]"""
        out = []
        terms = idx.terms.get(fi.qual, frozenset())
        for n in idx.calls.get(fi.qual, ()):
            hit = False
            for pair in PAIRS:
                if idx.term.get(n) not in _PAIR_OPENER_TERMS[pair.key]:
                    continue
                if _match(idx, fi.qual, n, pair.opener_quals,
                          pair.opener_chains):
                    out.append((pair, n, False))
                    hit = True
                    break
            if hit:
                continue
            for q in idx.byline.get(fi.qual, {}).get(n.lineno, ()):
                rp = returners.get(q)
                if rp is not None:
                    out.append((rp, n, True))
                    break
        del terms
        return out

    def check_site(fi, pair, call, via_returner):
        mod = fi.mod.mod
        stmt = _enclosing_stmt(mod, call)
        if stmt is None or _inside_withitem(mod, call):
            return None
        extra = extra_closers[pair.key]
        # discarded token: the closer can never be handed its token
        if pair.token and isinstance(stmt, ast.Expr):
            closer = pair.closer_quals[0].lstrip(".") \
                if pair.closer_quals else "the closer"
            return Finding(
                "R024", mod.rel, call.lineno,
                f"the {pair.desc} returned here is DISCARDED — "
                f"{closer}() can never be handed its token, so the "
                "resource leaks on every path; bind the result and "
                "close it in a finally (or a with block)")
        if pair.token and isinstance(stmt, ast.Return):
            # `return opener()` — ownership handed straight up, same as
            # bind-then-return: the function is a returner-wrapper and
            # its CALLERS owe the close (R024 at their sites)
            if not via_returner and fi.qual not in returners:
                returners[fi.qual] = pair
            return None
        token = _token_name(stmt) if pair.token else None
        if pair.token and token is None and not isinstance(stmt, ast.If):
            return None          # tuple-unpack / comprehension: punt
        if token is not None:
            how = _token_escapes(fi, proj, idx, stmt, token, pair, extra)
            if how == "returned":
                if not via_returner and fi.qual not in returners:
                    returners[fi.qual] = pair
                return None      # ownership transferred: R024 at callers
            if how:
                return None      # stored/captured/passed: object lifecycle
        if pair.scoped:
            # request-scoped pair: the closer legitimately lives in the
            # request-teardown frame; only path-check a function that
            # pairs opener AND closer itself
            has_closer = any(
                _match(idx, fi.qual, n, pair.closer_quals,
                       pair.closer_chains)
                for n in idx.calls.get(fi.qual, ()))
            if not has_closer:
                return None
        g = _cfg.get(mod, fi.node)
        closing = _closing_bids(g, idx, fi.qual, pair, extra)
        if not closing and _class_sibling_closes(fi, proj, idx, pair):
            return None
        if isinstance(stmt, ast.If):
            starts = _acquired_branch_starts(g, stmt, call)
        else:
            starts = []
            for bid in g.stmt_blocks.get(id(stmt), ()):
                starts.extend(g.norm_succs(bid))
        if not starts:
            return None
        skips = _token_guard_skips(g, token) \
            if (token and pair.falsy_ok) else frozenset()
        esc = _escape_with_skips(g, starts, closing, skips)
        if esc is None:
            return None
        kind, via = esc
        if kind == "raise" or via:
            caught = "propagates" if kind == "raise" else "is caught"
            where = (f"on the exception path out of line {via} "
                     f"(the error {caught} without the closer running)")
        else:
            where = ("on a normal path (early return or fall-through "
                     "skips the closer)")
        rule = "R024" if via_returner else "R022"
        closer = (pair.closer_quals[0].lstrip(".")
                  if pair.closer_quals else "the closer")
        return Finding(
            rule, mod.rel, call.lineno,
            f"{pair.desc} opened here is never closed {where}: "
            f"{closer}() must run on EVERY path — move it to a "
            "finally/with, or suppress with the reason the leak is "
            "impossible")

    # two rounds so wrappers discovered in round 1 get their callers
    # checked in round 2 (the R024 returner propagation)
    reported: set = set()
    for _round in range(2):
        for fi in proj.fns.values():
            for pair, call, via_ret in opener_sites(fi):
                key = (fi.qual, call.lineno, pair.key)
                if key in reported:
                    continue
                f = check_site(fi, pair, call, via_ret)
                if f is not None:
                    reported.add(key)
                    findings.append(f)
        if not returners:
            break
    findings.extend(_check_gauge_series(proj))
    return findings


# ---------------------------------------------------------------------------
# ghost gauge series (the flow-insensitive registry entry)
def _check_gauge_series(proj) -> list:
    findings = []
    for mi in proj.mods:
        mod = mi.mod
        gauges: dict = {}         # var -> assign line
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            chain = _cg._chain(node.value.func)
            if not (chain == "gauge" or chain.endswith(".gauge")):
                continue
            if any(kw.arg == "fn" for kw in node.value.keywords):
                continue          # callback gauge: no set/remove cycle
            for t in node.targets:
                if isinstance(t, ast.Name):
                    gauges[t.id] = node.lineno
        if not gauges:
            continue
        first_labeled_set: dict = {}
        removed: set = set()
        for n in mod.walk():
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in gauges):
                continue
            var = n.func.value.id
            if n.func.attr in ("set", "inc") and n.keywords:
                if var not in first_labeled_set:
                    first_labeled_set[var] = n.lineno
            elif n.func.attr == "remove":
                removed.add(var)
        for var, line in sorted(first_labeled_set.items()):
            if var in removed:
                continue
            findings.append(Finding(
                "R022", mod.rel, line,
                f"per-entity gauge {var!r} registers labeled series "
                "here but nothing in this module ever .remove()s one — "
                "a deleted entity leaves a ghost series on /metrics "
                "forever (the ISSUE-11 class); pair every labeled set "
                "with a remove in the entity's teardown, or suppress "
                "with the reason the label set is bounded"))
    return findings


# ---------------------------------------------------------------------------
# R023: swallowed control-flow exceptions
def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [getattr(e, "id", getattr(e, "attr", ""))
             for e in (t.elts if isinstance(t, ast.Tuple) else [t])]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_types(handler: ast.ExceptHandler) -> set:
    t = handler.type
    if t is None:
        return set()
    return {getattr(e, "id", getattr(e, "attr", ""))
            for e in (t.elts if isinstance(t, ast.Tuple) else [t])}


def _control_raisers(proj, idx: _Idx) -> set:
    """Functions that can (transitively) raise a typed control
    exception — the ONLY places where swallowing one is possible."""
    out: set = set()
    for qual, fi in proj.fns.items():
        for n in proj.fn_nodes(fi):
            if isinstance(n, ast.Raise) and n.exc is not None:
                e = n.exc
                t = _cg._terminal(e.func) if isinstance(e, ast.Call) \
                    else _cg._terminal(e)
                if t in CONTROL_EXCEPTIONS:
                    out.add(qual)
                    break
    changed = True
    guard = 0
    while changed and guard < 20:
        changed = False
        guard += 1
        for qual in proj.fns:
            if qual not in out and idx.callees.get(
                    qual, frozenset()) & out:
                out.add(qual)
                changed = True
    return out


def _control_can_arrive(fi, idx, try_node: ast.Try, raisers: set) -> bool:
    by = idx.byline.get(fi.qual, {})
    for b in try_node.body:
        for n in ast.walk(b):
            if isinstance(n, ast.Raise) and n.exc is not None:
                e = n.exc
                t = _cg._terminal(e.func) if isinstance(e, ast.Call) \
                    else _cg._terminal(e)
                if t in CONTROL_EXCEPTIONS:
                    return True
            elif isinstance(n, ast.Call):
                if any(q in raisers for q in by.get(n.lineno, ())):
                    return True
    return False


def _check_r023(proj, idx: _Idx) -> list:
    findings = []
    raisers = _control_raisers(proj, idx)
    seen: set = set()
    for fi in proj.fns.values():
        rel = fi.mod.mod.rel.replace("\\", "/")
        if not (rel.startswith(_R023_PREFIXES)
                or _cg._is_replay_root(fi, proj)):
            continue
        for n in proj.fn_nodes(fi):
            if not isinstance(n, ast.Try) or not n.handlers:
                continue
            filtered = False
            for h in n.handlers:
                if _handler_types(h) & CONTROL_EXCEPTIONS:
                    filtered = True    # a typed arm upstream sees them
                    continue
                if not _is_broad(h):
                    continue
                if filtered:
                    break
                if any(isinstance(s, ast.Raise)
                       for b in h.body for s in ast.walk(b)):
                    break               # re-raises (possibly filtered)
                if not _control_can_arrive(fi, idx, n, raisers):
                    break    # nothing below raises one: a loop
                    #          swallowing socket errors owes nothing
                key = (fi.mod.mod.rel, h.lineno)
                if key in seen:
                    break
                seen.add(key)
                findings.append(Finding(
                    "R023", fi.mod.mod.rel, h.lineno,
                    f"broad except on a dispatch/serving/replay path in "
                    f"{fi.qual}() swallows the typed control exceptions "
                    "(RateLimited/QuotaExceeded/DeadlineExceeded/"
                    "EpochChanged/DivergenceError) that its try body "
                    "can raise and that must propagate to produce "
                    "their status codes — re-raise them "
                    "(`if isinstance(e, (...)): raise`), add typed "
                    "arms above, or suppress with the reason the "
                    "swallow is intentional"))
                break
    return findings


# ---------------------------------------------------------------------------
# R025: export contract for scoring programs
_R025_ROOT_NAMES = {"_score_with_params", "_score_matrix"}
_FORBIDDEN_CALLBACKS = ("pure_callback", "io_callback")
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _module_device_consts(mi) -> dict:
    """Module-level names bound to device arrays (jnp.* constructions /
    device_put) — baked into any program whose closure captures them."""
    out: dict = {}
    for node in mi.mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        is_dev = False
        for sub in ast.walk(v):
            if isinstance(sub, ast.Call):
                chain = _cg._chain(sub.func)
                if chain.startswith(("jnp.", "jax.numpy.")) \
                        or chain.endswith("device_put"):
                    is_dev = True
                    break
        if is_dev:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.lineno
    return out


def _static_decorator_args(fn_node) -> set:
    """Arg names pinned static by a jit decorator (static_argnames, or
    static_argnums mapped positionally) — concrete at trace time."""
    out: set = set()
    pos = [a.arg for a in fn_node.args.posonlyargs + fn_node.args.args]
    for dec in fn_node.decorator_list:
        for sub in ast.walk(dec):
            if not isinstance(sub, ast.keyword):
                continue
            if sub.arg == "static_argnames":
                for c in ast.walk(sub.value):
                    if isinstance(c, ast.Constant) \
                            and isinstance(c.value, str):
                        out.add(c.value)
            elif sub.arg == "static_argnums":
                for c in ast.walk(sub.value):
                    if isinstance(c, ast.Constant) \
                            and isinstance(c.value, int) \
                            and 0 <= c.value < len(pos):
                        out.add(pos[c.value])
    return out


def _static_config_test(test) -> bool:
    """`if link == "logit":` / `if dist in ("poisson", "gamma"):` —
    string-constant dispatch on a config argument, concrete under
    trace (a tracer never equals a string)."""
    if not isinstance(test, ast.Compare):
        return False
    consts = []
    for comp in test.comparators:
        for c in ast.walk(comp):
            if isinstance(c, ast.Constant):
                consts.append(c.value)
            elif not isinstance(c, (ast.Tuple, ast.List, ast.Set,
                                    ast.expr_context)):
                return False
    return bool(consts) and all(isinstance(v, str) for v in consts)


def _r025_scan(fn_node, mi, rel: str, qual: str, parents: dict,
               seen: set) -> list:
    findings = []
    dev_consts = _module_device_consts(mi)
    params = {a.arg for a in fn_node.args.args
              + fn_node.args.posonlyargs + fn_node.args.kwonlyargs} \
        - {"self", "cls"} - _static_decorator_args(fn_node)
    nodes = list(ast.walk(fn_node))
    # taint: params plus locals assigned from tainted expressions
    tainted = set(params)
    assigns = [n for n in nodes if isinstance(n, ast.Assign)]

    def shielded(name_node) -> bool:
        """x.shape / x.ndim / len(x): static under trace."""
        p = parents.get(name_node)
        while p is not None:
            if isinstance(p, ast.Attribute) and p.attr in _STATIC_ATTRS:
                return True
            if isinstance(p, ast.Call) and _cg._terminal(p.func) == "len":
                return True
            if isinstance(p, ast.stmt):
                break
            p = parents.get(p)
        return False

    def expr_tainted(e) -> bool:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in tainted and not shielded(sub):
                return True
        return False

    for _ in range(3):
        changed = False
        for a in assigns:
            if expr_tainted(a.value):
                for t in a.targets:
                    if isinstance(t, ast.Name) and t.id not in tainted:
                        tainted.add(t.id)
                        changed = True
        if not changed:
            break

    def emit(line, msg):
        key = (rel, line)
        if key not in seen:
            seen.add(key)
            findings.append(Finding("R025", rel, line, msg))

    for n in nodes:
        if isinstance(n, ast.Call):
            chain = _cg._chain(n.func)
            term = _cg._terminal(n.func)
            if term in _FORBIDDEN_CALLBACKS or \
                    chain.endswith(("debug.callback", "debug.print")):
                emit(n.lineno,
                     f"{chain or term}() inside the {qual} scoring "
                     "program: a host callback cannot ride a "
                     "serialized/exported artifact — compute it outside "
                     "the traced body and pass the result as an "
                     "argument")
            elif term in ("float", "int", "bool") and n.args \
                    and expr_tainted(n.args[0]):
                emit(n.lineno,
                     f"{term}() concretizes a traced value in {qual}: "
                     "under jax.export this either fails to trace or "
                     "bakes one example's value into the artifact — "
                     "keep the computation in jnp ops")
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in dev_consts:
            emit(n.lineno,
                 f"module-level device array {n.id!r} (defined at "
                 f"{rel}:{dev_consts[n.id]}) captured by the {qual} "
                 "scoring program: the constant is baked into the "
                 "compiled artifact instead of arriving as a parameter "
                 "— thread it through the params pytree")
        elif isinstance(n, (ast.If, ast.While)):
            t = n.test
            if isinstance(t, ast.Compare) and len(t.ops) == 1 \
                    and isinstance(t.ops[0], (ast.Is, ast.IsNot)):
                continue          # `x is None`: concrete under trace
            if _static_config_test(t):
                continue          # string-constant config dispatch
            if expr_tainted(t):
                emit(n.lineno,
                     f"Python branch on a traced value in {qual}: the "
                     "branch is resolved ONCE at trace time (or fails "
                     "under jax.export) — use jnp.where / lax.cond")
    return findings


def _check_r025(proj) -> list:
    findings = []
    seen: set = set()
    # roots: the _score_with_params family, closed over the callgraph
    work = [fi.qual for fi in proj.fns.values()
            if getattr(fi.node, "name", "") in _R025_ROOT_NAMES]
    reach: set = set()
    while work:
        q = work.pop()
        if q in reach:
            continue
        reach.add(q)
        fi = proj.fns.get(q)
        if fi is None:
            continue
        for callee, _ln, _h, _b, _s in fi.calls:
            if callee not in reach:
                work.append(callee)
    for q in sorted(reach):
        fi = proj.fns.get(q)
        if fi is None:
            continue
        parents = fi.mod.mod.parents()
        findings.extend(_r025_scan(fi.node, fi.mod, fi.mod.mod.rel,
                                   getattr(fi.node, "name", q), parents,
                                   seen))
    # the scorer_cache _build trace closures (nested defs are not
    # project functions; they ARE the program that gets exported)
    for fi in proj.fns.values():
        if getattr(fi.node, "name", "") != "_build" \
                or "scorer_cache" not in fi.mod.mod.rel:
            continue
        parents = fi.mod.mod.parents()
        for n in proj.fn_nodes(fi):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fi.node \
                    and n.name.startswith("_score"):
                findings.extend(_r025_scan(
                    n, fi.mod, fi.mod.mod.rel,
                    f"_build.{n.name}", parents, seen))
    return findings


# ---------------------------------------------------------------------------
def check_project(proj, mods: list, timings: dict = None) -> list:
    """Run R022-R025 on the shared project index — called from
    callgraph.check after effects.check_project, same single-index
    discipline."""
    import time as _time
    t0 = _time.perf_counter()
    idx = _Idx(proj)
    if timings is not None:
        timings["lifecycle:index"] = timings.get(
            "lifecycle:index", 0.0) + (_time.perf_counter() - t0)
    findings = []
    for rule, fn in (("R022+R024", lambda: _check_r022_r024(proj, idx)),
                     ("R023", lambda: _check_r023(proj, idx)),
                     ("R025", lambda: _check_r025(proj))):
        t0 = _time.perf_counter()
        findings.extend(fn())
        if timings is not None:
            timings[rule] = timings.get(rule, 0.0) + \
                (_time.perf_counter() - t0)
    return findings
