"""R012 — logging discipline: no print(), no bare logging.getLogger().

The structured logging pillar (utils/log.py) only works if records go
THROUGH it: a `print(...)` bypasses the ring, the durable JSONL segments
under the ice root, the trace/span correlation, and the ERROR keep-rule
— on a worker it lands in a container stdout nobody aggregates, which is
exactly how the rendezvous-deadlock class stayed invisible. A bare
`logging.getLogger(...)` is subtler: the returned logger has none of the
structured handlers, so its records are second-class citizens that
GET /3/Logs cannot see.

R012 therefore flags, package-wide:
  * `print(...)` calls — use `h2o3_tpu.utils.log` (info/warn/err/debug
    or `get_logger("subsystem")`);
  * `logging.getLogger(...)` calls — use `utils.log.get_logger(name)`,
    which returns a child of the structured root.

Exemptions: `__main__.py` CLI entry modules (stdout IS their interface
— the analyzer's own finding report, the REPL banner), and test files
via the engine's TEST_RELAXED profile. Anything else that legitimately
prints (a CLI fallback inside a library module) carries an inline
`# h2o3-ok: R012 reason` waiver.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R012"}


def _is_cli_module(rel: str) -> bool:
    r = rel.replace("\\", "/")
    return r.endswith("/__main__.py") or r == "__main__.py"


def check(mod: Module) -> list:
    if _is_cli_module(mod.rel):
        return []
    findings = []
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "print":
            findings.append(Finding(
                "R012", mod.rel, node.lineno,
                "print() bypasses the structured logger (no ring, no "
                "durable JSONL, no trace correlation, invisible to "
                "GET /3/Logs) — use h2o3_tpu.utils.log"))
        elif isinstance(fn, ast.Attribute) and fn.attr == "getLogger" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "logging":
            findings.append(Finding(
                "R012", mod.rel, node.lineno,
                "bare logging.getLogger() yields a logger without the "
                "structured handlers — use "
                "h2o3_tpu.utils.log.get_logger(name)"))
    return findings


check.RULES = RULES
