"""R011 span-name drift + the generated span census.

The span timeline (`h2o3_tpu/obs/timeline.py`) is the trace viewer's
vocabulary: GET /3/Trace/{id}, the flight-recorder search and the SLO
alert spans all join on span NAMES. The same failure modes R005 guards
for metrics apply: a name spelled two ways splits one logical phase into
two rows of every trace view, a second declaration site drifts silently,
and a computed name cannot be censused and usually means unbounded
cardinality in the bounded ring.

R011 therefore enforces, package-wide:
  * every `timeline.span("...")` name is DECLARED at exactly one call
    site (pass-through wrappers that forward a name parameter are
    exempt, like R005's registry helpers);
  * declarations use literal names — a plain string, or a conditional
    expression whose arms are both literals (the scorer's
    `"scorer.warm_hit" if warm else "scorer.compile"` shape, censused as
    two names);
  * the census of what passed is committed as `h2o3_tpu/obs/SPANS.md`
    (`python -m h2o3_tpu.analysis --write-census`) so a span rename
    shows up in review as a census diff, not as a silently broken trace
    search.

Intentional same-name sites (one logical stage, two engines) carry an
inline `# h2o3-ok: R011 <why>` waiver. Tests are exempt wholesale
(TEST_RELAXED): throwaway fixture spans are the point of a test.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module
from h2o3_tpu.analysis.rules_metrics import _enclosing_params

RULES = {"R011"}

# receivers that denote the span timeline (`timeline.span(...)`,
# `_tl.span(...)`); bare-name calls additionally require the module to
# have imported `span` from obs.timeline (see _span_aliases)
_RECEIVER_ALIASES = {"timeline", "_timeline", "_tl", "_obs_tl"}


def _span_aliases(mod: Module) -> set:
    """Local names bound to obs.timeline's span() by import."""
    out = set()
    for node in mod.walk():
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("obs.timeline"):
            out.update(a.asname or a.name for a in node.names
                       if a.name == "span")
    return out


def _is_span_call(node: ast.Call, local_aliases: set) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in local_aliases
    if isinstance(fn, ast.Attribute) and fn.attr == "span" \
            and isinstance(fn.value, ast.Name):
        return fn.value.id in _RECEIVER_ALIASES
    return False


def _literal_names(first: ast.AST):
    """The span name(s) a literal first argument declares: a constant
    string, or an IfExp whose two arms are both constant strings.
    Returns None when the argument is not literal."""
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return [first.value]
    if isinstance(first, ast.IfExp) \
            and isinstance(first.body, ast.Constant) \
            and isinstance(first.body.value, str) \
            and isinstance(first.orelse, ast.Constant) \
            and isinstance(first.orelse.value, str):
        return [first.body.value, first.orelse.value]
    return None


def _wrapper_names(mod: Module, aliases: set) -> set:
    """Module-local functions that forward a name parameter into span()
    (mrtask._traced_dispatch): the literal names live at THEIR call
    sites, so those calls are censused like direct span() calls."""
    out = set()
    for fn in mod.walk():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = fn.args
        params = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and node.args \
                    and _is_span_call(node, aliases) \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in params:
                out.add(fn.name)
                break
    return out


def collect(mods: list):
    """(declarations, findings): declarations is {name: [(file, line)]}."""
    decls: dict = {}
    findings: list = []
    for mod in mods:
        rel = mod.rel.replace("\\", "/")
        if rel.endswith("obs/timeline.py"):
            continue   # the span() definition itself (begin() forwards)
        aliases = _span_aliases(mod)
        wrappers = _wrapper_names(mod, aliases)
        parents = None
        for node in mod.walk():
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not (_is_span_call(node, aliases)
                    or (isinstance(node.func, ast.Name)
                        and node.func.id in wrappers)):
                continue
            names = _literal_names(node.args[0])
            if names is not None:
                for name in names:
                    decls.setdefault(name, []).append((mod.rel,
                                                       node.lineno))
                continue
            if parents is None:
                parents = mod.parents()
            first = node.args[0]
            if isinstance(first, ast.Name) and \
                    first.id in _enclosing_params(node, parents):
                continue   # pass-through wrapper (mrtask._traced_dispatch)
            findings.append(Finding(
                "R011", mod.rel, node.lineno,
                "span() with a non-literal name: cannot be censused and "
                "risks unbounded span-name cardinality in the bounded "
                "timeline ring — declare the name as a string literal "
                "(attrs carry the variable part)"))
    return decls, findings


def check(mods: list) -> list:
    decls, findings = collect(mods)
    for name, sites in sorted(decls.items()):
        if len(sites) > 1:
            first = sites[0]
            for file, line in sites[1:]:
                findings.append(Finding(
                    "R011", file, line,
                    f"span name {name!r} is declared at more than one "
                    f"call site (first at {first[0]}:{first[1]}): "
                    "duplicate declarations drift apart and double-count "
                    "phases in trace views — declare once, or waive with "
                    "a reason if the stage genuinely has two engines"))
    return findings


check.RULES = RULES


def census_markdown(mods: list) -> str:
    """The committed h2o3_tpu/obs/SPANS.md body."""
    decls, _ = collect(mods)
    lines = [
        "# Span census — generated, do not edit",
        "",
        "Generated by `python -m h2o3_tpu.analysis --write-census`; the",
        "R011 rule keeps this file honest (literal names, one declaration",
        "site per name). Regenerate after adding or renaming a span.",
        "",
        "| span | declared at |",
        "|---|---|",
    ]
    for name, sites in sorted(decls.items()):
        # distinct files only, no line numbers: line-shift edits must
        # leave the committed census byte-identical
        where = ", ".join(sorted({f for f, _ln in sites}))
        lines.append(f"| `{name}` | {where} |")
    lines.append("")
    lines.append(f"{len(decls)} span names.")
    return "\n".join(lines) + "\n"
