"""R005 metric-name drift + the generated registry census.

The obs registry (`h2o3_tpu/obs/metrics.py`) is get-or-make: registering
"h2o3_scorer_cache_hits_total" twice silently returns the first metric,
so a typo'd duplicate ("..._hit_total") splits one logical series into
two, and a second registration site with a different help string wins or
loses by import order. Prometheus additionally requires a consistent
label set per metric name — emitting `inc(reason=...)` at one site and
`inc()` at another produces series that cannot be aggregated.

R005 therefore enforces, package-wide:
  * every `h2o3_*` metric name is DECLARED at exactly one call site
    (counter()/gauge()/histogram() with a literal name);
  * declarations use literal names (a computed name cannot be censused
    and usually means unbounded cardinality);
  * every emission site (`.inc/.observe/.set/.time`) for one metric uses
    the same label-key set.

The census of what passed is written to `h2o3_tpu/obs/METRICS.md` by
`python -m h2o3_tpu.analysis --write-census` and committed, so a metrics
rename shows up in review as a diff to the census, not as a silent
dashboard break.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R005"}

_DECL_FNS = {"counter", "gauge", "histogram"}
_EMIT_FNS = {"inc", "observe", "set", "time"}
_PREFIX = "h2o3_"
# receivers that denote the obs registry (`_om.counter(...)` etc.) — a
# same-named method on anything else (np.histogram!) is not a declaration
_REGISTRY_ALIASES = {"_om", "om", "_m", "_obs_m", "_obs_metrics",
                     "metrics", "_metrics", "REGISTRY"}


def _terminal(fn: ast.AST):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _enclosing_params(node: ast.AST, parents: dict) -> set:
    out: set = set()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = cur.args
            out.update(x.arg for x in a.posonlyargs + a.args + a.kwonlyargs)
        cur = parents.get(cur)
    return out


def _enclosing_class(node: ast.AST, parents: dict) -> str:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = parents.get(cur)
    return ""


def _registry_names(mod: Module) -> set:
    """Declaration helpers this module imported from the obs registry
    (`from h2o3_tpu.obs.metrics import counter, histogram`)."""
    out = set()
    for node in mod.walk():
        if isinstance(node, ast.ImportFrom) and node.module \
                and "obs" in node.module:
            out.update(a.asname or a.name for a in node.names
                       if a.name in _DECL_FNS)
    return out


def _is_registry_call(node: ast.Call, local_decl_names: set) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in local_decl_names
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return fn.value.id in _REGISTRY_ALIASES
    return False


def collect(mods: list):
    """(declarations, findings): declarations is
    {name: [{kind, help, file, line, var, labels:set}]}"""
    decls: dict = {}
    findings: list = []
    for mod in mods:
        parents = mod.parents()
        var_to_name: dict = {}    # module-level VAR -> metric name
        local_decl = _registry_names(mod)
        if mod.rel.replace("\\", "/").endswith("obs/metrics.py"):
            local_decl = set(_DECL_FNS)   # the registry's own module
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            kind = _terminal(node.func)
            if kind not in _DECL_FNS or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                name = first.value
                if not name.startswith(_PREFIX):
                    continue
                help_arg = ""
                if len(node.args) > 1 and \
                        isinstance(node.args[1], ast.Constant):
                    help_arg = str(node.args[1].value)
                for kw in node.keywords:
                    if kw.arg == "help" and isinstance(kw.value,
                                                      ast.Constant):
                        help_arg = str(kw.value.value)
                entry = {"kind": kind, "help": help_arg, "file": mod.rel,
                         "line": node.lineno, "labels": set()}
                decls.setdefault(name, []).append(entry)
                parent = parents.get(node)
                if isinstance(parent, ast.Assign):
                    for t in parent.targets:
                        if isinstance(t, ast.Name):
                            var_to_name[t.id] = name
                        elif isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id in ("self", "cls"):
                            # instance-attribute metric (self._burn = …),
                            # scoped by class so two classes in one module
                            # can't cross-wire each other's attrs
                            cls = _enclosing_class(parent, parents)
                            var_to_name[f"{cls}.{t.attr}"] = name
            elif not _is_registry_call(node, local_decl):
                pass   # np.histogram(...) and friends — not a metric
            elif isinstance(first, ast.Name) and \
                    first.id in _enclosing_params(node, parents):
                pass   # pass-through wrapper (the registry's own helpers)
            else:
                findings.append(Finding(
                    "R005", mod.rel, node.lineno,
                    f"{kind}() with a non-literal metric name: cannot be "
                    "censused and risks unbounded series cardinality — "
                    "declare the name as a string literal"))
        # emission label sets for module-level metric vars
        for node in mod.walk():
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _EMIT_FNS:
                continue
            recv = node.func.value
            key = None
            if isinstance(recv, ast.Name) and recv.id in var_to_name:
                key = recv.id
            elif isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id in ("self", "cls"):
                k = f"{_enclosing_class(node, parents)}.{recv.attr}"
                if k in var_to_name:
                    key = k
            if key is None:
                continue
            name = var_to_name[key]
            # `exemplar` is the reserved OpenMetrics exemplar kwarg on
            # HISTOGRAM observe/time only — there it is not a label, and
            # passing it at one site but not another must not split the
            # series. On inc()/set() no such parameter exists: the kwarg
            # would land in **labels and mint a series per trace id, so
            # it must stay visible to the cardinality check.
            labels = frozenset(
                kw.arg for kw in node.keywords
                if kw.arg is not None
                and not (kw.arg == "exemplar"
                         and node.func.attr in ("observe", "time")))
            for entry in decls.get(name, []):
                if entry["file"] == mod.rel:
                    entry.setdefault("emissions", []).append(
                        (mod.rel, node.lineno, labels))
    return decls, findings


def check(mods: list) -> list:
    decls, findings = collect(mods)
    for name, entries in sorted(decls.items()):
        if len(entries) > 1:
            first = entries[0]
            for extra in entries[1:]:
                findings.append(Finding(
                    "R005", extra["file"], extra["line"],
                    f"metric {name!r} is declared more than once (first "
                    f"at {first['file']}:{first['line']}): duplicate "
                    "registrations drift apart on help text and typos — "
                    "declare once, import the object"))
        emis = [e for entry in entries
                for e in entry.get("emissions", [])]
        label_sets = {lbls for _, _, lbls in emis}
        if len(label_sets) > 1:
            # report at the minority sites (most emissions define the norm)
            from collections import Counter
            common = Counter(l for _, _, l in emis).most_common(1)[0][0]
            for file, line, lbls in emis:
                if lbls != common:
                    findings.append(Finding(
                        "R005", file, line,
                        f"metric {name!r} emitted with labels "
                        f"{sorted(lbls) or '(none)'} here but "
                        f"{sorted(common) or '(none)'} elsewhere: "
                        "inconsistent label sets split the series — "
                        "emit one label schema per metric"))
    return findings


check.RULES = RULES


def census_markdown(mods: list) -> str:
    """The committed h2o3_tpu/obs/METRICS.md body."""
    decls, _ = collect(mods)
    lines = [
        "# Metric census — generated, do not edit",
        "",
        "Generated by `python -m h2o3_tpu.analysis --write-census`; the",
        "R005 rule keeps this file honest (one declaration per name,",
        "consistent label sets). Regenerate after adding or renaming a",
        "metric.",
        "",
        "| metric | kind | labels | declared at | help |",
        "|---|---|---|---|---|",
    ]
    for name, entries in sorted(decls.items()):
        e = entries[0]
        labels = sorted({lb for en in entries
                         for _, _, ls in en.get("emissions", [])
                         for lb in ls})
        # file only, no line: a pure line-shift edit upstream of a
        # declaration must leave the committed census byte-identical
        lines.append(
            f"| `{name}` | {e['kind']} | "
            f"{', '.join(f'`{l}`' for l in labels) or '—'} | "
            f"{e['file']} | {e['help'] or '—'} |")
    lines.append("")
    lines.append(f"{len(decls)} metrics.")
    return "\n".join(lines) + "\n"
