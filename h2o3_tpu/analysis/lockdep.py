"""Runtime lockdep — Linux-lockdep-style lock-order validation.

The static pass (callgraph.py R007) proves no lock-order cycle is
WRITTEN; this sanitizer proves none is EXECUTED — including orders the
static resolver can't see (callbacks, getattr dispatch, locks handed
through data structures). The idea is Linux's lockdep: every lock gets a
CLASS (a name), each thread tracks the stack of classes it holds, and
acquiring B while holding A records the order edge A→B in one global
graph. The first acquisition that would close a cycle (B→…→A already
recorded) is reported at the acquisition that PROVES the inversion — no
actual deadlock, no special interleaving needed: if thread 1 ever did
A→B and thread 2 ever does B→A, the second order is caught even when
the threads never overlap.

Usage: subsystem locks are created through `make_lock("name")` /
`make_rlock("name")` instead of `threading.Lock()`. Disabled (the
default), the wrapper delegates straight to the underlying lock — one
flag check of overhead. Enabled (env `H2O3_LOCKDEP=1|raise`, or
`H2O3_LOCKDEP=log` to count without raising, or `enable()` from code),
every acquisition is checked against the global order graph BEFORE
blocking, so an inversion raises `LockOrderInversion` instead of
deadlocking under the unlucky schedule.

Instrumented lock classes (see the callers): `dkv`, `scorer_cache`,
`scorer_cache.tokens`, `scorer_cache.broken`, `scorer_cache.build`,
`microbatch`, `metrics.registry`, `timeline.ring`, `timeline.trace`,
`replay_channel`, and the DKV chunk pager's `tiering.io` (per-chunk
transfer lock, one class for every instance) and `tiering.residency`
(pager maps/accounting) — ordered io → residency, neither ever nested
under `dkv`. Per-metric series locks stay plain `threading.Lock` — they
are leaf locks on the hottest counter path and never nest.

Manual `.acquire()`/`.release()` calls on a DepLock are instrumented
exactly like `with`-blocks (acquire/release ARE the with-protocol).
A non-blocking try-acquire (`acquire(blocking=False)`) records the lock
as held but adds NO order edge and is never reported as an inversion —
a trylock cannot wait, so it cannot complete a deadlock cycle (Linux
lockdep's trylock rule). Bounded acquires (`timeout=`) still record
order: timing out rescues the schedule but the ordering bug remains.

Metrics: `h2o3_lockdep_edges_total` (distinct order edges recorded),
`h2o3_lockdep_inversions_total` (cycles detected). Both are declared
lazily so this module can be imported by the metrics registry itself
without an import cycle.
"""

from __future__ import annotations

import os
import threading

# explicit "off" spellings — H2O3_LOCKDEP=0 must DISABLE, not enable
_OFF_VALUES = ("", "0", "false", "off", "no", "none")


def _mode_from_env(value: str) -> str:
    v = (value or "").strip().lower()
    if v in _OFF_VALUES:
        return ""
    return "log" if v == "log" else "raise"


class LockOrderInversion(RuntimeError):
    """Acquiring this lock would close a cycle in the global lock-order
    graph — the AB/BA deadlock schedule exists even if this exact run
    never interleaves into it."""


def env_mode() -> str:
    """The H2O3_LOCKDEP mode from the environment ("" disabled / "log" /
    "raise") — the variable's one declaration site; sanitizers
    install_from_env() reads it through this helper too."""
    from h2o3_tpu.utils.env import env_str
    return _mode_from_env(env_str("H2O3_LOCKDEP", ""))


class _State:
    def __init__(self):
        self.mode = env_mode()

    @property
    def enabled(self) -> bool:
        return bool(self.mode)


_STATE = _State()
_TLS = threading.local()

# global order graph: _SUCC[a] = {b: "file:line of the first a→b"}
_GRAPH_LOCK = threading.Lock()
_SUCC: dict = {}
_EDGE_COUNT = 0
_INVERSION_COUNT = 0


def enable(mode: str = "raise"):
    """Turn the checker on process-wide ('raise' or 'log')."""
    if mode not in ("raise", "log"):
        raise ValueError(f"lockdep mode {mode!r} (want 'raise' or 'log')")
    _STATE.mode = mode
    try:
        _metrics()      # counters visible at zero before the first edge
    except ImportError:     # metrics registry mid-import: stays lazy
        pass


def disable():
    _STATE.mode = ""


def enabled() -> bool:
    return _STATE.enabled


def reset():
    """Drop the recorded order graph (test isolation)."""
    global _SUCC, _EDGE_COUNT, _INVERSION_COUNT
    with _GRAPH_LOCK:
        _SUCC = {}
        _EDGE_COUNT = 0
        _INVERSION_COUNT = 0


def edges() -> dict:
    """{(a, b): first_site} snapshot of the recorded order graph."""
    with _GRAPH_LOCK:
        return {(a, b): site for a, nxt in _SUCC.items()
                for b, site in nxt.items()}


def _metrics():
    """Lazy counter lookup: metrics.py itself creates its registry lock
    through make_lock, so importing it at module top would cycle."""
    from h2o3_tpu.obs import metrics as _om
    return (_om.counter("h2o3_lockdep_edges_total",
                        "distinct lock-order edges recorded by the "
                        "runtime lockdep sanitizer (H2O3_LOCKDEP)"),
            _om.counter("h2o3_lockdep_inversions_total",
                        "lock-order inversions (cycles) detected by the "
                        "runtime lockdep sanitizer"))


def _held() -> list:
    st = getattr(_TLS, "held", None)
    if st is None:
        st = _TLS.held = []
    return st


def _busy() -> bool:
    return getattr(_TLS, "busy", False)


def _path(src: str, dst: str) -> list:
    """Shortest recorded path src→…→dst, as [(a, b, site), ...], or []."""
    prev: dict = {src: None}
    queue = [src]
    while queue:
        cur = queue.pop(0)
        for nxt in sorted(_SUCC.get(cur, ())):
            if nxt not in prev:
                prev[nxt] = cur
                if nxt == dst:
                    queue = []
                    break
                queue.append(nxt)
    if dst not in prev:
        return []
    hops = []
    cur = dst
    while prev[cur] is not None:
        hops.append((prev[cur], cur, _SUCC[prev[cur]][cur]))
        cur = prev[cur]
    hops.reverse()
    return hops


def _caller_site() -> str:
    import traceback
    for frame in reversed(traceback.extract_stack(limit=16)):
        if os.path.basename(frame.filename) != "lockdep.py":
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _note_acquire(name: str, trylock: bool = False):
    """Record intent to acquire `name`; raises on inversion BEFORE the
    underlying acquire, so the error surfaces instead of the deadlock.
    `trylock` (a non-blocking acquire) records held-ness only: it cannot
    wait, so it adds no order edge and never proves an inversion."""
    global _EDGE_COUNT, _INVERSION_COUNT
    held = _held()
    if trylock or name in held:  # trylock / re-entry: no new order edge
        held.append(name)
        return
    if not held:
        held.append(name)
        return
    _TLS.busy = True            # counters below take metric locks: the
    try:                        # instrumentation must not instrument itself
        site = None             # stack walk only when an edge is NEW —
        inversion = None        # steady state stays a dict lookup
        new_edges = 0
        with _GRAPH_LOCK:
            for h in held:
                if h == name:
                    continue
                if name not in _SUCC.get(h, ()):
                    if site is None:
                        site = _caller_site()
                    back = _path(name, h)
                    if back:
                        _INVERSION_COUNT += 1
                        inversion = (h, back)
                        break
                    _SUCC.setdefault(h, {})[name] = site
                    _EDGE_COUNT += 1
                    new_edges += 1
        try:
            e, i = _metrics()
            if new_edges:
                e.inc(new_edges)
            if inversion is not None:
                i.inc()
        except Exception:   # noqa: BLE001 — metrics must not break locking
            pass
        if inversion is not None and _STATE.mode == "raise":
            h, back = inversion
            chain = " ; ".join(f"{a}→{b} (first seen {s})"
                               for a, b, s in back)
            raise LockOrderInversion(
                f"lock-order inversion: acquiring {name!r} while holding "
                f"{h!r} at {site}, but the opposite order is already "
                f"recorded: {chain} — two threads running these paths "
                "concurrently deadlock")
    finally:
        _TLS.busy = False
    held.append(name)


def _note_release(name: str):
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class DepLock:
    """Drop-in threading.Lock/RLock with lockdep instrumentation. The
    `name` is the lock CLASS: every instance created with the same name
    shares an identity in the order graph (all per-key build locks are
    one class), matching how the static rules key locks by attribute."""

    __slots__ = ("name", "_reentrant", "_lock")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _STATE.enabled and not _busy():
            _note_acquire(self.name, trylock=not blocking)
            ok = self._lock.acquire(blocking, timeout)
            if not ok:
                _note_release(self.name)
            return ok
        return self._lock.acquire(blocking, timeout)

    def release(self):
        self._lock.release()
        if _STATE.enabled and not _busy():
            _note_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        inner = getattr(self._lock, "locked", None)
        return inner() if inner is not None else False

    def __repr__(self):
        kind = "RLock" if self._reentrant else "Lock"
        return f"<DepLock {self.name!r} ({kind})>"


def make_lock(name: str) -> DepLock:
    """A named, lockdep-instrumented mutual-exclusion lock."""
    return DepLock(name, reentrant=False)


def make_rlock(name: str) -> DepLock:
    """A named, lockdep-instrumented re-entrant lock."""
    return DepLock(name, reentrant=True)


def counts() -> dict:
    with _GRAPH_LOCK:
        return {"edges": _EDGE_COUNT, "inversions": _INVERSION_COUNT}
