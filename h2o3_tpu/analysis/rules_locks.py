"""R003 lock-discipline: attributes mutated both under and outside a lock.

Derived from the ISSUE 2 replay-channel bug class: Broadcaster state
(`_owed`, `_bufs`) touched from paths that sometimes held `self._lock` and
sometimes didn't froze /3/Timeline scrapes until the accounting was made
lock-consistent. The rule:

  * a class "declares" a lock when any method assigns `self.X =
    threading.Lock()/RLock()/Condition()` (aliased imports count via the
    terminal callee name);
  * every mutation of `self.Y` in a method body is classified as
    locked (lexically inside `with self.X:` for any declared lock) or
    bare;
  * an attribute with BOTH locked and bare mutation sites is reported at
    each bare site. `__init__` is construction — nothing else can hold a
    reference yet — so its mutations are exempt.

Mutation = assignment/augassign to `self.Y` or `self.Y[...]`, or a call
of a known mutating method (`append`, `pop`, `update`, …) on `self.Y`.
A bare site that is safe by construction (e.g. a helper only ever called
with the lock held) carries an inline `# h2o3-ok: R003 <why>` waiver —
the waiver IS the documentation the next reader needs.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R003"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore",
               # analysis.lockdep instrumented wrappers count as locks
               "make_lock", "make_rlock", "DepLock"}
_MUTATORS = {"append", "extend", "insert", "add", "remove", "discard",
             "pop", "popitem", "clear", "update", "setdefault",
             "move_to_end", "appendleft", "popleft", "extendleft",
             "sort", "reverse"}


def _self_attr(node: ast.AST):
    """'Y' when node is `self.Y`, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _self_attr_base(node: ast.AST):
    """'Y' for `self.Y`, `self.Y[...]`, `self.Y[...][...]` targets."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return _self_attr(node)


def _lock_attrs(cls: ast.ClassDef) -> set:
    locks = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = node.value.func
            name = callee.attr if isinstance(callee, ast.Attribute) \
                else (callee.id if isinstance(callee, ast.Name) else None)
            if name in _LOCK_CTORS:
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        locks.add(a)
    return locks


def _methods(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _mutations(method: ast.AST, lock_attrs: set):
    """Yield (attr, lineno, locked) for every self-attribute mutation,
    where locked means lexically inside `with self.<lock>:`."""

    def visit(node, locked):
        if isinstance(node, ast.With):
            holds = locked or any(
                _self_attr(item.context_expr) in lock_attrs
                for item in node.items)
            for item in node.items:
                yield from visit(item.context_expr, locked)
            for child in node.body:
                yield from visit(child, holds)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # nested scope: analyzed as part of its own method
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for e in elts:
                    a = _self_attr_base(e)
                    if a:
                        yield a, node.lineno, locked
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            a = _self_attr_base(node.func.value)
            if a:
                yield a, node.lineno, locked
        for child in ast.iter_child_nodes(node):
            yield from visit(child, locked)

    # start from the method's statements: visit() early-returns on nested
    # function nodes, and the method node itself is one
    for child in method.body:
        yield from visit(child, False)


def check(mod: Module) -> list:
    findings: list = []
    for cls in mod.walk():
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        sites: dict = {}      # attr -> [(lineno, locked, method)]
        for method in _methods(cls):
            if method.name == "__init__":
                continue
            for attr, lineno, locked in _mutations(method, locks):
                if attr in locks:
                    continue
                sites.setdefault(attr, []).append(
                    (lineno, locked, method.name))
        for attr, hits in sites.items():
            if not any(locked for _, locked, _ in hits):
                continue
            for lineno, locked, mname in hits:
                if locked:
                    continue
                findings.append(Finding(
                    "R003", mod.rel, lineno,
                    f"{cls.name}.{attr} is mutated under "
                    f"`with self.<lock>` elsewhere but bare in "
                    f"{mname}(): either take the lock here or waive "
                    "with `# h2o3-ok: R003 <why it is safe>`"))
    return findings


check.RULES = RULES
