"""R017 env-var config census + the generated ENV.md.

The config surface is 60+ `H2O3_*` environment variables. Before this
rule they were read through scattered `os.environ.get(...)` calls with
ad-hoc `int()`/`float()` parses — which shipped real defects: values
that crash at read time (`int("yes")`), the same variable read with two
different defaults (`.get(NAME, "60") or 0`), and zero visibility into
what the config surface even IS (a renamed variable broke deployments
silently, the exact drift class METRICS.md/SPANS.md already gate for
metric and span names).

R017 therefore enforces, package-wide:

  * every H2O3_* read goes through the typed accessors
    (`utils/env.env_str/env_int/env_float/env_bool`) — a direct
    `os.environ.get("H2O3_...")` / `os.environ["H2O3_..."]` /
    `os.getenv("H2O3_...")` is a finding (utils/env.py itself, the
    accessors' implementation, is exempt);
  * accessor calls use a LITERAL variable name and a LITERAL default
    (a computed name cannot be censused; a computed default defeats the
    one-default-per-variable contract). `env_int`/`env_float` must pass
    a default explicitly;
  * each variable is declared at exactly ONE accessor call site
    package-wide — modules that share a variable import the owning
    module's helper (utils/env.process_id, multihost._coordinator_address)
    instead of re-reading;
  * every `H2O3_*` token the README documents must exist in the census —
    documented-but-phantom variables are doc drift (checked only on
    full-package runs, where utils/env.py is among the analyzed modules).

The census of what passed is committed as `h2o3_tpu/analysis/ENV.md`
(`python -m h2o3_tpu.analysis --write-census`) and freshness-gated in
pre-commit/tier-1 exactly like the metric and span censuses.
"""

from __future__ import annotations

import ast
import os
import re

from h2o3_tpu.analysis.engine import Finding, Module, repo_root

RULES = {"R017"}

_ACCESSORS = {"env_str": "str", "env_int": "int",
              "env_float": "float", "env_bool": "bool"}
_DEFAULT_OPTIONAL = {"env_str", "env_bool"}
_PREFIX = "H2O3_"
_README_TOKEN = re.compile(r"H2O3_[A-Z0-9_]*[A-Z0-9]")
# README tokens that are namespace/template mentions, not variables
_README_IGNORE = {"H2O3_TPU"}


def _terminal(fn: ast.AST):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _chain(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_env_read(node: ast.Call):
    """(is_read, name_node) for os.environ.get(...)/os.getenv(...)."""
    chain = _chain(node.func)
    if chain.endswith("environ.get") or chain in ("os.getenv", "getenv"):
        return True, (node.args[0] if node.args else None)
    return False, None


def _literal_default(node: ast.AST) -> bool:
    """Constant, or an expression of constants only (1 << 20, -1.0) —
    the shapes that still declare ONE default, just spelled readably."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Constant, ast.BinOp, ast.UnaryOp,
                            ast.operator, ast.unaryop, ast.Tuple,
                            ast.expr_context)):
            continue        # expr_context: the Load ctx a Tuple carries
        return False
    return True


def _accessor_call(node: ast.Call):
    """kind for env_str(...)/env.env_int(...)-shaped calls, else None."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in _ACCESSORS:
        return _ACCESSORS[fn.id]
    if isinstance(fn, ast.Attribute) and fn.attr in _ACCESSORS and \
            isinstance(fn.value, ast.Name):
        return _ACCESSORS[fn.attr]
    return None


def _env_module(mod: Module) -> bool:
    return mod.rel.replace("\\", "/").endswith("utils/env.py")


def collect(mods: list):
    """(declarations, findings): declarations is
    {name: [{kind, default, file, line}]}."""
    decls: dict = {}
    findings: list = []
    for mod in mods:
        is_env_mod = _env_module(mod)
        for node in mod.walk():
            # ---- direct reads --------------------------------------------
            if isinstance(node, ast.Call) and not is_env_mod:
                is_read, name_node = _is_env_read(node)
                if is_read:
                    if isinstance(name_node, ast.Constant) and \
                            isinstance(name_node.value, str):
                        if name_node.value.startswith(_PREFIX):
                            findings.append(Finding(
                                "R017", mod.rel, node.lineno,
                                f"direct environment read of "
                                f"{name_node.value!r}: H2O3_* config goes "
                                "through the typed accessors (utils/env."
                                "env_str/env_int/env_float/env_bool) so "
                                "bad values can't crash and the variable "
                                "lands in the ENV.md census"))
                    elif name_node is not None:
                        findings.append(Finding(
                            "R017", mod.rel, node.lineno,
                            "environment read with a computed name: "
                            "cannot be censused — read through a typed "
                            "accessor with a literal name (or waive with "
                            "the reason the namespace is dynamic)"))
            if isinstance(node, ast.Subscript) and not is_env_mod and \
                    isinstance(node.ctx, ast.Load) and \
                    _chain(node.value).endswith("environ") and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str) and \
                    node.slice.value.startswith(_PREFIX):
                findings.append(Finding(
                    "R017", mod.rel, node.lineno,
                    f"direct os.environ[{node.slice.value!r}] read: "
                    "H2O3_* config goes through the typed accessors — a "
                    "missing variable here is a KeyError at request time"))
            # ---- accessor declarations -----------------------------------
            if not isinstance(node, ast.Call):
                continue
            kind = _accessor_call(node)
            if kind is None:
                continue
            name_node = node.args[0] if node.args else None
            if not (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)):
                findings.append(Finding(
                    "R017", mod.rel, node.lineno,
                    f"env_{kind}() with a non-literal variable name: "
                    "cannot be censused — declare the name as a string "
                    "literal"))
                continue
            name = name_node.value
            if not name.startswith(_PREFIX):
                continue            # out of the censused namespace
            default_node = node.args[1] if len(node.args) > 1 else None
            if default_node is None:
                for kw in node.keywords:
                    if kw.arg == "default":
                        default_node = kw.value
            fname = _terminal(node.func)
            if default_node is None:
                if fname not in _DEFAULT_OPTIONAL:
                    findings.append(Finding(
                        "R017", mod.rel, node.lineno,
                        f"{fname}({name!r}) without an explicit default: "
                        "every censused variable declares its default at "
                        "the declaration site"))
                default_repr = '""' if fname == "env_str" else "False"
            elif not _literal_default(default_node):
                findings.append(Finding(
                    "R017", mod.rel, node.lineno,
                    f"{fname}({name!r}, <computed default>): a computed "
                    "default defeats the one-default-per-variable "
                    "contract — declare a literal default (compose "
                    "fallbacks OUTSIDE the accessor: env_str(...) or "
                    "computed)"))
                default_repr = "<computed>"
            else:
                default_repr = ast.unparse(default_node)
            decls.setdefault(name, []).append(
                {"kind": kind, "default": default_repr,
                 "file": mod.rel, "line": node.lineno})
    return decls, findings


def _readme_tokens() -> list:
    path = os.path.join(repo_root(), "README.md")
    if not os.path.exists(path):
        return []
    out = []
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, start=1):
            for tok in _README_TOKEN.findall(line):
                if tok not in _README_IGNORE:
                    out.append((tok, i))
    return out


def check(mods: list) -> list:
    decls, findings = collect(mods)
    for name, entries in sorted(decls.items()):
        if len(entries) > 1:
            first = entries[0]
            for extra in entries[1:]:
                findings.append(Finding(
                    "R017", extra["file"], extra["line"],
                    f"env var {name!r} is declared at more than one "
                    f"accessor call site (first at {first['file']}:"
                    f"{first['line']}): two sites drift apart on type "
                    "and default — declare once, wrap in a helper and "
                    "import it"))
    # README cross-check only on full-package runs: seeded fixtures must
    # not be held against the real README's variable tables
    if any(_env_module(m) for m in mods):
        known = set(decls)
        seen: set = set()
        for tok, line in _readme_tokens():
            if tok in known or tok in seen:
                continue
            seen.add(tok)
            f = Finding(
                "R017", "README.md", line,
                f"README documents env var {tok!r} but no typed-accessor "
                "declaration exists in the package: doc drift — delete "
                "the row, or wire the variable through utils/env")
            f.snippet = tok     # stable fingerprint (README isn't parsed)
            findings.append(f)
    return findings


check.RULES = RULES


def census_markdown(mods: list) -> str:
    """The committed h2o3_tpu/analysis/ENV.md body."""
    decls, _ = collect(mods)
    readme = {tok for tok, _ in _readme_tokens()}
    lines = [
        "# Env-var config census — generated, do not edit",
        "",
        "Generated by `python -m h2o3_tpu.analysis --write-census`; the",
        "R017 rule keeps this file honest (every H2O3_* read goes through",
        "a typed accessor with one literal declaration site and one",
        "default; README rows must exist here). Regenerate after adding,",
        "renaming or re-defaulting a variable.",
        "",
        "| variable | type | default | declared at | README |",
        "|---|---|---|---|---|",
    ]
    for name, entries in sorted(decls.items()):
        e = entries[0]
        # file only, no line: line-shift edits must leave the committed
        # census byte-identical
        lines.append(
            f"| `{name}` | {e['kind']} | `{e['default']}` | "
            f"{e['file']} | "
            f"{'✓' if name in readme else '—'} |")
    lines.append("")
    lines.append(f"{len(decls)} variables.")
    return "\n".join(lines) + "\n"
