"""R006 route/handler drift — REST route patterns vs handler signatures.

The route surface is spread over api/server.py's literal ROUTES table,
four routes_ext*.py build_routes() functions and the flow module — ~150
(regex, method, handler) rows. Nothing ties a pattern's capture groups to
its handler's positional parameters: add a group without a parameter and
every request to that route 500s with a TypeError; the reverse 500s at
dispatch. The reference ships findbugs/error-prone gates for exactly this
shape-vs-signature class; here the analyzer closes it statically.

Checks, with no imports of the API package (pure AST + re.compile of the
literal pattern strings):
  * group count: handler must accept the pattern's capture groups —
    required positionals (after `h`) ≤ groups ≤ total positionals (or
    *args);
  * resolvable handler: a route row naming an undefined function is dead
    on arrival;
  * duplicate (pattern, method) rows: the route loop dispatches first
    match, so the second row is unreachable (a shadowed handler).
"""

from __future__ import annotations

import ast
import re

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R006"}


def _is_route_module(mod: Module) -> bool:
    rel = mod.rel.replace("\\", "/")
    return "/api/" in rel or rel.startswith("api/")


def _pattern_literal(node: ast.AST):
    """The pattern string of re.compile("..."), R("..."), including
    implicit adjacent-literal concatenation (handled by ast.Constant)."""
    if isinstance(node, ast.Call) and node.args:
        callee = node.func
        name = callee.attr if isinstance(callee, ast.Attribute) \
            else (callee.id if isinstance(callee, ast.Name) else None)
        if name in ("compile", "R"):
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
            if isinstance(a, ast.BinOp):   # "a" + variable — not literal
                return None
    return None


def _route_rows(mod: Module):
    """Yield (pattern_str, method, handler_node, lineno) for every tuple
    literal shaped like a route row anywhere in the module."""
    for node in mod.walk():
        if not isinstance(node, ast.Tuple) or len(node.elts) != 3:
            continue
        pat = _pattern_literal(node.elts[0])
        meth = node.elts[1]
        if pat is None or not (isinstance(meth, ast.Constant)
                               and isinstance(meth.value, str)):
            continue
        if meth.value not in ("GET", "POST", "PUT", "DELETE", "HEAD",
                              "PATCH"):
            continue
        yield pat, meth.value, node.elts[2], node.lineno


def _module_defs(mod: Module) -> dict:
    out = {}
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _import_aliases(mod: Module) -> dict:
    """{alias: module_basename} from `from h2o3_tpu.api import flow as
    _flow` style imports — enough to resolve `_flow.h_flow`."""
    out = {}
    for node in mod.walk():
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = alias.name
    return out


def _sig_bounds(fn: ast.AST):
    """(required, maximum) positional group-args after the handler `h`.
    maximum is None for *args."""
    a = fn.args
    pos = a.posonlyargs + a.args
    n_defaults = len(a.defaults)
    required = max(0, len(pos) - n_defaults - 1)   # minus `h`
    maximum = None if a.vararg is not None else max(0, len(pos) - 1)
    return required, maximum


def check(mods: list) -> list:
    findings: list = []
    api_mods = [m for m in mods if _is_route_module(m)]
    if not api_mods:
        return findings
    by_base = {m.rel.rsplit("/", 1)[-1][:-3]: m for m in api_mods}
    seen: dict = {}            # (pattern, method) -> (file, line)
    for mod in api_mods:
        defs = _module_defs(mod)
        aliases = _import_aliases(mod)
        for pat, method, handler, lineno in _route_rows(mod):
            try:
                ngroups = re.compile(pat).groups
            except re.error as ex:
                findings.append(Finding(
                    "R006", mod.rel, lineno,
                    f"route pattern {pat!r} does not compile: {ex}"))
                continue
            key = (pat, method)
            if key in seen:
                f0, l0 = seen[key]
                findings.append(Finding(
                    "R006", mod.rel, lineno,
                    f"duplicate route ({method} {pat!r}) also registered "
                    f"at {f0}:{l0}: first match wins, this row is "
                    "unreachable"))
            else:
                seen[key] = (mod.rel, lineno)
            # resolve the handler to a def we can check
            fn = None
            hname = None
            if isinstance(handler, ast.Name):
                hname = handler.id
                fn = defs.get(hname)
                if fn is None:
                    findings.append(Finding(
                        "R006", mod.rel, lineno,
                        f"route handler {hname!r} is not defined at "
                        "module level: the row dispatches to a missing "
                        "function"))
                    continue
            elif isinstance(handler, ast.Attribute) and \
                    isinstance(handler.value, ast.Name):
                target_mod = by_base.get(
                    aliases.get(handler.value.id, "").rsplit(".", 1)[-1])
                if target_mod is not None:
                    hname = f"{handler.value.id}.{handler.attr}"
                    fn = _module_defs(target_mod).get(handler.attr)
                    if fn is None:
                        findings.append(Finding(
                            "R006", mod.rel, lineno,
                            f"route handler {hname} not found in "
                            f"{target_mod.rel}"))
                        continue
            if fn is None:
                continue       # dynamic handler (factory call) — unchecked
            required, maximum = _sig_bounds(fn)
            if ngroups < required or \
                    (maximum is not None and ngroups > maximum):
                want = f"{required}" if maximum == required else \
                    f"{required}..{'*' if maximum is None else maximum}"
                findings.append(Finding(
                    "R006", mod.rel, lineno,
                    f"route {method} {pat!r} captures {ngroups} group(s) "
                    f"but handler {hname}() takes {want} after `h`: "
                    "dispatch raises TypeError on every request"))
    return findings


check.RULES = RULES
