"""R013 — timeout-less socket waits: recv/connect/accept with no bound.

The bug class this PR keeps meeting: a network wait with no deadline
turns a dead peer into a silently parked thread — the pre-elastic
Broadcaster's `srv.accept()` waited forever for a worker pod that would
never come, and a worker's `create_connection` retried into a void. The
membership layer's whole detection story rests on every wait being
bounded (ack deadlines, heartbeat, formation timeout), so the analyzer
now rejects regressions of the class.

R013 flags, per function scope:
  * `socket.create_connection(...)` without a `timeout=` kwarg;
  * `.recv(...)`, `.accept(...)` and `.connect(...)` calls on sockets
    CREATED IN THE SAME FUNCTION (`socket.socket(...)` or
    `socket.create_connection(...)`) when the function never calls
    `.settimeout(<non-None>)` on them.

Scope limits (documented, not accidental): a socket received as a
parameter or attribute is exempt — its creator owns the timeout
discipline (the framing helpers `_recv_frame(sock, ...)` would otherwise
all fire), and the interprocedural R008 already flags unbounded network
calls under locks. Waive true intentional unbounded waits with
`# h2o3-ok: R013 reason`.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R013"}

_WAIT_ATTRS = {"recv", "accept", "connect", "recv_into", "recvfrom"}


def _is_socket_ctor(call: ast.Call):
    """socket.socket(...) / socket.create_connection(...) — returns the
    ctor name or None."""
    fn = call.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "socket" \
            and fn.attr in ("socket", "create_connection"):
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id == "create_connection":
        return "create_connection"
    return None


def _has_timeout_kwarg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    # create_connection(addr, timeout) positional form
    return len(call.args) >= 2


def _scopes(tree: ast.AST, nodes=None):
    """Yield (scope_node, body_statements) for the module and every
    function — nested functions analyze as their own scope. `nodes` is
    the module's cached flat node list (Module.walk())."""
    yield tree, list(ast.iter_child_nodes(tree))
    for node in (nodes if nodes is not None else ast.walk(tree)):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body


def _walk_scope(body):
    """Walk statements without descending into nested function defs
    (those are their own scope, yielded by _scopes separately)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check(mod: Module) -> list:
    findings = []
    for _scope, body in _scopes(mod.tree, mod.walk()):
        local_socks: set = set()       # names bound to sockets made here
        timed: set = set()             # names that got .settimeout(x)
        waits: list = []               # (name, attr, lineno)
        for node in _walk_scope(body):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                ctor = _is_socket_ctor(node.value)
                if ctor is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_socks.add(tgt.id)
                            if ctor == "create_connection" \
                                    and _has_timeout_kwarg(node.value):
                                timed.add(tgt.id)
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            ctor = _is_socket_ctor(node)
            if ctor == "create_connection" \
                    and not _has_timeout_kwarg(node):
                findings.append(Finding(
                    "R013", mod.rel, node.lineno,
                    "socket.create_connection without timeout= — a dead "
                    "peer parks this thread forever; pass a deadline "
                    "(the membership layer's detection bound)"))
            if isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name):
                if fn.attr == "settimeout" and node.args \
                        and not (isinstance(node.args[0], ast.Constant)
                                 and node.args[0].value is None):
                    timed.add(fn.value.id)
                elif fn.attr in _WAIT_ATTRS:
                    waits.append((fn.value.id, fn.attr, node.lineno))
        for name, attr, lineno in waits:
            if name in local_socks and name not in timed:
                findings.append(Finding(
                    "R013", mod.rel, lineno,
                    f"timeout-less .{attr}() on a socket created in this "
                    "function with no settimeout — an unresponsive peer "
                    "turns this into an unbounded wait; set a deadline "
                    "or settimeout before waiting"))
    return findings


check.RULES = RULES
