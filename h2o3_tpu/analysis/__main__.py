"""CLI: python -m h2o3_tpu.analysis [paths] [options].

Exit status is the contract: 0 when every finding is suppressed or
baselined, 1 otherwise — so the tier-1 test and any pre-commit hook can
shell out to the same entry point the developer runs locally.

`--changed-only` scopes findings to git-modified files (staged, unstaged
and untracked): per-file rules skip unchanged modules and project-rule
findings are filtered to the changed set, so the pre-commit hook pays
seconds on a small diff while CI/tier-1 keep whole-package scope. The
census freshness gate still runs in full — a census is whole-package by
definition.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from h2o3_tpu.analysis import engine


def _git_changed_files(root: str):
    """Repo-relative paths of modified/staged/untracked files, or None
    when git is unavailable (fall back to a full run, never skip)."""
    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "--no-renames",
             "--untracked-files=all"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    changed = set()
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip().strip('"')
        if path:
            changed.add(path.replace("\\", "/"))
    return changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m h2o3_tpu.analysis",
        description="JAX-aware static analyzer (rules R001-R025)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the h2o3_tpu "
                         "package)")
    ap.add_argument("--baseline", default=None, metavar="JSON",
                    help="baseline file of grandfathered findings "
                         "(e.g. analysis_baseline.json)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. R001,R003")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout "
                         "(includes elapsed_s wall-time)")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write findings as SARIF 2.1.0 to PATH "
                         "(CI/editor annotation format)")
    ap.add_argument("--all", action="store_true",
                    help="also print suppressed/baselined findings")
    ap.add_argument("--changed-only", action="store_true",
                    help="scope findings to git-modified files "
                         "(pre-commit mode; project rules still see the "
                         "whole package for cross-file resolution)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather current findings into --baseline")
    ap.add_argument("--write-census", nargs="?", metavar="PATH",
                    const="__default__", default=None,
                    help="write the census markdown files (default: "
                         "h2o3_tpu/obs/METRICS.md + SPANS.md + "
                         "h2o3_tpu/analysis/ENV.md + "
                         "h2o3_tpu/deploy/PROTOCOL.md)")
    ap.add_argument("--check-census", action="store_true",
                    help="exit 1 when a committed census (METRICS.md / "
                         "SPANS.md / ENV.md / PROTOCOL.md) is stale "
                         "(pre-commit freshness gate)")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    rules = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    paths = args.paths or [engine.package_root()]
    mods = engine.load_modules(paths)

    only_files = None
    if args.changed_only:
        changed = _git_changed_files(engine.repo_root())
        if changed is not None:
            only_files = {m.rel for m in mods
                          if m.rel.replace("\\", "/") in changed}
            # R017's doc-drift findings target README.md itself — keep
            # them in scope when the README is what changed (else a
            # phantom config row sails through the hook)
            if "README.md" in changed:
                only_files.add("README.md")
    findings = engine.analyze_modules(mods, rules=rules,
                                      only_files=only_files)

    census_rc = 0
    if args.write_census is not None or args.check_census:
        from h2o3_tpu.analysis import rules_env, rules_metrics, \
            rules_protocol, rules_spans
        # the censuses are PACKAGE-wide by definition — independent of
        # which paths this invocation analyzes (the hook passes tests/
        # too, which must not leak fixture names into a census; a
        # --changed-only run must still gate the full surface). When the
        # analyzed paths cover the whole package, filter the
        # already-parsed modules instead of re-reading the tree;
        # re-load only for partial runs.
        pkg_root = engine.package_root()
        if any(os.path.abspath(p) == pkg_root for p in paths):
            pkg_mods = [m for m in mods
                        if m.path.startswith(pkg_root + os.sep)]
        else:
            pkg_mods = engine.load_modules([pkg_root])
        censuses = [
            (rules_metrics.census_markdown(pkg_mods), "metric",
             os.path.join(engine.package_root(), "obs", "METRICS.md")),
            (rules_spans.census_markdown(pkg_mods), "span",
             os.path.join(engine.package_root(), "obs", "SPANS.md")),
            (rules_env.census_markdown(pkg_mods), "env-var",
             os.path.join(engine.package_root(), "analysis", "ENV.md")),
            (rules_protocol.census_markdown(pkg_mods), "protocol",
             os.path.join(engine.package_root(), "deploy", "PROTOCOL.md")),
        ]
        if args.write_census is not None:
            targets = censuses
            if args.write_census != "__default__":
                # explicit path: the metric census only (legacy
                # spelling). Leave `censuses` itself alone — the
                # --check-census gate below must keep comparing the
                # COMMITTED files, not the file just written
                targets = [(censuses[0][0], "metric", args.write_census)]
            for body, _, out in targets:
                with open(out, "w", encoding="utf-8") as fh:
                    fh.write(body)
                print(f"census written: {out}", file=sys.stderr)
        if args.check_census:
            for body, what, path in censuses:
                have = ""
                if os.path.exists(path):
                    with open(path, encoding="utf-8") as fh:
                        have = fh.read()
                if have != body:
                    print(f"stale {what} census — run: python -m "
                          "h2o3_tpu.analysis --write-census",
                          file=sys.stderr)
                    census_rc = 1

    if args.baseline and not args.write_baseline:
        engine.apply_baseline(findings, engine.load_baseline(args.baseline))
    if args.write_baseline:
        path = args.baseline or "analysis_baseline.json"
        engine.write_baseline(findings, path)
        print(f"baseline written: {path} "
              f"({len([f for f in findings if not f.suppressed])} findings "
              "grandfathered)", file=sys.stderr)
        return 1 if census_rc else 0    # a stale census still gates

    elapsed = time.monotonic() - t0
    bad = engine.unsuppressed(findings)
    shown = findings if args.all else bad
    if args.sarif:
        from h2o3_tpu.analysis import sarif
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(sarif.to_sarif(findings), fh, indent=2,
                      sort_keys=True)
            fh.write("\n")
        print(f"sarif written: {args.sarif}", file=sys.stderr)
    if args.as_json:
        by_rule: dict = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        print(json.dumps({"findings": [f.to_dict() for f in shown],
                          "unsuppressed": len(bad),
                          "total": len(findings),
                          "by_rule": dict(sorted(by_rule.items())),
                          "files_analyzed": len(mods),
                          "changed_only": bool(args.changed_only),
                          "scoped_files": (len(only_files)
                                           if only_files is not None
                                           else None),
                          "elapsed_s": round(elapsed, 3),
                          "rule_timings_s": {
                              k: round(v, 4) for k, v in
                              sorted(engine.RULE_TIMINGS.items())}},
                         indent=2))
    else:
        for f in shown:
            tag = ""
            if f.suppressed:
                tag = " [suppressed]"
            elif f.baselined:
                tag = " [baselined]"
            print(f"{f}{tag}")
        n_sup = sum(1 for f in findings if f.suppressed)
        n_base = sum(1 for f in findings if f.baselined)
        scope = ""
        if only_files is not None:
            scope = f" [changed-only: {len(only_files)} file(s)]"
        print(f"{len(findings)} finding(s): {len(bad)} unsuppressed, "
              f"{n_sup} suppressed inline, {n_base} baselined "
              f"({elapsed:.1f}s){scope}",
              file=sys.stderr)
    return 1 if (bad or census_rc) else 0


if __name__ == "__main__":
    sys.exit(main())
