"""R014 — unguarded pjit/jit dispatch in the serving/parallel layers.

The bug class (ISSUE 10, re-opened risk with the mesh-sharded scorer):
XLA's CPU client shares ONE collective thread pool across concurrently
launched programs — two in-flight multi-replica executions park subsets
of their participants at the rendezvous and starve each other forever.
`parallel/compat.py` owns the fix: every device dispatch on a host mesh
must ride `guarded_jit` / `guard_collective` (or the `run_host_serialized`
funnel), which serializes launch→ready windows. A raw `jax.jit` or
`pjit` dispatch site in the serving or parallel layers silently re-opens
the hang — the scorer-cache programs now contain collectives (sharded
param args), so the stakes went up with this rebuild.

R014 flags, in files under `h2o3_tpu/serving/` and `h2o3_tpu/parallel/`
only (other layers route through these funnels or own their guards):
  * `jax.jit(...)` / `jit(...)` / `pjit(...)` /
    `jax.experimental.pjit.pjit(...)` calls that are NOT the direct
    argument of `guard_collective(...)` (any attribute path);
  * `@jax.jit`-style decorators without a `guard_collective` decorator
    above them on the same function.

`compat.py` itself is exempt — it is the module that DEFINES the guard
(its inner `jax.jit` calls are the guarded implementation). Waive true
host-side-only jits with `# h2o3-ok: R014 reason`.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R014"}

_SCOPED_PREFIXES = ("h2o3_tpu/serving/", "h2o3_tpu/parallel/")
_EXEMPT = ("h2o3_tpu/parallel/compat.py",)
_GUARDS = {"guard_collective", "guarded_jit"}


def _dotted(node) -> str:
    """'jax.experimental.pjit.pjit' for an attribute chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_name(name: str) -> bool:
    return name in ("jit", "pjit") or name.endswith(".jit") \
        or name.endswith(".pjit")


def _is_jit_maker(call: ast.Call) -> bool:
    name = _dotted(call.func)
    if _is_jit_name(name):
        return True
    # functools.partial(jax.jit, static_argnames=...) — the repo's
    # dominant static-args spelling: the jit is an ARGUMENT, not the
    # callee, but the partial IS the jit-maker being dispatched
    if name.split(".")[-1] == "partial":
        return any(_is_jit_name(_dotted(a)) for a in call.args)
    return False


def _is_guard(call_or_deco) -> bool:
    name = _dotted(call_or_deco.func if isinstance(call_or_deco, ast.Call)
                   else call_or_deco)
    return name.split(".")[-1] in _GUARDS


def check(mod: Module) -> list:
    rel = mod.rel.replace("\\", "/")
    if not rel.startswith(_SCOPED_PREFIXES) or rel in _EXEMPT:
        return []
    findings = []
    layer = rel.split("/")[1]
    # parent map: a jit call is fine when its direct consumer is a
    # guard_collective(...) call
    parents: dict = {}
    for node in mod.walk():
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    deco_nodes: set = set()       # decorators judged by the deco branch
    for node in mod.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            decos = list(node.decorator_list)
            deco_nodes.update(id(d) for d in decos)
            guarded = any(_is_guard(g) for g in decos)
            for d in decos:
                if isinstance(d, ast.Call):
                    is_jit = _is_jit_maker(d)
                    name = _dotted(d.func)
                else:
                    name = _dotted(d)
                    is_jit = _is_jit_name(name)
                if is_jit and not guarded:
                    findings.append(Finding(
                        "R014", mod.rel, d.lineno,
                        f"@{name} dispatch in {layer}/ not routed "
                        "through compat.guard_collective — an unguarded "
                        "collective launch on a host mesh re-opens the "
                        "XLA:CPU rendezvous hang; stack "
                        "@compat.guard_collective above it or use "
                        "compat.guarded_jit"))
    for node in mod.walk():
        if not isinstance(node, ast.Call) or not _is_jit_maker(node) \
                or id(node) in deco_nodes:
            continue
        site = node
        parent = parents.get(site)
        # partial(jax.jit, ...)(fn): the guard may wrap the INVOCATION
        # of the partial — hop to it before the guard check
        if isinstance(parent, ast.Call) and parent.func is site:
            site = parent
            parent = parents.get(site)
        if isinstance(parent, ast.Call) and _is_guard(parent) \
                and site in parent.args:
            continue        # guard_collective(jax.jit(...)) — the funnel
        name = _dotted(node.func)
        findings.append(Finding(
            "R014", mod.rel, node.lineno,
            f"raw {name}(...) dispatch in {layer}/ not routed through "
            "compat.guarded_jit/guard_collective — an unguarded "
            "collective launch on a host mesh re-opens the XLA:CPU "
            "rendezvous hang (ISSUE 10); wrap the jit in "
            "compat.guard_collective or use compat.guarded_jit"))
    return findings


check.RULES = RULES
