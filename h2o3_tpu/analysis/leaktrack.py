"""Runtime paired-protocol leak sanitizer (H2O3_LEAKTRACK=1|log).

The static lifecycle rules (R022/R024, lifecycle.py) prove the CODE
closes every registered pair on every path; this sanitizer proves the
RUNTIME did: when armed, the registered openers (FairGate.acquire,
qos.acquire_job_slot) hand out tracked _Token proxies that record their
acquisition site, and the matching closers unwrap them. A token that
DIES unreleased (its weakref finalizer fires with the release flag
still down) is a proven leak — reported with the file:line that opened
it, so the runtime report names the same site the static rule
fingerprints. The request-scoped pairs the static rule can only check
same-frame (edge_admit/end_request, usage begin/finish, watchdog watch
entries) are swept count-wise at end of request instead:
`sweep_request()` in server dispatch asserts every thread-local count
returned to zero.

Modes mirror the other runtime sanitizers: `log` counts and logs;
`raise` (the default for `=1`) defers the failure to
`raise_if_pending()` in server dispatch — a finalizer runs on the GC's
schedule, often on an unrelated thread, so raising in place would be
swallowed; failing the NEXT request is loud and attributable.

Metrics: h2o3_leaktrack_open{pair} (live tracked tokens + swept
request-scope entries) / h2o3_leaktrack_leaks_total{pair}.

Overhead caveat: only registered openers are wrapped, each costing one
stack walk per acquisition — the pairs guard admission and placement,
not per-row work, so the tax is per-request, not per-element.
"""

from __future__ import annotations

import functools
import threading
import traceback
import weakref

from h2o3_tpu.utils.env import env_str

_MAX_REPORTS = 64       # recent leak reports kept for tests / the log

_mode = ""              # "" (off) | "log" | "raise"
_lock = threading.Lock()
_tls = threading.local()
_wrapped: list = []     # (owner, attr, orig) for disable()
_open: dict = {}        # pair -> live tracked-token count
_pending = None         # first leak message awaiting raise_if_pending
_reports: list = []     # [(pair, site)] recent leaks (bounded)

# request-scoped pairs swept count-wise (opener attr -> pair label)
_SCOPED_PAIRS = ("qos.edge_admit", "usage.request", "watchdog.watch")


class LeakError(RuntimeError):
    """A tracked paired-protocol token died unreleased (or a
    request-scoped pair survived its request)."""


def _counter():
    from h2o3_tpu.obs import metrics as _om
    return _om.counter("h2o3_leaktrack_leaks_total",
                       "paired-protocol leaks proven at runtime")


def _open_series():
    with _lock:
        out = [({"pair": p}, float(n)) for p, n in sorted(_open.items())
               if n]
    return out


def env_mode() -> str:
    raw = env_str("H2O3_LEAKTRACK", "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return ""
    return "log" if raw == "log" else "raise"


def active() -> bool:
    return bool(_mode)


# ---------------------------------------------------------------------------
# tracked tokens
class _Token:
    """Proxy for an opener's return value. Truthiness delegates (the
    `if not took:` guards keep working); the wrapped closer unwraps
    .value before calling the original. The mutable `state` cell (not
    the token) is shared with the finalizer: a finalize callback must
    not hold its own referent."""
    __slots__ = ("value", "pair", "site", "state", "__weakref__")

    def __init__(self, value, pair: str, site: str):
        self.value = value
        self.pair = pair
        self.site = site
        self.state = {"released": False}

    def __bool__(self):
        return bool(self.value)

    def __repr__(self):
        return f"<leaktrack token {self.pair} @ {self.site}>"


def _acq_site() -> str:
    """file:line of the frame that called the wrapped opener — the
    first frame below us that is not this module."""
    for fr in reversed(traceback.extract_stack()[:-1]):
        if "leaktrack" not in fr.filename.replace("\\", "/"):
            return f"{fr.filename}:{fr.lineno}"
    return "<unknown>"


def _report(pair: str, site: str, what: str):
    global _pending
    msg = (f"leaktrack: {what} for pair {pair!r} opened at {site} — "
           "the closer never ran (H2O3_LEAKTRACK)")
    try:
        _counter().inc(pair=pair)
    except Exception:   # noqa: BLE001 — a leak report must never crash GC
        pass
    with _lock:
        _reports.append((pair, site))
        del _reports[:-_MAX_REPORTS]
        if _mode == "raise" and _pending is None:
            _pending = msg
    from h2o3_tpu.utils.log import get_logger
    get_logger("leaktrack").warning("%s", msg)


def _on_token_dead(pair: str, site: str, state: dict):
    if state.get("released"):
        return
    state["released"] = True       # a finalizer fires at most once, but
    #                                keep the flag honest for inspection
    with _lock:
        _open[pair] = max(0, _open.get(pair, 0) - 1)
    if _mode:
        _report(pair, site, "tracked token died unreleased")


def _release_token(tok: "_Token"):
    if not tok.state.get("released"):
        tok.state["released"] = True
        with _lock:
            _open[tok.pair] = max(0, _open.get(tok.pair, 0) - 1)


def _opener_factory(pair: str):
    def deco(orig):
        @functools.wraps(orig)
        def w(*a, **k):
            v = orig(*a, **k)
            if not _mode or not v:
                return v           # falsy return == nothing acquired
            site = _acq_site()
            tok = _Token(v, pair, site)
            with _lock:
                _open[pair] = _open.get(pair, 0) + 1
            weakref.finalize(tok, _on_token_dead, pair, site, tok.state)
            return tok
        return w
    return deco


def _closer_factory(pair: str):
    def deco(orig):
        @functools.wraps(orig)
        def w(*a, **k):
            a = tuple(_unwrap(x) for x in a)
            k = {key: _unwrap(x) for key, x in k.items()}
            return orig(*a, **k)
        return w
    return deco


def _unwrap(x):
    if isinstance(x, _Token):
        _release_token(x)
        return x.value
    return x


# ---------------------------------------------------------------------------
# request-scoped count pairs (swept at end of request)
def _counts() -> dict:
    c = getattr(_tls, "counts", None)
    if c is None:
        c = _tls.counts = {}
    return c


def _scoped_inc(pair: str):
    c = _counts()
    c[pair] = c.get(pair, 0) + 1
    with _lock:
        _open[pair] = _open.get(pair, 0) + 1


def _scoped_dec(pair: str, floor_zero: bool = True):
    c = _counts()
    n = c.get(pair, 0)
    if n <= 0 and floor_zero:
        return
    c[pair] = n - 1
    with _lock:
        _open[pair] = max(0, _open.get(pair, 0) - 1)


def _scoped_open_factory(pair: str):
    def deco(orig):
        @functools.wraps(orig)
        def w(*a, **k):
            out = orig(*a, **k)
            if _mode:
                _scoped_inc(pair)
            return out
        return w
    return deco


def _scoped_close_factory(pair: str, clears: bool = False):
    def deco(orig):
        @functools.wraps(orig)
        def w(*a, **k):
            out = orig(*a, **k)
            if _mode:
                if clears:      # idempotent clearer: zero the count
                    c = _counts()
                    n = c.pop(pair, 0)
                    if n:
                        with _lock:
                            _open[pair] = max(0, _open.get(pair, 0) - n)
                else:
                    _scoped_dec(pair)
            return out
        return w
    return deco


def sweep_request():
    """End-of-request assertion: every request-scoped pair this thread
    opened is closed. Wired into server dispatch right after
    qos.end_request() — the one instant the counts MUST be zero."""
    if not _mode:
        return
    c = getattr(_tls, "counts", None)
    if not c:
        return
    for pair, n in list(c.items()):
        if n > 0:
            _report(pair, "<request scope>",
                    f"request finished with {n} open entr"
                    f"{'y' if n == 1 else 'ies'}")
        if n:
            with _lock:
                _open[pair] = max(0, _open.get(pair, 0) - n)
        c.pop(pair, None)


def raise_if_pending():
    """Surface a deferred leak (raise mode): called from server
    dispatch, failing the NEXT request — a finalizer on a GC thread
    cannot fail the request that leaked."""
    global _pending
    if _pending is None:
        return
    with _lock:
        msg, _pending = _pending, None
    raise LeakError(msg)


def reports() -> list:
    with _lock:
        return list(_reports)


def open_counts() -> dict:
    with _lock:
        return {p: n for p, n in _open.items() if n}


# ---------------------------------------------------------------------------
def _wrap(owner, attr: str, factory):
    orig = getattr(owner, attr)
    if getattr(orig, "_leaktrack_wrapped", False):
        return
    new = factory(orig)
    new._leaktrack_wrapped = True
    setattr(owner, attr, new)
    _wrapped.append((owner, attr, orig))


def enable(mode: str = "raise"):
    """Arm the sanitizer: wrap the registered openers/closers in place
    (the lifecycle.py pair registry's runtime half). Idempotent."""
    global _mode
    _mode = mode
    from h2o3_tpu.obs import metrics as _om
    from h2o3_tpu.obs import usage as _usage
    from h2o3_tpu.obs import watchdog as _wd
    from h2o3_tpu.serving import qos as _qos
    _om.gauge("h2o3_leaktrack_open",
              "live tracked paired-protocol tokens", fn=_open_series)
    # token pairs: opener returns proxy, closer unwraps
    _wrap(_qos.FairGate, "acquire", _opener_factory("qos.gate"))
    _wrap(_qos.FairGate, "release", _closer_factory("qos.gate"))
    _wrap(_qos, "acquire_job_slot", _opener_factory("qos.job_slot"))
    _wrap(_qos, "release_job_slot", _closer_factory("qos.job_slot"))
    # request-scoped count pairs: swept by sweep_request()
    _wrap(_qos, "edge_admit", _scoped_open_factory("qos.edge_admit"))
    _wrap(_qos, "end_request",
          _scoped_close_factory("qos.edge_admit", clears=True))
    _wrap(_usage, "begin_request", _scoped_open_factory("usage.request"))
    _wrap(_usage, "finish_request",
          _scoped_close_factory("usage.request", clears=True))
    _wrap(_usage, "clear_request",
          _scoped_close_factory("usage.request", clears=True))
    _wrap(_wd._Watch, "__enter__",
          _scoped_open_factory("watchdog.watch"))
    _wrap(_wd._Watch, "__exit__",
          _scoped_close_factory("watchdog.watch"))


def disable():
    global _mode, _pending
    _mode = ""
    _pending = None
    for owner, attr, orig in reversed(_wrapped):
        setattr(owner, attr, orig)
    del _wrapped[:]
    with _lock:
        _open.clear()
        del _reports[:]
    _tls.counts = {}
