"""h2o3_tpu.analysis — JAX-aware static analyzer + runtime sanitizers.

The reference gates its Java tree with findbugs/error-prone; this package
is the analog for a JAX serving runtime, with rules distilled from defect
classes this repo actually shipped:

  R001 jit-in-hot-path   jax.jit on a lambda/closure built per call →
                         recompiles every invocation
  R002 host-sync         np.asarray/.item()/.tolist()/block_until_ready
                         under trace or inside timeline.span hot paths
  R003 lock-discipline   self.X mutated both under `with self._lock` and
                         bare
  R004 impure-jit        time.*/random.*/global mutation captured at
                         trace time
  R005 metric-name drift h2o3_* metric declared twice / non-literal name /
                         inconsistent label sets (census: obs/METRICS.md)
  R006 route drift       REST route capture groups vs handler signatures
  R011 span-name drift   timeline span names vs the obs/SPANS.md census
  R012 logging drift     print()/bare logging in package code → the
                         structured utils/log logger
  R013 socket deadlines  timeout-less recv/connect/accept waits
  R014 unguarded pjit    raw jax.jit/pjit dispatch in serving/ or
                         parallel/ not routed through
                         compat.guarded_jit/guard_collective (the
                         XLA:CPU collective-rendezvous hang class)
  R017 env-config census direct os.environ reads of H2O3_*; accessor
                         calls with non-literal names/defaults;
                         duplicate declaration sites; README rows naming
                         phantom variables (census: analysis/ENV.md;
                         typed accessors: utils/env.py)

Interprocedural concurrency rules (callgraph.py: project-wide call graph
+ lock-acquisition graph):

  R007 lock-order cycle  holding A while taking B (directly or via any
                         call chain) vs. B-then-A anywhere else
  R008 blocking-while-locked  device syncs, socket/HTTP/subprocess waits,
                         timeout-less .wait()/.get()/.join() reachable
                         with a lock held
  R009 use-after-donate  a donate_argnums buffer read after the jitted
                         call that consumed it
  R010 thread/exec leak  Thread without daemon/join; executor futures
                         discarded; un-shutdown ThreadPoolExecutor
  R015 host-sync taint   a call inside a timeline.span block (or on the
                         serving dispatch path) whose callee
                         TRANSITIVELY performs a device→host sync
  R016 replay-determinism nondeterminism (time/random/uuid/urandom/id/
                         unordered-set iteration) feeding state mutation
                         in broadcast-replayed code — divergent per-host
                         values fork the SPMD-replicated state

Replicated-state integrity rules (effects.py + rules_protocol.py: the
effect-lattice pass classifying every function's effect on replicated
vs host-local state, closed to a fixpoint over the same call graph):

  R018 coordinator-only mutation  a replay-EXEMPT route's handler
                         (static/obs/non-broadcast paths) transitively
                         mutates replicated state — the write lands on
                         the coordinator only
  R019 host-divergence taint  broadcast-replayed code feeding a host
                         identity (pid/hostname/platform/raw env read)
                         into replicated state, interprocedurally —
                         generalizes R016 to the full call graph
  R020 protocol drift    replay-channel collect/control op names sent
                         without a worker-side handler arm, or handler
                         arms nothing sends (census: deploy/PROTOCOL.md)
  R021 wire-format drift npz writer/reader sites in one module that
                         disagree on the plane/key set

The call graph models DYNAMIC DISPATCH (class-hierarchy analysis):
cross-module base classes, self.m()/receiver-typed calls widened to
every subclass override, and duck-typed seams resolved by distinctive
method name under a one-hierarchy guard — so all the interprocedural
rules see through polymorphism.

Run `python -m h2o3_tpu.analysis --baseline analysis_baseline.json`; the
tier-1 suite enforces zero unsuppressed findings over BOTH the package
and tests/ (tests run the relaxed profile: R001/R004 waived). Runtime
sanitizers (transfer_guard / debug_nans) live in .sanitizers; the
runtime lock-order checker (H2O3_LOCKDEP) in .lockdep; the replay
divergence sanitizer (H2O3_DIVERGENCE — per-request digests of
replicated-state mutations compared coordinator vs worker) in
.divergence.
"""

from h2o3_tpu.analysis.engine import (   # noqa: F401
    Finding, analyze_paths, analyze_source, analyze_sources,
    apply_baseline, load_baseline, package_root, repo_root, run,
    tests_root, unsuppressed, write_baseline)
from h2o3_tpu.analysis.sanitizers import (   # noqa: F401
    debug_nans, install_from_env, transfer_guard)

ALL_RULES = ("R001", "R002", "R003", "R004", "R005", "R006",
             "R007", "R008", "R009", "R010", "R011", "R012", "R013",
             "R014", "R015", "R016", "R017", "R018", "R019", "R020",
             "R021")
