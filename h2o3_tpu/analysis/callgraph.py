"""R007-R010 — interprocedural concurrency rules over a project-wide
call graph + lock-acquisition graph.

ISSUE 3's per-file rules caught the lock bugs a single screenful shows
(R003 found the real Broadcaster._drain_owed case), but H2O-3's hardest
bugs were CROSS-file: the DKV, the replay channel and the scoring queues
nest each other's locks, and a lock-order cycle or a device wait under a
lock only exists in the composition. This module builds the composition:

  * a CALL GRAPH over every module handed to the analyzer — module-level
    functions, methods (`self.m()`, `Cls.m()`, same-module singleton
    `OBJ.m()`), and cross-module calls resolved through `import`/`from`
    aliases and module-level singletons (`DKV = _DKV()` makes `DKV.put`
    resolve to `_DKV.put` from any importer);
  * a LOCK-ACQUISITION GRAPH: lock identities are class attributes
    assigned a Lock/RLock/Condition/Semaphore (or an analysis.lockdep
    make_lock/make_rlock/DepLock) — id `module.Class.attr` — and
    module-level lock globals — id `module.NAME`. `with <lock>:` blocks
    are tracked lexically; a `with` on something unresolvable holds
    nothing (conservative: silence over noise). Manual
    `<lock>.acquire()` / `<lock>.release()` pairs on resolvable locks
    are modeled linearly in statement order within a function body
    (try/finally release lands after the guarded statements, matching
    the AST walk), so a pager-style I/O lock held across explicit
    acquire/release cannot dodge R007/R008; `acquire(blocking=False)`
    try-locks add held-ness but no order edge (a trylock cannot wait).

Per-function summaries (locks acquired, blocking ops, out-calls, each
with the lexically-held lock set) are closed over the call graph to a
fixpoint, then feed four rule families:

  R007 lock-order cycles  holding A while taking B (directly, or via any
                          call chain that takes B) adds edge A→B; a cycle
                          in the global edge set is a deadlock schedule
                          waiting for its interleaving. One finding per
                          cycle, at the edge site that closes it.
  R008 blocking-while-locked  a blocking operation reachable while a lock
                          is held: device syncs (block_until_ready /
                          device_get / host_fetch), replay-channel
                          collect, socket recv/accept/connect/sendall,
                          HTTP (urlopen), subprocess, time.sleep, and
                          timeout-less `.wait()` / `.get()` / `.join()` /
                          `.result()`. A stalled device or peer then
                          freezes every thread that touches the lock —
                          the "one wedged worker stops /metrics" class.
                          A call carrying a `timeout=`/`deadline=` kwarg
                          is treated as bounded and not descended into.
  R009 use-after-donate   an argument buffer donated to a jitted call
                          (donate_argnums) is read after the call: XLA
                          may already have aliased its memory, so the
                          read returns garbage (or raises under jax
                          buffer-donation checking). Tracks jit(...,
                          donate_argnums=...) values AND factory
                          functions that return them (scorer_cache
                          _build → program → score_rows chain).
  R010 thread/executor leaks  threading.Thread started with neither
                          daemon=True nor a reachable .join() — the
                          process can't exit and failures vanish;
                          ThreadPoolExecutor neither context-managed nor
                          .shutdown(); an executor .submit() whose future
                          is discarded (its exception is silently lost).

Suppress a verified-safe site with `# h2o3-ok: R00n <why>` as usual.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R007", "R008", "R009", "R010"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_LOCK_FACTORIES = {"make_lock", "make_rlock", "DepLock"}
_REENTRANT_CTORS = {"RLock", "make_rlock"}
_TIME_ROOTS = {"time", "_time", "_time_mod"}


# ---------------------------------------------------------------------------
# small AST helpers
def _terminal(fn: ast.AST):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _chain(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _mod_key(rel: str) -> str:
    """'h2o3_tpu/core/kvstore.py' -> 'h2o3_tpu.core.kvstore'."""
    r = rel.replace("\\", "/")
    if r.endswith(".py"):
        r = r[:-3]
    if r.endswith("/__init__"):
        r = r[: -len("/__init__")]
    return r.replace("/", ".")


def _parent_map(tree: ast.AST) -> dict:
    return {c: p for p in ast.walk(tree) for c in ast.iter_child_nodes(p)}


def _has_bound(call: ast.Call) -> bool:
    """True when the call carries a non-None timeout/deadline kwarg —
    treated as a bounded wait (the sanctioned R008 fix shape)."""
    for kw in call.keywords:
        if kw.arg in ("timeout", "deadline", "timeout_s"):
            if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                return False
            return True
    return False


# ---------------------------------------------------------------------------
# project index: classes, functions, singletons, locks, imports
@dataclass
class _ClassInfo:
    name: str
    methods: dict = field(default_factory=dict)   # name -> qual
    lock_attrs: dict = field(default_factory=dict)  # attr -> (id, reentrant)
    bases: list = field(default_factory=list)     # base names (same module)


@dataclass
class _ModInfo:
    key: str
    mod: Module
    defs: dict = field(default_factory=dict)        # fn name -> qual
    classes: dict = field(default_factory=dict)     # cls name -> _ClassInfo
    singletons: dict = field(default_factory=dict)  # var -> cls name
    locks: dict = field(default_factory=dict)       # var -> (id, reentrant)
    imports: dict = field(default_factory=dict)     # alias -> (modkey, sym)


@dataclass
class _FnInfo:
    qual: str
    mod: _ModInfo
    cls: str            # "" for module-level functions
    node: ast.AST
    # summaries (filled by _summarize)
    acquires: list = field(default_factory=list)   # (lock_id, line, held fs)
    calls: list = field(default_factory=list)      # (qual, line, held, bound)
    blocking: list = field(default_factory=list)   # (desc, line, held)
    # closures (filled by fixpoint)
    locks_in: set = field(default_factory=set)     # {(lock_id, rel, line)}
    blocks_in: set = field(default_factory=set)    # {(desc, rel, line)}


def _lock_ctor(value: ast.AST):
    """(is_lock, reentrant) for `threading.Lock()`-shaped values."""
    if isinstance(value, ast.Call):
        t = _terminal(value.func)
        if t in _LOCK_CTORS or t in _LOCK_FACTORIES:
            return True, t in _REENTRANT_CTORS
    return False, False


def _index_module(mod: Module) -> _ModInfo:
    mi = _ModInfo(key=_mod_key(mod.rel), mod=mod)
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mi.defs[node.name] = f"{mi.key}.{node.name}"
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(name=node.name)
            ci.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = f"{mi.key}.{node.name}.{sub.name}"
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    is_lock, reent = _lock_ctor(sub.value)
                    if not is_lock:
                        continue
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            ci.lock_attrs[t.attr] = (
                                f"{mi.key}.{node.name}.{t.attr}", reent)
            mi.classes[node.name] = ci
        elif isinstance(node, ast.Assign):
            is_lock, reent = _lock_ctor(node.value)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if is_lock:
                    mi.locks[t.id] = (f"{mi.key}.{t.id}", reent)
                elif isinstance(node.value, ast.Call):
                    ctor = _terminal(node.value.func)
                    if ctor in mi.classes:
                        mi.singletons[t.id] = ctor
        if isinstance(node, ast.Import):
            for a in node.names:
                mi.imports[a.asname or a.name.split(".")[0]] = (a.name, None)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                mi.imports[a.asname or a.name] = (node.module, a.name)
    return mi


def _class_lock(ci: _ClassInfo, mi: _ModInfo, attr: str, depth=0):
    """Resolve a lock attribute through same-module base classes."""
    if attr in ci.lock_attrs:
        return ci.lock_attrs[attr]
    if depth < 4:
        for b in ci.bases:
            base = mi.classes.get(b)
            if base is not None:
                got = _class_lock(base, mi, attr, depth + 1)
                if got is not None:
                    return got
    return None


def _class_method(ci: _ClassInfo, mi: _ModInfo, name: str, depth=0):
    if name in ci.methods:
        return ci.methods[name]
    if depth < 4:
        for b in ci.bases:
            base = mi.classes.get(b)
            if base is not None:
                got = _class_method(base, mi, name, depth + 1)
                if got is not None:
                    return got
    return None


class _Project:
    def __init__(self, mods: list):
        self.mods = [_index_module(m) for m in mods
                     if m.source]          # skip unreadable stubs
        self.by_key = {mi.key: mi for mi in self.mods}
        self.fns: dict = {}                # qual -> _FnInfo
        for mi in self.mods:
            for node in mi.mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = mi.defs[node.name]
                    self.fns[q] = _FnInfo(q, mi, "", node)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            q = f"{mi.key}.{node.name}.{sub.name}"
                            self.fns[q] = _FnInfo(q, mi, node.name, sub)
        self.lock_reentrant: dict = {}     # lock_id -> bool
        for mi in self.mods:
            for lid, reent in mi.locks.values():
                self.lock_reentrant[lid] = reent
            for ci in mi.classes.values():
                for lid, reent in ci.lock_attrs.values():
                    self.lock_reentrant[lid] = reent

    # -- symbol resolution ------------------------------------------------
    def _import_target(self, mi: _ModInfo, alias: str):
        """(target_module_info, symbol_or_None) for an imported alias."""
        got = mi.imports.get(alias)
        if got is None:
            return None, None
        modkey, sym = got
        tgt = self.by_key.get(modkey)
        if sym is None:
            return tgt, None
        if tgt is None:
            # `from pkg import module` — the alias IS a module
            sub = self.by_key.get(f"{modkey}.{sym}")
            if sub is not None:
                return sub, None
            return None, None
        return tgt, sym

    def resolve_lock(self, mi: _ModInfo, cls: str, expr: ast.AST):
        """Lock id for a `with <expr>:` context, or None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            recv, attr = expr.value.id, expr.attr
            if recv == "self" and cls:
                ci = mi.classes.get(cls)
                if ci is not None:
                    got = _class_lock(ci, mi, attr)
                    if got is not None:
                        return got[0]
                return None
            if recv in mi.singletons:
                ci = mi.classes.get(mi.singletons[recv])
                if ci is not None:
                    got = _class_lock(ci, mi, attr)
                    if got is not None:
                        return got[0]
                return None
            tgt, sym = self._import_target(mi, recv)
            if tgt is not None and sym is None and attr in tgt.locks:
                return tgt.locks[attr][0]
            if tgt is not None and sym is not None \
                    and sym in tgt.singletons:
                ci = tgt.classes.get(tgt.singletons[sym])
                if ci is not None:
                    got = _class_lock(ci, tgt, attr)
                    if got is not None:
                        return got[0]
            return None
        if isinstance(expr, ast.Name):
            if expr.id in mi.locks:
                return mi.locks[expr.id][0]
            tgt, sym = self._import_target(mi, expr.id)
            if tgt is not None and sym is not None and sym in tgt.locks:
                return tgt.locks[sym][0]
        return None

    def resolve_call(self, mi: _ModInfo, cls: str, call: ast.Call):
        """Qualified name of the callee, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in mi.defs:
                return mi.defs[fn.id]
            if fn.id in mi.classes:          # constructor
                return _class_method(mi.classes[fn.id], mi, "__init__")
            tgt, sym = self._import_target(mi, fn.id)
            if tgt is not None and sym is not None:
                if sym in tgt.defs:
                    return tgt.defs[sym]
                if sym in tgt.classes:
                    return _class_method(tgt.classes[sym], tgt, "__init__")
            return None
        if not (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)):
            return None
        recv, meth = fn.value.id, fn.attr
        if recv == "self" and cls:
            ci = mi.classes.get(cls)
            if ci is not None:
                return _class_method(ci, mi, meth)
            return None
        if recv in mi.classes:               # Cls.static(...)
            return _class_method(mi.classes[recv], mi, meth)
        if recv in mi.singletons:
            ci = mi.classes.get(mi.singletons[recv])
            if ci is not None:
                return _class_method(ci, mi, meth)
            return None
        tgt, sym = self._import_target(mi, recv)
        if tgt is not None:
            if sym is None:                  # module alias: mod.f()
                if meth in tgt.defs:
                    return tgt.defs[meth]
                if meth in tgt.singletons or meth in tgt.classes:
                    return None
                return None
            if sym in tgt.singletons:        # from m import OBJ; OBJ.f()
                ci = tgt.classes.get(tgt.singletons[sym])
                if ci is not None:
                    return _class_method(ci, tgt, meth)
            if sym in tgt.classes:
                return _class_method(tgt.classes[sym], tgt, meth)
        return None


# ---------------------------------------------------------------------------
# blocking-operation classification (R008)
def _blocking_desc(call: ast.Call):
    """Human-readable description when `call` is a potentially-unbounded
    blocking operation, else None."""
    fn = call.func
    bounded = _has_bound(call)
    term = _terminal(fn)
    chain = _chain(fn)
    root = chain.split(".", 1)[0] if chain else ""
    if isinstance(fn, ast.Attribute):
        if term in ("wait", "get", "join", "result") and not call.args \
                and not bounded:
            what = {"wait": "Event/Condition.wait",
                    "get": "queue.get", "join": "join",
                    "result": "future.result"}[term]
            return f".{term}() [{what} with no timeout]"
        if term in ("recv", "recv_into", "accept", "getresponse"):
            return f"socket .{term}()"
        if term in ("connect", "sendall") and root not in ("self",):
            return f"socket .{term}()"
        if term == "collect" and "broadcast" in chain.lower():
            return "replay-channel collect()"
        if term == "block_until_ready":
            return "block_until_ready (device barrier)"
        if term in ("device_get", "host_fetch"):
            return f"{term} (device→host sync)"
        if term == "sleep" and root in _TIME_ROOTS:
            return "time.sleep"
        if term == "urlopen":
            return "HTTP urlopen"
        if root in ("requests", "httpx") and \
                term in ("get", "post", "put", "delete", "request"):
            return f"HTTP {chain}"
        if root == "subprocess" and term in ("run", "check_call",
                                             "check_output", "call"):
            return f"subprocess.{term}"
        if term == "communicate" and not bounded:
            return "subprocess .communicate() with no timeout"
    elif isinstance(fn, ast.Name):
        if term in ("block_until_ready", "device_get", "host_fetch"):
            return f"{term} (device sync)"
        if term == "urlopen":
            return "HTTP urlopen"
        if term == "sleep":
            return "time.sleep"
        if term == "create_connection" and not bounded:
            return "socket create_connection with no timeout"
    return None


# ---------------------------------------------------------------------------
# per-function lexical summary
def _is_trylock(call: ast.Call) -> bool:
    """acquire(False) / acquire(blocking=False): cannot wait, so it adds
    held-ness but no order dependency (Linux lockdep's trylock rule)."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def _summarize(fi: _FnInfo, proj: _Project):
    mi, cls = fi.mod, fi.cls
    # locks held via manual .acquire()/.release(): tracked linearly in
    # statement order across the whole function body (the AST walk visits
    # try bodies before finally blocks, so the common acquire/try/finally-
    # release shape holds exactly the guarded statements)
    manual: list = []

    def held_set(held: tuple) -> frozenset:
        return frozenset(held) | frozenset(manual)

    def visit(node, held: tuple):
        if isinstance(node, ast.With):
            ids = []
            for item in node.items:
                lid = proj.resolve_lock(mi, cls, item.context_expr)
                if lid is not None:
                    fi.acquires.append((lid, node.lineno, held_set(held)))
                    ids.append(lid)
                visit(item.context_expr, held)
            inner = tuple(held) + tuple(i for i in ids if i not in held)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # nested scope: summarized separately (module defs)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("acquire", "release"):
                lid = proj.resolve_lock(mi, cls, node.func.value)
                if lid is not None:
                    if node.func.attr == "acquire":
                        if not _is_trylock(node):
                            fi.acquires.append(
                                (lid, node.lineno, held_set(held)))
                        manual.append(lid)
                    elif lid in manual:
                        manual.remove(lid)
                    for child in ast.iter_child_nodes(node):
                        visit(child, held)
                    return
            desc = _blocking_desc(node)
            if desc is not None:
                fi.blocking.append((desc, node.lineno, held_set(held)))
            callee = proj.resolve_call(mi, cls, node)
            if callee is not None and callee in proj.fns:
                fi.calls.append((callee, node.lineno, held_set(held),
                                 _has_bound(node)))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    body = fi.node.body if hasattr(fi.node, "body") else []
    for child in body:
        visit(child, ())


def _fixpoint(proj: _Project):
    """Close locks_in / blocks_in over the call graph. blocks_in does not
    propagate through bounded (timeout-kwarg) calls; locks_in always
    propagates (a bounded wait still nests the callee's locks)."""
    for fi in proj.fns.values():
        fi.locks_in = {(lid, fi.mod.mod.rel, ln)
                       for lid, ln, _ in fi.acquires}
        fi.blocks_in = {(d, fi.mod.mod.rel, ln)
                        for d, ln, _ in fi.blocking}
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for fi in proj.fns.values():
            for callee, _ln, _held, bound in fi.calls:
                cf = proj.fns.get(callee)
                if cf is None:
                    continue
                if not cf.locks_in <= fi.locks_in:
                    fi.locks_in |= cf.locks_in
                    changed = True
                if not bound and not cf.blocks_in <= fi.blocks_in:
                    fi.blocks_in |= cf.blocks_in
                    changed = True


# ---------------------------------------------------------------------------
# R007: lock-order cycles
def _lock_edges(proj: _Project):
    """{(a, b): (rel, line, note)} — first site seen for each order edge."""
    edges: dict = {}

    def add(a, b, rel, line, note):
        if a == b:
            return              # re-entry: handled by reentrancy, not order
        edges.setdefault((a, b), (rel, line, note))

    for fi in proj.fns.values():
        rel = fi.mod.mod.rel
        for lid, line, held in fi.acquires:
            for h in held:
                add(h, lid, rel, line, f"{_short(h)} → {_short(lid)}")
        for callee, line, held, _bound in fi.calls:
            if not held:
                continue
            cf = proj.fns.get(callee)
            if cf is None:
                continue
            for (lid, orel, oline) in cf.locks_in:
                for h in held:
                    add(h, lid, rel, line,
                        f"{_short(h)} → {_short(lid)} via {callee}() "
                        f"(acquired at {orel}:{oline})")
    return edges


def _short(lock_id: str) -> str:
    return lock_id.split(".", 2)[-1] if lock_id.count(".") > 2 else lock_id


def _find_cycles(edges: dict) -> list:
    """Minimal cycles as lists of (a, b) edges, one per cycle set."""
    succ: dict = {}
    for (a, b) in edges:
        succ.setdefault(a, set()).add(b)
    cycles = []
    seen_cycle_keys = set()
    for start in sorted(succ):
        # BFS back to start
        prev = {start: None}
        queue = [start]
        found = None
        while queue and found is None:
            cur = queue.pop(0)
            for nxt in sorted(succ.get(cur, ())):
                if nxt == start:
                    found = cur
                    break
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        if found is None:
            continue
        path = [found]
        while prev[path[-1]] is not None:
            path.append(prev[path[-1]])
        path.reverse()              # start ... found
        nodes = [start] if path == [start] else path
        cyc = [(nodes[i], nodes[(i + 1) % len(nodes)])
               for i in range(len(nodes))]
        if len(nodes) == 1:
            continue
        key = frozenset(nodes)
        if key not in seen_cycle_keys:
            seen_cycle_keys.add(key)
            cycles.append(cyc)
    return cycles


def _check_r007(proj: _Project) -> list:
    findings = []
    edges = _lock_edges(proj)
    for cyc in _find_cycles(edges):
        sites = [edges[e] for e in cyc]
        rel, line, _ = sites[0]
        desc = " ; ".join(
            f"{_short(a)}→{_short(b)} ({edges[(a, b)][0]}:"
            f"{edges[(a, b)][1]})" for a, b in cyc)
        findings.append(Finding(
            "R007", rel, line,
            f"lock-order cycle: {desc} — two threads taking these locks "
            "in opposing order deadlock; pick one global order (or merge "
            "the critical sections)"))
    return findings


# ---------------------------------------------------------------------------
# R008: blocking while holding a lock
def _check_r008(proj: _Project) -> list:
    findings = []
    for fi in proj.fns.values():
        rel = fi.mod.mod.rel
        for desc, line, held in fi.blocking:
            if held:
                findings.append(Finding(
                    "R008", rel, line,
                    f"{desc} while holding {_short(sorted(held)[0])}: a "
                    "stall here wedges every thread touching the lock — "
                    "bound the wait (timeout=) or move it outside the "
                    "critical section"))
        for callee, line, held, bound in fi.calls:
            if not held or bound:
                continue
            cf = proj.fns.get(callee)
            if cf is None or not cf.blocks_in:
                continue
            desc, orel, oline = sorted(cf.blocks_in)[0]
            findings.append(Finding(
                "R008", rel, line,
                f"call into {callee}() while holding "
                f"{_short(sorted(held)[0])}: it reaches {desc} "
                f"({orel}:{oline}) — a stall there wedges the lock; "
                "bound the wait or hoist the call out of the critical "
                "section"))
    return findings


# ---------------------------------------------------------------------------
# R009: donated-buffer use-after-donate
def _donate_positions(call: ast.Call):
    """Donated arg positions of a jax.jit(...) call, or None if not a
    donating jit. Non-literal donate_argnums conservatively means 'all'."""
    if _terminal(call.func) not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for e in v.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, int):
                        out.add(e.value)
                return out if out else set()
            return None if isinstance(v, ast.Constant) and v.value is None \
                else {"*"}          # computed: any positional arg
    return None


def _donating_factories(proj: _Project) -> dict:
    """{qual: positions} for functions that RETURN a donating jit —
    directly, via a local var, or via a call to another donating factory
    (fixpoint, so scorer_cache's _build → program chain resolves)."""
    out: dict = {}
    changed = True
    guard = 0
    while changed and guard < 10:
        changed = False
        guard += 1
        for fi in proj.fns.values():
            if fi.qual in out:
                continue
            # local name -> positions (assigned from jit or factory call)
            local: dict = {}
            pos = None
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    p = _donate_positions(node.value)
                    if p is None:
                        callee = proj.resolve_call(fi.mod, fi.cls,
                                                   node.value)
                        p = out.get(callee)
                    if p:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local[t.id] = p
                if isinstance(node, ast.Return) and node.value is not None:
                    v = node.value
                    if isinstance(v, ast.Call):
                        p = _donate_positions(v)
                        if p is None:
                            callee = proj.resolve_call(fi.mod, fi.cls, v)
                            p = out.get(callee)
                        if p:
                            pos = (pos or set()) | p
                    elif isinstance(v, ast.Name) and v.id in local:
                        pos = (pos or set()) | local[v.id]
            if pos:
                out[fi.qual] = pos
                changed = True
    return out


def _check_r009(proj: _Project) -> list:
    findings = []
    factories = _donating_factories(proj)
    for fi in proj.fns.values():
        rel = fi.mod.mod.rel
        # donating callables visible in this function body: local vars
        donating: dict = {}        # var name -> positions
        calls = []                 # (lineno, donated arg Name -> str)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                p = _donate_positions(node.value)
                if p is None:
                    callee = proj.resolve_call(fi.mod, fi.cls, node.value)
                    p = factories.get(callee)
                if p:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            donating[t.id] = p
        if not donating:
            continue
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in donating:
                pos = donating[node.func.id]
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and \
                            ("*" in pos or i in pos):
                        calls.append((node.lineno, arg.id, node.func.id))
        if not calls:
            continue
        stores: dict = {}          # name -> sorted store linenos after def
        loads: dict = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Name):
                d = stores if isinstance(node.ctx, ast.Store) else loads
                d.setdefault(node.id, []).append(node.lineno)
        for call_line, buf, fname in calls:
            rebinds = [ln for ln in stores.get(buf, []) if ln > call_line]
            kill = min(rebinds) if rebinds else None
            for ln in sorted(loads.get(buf, [])):
                if ln <= call_line:
                    continue
                if kill is not None and ln > kill:
                    break
                findings.append(Finding(
                    "R009", rel, ln,
                    f"{buf!r} is read after being donated to {fname}() at "
                    f"line {call_line}: donate_argnums lets XLA alias the "
                    "buffer, so this read returns garbage — copy before "
                    "the call or drop the donation"))
                break              # one finding per donated call is enough
    return findings


# ---------------------------------------------------------------------------
# R010: thread / executor leaks
def _check_r010_module(mod: Module) -> list:
    findings = []
    parents = _parent_map(mod.tree)
    src = mod.source

    def _kw(call, name):
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        term = _terminal(node.func)
        chain = _chain(node.func)
        if term == "Thread" and (chain in ("Thread", "threading.Thread")
                                 or chain.endswith(".Thread")):
            d = _kw(node, "daemon")
            if isinstance(d, ast.Constant) and d.value:
                continue
            parent = parents.get(node)
            target = None
            if isinstance(parent, ast.Attribute) and parent.attr == "start":
                # Thread(...).start(): no handle survives to join
                findings.append(Finding(
                    "R010", mod.rel, node.lineno,
                    "Thread(...).start() without daemon=True and without "
                    "keeping a handle: the thread can never be joined, "
                    "and a non-daemon leak blocks interpreter exit"))
                continue
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        target = t.id
                    elif isinstance(t, ast.Attribute):
                        target = t.attr
            if target is None:
                continue            # handed elsewhere: give benefit of doubt
            if f"{target}.join" in src or f"{target}.daemon" in src:
                continue
            findings.append(Finding(
                "R010", mod.rel, node.lineno,
                f"thread {target!r} is started with neither daemon=True "
                "nor any .join() in this module: it leaks past its owner "
                "(failures vanish, exit hangs) — join it, or mark daemon "
                "with a reason"))
        elif term == "ThreadPoolExecutor":
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            target = None
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        target = t.id
                    elif isinstance(t, ast.Attribute):
                        target = t.attr
            if target is not None and (f"{target}.shutdown" in src
                                       or f"with {target}" in src):
                continue
            findings.append(Finding(
                "R010", mod.rel, node.lineno,
                "ThreadPoolExecutor neither context-managed nor "
                ".shutdown(): worker threads outlive the work — use "
                "`with ThreadPoolExecutor(...) as pool:`"))
        elif term == "submit" and isinstance(node.func, ast.Attribute):
            parent = parents.get(node)
            if isinstance(parent, ast.Expr):
                findings.append(Finding(
                    "R010", mod.rel, node.lineno,
                    "executor .submit() with the future discarded: the "
                    "task's exception is silently lost — keep the future "
                    "and .result() it (or collect via as_completed)"))
    return findings


# ---------------------------------------------------------------------------
def check(mods: list) -> list:
    proj = _Project(mods)
    for fi in proj.fns.values():
        _summarize(fi, proj)
    _fixpoint(proj)
    findings = []
    findings.extend(_check_r007(proj))
    findings.extend(_check_r008(proj))
    findings.extend(_check_r009(proj))
    for mi in proj.mods:
        findings.extend(_check_r010_module(mi.mod))
    return findings


check.RULES = RULES
