"""R007-R010 + R015/R016 — interprocedural rules over a project-wide
call graph, lock-acquisition graph and CLASS HIERARCHY.

ISSUE 3's per-file rules caught the lock bugs a single screenful shows
(R003 found the real Broadcaster._drain_owed case), but H2O-3's hardest
bugs were CROSS-file: the DKV, the replay channel and the scoring queues
nest each other's locks, and a lock-order cycle or a device wait under a
lock only exists in the composition. This module builds the composition:

  * a CALL GRAPH over every module handed to the analyzer — module-level
    functions, methods (`self.m()`, `Cls.m()`, same-module singleton
    `OBJ.m()`), and cross-module calls resolved through `import`/`from`
    aliases and module-level singletons (`DKV = _DKV()` makes `DKV.put`
    resolve to `_DKV.put` from any importer);
  * a CLASS HIERARCHY (v2): base classes resolve across modules
    (`class ElasticBroadcaster(_mh.Broadcaster)` links into multihost),
    and DYNAMIC DISPATCH is modeled by class-hierarchy analysis — a
    `self.m()` or receiver-typed `obj.m()` call resolves to the SET of
    possible overrides (the static type's method plus every subclass
    override), so a lock taken or a blocking wait performed inside an
    overridden method is visible from base-class call sites.  Known
    duck-typed seams (`model._score_with_params`, broadcaster handler
    methods, TierChunk hooks) resolve by method name when the name is
    private-or-whitelisted and every definition lives in ONE hierarchy —
    unrelated same-named methods never cross-wire;
  * a LOCK-ACQUISITION GRAPH: lock identities are class attributes
    assigned a Lock/RLock/Condition/Semaphore (or an analysis.lockdep
    make_lock/make_rlock/DepLock) — id `module.Class.attr`, resolved
    through cross-module base classes for inherited locks — and
    module-level lock globals — id `module.NAME`. `with <lock>:` blocks
    are tracked lexically; a `with` on something unresolvable holds
    nothing (conservative: silence over noise). Manual
    `<lock>.acquire()` / `<lock>.release()` pairs on resolvable locks
    are modeled linearly in statement order within a function body
    (try/finally shape handled); `acquire(blocking=False)` try-locks add
    held-ness but no order edge (a trylock cannot wait).

Per-function summaries (locks acquired, blocking ops, host syncs,
nondeterminism-fed state mutations, out-calls — each with the lexically
held lock set and span context) are closed over the widened call graph
to a fixpoint, then feed the rule families:

  R007 lock-order cycles  holding A while taking B (directly, or via any
                          call chain — including a subclass override —
                          that takes B) adds edge A→B; a cycle in the
                          global edge set is a deadlock schedule waiting
                          for its interleaving.
  R008 blocking-while-locked  a blocking operation reachable while a lock
                          is held: device syncs, socket/HTTP/subprocess,
                          timeout-less .wait()/.get()/.join()/.result().
                          `timeout=` kwarg calls are treated bounded.
  R009 use-after-donate   an argument buffer donated to a jitted call is
                          read after the call (tracks donating factories
                          through the scorer_cache _build → program
                          chain).
  R010 thread/executor leaks  Thread without daemon/join, unmanaged
                          ThreadPoolExecutor, discarded futures.
  R015 host-sync taint    interprocedural extension of R002's span-block
                          check: a call made inside a `timeline.span`
                          block (or from the serving dispatch layer)
                          whose callee TRANSITIVELY performs a device→
                          host sync (device_get/host_fetch/
                          block_until_ready/.item()/.tolist()/
                          float(jnp...)) hides a barrier on an
                          instrumented hot path. Plain np.asarray of
                          host data is host-side work and is NOT
                          propagated; np.asarray over a jnp expression
                          is.
  R016 replay-determinism broadcast-replayed code (Broadcaster/
                          ReplayHandler methods, mutating route
                          handlers, deploy/membership workers, DKV
                          re-home) reaching a nondeterminism source —
                          time.*, random/secrets/uuid/os.urandom, id(),
                          unordered-set iteration — that FEEDS state
                          mutation (self-attr writes, DKV.put,
                          global mutation). Every cloud member replays
                          the same request; divergent per-host values
                          silently fork the replicated state the
                          symmetric-peer design depends on.

Suppress a verified-safe site with `# h2o3-ok: R00n <why>` as usual.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R007", "R008", "R009", "R010", "R015", "R016"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_LOCK_FACTORIES = {"make_lock", "make_rlock", "DepLock"}
_REENTRANT_CTORS = {"RLock", "make_rlock"}
_TIME_ROOTS = {"time", "_time", "_time_mod"}
_NP_ROOTS = {"np", "numpy", "_np", "onp"}

# ---- dynamic-dispatch duck seams ------------------------------------------
# A receiver we cannot type (`model`, `chunk`, a parameter) still resolves
# when the method NAME is distinctive: private (leading underscore, not
# dunder) or explicitly whitelisted, AND every project class defining it
# shares one hierarchy root. Public seam names that are part of the
# polymorphic serving/replay surface:
_DUCK_SEAMS = {"broadcast"}
# Private names too generic to duck-resolve even when currently unique:
_DUCK_BLACKLIST = {"_lock", "_init", "_close", "_reset"}

# external-module receiver roots that must never duck-resolve (gc.collect
# must not become Broadcaster.collect)
_EXTERNAL_ROOTS = {
    "jax", "jnp", "np", "numpy", "os", "sys", "io", "re", "json", "math",
    "time", "socket", "struct", "threading", "queue", "logging", "gc",
    "random", "secrets", "uuid", "subprocess", "shutil", "tempfile",
    "itertools", "functools", "collections", "weakref", "ctypes",
}


# ---------------------------------------------------------------------------
# small AST helpers
def _terminal(fn: ast.AST):
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _chain(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _mod_key(rel: str) -> str:
    """'h2o3_tpu/core/kvstore.py' -> 'h2o3_tpu.core.kvstore'."""
    r = rel.replace("\\", "/")
    if r.endswith(".py"):
        r = r[:-3]
    if r.endswith("/__init__"):
        r = r[: -len("/__init__")]
    return r.replace("/", ".")


def _has_bound(call: ast.Call) -> bool:
    """True when the call carries a non-None timeout/deadline kwarg —
    treated as a bounded wait (the sanctioned R008 fix shape)."""
    for kw in call.keywords:
        if kw.arg in ("timeout", "deadline", "timeout_s"):
            if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                return False
            return True
    return False


def _contains_jnp(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and \
                _chain(sub).startswith(("jnp.", "jax.numpy.")):
            return True
    return False


# ---------------------------------------------------------------------------
# project index: classes, functions, singletons, locks, imports
@dataclass
class _ClassInfo:
    name: str
    qual: str = ""                                  # module.Cls
    methods: dict = field(default_factory=dict)     # name -> qual
    lock_attrs: dict = field(default_factory=dict)  # attr -> (id, reentrant)
    base_exprs: list = field(default_factory=list)  # base AST nodes
    base_quals: list = field(default_factory=list)  # resolved project bases


@dataclass
class _ModInfo:
    key: str
    mod: Module
    defs: dict = field(default_factory=dict)        # fn name -> qual
    classes: dict = field(default_factory=dict)     # cls name -> _ClassInfo
    singletons: dict = field(default_factory=dict)  # var -> cls name
    locks: dict = field(default_factory=dict)       # var -> (id, reentrant)
    imports: dict = field(default_factory=dict)     # alias -> (modkey, sym)


@dataclass
class _FnInfo:
    qual: str
    mod: _ModInfo
    cls: str            # "" for module-level functions
    node: ast.AST
    # summaries (filled by _summarize)
    acquires: list = field(default_factory=list)   # (lock_id, line, held fs)
    calls: list = field(default_factory=list)      # (qual, line, held,
    #                                                 bound, in_span)
    blocking: list = field(default_factory=list)   # (desc, line, held)
    syncs: list = field(default_factory=list)      # (desc, line) host syncs
    nondet: list = field(default_factory=list)     # (desc, line) R016 sites
    # closures (filled by fixpoint)
    locks_in: set = field(default_factory=set)     # {(lock_id, rel, line)}
    blocks_in: set = field(default_factory=set)    # {(desc, rel, line)}
    syncs_in: set = field(default_factory=set)     # {(desc, rel, line)}


def _lock_ctor(value: ast.AST):
    """(is_lock, reentrant) for `threading.Lock()`-shaped values."""
    if isinstance(value, ast.Call):
        t = _terminal(value.func)
        if t in _LOCK_CTORS or t in _LOCK_FACTORIES:
            return True, t in _REENTRANT_CTORS
    return False, False


def _index_module(mod: Module) -> _ModInfo:
    mi = _ModInfo(key=_mod_key(mod.rel), mod=mod)
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mi.defs[node.name] = f"{mi.key}.{node.name}"
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(name=node.name, qual=f"{mi.key}.{node.name}")
            ci.base_exprs = list(node.bases)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = f"{mi.key}.{node.name}.{sub.name}"
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    is_lock, reent = _lock_ctor(sub.value)
                    if not is_lock:
                        continue
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            ci.lock_attrs[t.attr] = (
                                f"{mi.key}.{node.name}.{t.attr}", reent)
            mi.classes[node.name] = ci
        elif isinstance(node, ast.Assign):
            is_lock, reent = _lock_ctor(node.value)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if is_lock:
                    mi.locks[t.id] = (f"{mi.key}.{t.id}", reent)
                elif isinstance(node.value, ast.Call):
                    ctor = _terminal(node.value.func)
                    if ctor in mi.classes:
                        mi.singletons[t.id] = ctor
        if isinstance(node, ast.Import):
            for a in node.names:
                mi.imports[a.asname or a.name.split(".")[0]] = (a.name, None)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                mi.imports[a.asname or a.name] = (node.module, a.name)
    return mi


class _Project:
    def __init__(self, mods: list):
        self.mods = [_index_module(m) for m in mods
                     if m.source]          # skip unreadable stubs
        self.by_key = {mi.key: mi for mi in self.mods}
        self.classes: dict = {}            # qual -> (_ClassInfo, _ModInfo)
        for mi in self.mods:
            for ci in mi.classes.values():
                self.classes[ci.qual] = (ci, mi)
        # resolve base classes ACROSS modules (class-hierarchy analysis)
        for mi in self.mods:
            for ci in mi.classes.values():
                for b in ci.base_exprs:
                    q = self._class_qual(mi, b)
                    if q is not None:
                        ci.base_quals.append(q)
        self.subs: dict = {}               # qual -> direct subclass quals
        for q, (ci, _mi) in self.classes.items():
            for bq in ci.base_quals:
                self.subs.setdefault(bq, set()).add(q)
        self._all_subs_memo: dict = {}
        self._ancestors_memo: dict = {}
        # method name -> defining class quals (the duck-seam index)
        self.method_defs: dict = {}
        for q, (ci, _mi) in self.classes.items():
            for mname in ci.methods:
                self.method_defs.setdefault(mname, set()).add(q)
        self.fns: dict = {}                # qual -> _FnInfo
        for mi in self.mods:
            for node in mi.mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = mi.defs[node.name]
                    self.fns[q] = _FnInfo(q, mi, "", node)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            q = f"{mi.key}.{node.name}.{sub.name}"
                            self.fns[q] = _FnInfo(q, mi, node.name, sub)
        self.lock_reentrant: dict = {}     # lock_id -> bool
        for mi in self.mods:
            for lid, reent in mi.locks.values():
                self.lock_reentrant[lid] = reent
            for ci in mi.classes.values():
                for lid, reent in ci.lock_attrs.values():
                    self.lock_reentrant[lid] = reent
        self.replay_handlers = self._route_handlers()
        self._fn_nodes_memo: dict = {}

    def fn_nodes(self, fi: "_FnInfo") -> list:
        """Cached flat node list of one function body — several rules
        (R009's factory fixpoint, R016's taint passes) re-scan the same
        functions; one walk each."""
        got = self._fn_nodes_memo.get(fi.qual)
        if got is None:
            got = list(ast.walk(fi.node))
            self._fn_nodes_memo[fi.qual] = got
        return got

    # -- class hierarchy --------------------------------------------------
    def _class_qual(self, mi: _ModInfo, expr: ast.AST):
        """Project-class qual for a base-class expression, or None for
        external bases (object, Exception, third-party)."""
        if isinstance(expr, ast.Name):
            if expr.id in mi.classes:
                return mi.classes[expr.id].qual
            tgt, sym = self._import_target(mi, expr.id)
            if tgt is not None and sym in tgt.classes:
                return tgt.classes[sym].qual
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            tgt, sym = self._import_target(mi, expr.value.id)
            if tgt is not None and sym is None and expr.attr in tgt.classes:
                return tgt.classes[expr.attr].qual
        return None

    def all_subs(self, qual: str) -> set:
        """Transitive subclasses of `qual` (excluding itself)."""
        got = self._all_subs_memo.get(qual)
        if got is not None:
            return got
        out: set = set()
        work = deque(self.subs.get(qual, ()))
        while work:
            q = work.popleft()
            if q in out:
                continue
            out.add(q)
            work.extend(self.subs.get(q, ()))
        self._all_subs_memo[qual] = out
        return out

    def ancestors(self, qual: str) -> set:
        got = self._ancestors_memo.get(qual)
        if got is not None:
            return got
        out: set = set()
        work = deque([qual])
        seen = {qual}
        while work:
            q = work.popleft()
            ci_mi = self.classes.get(q)
            if ci_mi is None:
                continue
            for bq in ci_mi[0].base_quals:
                if bq not in seen:
                    seen.add(bq)
                    out.add(bq)
                    work.append(bq)
        self._ancestors_memo[qual] = out
        return out

    def mro_method(self, qual: str, name: str, _depth: int = 0):
        """The def that a call on an instance statically typed `qual`
        binds (own method, else nearest base's), or None."""
        if _depth > 8:
            return None
        got = self.classes.get(qual)
        if got is None:
            return None
        ci, _mi = got
        if name in ci.methods:
            return ci.methods[name]
        for bq in ci.base_quals:
            m = self.mro_method(bq, name, _depth + 1)
            if m is not None:
                return m
        return None

    def mro_lock(self, qual: str, attr: str, _depth: int = 0):
        """(lock_id, reentrant) for a `self.<attr>` lock, resolved
        through cross-module base classes (ElasticBroadcaster methods
        holding the base Broadcaster's _lock resolve to it)."""
        if _depth > 8:
            return None
        got = self.classes.get(qual)
        if got is None:
            return None
        ci, _mi = got
        if attr in ci.lock_attrs:
            return ci.lock_attrs[attr]
        for bq in ci.base_quals:
            m = self.mro_lock(bq, attr, _depth + 1)
            if m is not None:
                return m
        return None

    def virtual_targets(self, qual: str, name: str) -> set:
        """Class-hierarchy-analysis dispatch: the set of defs a virtual
        call `obj.name()` can bind when obj is statically `qual` — the
        static target plus every subclass override."""
        out: set = set()
        m = self.mro_method(qual, name)
        if m is not None:
            out.add(m)
        for sub in self.all_subs(qual):
            m = self.mro_method(sub, name)
            if m is not None:
                out.add(m)
        return out

    def duck_targets(self, name: str) -> set:
        """Resolve an untypable receiver's method call by NAME when the
        name is distinctive (private or a whitelisted seam) and every
        project class defining it shares one hierarchy — the
        `model._score_with_params` / TierChunk-hook seams. Unrelated
        same-named methods (or common names) resolve to nothing."""
        if name.startswith("__") or name in _DUCK_BLACKLIST:
            return set()
        if not (name.startswith("_") or name in _DUCK_SEAMS):
            return set()
        defs = self.method_defs.get(name)
        if not defs:
            return set()
        common = None
        for q in defs:
            fam = self.ancestors(q) | {q}
            common = fam if common is None else (common & fam)
        if not common:
            return set()          # multiple unrelated hierarchies: punt
        root = sorted(common)[0]
        return self.virtual_targets(root, name)

    # -- symbol resolution ------------------------------------------------
    def _import_target(self, mi: _ModInfo, alias: str):
        """(target_module_info, symbol_or_None) for an imported alias."""
        got = mi.imports.get(alias)
        if got is None:
            return None, None
        modkey, sym = got
        tgt = self.by_key.get(modkey)
        if sym is None:
            return tgt, None
        if tgt is None:
            # `from pkg import module` — the alias IS a module
            sub = self.by_key.get(f"{modkey}.{sym}")
            if sub is not None:
                return sub, None
            return None, None
        return tgt, sym

    def resolve_lock(self, mi: _ModInfo, cls: str, expr: ast.AST):
        """Lock id for a `with <expr>:` context, or None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            recv, attr = expr.value.id, expr.attr
            if recv == "self" and cls:
                ci = mi.classes.get(cls)
                if ci is not None:
                    got = self.mro_lock(ci.qual, attr)
                    if got is not None:
                        return got[0]
                return None
            if recv in mi.singletons:
                ci = mi.classes.get(mi.singletons[recv])
                if ci is not None:
                    got = self.mro_lock(ci.qual, attr)
                    if got is not None:
                        return got[0]
                return None
            tgt, sym = self._import_target(mi, recv)
            if tgt is not None and sym is None and attr in tgt.locks:
                return tgt.locks[attr][0]
            if tgt is not None and sym is not None \
                    and sym in tgt.singletons:
                ci = tgt.classes.get(tgt.singletons[sym])
                if ci is not None:
                    got = self.mro_lock(ci.qual, attr)
                    if got is not None:
                        return got[0]
            return None
        if isinstance(expr, ast.Name):
            if expr.id in mi.locks:
                return mi.locks[expr.id][0]
            tgt, sym = self._import_target(mi, expr.id)
            if tgt is not None and sym is not None and sym in tgt.locks:
                return tgt.locks[sym][0]
        return None

    def resolve_calls(self, mi: _ModInfo, cls: str, call: ast.Call,
                      local_types: dict = None) -> set:
        """The SET of project defs this call can dispatch to (v2:
        virtual calls widen to every override; empty set = external or
        unresolvable)."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in mi.defs:
                return {mi.defs[fn.id]}
            if fn.id in mi.classes:          # constructor: exact type
                m = self.mro_method(mi.classes[fn.id].qual, "__init__")
                return {m} if m else set()
            tgt, sym = self._import_target(mi, fn.id)
            if tgt is not None and sym is not None:
                if sym in tgt.defs:
                    return {tgt.defs[sym]}
                if sym in tgt.classes:
                    m = self.mro_method(tgt.classes[sym].qual, "__init__")
                    return {m} if m else set()
            return set()
        if not isinstance(fn, ast.Attribute):
            return set()
        meth = fn.attr
        # super().m() — exact: the nearest base's def
        if isinstance(fn.value, ast.Call) and \
                _terminal(fn.value.func) == "super" and cls:
            ci = mi.classes.get(cls)
            if ci is not None:
                for bq in ci.base_quals:
                    m = self.mro_method(bq, meth)
                    if m is not None:
                        return {m}
            return set()
        if isinstance(fn.value, ast.Name):
            recv = fn.value.id
            if recv == "self" and cls:
                ci = mi.classes.get(cls)
                if ci is not None:
                    return self.virtual_targets(ci.qual, meth)
                return set()
            if local_types and recv in local_types:
                return self.virtual_targets(local_types[recv], meth)
            if recv in mi.classes:           # Cls.static(...): exact
                m = self.mro_method(mi.classes[recv].qual, meth)
                return {m} if m else set()
            if recv in mi.singletons:
                ci = mi.classes.get(mi.singletons[recv])
                if ci is not None:
                    return self.virtual_targets(ci.qual, meth)
                return set()
            tgt, sym = self._import_target(mi, recv)
            if tgt is not None:
                if sym is None:              # module alias: mod.f()
                    if meth in tgt.defs:
                        return {tgt.defs[meth]}
                    return set()
                if sym in tgt.singletons:    # from m import OBJ; OBJ.f()
                    ci = tgt.classes.get(tgt.singletons[sym])
                    if ci is not None:
                        return self.virtual_targets(ci.qual, meth)
                if sym in tgt.classes:
                    m = self.mro_method(tgt.classes[sym].qual, meth)
                    return {m} if m else set()
                return set()
            if recv in mi.imports:
                return set()    # external module: never duck-resolve
            return self.duck_targets(meth)
        # attribute-chain receiver (self.x.y.m(), h.server.broadcaster.m())
        chain = _chain(fn)
        root = chain.split(".", 1)[0] if chain else ""
        if root in mi.imports or root in _EXTERNAL_ROOTS:
            return set()
        return self.duck_targets(meth)

    # -- replay roots (R016) ----------------------------------------------
    def _route_handlers(self) -> set:
        """Defs registered as MUTATING route handlers: 3-tuples
        (re.compile(...), "<METHOD>", handler) in module-level route
        tables. Non-GET requests are broadcast-replayed on every worker
        (deploy/multihost.replay_request), so their handlers execute on
        every cloud member and carry the SPMD determinism obligation."""
        out: set = set()
        for mi in self.mods:
            # aliases of re.compile anywhere in the module (routes_ext's
            # local `R = re.compile` shorthand builds most of the table)
            compile_aliases = {"compile"}
            for node in mi.mod.walk():
                if isinstance(node, ast.Assign) and \
                        _chain(node.value) in ("re.compile", "compile"):
                    compile_aliases.update(
                        t.id for t in node.targets
                        if isinstance(t, ast.Name))
            for node in mi.mod.walk():
                if not (isinstance(node, ast.Tuple)
                        and len(node.elts) == 3):
                    continue
                pat, meth, ref = node.elts
                if not (isinstance(pat, ast.Call)
                        and _terminal(pat.func) in compile_aliases):
                    continue
                if not (isinstance(meth, ast.Constant)
                        and isinstance(meth.value, str)
                        and meth.value.upper() != "GET"):
                    continue
                t = _terminal(ref)
                if t is None:
                    continue
                if t in mi.defs:
                    out.add(mi.defs[t])
                    continue
                for ci in mi.classes.values():
                    if t in ci.methods:
                        out.add(ci.methods[t])
                        break
        return out


def _is_replay_root(fi: _FnInfo, proj: _Project) -> bool:
    """Functions that execute identically on every cloud member: the
    broadcast-replay surface (R016's root set)."""
    if fi.cls and ("Broadcaster" in fi.cls or "ReplayHandler" in fi.cls):
        return True
    rel = fi.mod.mod.rel.replace("\\", "/")
    if rel.endswith("deploy/membership.py"):
        return True
    name = getattr(fi.node, "name", "")
    if "rehome" in name or name == "replay_request":
        return True
    return fi.qual in proj.replay_handlers


# ---------------------------------------------------------------------------
# blocking-operation classification (R008)
def _blocking_desc(call: ast.Call):
    """Human-readable description when `call` is a potentially-unbounded
    blocking operation, else None."""
    fn = call.func
    bounded = _has_bound(call)
    term = _terminal(fn)
    chain = _chain(fn)
    root = chain.split(".", 1)[0] if chain else ""
    if isinstance(fn, ast.Attribute):
        if term in ("wait", "get", "join", "result") and not call.args \
                and not bounded:
            what = {"wait": "Event/Condition.wait",
                    "get": "queue.get", "join": "join",
                    "result": "future.result"}[term]
            return f".{term}() [{what} with no timeout]"
        if term in ("recv", "recv_into", "accept", "getresponse"):
            return f"socket .{term}()"
        if term in ("connect", "sendall") and root not in ("self",):
            return f"socket .{term}()"
        if term == "collect" and "broadcast" in chain.lower():
            return "replay-channel collect()"
        if term == "block_until_ready":
            return "block_until_ready (device barrier)"
        if term in ("device_get", "host_fetch"):
            return f"{term} (device→host sync)"
        if term == "sleep" and root in _TIME_ROOTS:
            return "time.sleep"
        if term == "urlopen":
            return "HTTP urlopen"
        if root in ("requests", "httpx") and \
                term in ("get", "post", "put", "delete", "request"):
            return f"HTTP {chain}"
        if root == "subprocess" and term in ("run", "check_call",
                                             "check_output", "call"):
            return f"subprocess.{term}"
        if term == "communicate" and not bounded:
            return "subprocess .communicate() with no timeout"
    elif isinstance(fn, ast.Name):
        if term in ("block_until_ready", "device_get", "host_fetch"):
            return f"{term} (device sync)"
        if term == "urlopen":
            return "HTTP urlopen"
        if term == "sleep":
            return "time.sleep"
        if term == "create_connection" and not bounded:
            return "socket create_connection with no timeout"
    return None


# ---------------------------------------------------------------------------
# host-sync classification (R015 — the R002 vocabulary, interprocedural)
def _sync_desc(call: ast.Call):
    fn = call.func
    term = _terminal(fn)
    if term in ("device_get", "host_fetch", "block_until_ready"):
        return f"{term}()"
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("item", "tolist") and not call.args \
                and not call.keywords:
            return f".{fn.attr}()"
        base = _chain(fn.value)
        if fn.attr in ("asarray", "array") and base in _NP_ROOTS \
                and call.args and _contains_jnp(call.args[0]):
            return f"{base}.{fn.attr}(<jnp>)"
    elif isinstance(fn, ast.Name) and term in ("float", "int") \
            and call.args and _contains_jnp(call.args[0]):
        return f"{term}(<jnp>)"
    return None


# ---------------------------------------------------------------------------
# nondeterminism classification (R016)
_NONDET_TIME = {"time", "time_ns", "monotonic", "monotonic_ns",
                "perf_counter", "perf_counter_ns", "process_time"}
_RANDOM_ROOTS = {"random", "_random", "secrets", "_secrets"}
_MUTATORS = {"append", "appendleft", "add", "insert", "extend", "update",
             "setdefault"}


def _nondet_desc(call: ast.Call):
    fn = call.func
    term = _terminal(fn)
    chain = _chain(fn)
    root = chain.split(".", 1)[0] if chain else ""
    if root in _TIME_ROOTS and term in _NONDET_TIME:
        return f"{chain}()"
    if root in _RANDOM_ROOTS and isinstance(fn, ast.Attribute):
        return f"{chain}()"
    if chain.startswith(("np.random.", "numpy.random.", "onp.random.")):
        return f"{chain}()"
    if root in ("uuid", "_uuid") and term in ("uuid1", "uuid4"):
        return f"{chain}()"
    if chain == "os.urandom":
        return "os.urandom()"
    if isinstance(fn, ast.Name):
        if term == "id" and call.args:
            return "id()"
        if term in ("token_hex", "token_bytes", "token_urlsafe"):
            return f"{term}()"
    return None


def _is_setish(expr: ast.AST, set_locals: set) -> bool:
    """Expression whose iteration order is Python-set order — which
    varies per process under hash randomization, so iterating it to
    mutate replicated state forks the cloud. sorted(...) is the fix."""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call) and \
            _terminal(expr.func) in ("set", "frozenset"):
        return True
    if isinstance(expr, ast.Name) and expr.id in set_locals:
        return True
    return False


def _nondet_mutations(fi: _FnInfo, nodes: list = None, desc_fn=None,
                      call_desc=None,
                      include_set_iteration: bool = True) -> list:
    """(desc, line) sites in this function where a nondeterministic value
    (or unordered-set iteration) feeds replicated-state mutation:
    self-attribute writes, module-global container stores
    (SESSIONS[sid] = ..., OBJ.attr = ...), DKV.put, `global` rebinding.
    Local use of nondeterminism (jitter before a sleep, metrics timings
    passed to observe()) does not count — only values that LAND in
    state.

    The taint machinery is shared with R019 (effects.py), which swaps
    the source vocabulary: `desc_fn(call)` replaces _nondet_desc for the
    direct-source check, `call_desc(call)` (if given) additionally marks
    calls to interprocedurally-known divergent functions, and
    `include_set_iteration=False` drops the set-order pattern (R016
    already owns it — one site, one rule)."""
    if desc_fn is None:
        desc_fn = _nondet_desc
    node = fi.node
    if nodes is None:
        nodes = list(ast.walk(node))
    global_names: set = set()
    for n in nodes:
        if isinstance(n, ast.Global):
            global_names.update(n.names)
    # module-level names this module (or an import) binds — a subscript
    # or attribute store rooted at one mutates shared state even without
    # a `global` declaration. Plain-Name assignments in THIS function
    # shadow them (Python scoping), so those names drop out.
    mod_globals: set = set()
    mi = fi.mod
    for top in mi.mod.tree.body:
        if isinstance(top, ast.Assign):
            mod_globals.update(t.id for t in top.targets
                               if isinstance(t, ast.Name))
        elif isinstance(top, ast.AnnAssign) and \
                isinstance(top.target, ast.Name):
            mod_globals.add(top.target.id)
    mod_globals.update(mi.imports)
    # function-local module imports (`from h2o3_tpu.api import server as
    # _srv` inside the handler) alias shared module state too — a store
    # through them is replicated-state mutation
    for n in nodes:
        if isinstance(n, (ast.Import, ast.ImportFrom)):
            mod_globals.update(a.asname or a.name.split(".")[0]
                               for a in n.names)
    local_shadow: set = set()
    for n in nodes:
        if isinstance(n, ast.Assign):
            local_shadow.update(t.id for t in n.targets
                                if isinstance(t, ast.Name)
                                and t.id not in global_names)
    mod_globals -= local_shadow

    assigns = [n for n in nodes
               if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))]
    tainted: set = set()
    set_locals: set = set()

    def expr_taint(e: ast.AST):
        for sub in ast.walk(e):
            if isinstance(sub, ast.Call):
                d = desc_fn(sub)
                if d is None and call_desc is not None:
                    d = call_desc(sub)
                if d is not None:
                    return d
            elif isinstance(sub, ast.Name) and \
                    isinstance(sub.ctx, ast.Load) and sub.id in tainted:
                return f"a value derived from nondeterministic {sub.id!r}"
        return None

    changed, guard = True, 0
    while changed and guard < 6:
        changed = False
        guard += 1
        for a in assigns:
            v = getattr(a, "value", None)
            if v is None:
                continue
            tgts = a.targets if isinstance(a, ast.Assign) else [a.target]
            if expr_taint(v) is not None:
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id not in tainted:
                        tainted.add(t.id)
                        changed = True
            if _is_setish(v, set_locals):
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id not in set_locals:
                        set_locals.add(t.id)
                        changed = True

    def _is_state_target(t: ast.AST) -> bool:
        if isinstance(t, ast.Attribute):
            c = _chain(t)
        elif isinstance(t, ast.Subscript):
            c = _chain(t.value)
        elif isinstance(t, ast.Name):
            return t.id in global_names
        else:
            return False
        if not c:
            return False
        root = c.split(".", 1)[0]
        return root == "self" or root in mod_globals

    out: list = []
    for a in assigns:
        tgts = a.targets if isinstance(a, ast.Assign) else [a.target]
        if not any(_is_state_target(t) for t in tgts):
            continue
        d = None
        v = getattr(a, "value", None)
        if v is not None:
            d = expr_taint(v)
        if d is None:
            for t in tgts:
                if isinstance(t, ast.Subscript):
                    d = expr_taint(t.slice)
                    if d is not None:
                        break
        if d is not None:
            out.append((d, a.lineno))

    for n in nodes:
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)):
            continue
        recv_chain = _chain(n.func.value)
        root = recv_chain.split(".", 1)[0] if recv_chain else ""
        is_state = (root == "self" and n.func.attr in _MUTATORS) or \
            (root in mod_globals and n.func.attr in _MUTATORS) or \
            (n.func.attr == "put"
             and "dkv" in recv_chain.lower())
        if not is_state:
            continue
        for arg in list(n.args) + [kw.value for kw in n.keywords]:
            d = expr_taint(arg)
            if d is not None:
                out.append(
                    (f"{d} flowing into {recv_chain}.{n.func.attr}()",
                     n.lineno))
                break

    def _mutates_state(body: list) -> bool:
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    tg = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    if any(_is_state_target(t) for t in tg):
                        return True
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    rc = _chain(sub.func.value)
                    rt = rc.split(".", 1)[0] if rc else ""
                    if ((rt == "self" or rt in mod_globals)
                            and sub.func.attr in _MUTATORS) or \
                            (sub.func.attr == "put"
                             and "dkv" in rc.lower()):
                        return True
        return False

    if include_set_iteration:
        for n in nodes:
            if isinstance(n, ast.For) and _is_setish(n.iter, set_locals) \
                    and _mutates_state(n.body):
                out.append(("iteration over an unordered set", n.lineno))
    return out


# ---------------------------------------------------------------------------
# per-function lexical summary
def _is_trylock(call: ast.Call) -> bool:
    """acquire(False) / acquire(blocking=False): cannot wait, so it adds
    held-ness but no order dependency (Linux lockdep's trylock rule)."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def _local_ctor_types(fi: _FnInfo, proj: _Project) -> dict:
    """{local var: class qual} for `x = Cls(...)` assignments — lets
    `x.m()` dispatch through the hierarchy of the constructed type."""
    mi = fi.mod
    out: dict = {}
    for node in proj.fn_nodes(fi):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        f = node.value.func
        cq = None
        if isinstance(f, ast.Name):
            if f.id in mi.classes:
                cq = mi.classes[f.id].qual
            else:
                tgt, sym = proj._import_target(mi, f.id)
                if tgt is not None and sym in tgt.classes:
                    cq = tgt.classes[sym].qual
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            tgt, sym = proj._import_target(mi, f.value.id)
            if tgt is not None and sym is None and f.attr in tgt.classes:
                cq = tgt.classes[f.attr].qual
        if cq is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = cq
    return out


def _is_span_item(item: ast.withitem) -> bool:
    ctx = item.context_expr
    return isinstance(ctx, ast.Call) and \
        _terminal(ctx.func) in ("span", "_span")


def _summarize(fi: _FnInfo, proj: _Project):
    mi, cls = fi.mod, fi.cls
    local_types = _local_ctor_types(fi, proj)
    # locks held via manual .acquire()/.release(): tracked linearly in
    # statement order across the whole function body (the AST walk visits
    # try bodies before finally blocks, so the common acquire/try/finally-
    # release shape holds exactly the guarded statements)
    manual: list = []

    def held_set(held: tuple) -> frozenset:
        return frozenset(held) | frozenset(manual)

    def visit(node, held: tuple, in_span: bool):
        if isinstance(node, ast.With):
            ids = []
            span_here = in_span
            for item in node.items:
                lid = proj.resolve_lock(mi, cls, item.context_expr)
                if lid is not None:
                    fi.acquires.append((lid, node.lineno, held_set(held)))
                    ids.append(lid)
                if _is_span_item(item):
                    span_here = True
                visit(item.context_expr, held, in_span)
            inner = tuple(held) + tuple(i for i in ids if i not in held)
            for child in node.body:
                visit(child, inner, span_here)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # nested scope: summarized separately (module defs)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("acquire", "release"):
                lid = proj.resolve_lock(mi, cls, node.func.value)
                if lid is not None:
                    if node.func.attr == "acquire":
                        if not _is_trylock(node):
                            fi.acquires.append(
                                (lid, node.lineno, held_set(held)))
                        manual.append(lid)
                    elif lid in manual:
                        manual.remove(lid)
                    for child in ast.iter_child_nodes(node):
                        visit(child, held, in_span)
                    return
            desc = _blocking_desc(node)
            if desc is not None:
                fi.blocking.append((desc, node.lineno, held_set(held)))
            sdesc = _sync_desc(node)
            if sdesc is not None:
                fi.syncs.append((sdesc, node.lineno))
            for callee in proj.resolve_calls(mi, cls, node, local_types):
                if callee in proj.fns:
                    fi.calls.append((callee, node.lineno, held_set(held),
                                     _has_bound(node), in_span))
        for child in ast.iter_child_nodes(node):
            visit(child, held, in_span)

    body = fi.node.body if hasattr(fi.node, "body") else []
    for child in body:
        visit(child, (), False)
    fi.nondet = _nondet_mutations(fi, proj.fn_nodes(fi))


def _fixpoint(proj: _Project):
    """Close locks_in / blocks_in / syncs_in over the call graph.
    blocks_in does not propagate through bounded (timeout-kwarg) calls;
    locks_in and syncs_in always propagate (a bounded wait still nests
    the callee's locks, and a bounded call still pays its syncs)."""
    for fi in proj.fns.values():
        fi.locks_in = {(lid, fi.mod.mod.rel, ln)
                       for lid, ln, _ in fi.acquires}
        fi.blocks_in = {(d, fi.mod.mod.rel, ln)
                        for d, ln, _ in fi.blocking}
        fi.syncs_in = {(d, fi.mod.mod.rel, ln)
                       for d, ln in fi.syncs}
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for fi in proj.fns.values():
            for callee, _ln, _held, bound, _sp in fi.calls:
                cf = proj.fns.get(callee)
                if cf is None:
                    continue
                if not cf.locks_in <= fi.locks_in:
                    fi.locks_in |= cf.locks_in
                    changed = True
                if not cf.syncs_in <= fi.syncs_in:
                    fi.syncs_in |= cf.syncs_in
                    changed = True
                if not bound and not cf.blocks_in <= fi.blocks_in:
                    fi.blocks_in |= cf.blocks_in
                    changed = True


def build_project(mods: list) -> _Project:
    """Index + summarize + close: the shared analysis context every rule
    in this module (and the tests) runs against — built ONCE per
    analyzer invocation."""
    proj = _Project(mods)
    for fi in proj.fns.values():
        _summarize(fi, proj)
    _fixpoint(proj)
    return proj


# ---------------------------------------------------------------------------
# R007: lock-order cycles
def _lock_edges(proj: _Project):
    """{(a, b): (rel, line, note)} — first site seen for each order edge."""
    edges: dict = {}

    def add(a, b, rel, line, note):
        if a == b:
            return              # re-entry: handled by reentrancy, not order
        edges.setdefault((a, b), (rel, line, note))

    for fi in proj.fns.values():
        rel = fi.mod.mod.rel
        for lid, line, held in fi.acquires:
            for h in held:
                add(h, lid, rel, line, f"{_short(h)} → {_short(lid)}")
        for callee, line, held, _bound, _sp in fi.calls:
            if not held:
                continue
            cf = proj.fns.get(callee)
            if cf is None:
                continue
            for (lid, orel, oline) in cf.locks_in:
                for h in held:
                    add(h, lid, rel, line,
                        f"{_short(h)} → {_short(lid)} via {callee}() "
                        f"(acquired at {orel}:{oline})")
    return edges


def _short(lock_id: str) -> str:
    return lock_id.split(".", 2)[-1] if lock_id.count(".") > 2 else lock_id


def _find_cycles(edges: dict) -> list:
    """Minimal cycles as lists of (a, b) edges, one per cycle set."""
    succ: dict = {}
    for (a, b) in edges:
        succ.setdefault(a, set()).add(b)
    cycles = []
    seen_cycle_keys = set()
    for start in sorted(succ):
        # BFS back to start
        prev = {start: None}
        queue = [start]
        found = None
        while queue and found is None:
            cur = queue.pop(0)
            for nxt in sorted(succ.get(cur, ())):
                if nxt == start:
                    found = cur
                    break
                if nxt not in prev:
                    prev[nxt] = cur
                    queue.append(nxt)
        if found is None:
            continue
        path = [found]
        while prev[path[-1]] is not None:
            path.append(prev[path[-1]])
        path.reverse()              # start ... found
        nodes = [start] if path == [start] else path
        cyc = [(nodes[i], nodes[(i + 1) % len(nodes)])
               for i in range(len(nodes))]
        if len(nodes) == 1:
            continue
        key = frozenset(nodes)
        if key not in seen_cycle_keys:
            seen_cycle_keys.add(key)
            cycles.append(cyc)
    return cycles


def _check_r007(proj: _Project) -> list:
    findings = []
    edges = _lock_edges(proj)
    for cyc in _find_cycles(edges):
        sites = [edges[e] for e in cyc]
        rel, line, _ = sites[0]
        desc = " ; ".join(
            f"{_short(a)}→{_short(b)} ({edges[(a, b)][0]}:"
            f"{edges[(a, b)][1]})" for a, b in cyc)
        findings.append(Finding(
            "R007", rel, line,
            f"lock-order cycle: {desc} — two threads taking these locks "
            "in opposing order deadlock; pick one global order (or merge "
            "the critical sections)"))
    return findings


# ---------------------------------------------------------------------------
# R008: blocking while holding a lock
def _check_r008(proj: _Project) -> list:
    findings = []
    for fi in proj.fns.values():
        rel = fi.mod.mod.rel
        for desc, line, held in fi.blocking:
            if held:
                findings.append(Finding(
                    "R008", rel, line,
                    f"{desc} while holding {_short(sorted(held)[0])}: a "
                    "stall here wedges every thread touching the lock — "
                    "bound the wait (timeout=) or move it outside the "
                    "critical section"))
        for callee, line, held, bound, _sp in fi.calls:
            if not held or bound:
                continue
            cf = proj.fns.get(callee)
            if cf is None or not cf.blocks_in:
                continue
            desc, orel, oline = sorted(cf.blocks_in)[0]
            findings.append(Finding(
                "R008", rel, line,
                f"call into {callee}() while holding "
                f"{_short(sorted(held)[0])}: it reaches {desc} "
                f"({orel}:{oline}) — a stall there wedges the lock; "
                "bound the wait or hoist the call out of the critical "
                "section"))
    return findings


# ---------------------------------------------------------------------------
# R009: donated-buffer use-after-donate
def _donate_positions(call: ast.Call):
    """Donated arg positions of a jax.jit(...) call, or None if not a
    donating jit. Non-literal donate_argnums conservatively means 'all'."""
    if _terminal(call.func) not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for e in v.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, int):
                        out.add(e.value)
                return out if out else set()
            return None if isinstance(v, ast.Constant) and v.value is None \
                else {"*"}          # computed: any positional arg
    return None


def _resolved_positions(proj, fi, call, table):
    """Donate positions for an assignment RHS: a direct donating jit, or
    a call into a factory already known to `table` (any dispatch
    target)."""
    p = _donate_positions(call)
    if p:
        return p
    out = None
    for callee in proj.resolve_calls(fi.mod, fi.cls, call):
        got = table.get(callee)
        if got:
            out = (out or set()) | got
    return out


def _donating_factories(proj: _Project) -> dict:
    """{qual: positions} for functions that RETURN a donating jit —
    directly, via a local var, or via a call to another donating factory
    (fixpoint, so scorer_cache's _build → program chain resolves)."""
    out: dict = {}
    changed = True
    guard = 0
    while changed and guard < 10:
        changed = False
        guard += 1
        for fi in proj.fns.values():
            if fi.qual in out:
                continue
            # local name -> positions (assigned from jit or factory call)
            local: dict = {}
            pos = None
            for node in proj.fn_nodes(fi):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    p = _resolved_positions(proj, fi, node.value, out)
                    if p:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local[t.id] = p
                if isinstance(node, ast.Return) and node.value is not None:
                    v = node.value
                    if isinstance(v, ast.Call):
                        p = _resolved_positions(proj, fi, v, out)
                        if p:
                            pos = (pos or set()) | p
                    elif isinstance(v, ast.Name) and v.id in local:
                        pos = (pos or set()) | local[v.id]
            if pos:
                out[fi.qual] = pos
                changed = True
    return out


def _check_r009(proj: _Project) -> list:
    findings = []
    factories = _donating_factories(proj)
    for fi in proj.fns.values():
        rel = fi.mod.mod.rel
        # donating callables visible in this function body: local vars
        donating: dict = {}        # var name -> positions
        calls = []                 # (lineno, donated arg Name -> str)
        for node in proj.fn_nodes(fi):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                p = _resolved_positions(proj, fi, node.value, factories)
                if p:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            donating[t.id] = p
        if not donating:
            continue
        for node in proj.fn_nodes(fi):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in donating:
                pos = donating[node.func.id]
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) and \
                            ("*" in pos or i in pos):
                        calls.append((node.lineno, arg.id, node.func.id))
        if not calls:
            continue
        stores: dict = {}          # name -> sorted store linenos after def
        loads: dict = {}
        for node in proj.fn_nodes(fi):
            if isinstance(node, ast.Name):
                d = stores if isinstance(node.ctx, ast.Store) else loads
                d.setdefault(node.id, []).append(node.lineno)
        for call_line, buf, fname in calls:
            rebinds = [ln for ln in stores.get(buf, []) if ln > call_line]
            kill = min(rebinds) if rebinds else None
            for ln in sorted(loads.get(buf, [])):
                if ln <= call_line:
                    continue
                if kill is not None and ln > kill:
                    break
                findings.append(Finding(
                    "R009", rel, ln,
                    f"{buf!r} is read after being donated to {fname}() at "
                    f"line {call_line}: donate_argnums lets XLA alias the "
                    "buffer, so this read returns garbage — copy before "
                    "the call or drop the donation"))
                break              # one finding per donated call is enough
    return findings


# ---------------------------------------------------------------------------
# R010: thread / executor leaks
def _check_r010_module(mod: Module) -> list:
    findings = []
    parents = mod.parents()
    src = mod.source

    def _kw(call, name):
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        term = _terminal(node.func)
        chain = _chain(node.func)
        if term == "Thread" and (chain in ("Thread", "threading.Thread")
                                 or chain.endswith(".Thread")):
            d = _kw(node, "daemon")
            if isinstance(d, ast.Constant) and d.value:
                continue
            parent = parents.get(node)
            target = None
            if isinstance(parent, ast.Attribute) and parent.attr == "start":
                # Thread(...).start(): no handle survives to join
                findings.append(Finding(
                    "R010", mod.rel, node.lineno,
                    "Thread(...).start() without daemon=True and without "
                    "keeping a handle: the thread can never be joined, "
                    "and a non-daemon leak blocks interpreter exit"))
                continue
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        target = t.id
                    elif isinstance(t, ast.Attribute):
                        target = t.attr
            if target is None:
                continue            # handed elsewhere: give benefit of doubt
            if f"{target}.join" in src or f"{target}.daemon" in src:
                continue
            findings.append(Finding(
                "R010", mod.rel, node.lineno,
                f"thread {target!r} is started with neither daemon=True "
                "nor any .join() in this module: it leaks past its owner "
                "(failures vanish, exit hangs) — join it, or mark daemon "
                "with a reason"))
        elif term == "ThreadPoolExecutor":
            parent = parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            target = None
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        target = t.id
                    elif isinstance(t, ast.Attribute):
                        target = t.attr
            if target is not None and (f"{target}.shutdown" in src
                                       or f"with {target}" in src):
                continue
            findings.append(Finding(
                "R010", mod.rel, node.lineno,
                "ThreadPoolExecutor neither context-managed nor "
                ".shutdown(): worker threads outlive the work — use "
                "`with ThreadPoolExecutor(...) as pool:`"))
        elif term == "submit" and isinstance(node.func, ast.Attribute):
            parent = parents.get(node)
            if isinstance(parent, ast.Expr):
                findings.append(Finding(
                    "R010", mod.rel, node.lineno,
                    "executor .submit() with the future discarded: the "
                    "task's exception is silently lost — keep the future "
                    "and .result() it (or collect via as_completed)"))
    return findings


# ---------------------------------------------------------------------------
# R015: interprocedural host-sync taint
_DIRECT_SYNC_LEAVES = {"host_fetch", "device_get", "block_until_ready"}


def _is_explicit_sync(desc: str) -> bool:
    """device_get/host_fetch are the SANCTIONED explicit-transfer
    spelling (the ISSUE-3 fix shape, proven clean under
    jax.transfer_guard('disallow')) — on the serving dispatch path they
    are staging, not a hidden barrier. Inside a span block even an
    explicit transfer distorts the measurement, so span roots keep the
    strict check."""
    return desc.startswith(("device_get", "host_fetch"))


def _check_r015(proj: _Project) -> list:
    findings = []
    seen: set = set()
    for fi in proj.fns.values():
        rel = fi.mod.mod.rel.replace("\\", "/")
        serving_root = rel.startswith("h2o3_tpu/serving/")
        for callee, line, _held, _bound, in_span in fi.calls:
            if not (in_span or serving_root):
                continue
            cf = proj.fns.get(callee)
            if cf is None or not cf.syncs_in:
                continue
            if callee.rsplit(".", 1)[-1] in _DIRECT_SYNC_LEAVES:
                continue    # the call IS the sync: R002 flags it lexically
            syncs = cf.syncs_in
            if not in_span:
                syncs = {s for s in syncs if not _is_explicit_sync(s[0])}
            if not syncs:
                continue
            key = (fi.mod.mod.rel, line)
            if key in seen:
                continue
            seen.add(key)
            desc, orel, oline = sorted(syncs)[0]
            where = "inside a timeline.span block" if in_span \
                else "on the serving dispatch path"
            findings.append(Finding(
                "R015", fi.mod.mod.rel, line,
                f"call into {callee}() {where} reaches {desc} "
                f"({orel}:{oline}): a hidden device→host sync on an "
                "instrumented hot path — the measurement includes the "
                "transfer, and the barrier serializes the pipeline; "
                "hoist the readback out (explicit device_get at the "
                "edge), or suppress with the reason the sync IS the "
                "work"))
    return findings


# ---------------------------------------------------------------------------
# R016: replay determinism
def _check_r016(proj: _Project) -> list:
    roots = [fi.qual for fi in proj.fns.values()
             if _is_replay_root(fi, proj)]
    if not roots:
        return []
    parent: dict = {}
    work: deque = deque()
    for r in sorted(roots):
        if r not in parent:
            parent[r] = None
            work.append(r)
    while work:
        cur = work.popleft()
        cf = proj.fns.get(cur)
        if cf is None:
            continue
        for callee, _ln, _held, _bound, _sp in cf.calls:
            if callee not in parent:
                parent[callee] = cur
                work.append(callee)
    findings = []
    seen: set = set()
    for qual in parent:
        fi = proj.fns.get(qual)
        if fi is None or not fi.nondet:
            continue
        root = qual
        while parent[root] is not None:
            root = parent[root]
        via = "" if root == qual else f", reachable from {root}()"
        for desc, line in fi.nondet:
            key = (fi.mod.mod.rel, line)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "R016", fi.mod.mod.rel, line,
                f"{desc} feeds state mutation in {qual}() — broadcast-"
                f"replayed code{via}: every cloud member replays this "
                "with its OWN nondeterministic value, silently forking "
                "the replicated state the SPMD design depends on — "
                "derive the value from the replayed request, sort the "
                "iteration, or compute once on the coordinator and ship "
                "the result"))
    return findings


# ---------------------------------------------------------------------------
def check(mods: list) -> list:
    """R007–R010/R015/R016 plus the effect-lattice rules (R018/R019/
    R021, effects.py) and the flow-sensitive lifecycle rules (R022–
    R025, lifecycle.py) — all off ONE build_project() index: the
    interprocedural passes share the analyzer's single biggest cost.
    Per-rule wall time lands in engine.RULE_TIMINGS (SELF_TIMED: the
    engine's per-check timer can't see inside this shared pass)."""
    import time as _time

    from h2o3_tpu.analysis import effects as _effects
    from h2o3_tpu.analysis import engine as _engine
    timings = _engine.RULE_TIMINGS

    def _timed(key, fn, *a):
        t0 = _time.perf_counter()
        out = fn(*a)
        timings[key] = timings.get(key, 0.0) + (_time.perf_counter() - t0)
        return out

    t0 = _time.perf_counter()
    proj = build_project(mods)
    timings["callgraph:index"] = timings.get(
        "callgraph:index", 0.0) + (_time.perf_counter() - t0)
    findings = []
    findings.extend(_timed("R007", _check_r007, proj))
    findings.extend(_timed("R008", _check_r008, proj))
    findings.extend(_timed("R009", _check_r009, proj))
    t0 = _time.perf_counter()
    for mi in proj.mods:
        findings.extend(_check_r010_module(mi.mod))
    timings["R010"] = timings.get("R010", 0.0) + \
        (_time.perf_counter() - t0)
    findings.extend(_timed("R015", _check_r015, proj))
    findings.extend(_timed("R016", _check_r016, proj))
    findings.extend(_effects.check_project(proj, mods, timings))
    from h2o3_tpu.analysis import lifecycle as _lifecycle
    findings.extend(_lifecycle.check_project(proj, mods, timings))
    return findings


check.RULES = RULES | {"R018", "R019", "R021"} \
    | {"R022", "R023", "R024", "R025"}
check.SELF_TIMED = True
