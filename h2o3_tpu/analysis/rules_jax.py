"""JAX-aware rules: R001 jit-in-hot-path, R002 host-sync, R004 impure-jit.

All three rules share one observation about jax.jit's caching contract:
the trace/compile cache is keyed on the *function object*, so

  * a `jax.jit(lambda ...)` or `jax.jit(<nested def>)(...)` inside a
    function body mints a fresh function identity per call and recompiles
    every time (R001 — the exact bug class killed one-by-one in
    engine.predict_ensemble, GLM/DL _score_matrix and DataInfo.weights);
  * code lexically inside a traced function runs at TRACE time, so host
    syncs (np.asarray/.item()/.tolist()/device_get — R002) and impure
    calls (time.*/random.*/global mutation — R004) either crash on
    tracers or silently bake a trace-time value into the compiled
    program.

R002 additionally covers `timeline.span`-instrumented hot paths: a
`block_until_ready` (or float() of a jnp expression) inside a span block
is a device sync on a path we explicitly measure — it must be intentional
(suppressed with a reason) or gone.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis.engine import Finding, Module

RULES = {"R001", "R002", "R004"}

# names that wrap jax.jit (call makes a fresh jit wrapper per evaluation)
_JIT_MAKERS = {"jit", "pjit", "jit_rows", "mr_define", "guarded_jit"}
# transform entry points whose function args run under trace
_TRACED_ARG_FNS = _JIT_MAKERS | {
    "shard_map", "vmap", "pmap", "grad", "value_and_grad", "hessian",
    "jacfwd", "jacrev", "checkpoint", "remat", "custom_jvp", "custom_vjp",
    "scan", "while_loop", "fori_loop", "cond", "switch",
}
# the sanctioned fix: a code-object-keyed wrapper cache (parallel/mrtask)
_CACHED_JIT = {"cached_jit"}

_MUT_NP = {"asarray", "array"}
_NP_NAMES = {"np", "numpy", "_np", "onp"}
_HOST_SYNC_METHODS = {"item", "tolist"}
_HOST_SYNC_FNS = {"device_get", "host_fetch", "block_until_ready"}
_TIME_NAMES = {"time", "_time", "_time_mod"}
_TIME_FNS = {"time", "perf_counter", "monotonic", "process_time", "sleep"}


def _terminal_name(fn: ast.AST):
    """'jax.jit' -> 'jit'; 'jit' -> 'jit'; anything else -> None."""
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an Attribute/Name chain ('' when not a plain chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_call(node: ast.Call) -> bool:
    name = _terminal_name(node.func)
    return name in _JIT_MAKERS and name not in _CACHED_JIT


def _enclosing_function(node: ast.AST, parents: dict):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return cur
        cur = parents.get(cur)
    return None


def _decorator_is_traced(dec: ast.AST) -> bool:
    """@jax.jit, @jit, @functools.partial(jax.jit, ...), @jit_rows(...)"""
    if _terminal_name(dec) in _JIT_MAKERS:
        return True
    if isinstance(dec, ast.Call):
        if _terminal_name(dec.func) in _JIT_MAKERS:
            return True
        if _terminal_name(dec.func) == "partial" and dec.args \
                and _terminal_name(dec.args[0]) in _JIT_MAKERS:
            return True
    return False


def _traced_functions(nodes: list, parents: dict) -> set:
    """Every FunctionDef/Lambda whose body runs under jax tracing:
    jit-decorated defs, and function-valued args to jit/shard_map/vmap/
    grad/lax-control-flow calls (resolved to same-scope nested defs).
    `nodes` is the module's cached flat node list (Module.walk())."""
    traced: set = set()
    fnlike = [n for n in nodes
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda))]
    # name -> def node, per enclosing scope, for resolving jit(fn_name)
    defs_by_scope: dict = {}
    for node in fnlike:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = _enclosing_function(node, parents)
            defs_by_scope.setdefault(scope, {})[node.name] = node
            if any(_decorator_is_traced(d) for d in node.decorator_list):
                traced.add(node)
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        callee = _terminal_name(node.func)
        if callee not in _TRACED_ARG_FNS and callee not in _CACHED_JIT:
            continue
        scope = _enclosing_function(node, parents)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                traced.add(arg)
            elif isinstance(arg, ast.Name):
                # walk outward through enclosing scopes for the def
                s = scope
                while True:
                    d = defs_by_scope.get(s, {}).get(arg.id)
                    if d is not None:
                        traced.add(d)
                        break
                    if s is None:
                        break
                    s = _enclosing_function(s, parents)
    # close over nesting: a def/lambda inside a traced function traces too
    changed = True
    while changed:
        changed = False
        for node in fnlike:
            if node not in traced:
                enc = _enclosing_function(node, parents)
                if enc in traced:
                    traced.add(node)
                    changed = True
    return traced


def _in_traced(node: ast.AST, parents: dict, traced: set) -> bool:
    enc = _enclosing_function(node, parents)
    return enc in traced


def _span_blocks(nodes: list) -> list:
    """With-statements whose context manager is a timeline span() call."""
    out = []
    for node in nodes:
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Call) \
                        and _terminal_name(ctx.func) in ("span", "_span"):
                    out.append(node)
                    break
    return out


def _contains_jnp_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            if chain.startswith(("jnp.", "jax.numpy.")):
                return True
        elif isinstance(sub, ast.Attribute) and \
                _attr_chain(sub).startswith(("jnp.", "jax.numpy.")):
            return True
    return False


def check(mod: Module) -> list:
    findings: list = []
    nodes = mod.walk()
    parents = mod.parents()
    traced = _traced_functions(nodes, parents)

    for node in nodes:
        if not isinstance(node, ast.Call):
            continue

        # ---- R001: fresh jit identity per call ------------------------
        if _is_jit_call(node) and \
                _enclosing_function(node, parents) is not None:
            callee = _terminal_name(node.func)
            parent = parents.get(node)
            immediate = isinstance(parent, ast.Call) and parent.func is node
            first_lambda = bool(node.args) \
                and isinstance(node.args[0], ast.Lambda)
            if first_lambda:
                findings.append(Finding(
                    "R001", mod.rel, node.lineno,
                    f"{callee}(<lambda>) inside a function body: the "
                    "lambda is a fresh function identity per call, so "
                    "this re-traces and recompiles every invocation — "
                    "hoist to module level or use cached_jit"))
            elif immediate:
                findings.append(Finding(
                    "R001", mod.rel, node.lineno,
                    f"{callee}(...)(...) built and invoked per call: the "
                    "wrapper (and for closures the compiled program) is "
                    "rebuilt on every invocation — bind the jitted "
                    "function once at module/instance level or use "
                    "cached_jit"))

        # ---- R002: host sync under trace ------------------------------
        if _in_traced(node, parents, traced):
            fn = node.func
            term = _terminal_name(fn)
            if isinstance(fn, ast.Attribute):
                base = _attr_chain(fn.value)
                if fn.attr in _MUT_NP and base in _NP_NAMES:
                    findings.append(Finding(
                        "R002", mod.rel, node.lineno,
                        f"{base}.{fn.attr}() inside a traced function: "
                        "forces a device→host sync at trace time (or a "
                        "TracerArrayConversionError) — keep the value on "
                        "device (jnp) or move the readback outside jit"))
                elif fn.attr in _HOST_SYNC_METHODS and not node.args \
                        and not node.keywords:
                    findings.append(Finding(
                        "R002", mod.rel, node.lineno,
                        f".{fn.attr}() inside a traced function: "
                        "device→host sync at trace time — hoist out of "
                        "the jitted body"))
                elif fn.attr in _HOST_SYNC_FNS:
                    findings.append(Finding(
                        "R002", mod.rel, node.lineno,
                        f"{_attr_chain(fn) or fn.attr}() inside a traced "
                        "function: explicit host sync has no meaning "
                        "under trace — move it to the caller"))
            elif isinstance(fn, ast.Name) and term in _HOST_SYNC_FNS:
                findings.append(Finding(
                    "R002", mod.rel, node.lineno,
                    f"{term}() inside a traced function: host sync "
                    "under trace — move it to the caller"))
            elif isinstance(fn, ast.Name) and term in ("float", "int") \
                    and node.args and _contains_jnp_call(node.args[0]):
                findings.append(Finding(
                    "R002", mod.rel, node.lineno,
                    f"{term}(<jnp expression>) inside a traced function: "
                    "concretizes a tracer (device sync / TracerError) — "
                    "keep the math in jnp"))

            # ---- R004: impurity under trace ---------------------------
            chain = _attr_chain(node.func)
            root = chain.split(".", 1)[0] if chain else ""
            if root in _TIME_NAMES and term in _TIME_FNS:
                findings.append(Finding(
                    "R004", mod.rel, node.lineno,
                    f"{chain}() inside a traced function: evaluated once "
                    "at trace time and baked into the compiled program — "
                    "pass timestamps in as arguments"))
            elif chain.startswith(("random.", "np.random.",
                                   "numpy.random.")):
                findings.append(Finding(
                    "R004", mod.rel, node.lineno,
                    f"{chain}() inside a traced function: host RNG runs "
                    "at trace time (same 'random' draw replayed every "
                    "call) — use jax.random with an explicit key"))

    # R004: global-mutation capture
    for node in nodes:
        if isinstance(node, ast.Global) and _in_traced(node, parents,
                                                       traced):
            findings.append(Finding(
                "R004", mod.rel, node.lineno,
                f"global {', '.join(node.names)} inside a traced "
                "function: the mutation runs at trace time only — "
                "thread state through function arguments/outputs"))

    # R002: device syncs inside span-instrumented hot paths
    traced_lines = {f.line for f in findings}
    for block in _span_blocks(nodes):
        for node in ast.walk(block):
            if not isinstance(node, ast.Call) \
                    or node.lineno in traced_lines:
                continue
            term = _terminal_name(node.func)
            if term == "block_until_ready":
                findings.append(Finding(
                    "R002", mod.rel, node.lineno,
                    "block_until_ready inside a timeline.span block: a "
                    "device barrier on an instrumented hot path — make "
                    "it intentional (suppress with a reason) or remove"))
            elif term in ("host_fetch", "device_get"):
                findings.append(Finding(
                    "R002", mod.rel, node.lineno,
                    f"{term} inside a timeline.span block: a device→host "
                    "sync on an instrumented hot path, so the span "
                    "measures the transfer, not the work — fetch outside "
                    "the span, or suppress with the reason the sync IS "
                    "the work"))
            elif isinstance(node.func, ast.Name) \
                    and term in ("float", "int") and node.args \
                    and _contains_jnp_call(node.args[0]):
                findings.append(Finding(
                    "R002", mod.rel, node.lineno,
                    f"{term}(<jnp expression>) inside a timeline.span "
                    "block: hidden device→host sync on an instrumented "
                    "hot path — fetch once outside the span or batch "
                    "the readback"))
    return findings


check.RULES = RULES
