"""Effect-lattice rules: replicated-state integrity (R018/R019/R021).

The SPMD replay design (deploy/multihost) rests on one invariant: every
host that replays a broadcast request ends up with bit-identical
replicated state. This pass classifies every function's effect on
REPLICATED state — the DKV registry (put/remove/atomic/locks/clear),
cloud membership and ring placement — as opposed to HOST-LOCAL state
(metrics, timeline, flight recorder, logs, spill files, caches), and
closes the classification to a fixpoint over the ISSUE-12 callgraph so
the obligation survives arbitrarily deep call chains and dynamic
dispatch.

  * R018 — coordinator-only mutation: a handler on a NON-broadcast route
    (the replay-exempt set: static Flow assets, the observability
    endpoints, /3/PostFile, /3/ParseDistributed — extracted from the
    server's own `_is_static_path`/`_is_obs_path`/`_dispatch_routed`
    predicates, never hand-listed here) that transitively mutates
    replicated state. The mutation lands only on the coordinator; every
    worker's replica silently diverges. Intentional coordinator-only
    control surfaces (cloud drain) carry a reasoned `# h2o3-ok: R018`.
  * R019 — host-divergence consumption: broadcast-replayed code that
    feeds a host-identity source (pid, hostname, platform, direct
    environ reads outside the R017 census accessors) into replicated
    state — generalizing R016's intraprocedural wall-clock rule to the
    full callgraph: a replayed function storing the RESULT of a helper
    that returns pid/uuid/wall-clock-derived values is flagged even
    though the source call is modules away. `utils/env.py` and
    `utils/config.py` are the censused config layer (deployment-uniform
    by the R017 contract) and are exempt as sources; writes inside
    host-local modules (obs/, utils/log, io/spill, analysis/) are
    host-local by classification and never flagged.
  * R021 — npz wire-format pairing: within a module that both writes
    (np.savez) and reads (np.load) npz payloads, every key a reader
    requires must be produced by some writer and every statically-known
    writer key must be consumed by some reader. Dict-literal key sets
    and `"k" in z.files` guards are understood; f-string/dynamic keys
    make a site open (satisfies/consumes everything). This is the
    static face of the chunk byte-plane contract (io/spill, the DKV
    re-home `_plane_payload`/`_plane_restore` pair, the dparse string
    planes).

All three run on the ONE `_Project` built by callgraph.check — the
analyzer's wall-time budget (tier-1 asserts < 2x the ISSUE-12 baseline)
cannot afford a second interprocedural index.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from h2o3_tpu.analysis import callgraph as _cg
from h2o3_tpu.analysis.engine import Finding

RULES = {"R018", "R019", "R021"}

# replicated-state mutation vocabulary: the DKV registry and the
# membership/ring state machine. Receiver-chain matching catches the
# call SITES (DKV.put, self.membership.leave, MEMBERSHIP.excise);
# resolved-callee matching catches calls that land ON those methods
# through aliases the chain text can't see.
_DKV_MUTATORS = {"put", "remove", "atomic", "write_lock", "unlock",
                 "clear", "set_membership"}
_MEMB_MUTATORS = {"register", "excise", "leave", "join", "start_drain",
                  "reset"}

# modules whose writes are HOST-LOCAL by effect classification —
# observability rings, log segments, spill files, the analyzer itself.
# A divergent value landing here is per-host telemetry, not a fork.
_HOST_LOCAL_PREFIXES = ("h2o3_tpu/obs/", "h2o3_tpu/utils/log",
                        "h2o3_tpu/io/spill", "h2o3_tpu/analysis/")

# the R017-censused config layer: env reads routed through these typed
# accessors are deployment-uniform by contract, so they are NOT
# host-divergence sources (a raw os.environ.get anywhere else is)
_UNIFORM_ENV_MODULES = ("utils/env.py", "utils/config.py")

# untyped-receiver seam for the broadcaster surface: `bc =
# getattr(self.server, "broadcaster", None); bc.drain(...)` — the names
# are public so the generic duck resolver punts, but a receiver SPELLED
# bc/broadcaster with one of these methods is the replay channel.
# `collect` is deliberately NOT a seam: it is the read side, and the
# dead-peer excision its failure detector may perform is the liveness
# protocol's own (coordinator-owned by design) — routing it into the
# closure would flag every observability handler that polls workers.
_BC_SEAM_METHODS = {"drain", "broadcast"}

# functions whose bodies define the replay-exempt route set
_EXEMPT_PREDICATE_FNS = {"_is_static_path", "_is_obs_path",
                         "_dispatch_routed"}

_REGEX_META = re.compile(r"[\\\[\](){}?*+|^$.]")


# ---------------------------------------------------------------------------
# effect edges: fi.calls plus function-local imports and the bc seam
def _effect_edges(proj) -> dict:
    """{qual: {(callee_qual, line)}} — the callgraph's resolved calls,
    augmented with (a) calls resolved through FUNCTION-LOCAL imports
    (the `def work(): from ... import dparse` closure shape nested in
    job-starting handlers) and (b) the broadcaster seam above. Nested
    function defs are attributed to their enclosing def: a handler that
    schedules `work` onto a Job owns work's effects."""
    edges: dict = {}
    for qual, fi in proj.fns.items():
        es = {(c, ln) for c, ln, _h, _b, _s in fi.calls}
        nodes = proj.fn_nodes(fi)
        local_imports: dict = {}
        for n in nodes:
            if isinstance(n, ast.Import):
                for a in n.names:
                    local_imports[a.asname or a.name.split(".")[0]] = \
                        (a.name, None)
            elif isinstance(n, ast.ImportFrom) and n.module \
                    and n.level == 0:
                for a in n.names:
                    local_imports[a.asname or a.name] = (n.module, a.name)
        if local_imports:
            mi2 = dataclasses.replace(
                fi.mod, imports={**fi.mod.imports, **local_imports})
            ltypes = _cg._local_ctor_types(fi, proj)
            for n in nodes:
                if isinstance(n, ast.Call):
                    for tgt in proj.resolve_calls(mi2, fi.cls, n, ltypes):
                        es.add((tgt, n.lineno))
        for n in nodes:
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _BC_SEAM_METHODS):
                continue
            recv = _cg._chain(n.func.value)
            root = recv.split(".", 1)[0] if recv else ""
            if root in ("bc", "broadcaster") or "broadcaster" in recv:
                for _cq, (ci, _mi) in proj.classes.items():
                    if "Broadcaster" in ci.name \
                            and n.func.attr in ci.methods:
                        es.add((ci.methods[n.func.attr], n.lineno))
        edges[qual] = es
    return edges


def _calls_by_line(edges: dict) -> dict:
    """{qual: {line: {callee}}} — taint passes resolve Call nodes by
    line instead of re-running symbol resolution per node."""
    out: dict = {}
    for qual, es in edges.items():
        by = out.setdefault(qual, {})
        for callee, ln in es:
            by.setdefault(ln, set()).add(callee)
    return out


# ---------------------------------------------------------------------------
# the replicated-effect closure
def _replicated_sites(fi, proj) -> list:
    """(desc, line) direct replicated-state mutation sites in this
    function, by receiver-chain vocabulary."""
    out = []
    for n in proj.fn_nodes(fi):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)):
            continue
        recv = _cg._chain(n.func.value)
        if not recv:
            continue
        low = recv.lower()
        if n.func.attr in _DKV_MUTATORS and "dkv" in low:
            out.append((f"{recv}.{n.func.attr}()", n.lineno))
        elif n.func.attr in _MEMB_MUTATORS and "membership" in low:
            out.append((f"{recv}.{n.func.attr}()", n.lineno))
    return out


def _is_mutator_method(callee: str, proj) -> bool:
    """A resolved callee that IS a DKV/Membership mutator method — the
    leaf the chain vocabulary can't see (inside _DKV.put the receiver is
    `self._store`, not `DKV`)."""
    cf = proj.fns.get(callee)
    if cf is None or not cf.cls:
        return False
    meth = callee.rsplit(".", 1)[-1]
    return ("DKV" in cf.cls and meth in _DKV_MUTATORS) or \
        (cf.cls == "Membership" and meth in _MEMB_MUTATORS)


def effect_closure(proj, edges: dict) -> dict:
    """{qual: {(desc, rel, line)}} — every replicated-state mutation
    site reachable from each function (the effect lattice, closed to a
    fixpoint over the augmented callgraph)."""
    closure: dict = {}
    for qual, fi in proj.fns.items():
        rel = fi.mod.mod.rel
        sites = {(d, rel, ln) for d, ln in _replicated_sites(fi, proj)}
        for callee, ln in edges.get(qual, ()):
            if _is_mutator_method(callee, proj):
                cf = proj.fns[callee]
                sites.add((f"{cf.cls}.{callee.rsplit('.', 1)[-1]}()",
                           rel, ln))
        closure[qual] = sites
    changed, guard = True, 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for qual, es in edges.items():
            cur = closure[qual]
            before = len(cur)
            for callee, _ln in es:
                cs = closure.get(callee)
                if cs:
                    cur |= cs
            if len(cur) != before:
                changed = True
    return closure


# ---------------------------------------------------------------------------
# R018: coordinator-only mutation through replay-exempt routes
def _route_rows(proj) -> list:
    """(pattern_literal, METHOD, handler_qual) for every route-table row
    — like callgraph._route_handlers but KEEPING the pattern literal and
    GET rows: in this repo GETs broadcast too, so the non-replayed set
    is purely the path-exempt one."""
    rows = []
    for mi in proj.mods:
        compile_aliases = {"compile"}
        for node in mi.mod.walk():
            if isinstance(node, ast.Assign) and \
                    _cg._chain(node.value) in ("re.compile", "compile"):
                compile_aliases.update(t.id for t in node.targets
                                       if isinstance(t, ast.Name))
        for node in mi.mod.walk():
            if not (isinstance(node, ast.Tuple) and len(node.elts) == 3):
                continue
            pat, meth, ref = node.elts
            if not (isinstance(pat, ast.Call)
                    and _cg._terminal(pat.func) in compile_aliases
                    and pat.args
                    and isinstance(pat.args[0], ast.Constant)
                    and isinstance(pat.args[0].value, str)):
                continue
            if not (isinstance(meth, ast.Constant)
                    and isinstance(meth.value, str)):
                continue
            t = _cg._terminal(ref)
            if t is None:
                continue
            qual = None
            if t in mi.defs:
                qual = mi.defs[t]
            else:
                for ci in mi.classes.values():
                    if t in ci.methods:
                        qual = ci.methods[t]
                        break
            if qual is not None:
                rows.append((pat.args[0].value, meth.value.upper(), qual))
    return rows


def _exempt_specs(proj):
    """(exact_paths, path_prefixes) recovered from the server's own
    predicate functions — `path == "..."`, `path in (...)` and
    `path.startswith("...")` shapes inside _is_static_path /
    _is_obs_path / _dispatch_routed. Extracting instead of hand-listing
    keeps the rule honest when the exempt set changes."""
    exact: set = set()
    prefixes: set = set()
    for fi in proj.fns.values():
        if getattr(fi.node, "name", "") not in _EXEMPT_PREDICATE_FNS:
            continue
        for n in proj.fn_nodes(fi):
            if isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.left, ast.Name) \
                    and n.left.id == "path":
                cmp = n.comparators[0]
                if isinstance(n.ops[0], ast.Eq) \
                        and isinstance(cmp, ast.Constant) \
                        and isinstance(cmp.value, str):
                    exact.add(cmp.value)
                elif isinstance(n.ops[0], ast.In) \
                        and isinstance(cmp, (ast.Tuple, ast.List,
                                             ast.Set)):
                    exact.update(e.value for e in cmp.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str))
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "startswith" \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == "path" and n.args:
                a = n.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value,
                                                              str):
                    prefixes.add(a.value)
                elif isinstance(a, ast.Tuple):
                    prefixes.update(e.value for e in a.elts
                                    if isinstance(e, ast.Constant)
                                    and isinstance(e.value, str))
    return exact, prefixes


def _literal_prefix(pattern: str):
    """(literal_prefix, is_fully_literal) of a route regex."""
    m = _REGEX_META.search(pattern)
    if m is None:
        return pattern, True
    return pattern[:m.start()], False


def _is_exempt_route(pattern: str, exact: set, prefixes: set) -> bool:
    lit, full = _literal_prefix(pattern)
    if full and lit in exact:
        return True
    return any(lit.startswith(p) for p in prefixes if p)


def _check_r018(proj, edges: dict, closure: dict) -> list:
    exact, prefixes = _exempt_specs(proj)
    if not exact and not prefixes:
        return []      # no replay layer in this project: nothing exempt
    findings = []
    seen: set = set()
    for patlit, method, qual in _route_rows(proj):
        if not _is_exempt_route(patlit, exact, prefixes):
            continue
        fi = proj.fns.get(qual)
        if fi is None or qual in seen:
            continue
        sites = closure.get(qual)
        if not sites:
            continue
        seen.add(qual)
        desc, orel, oline = sorted(sites)[0]
        findings.append(Finding(
            "R018", fi.mod.mod.rel, fi.node.lineno,
            f"{method} {patlit} is replay-EXEMPT (static/obs/"
            f"non-broadcast path) but its handler transitively mutates "
            f"replicated state via {desc} ({orel}:{oline}): the "
            "mutation lands only on the coordinator, silently forking "
            "every worker's replica — route it through a broadcast-"
            "replayed endpoint, or suppress with the reason the "
            "coordinator-only effect is the design"))
    return findings


# ---------------------------------------------------------------------------
# R019: host-divergence sources into replicated state (interprocedural)
_HOST_ID_CALLS = {
    "os.getpid", "os.getppid", "os.uname",
    "socket.gethostname", "socket.getfqdn", "socket.gethostbyname",
    "platform.node", "platform.uname", "platform.platform",
    "platform.machine", "platform.system", "platform.release",
}


def _div_desc(call: ast.Call):
    """Host-identity source vocabulary (the R019 direct sources).
    Wall-clock/random/uuid are R016's lexical vocabulary — R019 adds
    them only through the RETURN-propagation below, so one site is never
    double-flagged by both rules."""
    chain = _cg._chain(call.func)
    if chain in _HOST_ID_CALLS:
        return f"{chain}()"
    if chain.endswith("environ.get") or chain in ("os.getenv", "getenv"):
        return f"{chain}()"
    return None


def _div_or_nondet_desc(call: ast.Call):
    return _div_desc(call) or _cg._nondet_desc(call)


def _is_uniform_env_module(rel: str) -> bool:
    return rel.replace("\\", "/").endswith(_UNIFORM_ENV_MODULES)


def _divergent_returners(proj, by_line: dict) -> dict:
    """{qual: desc} for functions whose RETURN value derives from a
    host-divergence (or nondeterminism) source — directly or through a
    call to another marked function. The censused config accessors are
    exempt: their reads are deployment-uniform by the R017 contract."""
    marked: dict = {}

    def returns_divergent(fi, qual):
        nodes = proj.fn_nodes(fi)
        lines = by_line.get(qual, {})
        tainted: dict = {}

        def node_div(e):
            for sub in ast.walk(e):
                if isinstance(sub, ast.Call):
                    d = _div_or_nondet_desc(sub)
                    if d is not None:
                        return d
                    for tgt in lines.get(sub.lineno, ()):
                        if tgt in marked:
                            return f"{tgt}() [{marked[tgt]}]"
                elif isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load) \
                        and sub.id in tainted:
                    return tainted[sub.id]
            return None

        assigns = [n for n in nodes
                   if isinstance(n, (ast.Assign, ast.AnnAssign))]
        for _ in range(4):
            changed = False
            for a in assigns:
                v = getattr(a, "value", None)
                if v is None:
                    continue
                d = node_div(v)
                if d is None:
                    continue
                tgts = a.targets if isinstance(a, ast.Assign) \
                    else [a.target]
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id not in tainted:
                        tainted[t.id] = d
                        changed = True
            if not changed:
                break
        for n in nodes:
            if isinstance(n, ast.Return) and n.value is not None:
                d = node_div(n.value)
                if d is not None:
                    return d
        return None

    changed, guard = True, 0
    while changed and guard < 20:
        changed = False
        guard += 1
        for qual, fi in proj.fns.items():
            if qual in marked or _is_uniform_env_module(fi.mod.mod.rel):
                continue
            d = returns_divergent(fi, qual)
            if d is not None:
                marked[qual] = d
                changed = True
    return marked


def _replay_reach(proj, edges: dict) -> dict:
    """{qual: parent_or_None} BFS over the augmented edges from the
    replay roots (R016's root set) — parent pointers give the root
    attribution the finding message names."""
    roots = sorted(fi.qual for fi in proj.fns.values()
                   if _cg._is_replay_root(fi, proj))
    parent: dict = {}
    work = []
    for r in roots:
        if r not in parent:
            parent[r] = None
            work.append(r)
    while work:
        cur = work.pop()
        for callee, _ln in edges.get(cur, ()):
            if callee not in parent and callee in proj.fns:
                parent[callee] = cur
                work.append(callee)
    return parent


def _check_r019(proj, edges: dict, by_line: dict) -> list:
    marked = _divergent_returners(proj, by_line)
    parent = _replay_reach(proj, edges)
    findings = []
    seen: set = set()
    for qual in parent:
        fi = proj.fns.get(qual)
        if fi is None:
            continue
        rel = fi.mod.mod.rel.replace("\\", "/")
        if rel.startswith(_HOST_LOCAL_PREFIXES) \
                or _is_uniform_env_module(rel):
            continue
        lines = by_line.get(qual, {})

        def call_desc(call, _lines=lines):
            for tgt in _lines.get(call.lineno, ()):
                if tgt in marked:
                    return f"a value returned by {tgt}() [{marked[tgt]}]"
            return None

        sites = _cg._nondet_mutations(
            fi, proj.fn_nodes(fi), desc_fn=_div_desc,
            call_desc=call_desc, include_set_iteration=False)
        if not sites:
            continue
        root = qual
        while parent[root] is not None:
            root = parent[root]
        via = "" if root == qual else f", reachable from {root}()"
        for desc, line in sites:
            key = (fi.mod.mod.rel, line)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "R019", fi.mod.mod.rel, line,
                f"{desc} feeds replicated-state mutation in {qual}() — "
                f"broadcast-replayed code{via}: every cloud member "
                "replays this with its OWN host identity, silently "
                "forking the replicated state — derive the value from "
                "the replayed request, read config through the censused "
                "accessors, or compute once on the coordinator and ship "
                "the result"))
    return findings


# ---------------------------------------------------------------------------
# R021: npz wire-format pairing
class _NpzSite:
    __slots__ = ("line", "required", "optional", "dynamic")

    def __init__(self, line, required=(), optional=(), dynamic=False):
        self.line = line
        self.required = set(required)
        self.optional = set(optional)
        self.dynamic = dynamic

    @property
    def keys(self):
        return self.required | self.optional


_SAVEZ_CHAINS = {"np.savez", "np.savez_compressed", "numpy.savez",
                 "numpy.savez_compressed", "onp.savez",
                 "onp.savez_compressed"}
_LOAD_CHAINS = {"np.load", "numpy.load", "onp.load"}


def _npz_scope_sites(scope_nodes: list):
    """(writers, readers) for one function scope."""
    dict_static: dict = {}
    dict_opt: dict = {}
    dict_dyn: set = set()
    npz_vars: set = set()
    # pass 1: dict-literal tracking and np.load vars
    for n in scope_nodes:
        if isinstance(n, ast.Assign):
            v = n.value
            if isinstance(v, ast.Dict):
                keys, dyn = set(), False
                for k in v.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        keys.add(k.value)
                    else:
                        dyn = True
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        dict_static[t.id] = keys
                        if dyn:
                            dict_dyn.add(t.id)
            elif isinstance(v, (ast.DictComp,)):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        dict_static.setdefault(t.id, set())
                        dict_dyn.add(t.id)
            elif isinstance(v, ast.Call) \
                    and _cg._chain(v.func) in _LOAD_CHAINS:
                npz_vars.update(t.id for t in n.targets
                                if isinstance(t, ast.Name))
            # dict_var["k"] = ... — a conditionally-added (optional) key
            for t in n.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in dict_static:
                    s = t.slice
                    if isinstance(s, ast.Constant) \
                            and isinstance(s.value, str):
                        dict_opt.setdefault(t.value.id, set()).add(s.value)
                    else:
                        dict_dyn.add(t.value.id)
        elif isinstance(n, ast.With):
            for item in n.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Call) \
                        and _cg._chain(ctx.func) in _LOAD_CHAINS \
                        and isinstance(item.optional_vars, ast.Name):
                    npz_vars.add(item.optional_vars.id)
    # pass 2: writer sites
    writers = []
    for n in scope_nodes:
        if not (isinstance(n, ast.Call)
                and _cg._chain(n.func) in _SAVEZ_CHAINS):
            continue
        site = _NpzSite(n.lineno)
        saw_keys = False
        for kw in n.keywords:
            if kw.arg is not None:
                site.required.add(kw.arg)
                saw_keys = True
            elif isinstance(kw.value, ast.Name) \
                    and kw.value.id in dict_static:
                site.required |= dict_static[kw.value.id]
                site.optional |= dict_opt.get(kw.value.id, set())
                if kw.value.id in dict_dyn:
                    site.dynamic = True
                saw_keys = True
            else:
                site.dynamic = True      # **<untracked> — open format
                saw_keys = True
        if not saw_keys:
            site.dynamic = True          # positional arrays → arr_0...
        writers.append(site)
    # pass 3: reader sites (one per np.load var in this scope)
    readers = []
    for var in sorted(npz_vars):
        site = None
        subs: set = set()
        opts: set = set()
        dynamic = False
        for n in scope_nodes:
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == var \
                    and isinstance(n.ctx, ast.Load):
                if site is None or n.lineno < site:
                    site = n.lineno
                s = n.slice
                if isinstance(s, ast.Constant) \
                        and isinstance(s.value, str):
                    subs.add(s.value)
                else:
                    dynamic = True
            elif isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.ops[0], ast.In) \
                    and isinstance(n.left, ast.Constant) \
                    and isinstance(n.left.value, str):
                cmp = n.comparators[0]
                if isinstance(cmp, ast.Attribute) \
                        and cmp.attr == "files" \
                        and isinstance(cmp.value, ast.Name) \
                        and cmp.value.id == var:
                    opts.add(n.left.value)
            elif isinstance(n, ast.Attribute) and n.attr == "files" \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == var:
                # .files used outside a membership guard: iteration /
                # len() — the reader consumes whatever the writer wrote
                dynamic = True
        if site is None and not opts and not dynamic:
            continue
        # a guarded subscript (z["mask"] if "mask" in z.files ...) is
        # optional, not required — membership checks win
        membership_guarded = {o for o in opts}
        # an un-guarded .files sighting that ALSO appears as a guard is
        # not dynamic; re-check: Compare comparators were walked above as
        # plain Attributes too, so subtract guard sightings
        if dynamic and opts and not subs - opts:
            dynamic = len(opts) == 0
        readers.append(_NpzSite(site or 0,
                                required=subs - membership_guarded,
                                optional=opts, dynamic=dynamic))
    return writers, readers


def _check_r021(mods: list) -> list:
    findings = []
    for mod in mods:
        writers: list = []
        readers: list = []
        scopes = [n for n in mod.walk()
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        for fn in scopes:
            w, r = _npz_scope_sites(list(ast.walk(fn)))
            writers.extend(w)
            readers.extend(r)
        # dedupe (nested defs are walked by their parents too)
        writers = list({w.line: w for w in writers}.values())
        readers = list({r.line: r for r in readers}.values())
        if not writers or not readers:
            continue    # cross-module formats pair elsewhere: skip
        for r in readers:
            if r.dynamic:
                continue
            for k in sorted(r.required | r.optional):
                if any(w.dynamic or k in w.keys for w in writers):
                    continue
                what = "requires" if k in r.required else \
                    "probes optional"
                findings.append(Finding(
                    "R021", mod.rel, r.line,
                    f"npz reader {what} key {k!r} that no writer in "
                    "this module produces — wire-format drift: the "
                    "writer and reader of one payload format must agree "
                    "on the plane/key set (add the plane to the writer, "
                    "or drop the dead read)"))
        for w in writers:
            if w.dynamic:
                continue
            for k in sorted(w.keys):
                if any(r.dynamic or k in r.keys for r in readers):
                    continue
                findings.append(Finding(
                    "R021", mod.rel, w.line,
                    f"npz writer produces key {k!r} that no reader in "
                    "this module consumes — wire-format drift: either "
                    "the reader silently ignores a plane the writer "
                    "pays to serialize, or the matching read was lost"))
    return findings


# ---------------------------------------------------------------------------
def check_project(proj, mods: list, timings: dict = None) -> list:
    """Run R018/R019/R021 on the shared project. Called from
    callgraph.check so the interprocedural index is built ONCE."""
    import time as _time
    findings = []
    t0 = _time.perf_counter()
    edges = _effect_edges(proj)
    by_line = _calls_by_line(edges)
    closure = effect_closure(proj, edges)
    if timings is not None:
        timings["effects:closure"] = timings.get(
            "effects:closure", 0.0) + (_time.perf_counter() - t0)
    for rule, fn in (("R018", lambda: _check_r018(proj, edges, closure)),
                     ("R019", lambda: _check_r019(proj, edges, by_line)),
                     ("R021", lambda: _check_r021(mods))):
        t0 = _time.perf_counter()
        findings.extend(fn())
        if timings is not None:
            timings[rule] = timings.get(rule, 0.0) + \
                (_time.perf_counter() - t0)
    return findings
