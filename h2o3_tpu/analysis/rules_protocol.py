"""R020 replay-channel protocol census + the generated PROTOCOL.md.

The coordinator⇄worker wire protocol has two op namespaces riding the
same frame stream as broadcasts: COLLECT ops (`bc.collect("metrics")` →
worker `_collect_local(op)` → data in the ack) and CONTROL frames
(`{"seq": -1, "op": "leave"}` — the drain handshake). Both sides are
plain string matching in separate files (`deploy/multihost.py` sends
and handles, `deploy/membership.py` extends both) — exactly the drift
shape R006 already gates for REST routes: an op renamed on one side
compiles fine and fails at runtime as a timeout or an
`{"error": "unknown op"}` ack.

R020 therefore enforces, project-wide:

  * every op NAME the coordinator sends — a string literal (or literal
    prefix of an f-string/concat, for the parameterized `trace:<id>` /
    `logs:search:<q>` families) reaching `X.collect(...)`, or the
    literal `"op"` value of a control-frame dict that also carries a
    `"seq"` key — must have a worker-side match: an `op == "..."` /
    `op in (...)` / `op.startswith("...")` arm inside a
    `_collect_local` body, or a `msg.get("op") == "..."` /
    `msg["op"] == "..."` dispatch test anywhere;
  * and vice versa: a handler arm whose op no coordinator ever sends is
    dead protocol — either the send was renamed (the live bug) or the
    arm should be deleted.

Ops with computed names (a variable reaching collect()) are
passthroughs, not declarations, and are skipped. The census of the
matched protocol is committed as `h2o3_tpu/deploy/PROTOCOL.md`
(`python -m h2o3_tpu.analysis --write-census`) and freshness-gated in
pre-commit/tier-1 exactly like the metric/span/env censuses.
"""

from __future__ import annotations

import ast

from h2o3_tpu.analysis import callgraph as _cg
from h2o3_tpu.analysis.engine import Finding

RULES = {"R020"}

_HANDLER_FNS = {"_collect_local"}


def _enclosing_fn(mod, node) -> str:
    parents = mod.parents()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
        cur = parents.get(cur)
    return "<module>"


def _op_literals(node: ast.AST) -> list:
    """[(text, kind)] for an op-name expression: a full literal is
    exact; an f-string or `"p:" + x` concat with a literal head declares
    the prefix family; a conditional contributes both branches. Anything
    else is a computed passthrough → empty."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, "exact")]
    if isinstance(node, ast.JoinedStr) and node.values \
            and isinstance(node.values[0], ast.Constant) \
            and isinstance(node.values[0].value, str) \
            and node.values[0].value:
        return [(node.values[0].value, "prefix")]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add) \
            and isinstance(node.left, ast.Constant) \
            and isinstance(node.left.value, str):
        return [(node.left.value, "prefix")]
    if isinstance(node, ast.IfExp):
        return _op_literals(node.body) + _op_literals(node.orelse)
    return []


def _name_op_literals(mod, call, name: str) -> list:
    """Resolve `op = "logs:search:" + q; bc.collect(op)` — the repo's
    idiomatic send shape: union every literal-able assignment to `name`
    in the ENCLOSING function scope of the collect call."""
    parents = mod.parents()
    scope = parents.get(call)
    while scope is not None and not isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        scope = parents.get(scope)
    if scope is None:
        return []
    out = []
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in n.targets):
            out.extend(_op_literals(n.value))
    return out


def _msg_op_expr(node: ast.AST) -> bool:
    """msg.get("op") / msg["op"] — the control-dispatch accessor."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value == "op":
        return True
    if isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and node.slice.value == "op":
        return True
    return False


def collect(mods: list):
    """(sent, handled): lists of {op, kind, file, line, fn} entries.
    kind is exact|prefix for collect ops, control for control frames."""
    sent: list = []
    handled: list = []
    for mod in mods:
        handler_fns = [n for n in mod.walk()
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and n.name in _HANDLER_FNS]
        handler_nodes = {id(sub) for fn in handler_fns
                         for sub in ast.walk(fn)}
        for node in mod.walk():
            # ---- coordinator sends ---------------------------------------
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "collect" and node.args:
                recv = _cg._chain(node.func.value)
                root = recv.split(".", 1)[0] if recv else ""
                if root and root not in _cg._EXTERNAL_ROOTS:
                    arg = node.args[0]
                    lits = _op_literals(arg)
                    if not lits and isinstance(arg, ast.Name):
                        lits = _name_op_literals(mod, node, arg.id)
                    sent.extend({"op": op, "kind": kind,
                                 "file": mod.rel, "line": node.lineno,
                                 "fn": _enclosing_fn(mod, node)}
                                for op, kind in lits)
            if isinstance(node, ast.Dict):
                keys = {k.value for k in node.keys
                        if isinstance(k, ast.Constant)}
                if "seq" in keys and "op" in keys:
                    for k, v in zip(node.keys, node.values):
                        if isinstance(k, ast.Constant) \
                                and k.value == "op" \
                                and isinstance(v, ast.Constant) \
                                and isinstance(v.value, str):
                            sent.append({"op": v.value, "kind": "control",
                                         "file": mod.rel,
                                         "line": node.lineno,
                                         "fn": _enclosing_fn(mod, node)})
            # ---- worker handlers -----------------------------------------
            in_handler = id(node) in handler_nodes
            if isinstance(node, ast.Compare) and len(node.ops) == 1:
                left, cmp = node.left, node.comparators[0]
                is_op_name = isinstance(left, ast.Name) \
                    and left.id == "op" and in_handler
                if is_op_name or _msg_op_expr(left):
                    if isinstance(node.ops[0], ast.Eq) \
                            and isinstance(cmp, ast.Constant) \
                            and isinstance(cmp.value, str):
                        handled.append(
                            {"op": cmp.value, "kind": "exact",
                             "file": mod.rel, "line": node.lineno,
                             "fn": _enclosing_fn(mod, node)})
                    elif isinstance(node.ops[0], ast.In) \
                            and isinstance(cmp, (ast.Tuple, ast.List,
                                                 ast.Set)):
                        handled.extend(
                            {"op": e.value, "kind": "exact",
                             "file": mod.rel, "line": node.lineno,
                             "fn": _enclosing_fn(mod, node)}
                            for e in cmp.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "startswith" and node.args \
                    and in_handler \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "op":
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    handled.append({"op": a.value, "kind": "prefix",
                                    "file": mod.rel, "line": node.lineno,
                                    "fn": _enclosing_fn(mod, node)})
                elif isinstance(a, ast.Tuple):
                    handled.extend({"op": e.value, "kind": "prefix",
                                    "file": mod.rel, "line": node.lineno,
                                    "fn": _enclosing_fn(mod, node)}
                                   for e in a.elts
                                   if isinstance(e, ast.Constant)
                                   and isinstance(e.value, str))
    return sent, handled


def _send_matched(s: dict, handled: list) -> bool:
    for h in handled:
        if h["kind"] == "exact":
            if s["kind"] in ("exact", "control") and s["op"] == h["op"]:
                return True
            if s["kind"] == "prefix" and h["op"].startswith(s["op"]):
                return True
        else:                                       # handled prefix
            if s["op"].startswith(h["op"]) or h["op"].startswith(s["op"]):
                return True
    return False


def _handler_matched(h: dict, sent: list) -> bool:
    for s in sent:
        if h["kind"] == "exact":
            if s["kind"] in ("exact", "control") and s["op"] == h["op"]:
                return True
            if s["kind"] == "prefix" and h["op"].startswith(s["op"]):
                return True
        else:
            if s["op"].startswith(h["op"]) or h["op"].startswith(s["op"]):
                return True
    return False


def _is_protocol_project(mods: list) -> bool:
    """Pairing needs both endpoints in the analyzed set — a scoped run
    over one file must not call every send unhandled."""
    sent, handled = collect(mods)
    return bool(sent) and bool(handled)


def check(mods: list) -> list:
    sent, handled = collect(mods)
    if not sent or not handled:
        return []           # one endpoint out of scope: cannot pair
    findings = []
    seen: set = set()
    for s in sent:
        if _send_matched(s, handled):
            continue
        key = (s["file"], s["line"], s["op"])
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            "R020", s["file"], s["line"],
            f"replay-channel {s['kind']} op {s['op']!r} sent by "
            f"{s['fn']}() has no worker-side handler arm "
            "(_collect_local / control dispatch): protocol drift — the "
            "worker acks an error or times out at runtime; add the "
            "handler arm or fix the renamed op"))
    for h in handled:
        if _handler_matched(h, sent):
            continue
        key = (h["file"], h["line"], h["op"])
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            "R020", h["file"], h["line"],
            f"worker-side handler arm for {h['kind']} op {h['op']!r} "
            f"in {h['fn']}() that no coordinator code ever sends: dead "
            "protocol — the send was renamed out from under it, or the "
            "arm should be deleted"))
    return findings


check.RULES = RULES


def census_markdown(mods: list) -> str:
    """The committed h2o3_tpu/deploy/PROTOCOL.md body. Sites are
    `file (function)` — content-addressed, no line numbers, so pure
    line-shift edits leave the census byte-identical."""
    sent, handled = collect(mods)
    ops: dict = {}
    for s in sent:
        e = ops.setdefault((s["op"], s["kind"]),
                           {"sent": set(), "handled": set()})
        e["sent"].add(f"{s['file']} ({s['fn']})")
    for h in handled:
        # fold a handler into every sent family it serves; standalone
        # handlers (none today — they'd be R020 findings) get own rows
        matched = False
        for (op, kind), e in ops.items():
            fake = {"op": op, "kind": kind}
            if _send_matched(fake, [h]):
                e["handled"].add(f"{h['file']} ({h['fn']})")
                matched = True
        if not matched:
            e = ops.setdefault((h["op"], h["kind"]),
                               {"sent": set(), "handled": set()})
            e["handled"].add(f"{h['file']} ({h['fn']})")
    lines = [
        "# Replay-channel protocol census — generated, do not edit",
        "",
        "Generated by `python -m h2o3_tpu.analysis --write-census`; the",
        "R020 rule keeps this honest (every op the coordinator sends has",
        "a worker-side handler arm and vice versa). `prefix` ops are",
        "parameterized families (`trace:<id>`). Regenerate after adding,",
        "renaming or retiring an op.",
        "",
        "| op | kind | sent from | handled in |",
        "|---|---|---|---|",
    ]
    for (op, kind) in sorted(ops):
        e = ops[(op, kind)]
        lines.append(
            f"| `{op}` | {kind} | "
            f"{'; '.join(sorted(e['sent'])) or '—'} | "
            f"{'; '.join(sorted(e['handled'])) or '—'} |")
    lines.append("")
    lines.append(f"{len(ops)} ops.")
    return "\n".join(lines) + "\n"
