"""Runtime sanitizers — dynamic counterparts of the static rules.

Static analysis proves the code doesn't *write* a stray host↔device
transfer; the transfer guard proves the runtime doesn't *do* one. The two
compose: R002 keeps `np.asarray`-style implicit syncs out of hot paths,
and `transfer_guard("disallow")` makes any survivor raise instead of
silently eating PCIe/ICI bandwidth. The warm-cache scoring path is held
to exactly this standard in tier-1 (tests/test_static_analysis.py): every
transfer it performs is explicit (`device_put` staging in,
`jax.device_get` results out), so the whole warm request runs under
`disallow`.

Env gates (read by install_from_env, called at server start):
  H2O3_DEBUG_NANS=1          jax_debug_nans — every jitted function
                             re-runs un-jitted on NaN output and pinpoints
                             the producing primitive
  H2O3_TRANSFER_GUARD=LEVEL  jax_transfer_guard for the whole process
                             (log | disallow | log_explicit |
                             disallow_explicit)
  H2O3_LOCKDEP=1|raise|log   runtime lock-order checking on the
                             instrumented subsystem locks (see
                             analysis/lockdep.py) — "raise" turns an
                             inversion into LockOrderInversion at the
                             acquisition that proves it, "log" only
                             counts h2o3_lockdep_inversions_total
  H2O3_DIVERGENCE=1|log      replay-divergence checking (see
                             analysis/divergence.py) — replicated-state
                             mutations digest per broadcast request,
                             coordinator vs worker digests compared on
                             the ack stream; "1"/"raise" surfaces the
                             first mismatch as DivergenceError on the
                             next dispatch, "log" only counts
  H2O3_LEAKTRACK=1|log       paired-protocol leak tracking (see
                             analysis/leaktrack.py) — registered openers
                             hand out tokens recording their acquisition
                             site; a token dying unreleased (or a
                             request-scoped pair surviving its request)
                             is a proven leak; "1"/"raise" fails the
                             next dispatch with LeakError, "log" only
                             counts h2o3_leaktrack_leaks_total
                             h2o3_divergence_mismatches_total
"""

from __future__ import annotations

import contextlib

from h2o3_tpu.utils.env import env_bool, env_str


@contextlib.contextmanager
def transfer_guard(level: str = "disallow"):
    """Scoped jax.transfer_guard: implicit transfers inside the block
    raise (or log). Explicit device_put/device_get stay allowed under
    "disallow" — which is the point: intended transfers are spelled out,
    stray ones crash."""
    import jax
    with jax.transfer_guard(level):
        yield


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Scoped jax_debug_nans — expensive (re-runs producers un-jitted on
    NaN), so scoped rather than global by default."""
    import jax
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def install_from_env() -> dict:
    """Apply env-gated sanitizers process-wide; returns what was enabled.
    Called by H2OServer.start() so a deployment can flip them without a
    code change; a no-op when the env vars are unset."""
    enabled = {}
    from h2o3_tpu.analysis import lockdep
    lockdep_mode = lockdep.env_mode()
    if lockdep_mode:
        lockdep.enable(lockdep_mode)
        enabled["lockdep"] = lockdep_mode
    # divergence joins lockdep ABOVE the jax gate: both sanitize pure
    # host-side state machines and must arm even where jax is absent
    from h2o3_tpu.analysis import divergence
    divergence_mode = divergence.env_mode()
    if divergence_mode:
        divergence.enable(divergence_mode)
        enabled["divergence"] = divergence_mode
    # leaktrack too: the paired protocols it tracks (QoS slots, usage
    # records, watchdog entries) are host-side state machines
    from h2o3_tpu.analysis import leaktrack
    leaktrack_mode = leaktrack.env_mode()
    if leaktrack_mode:
        leaktrack.enable(leaktrack_mode)
        enabled["leaktrack"] = leaktrack_mode
    try:
        import jax
    except Exception:   # noqa: BLE001 — no jax, nothing else to sanitize
        return enabled
    if env_bool("H2O3_DEBUG_NANS", False):
        jax.config.update("jax_debug_nans", True)
        enabled["debug_nans"] = True
    guard = env_str("H2O3_TRANSFER_GUARD", "").strip()
    if guard:
        jax.config.update("jax_transfer_guard", guard)
        enabled["transfer_guard"] = guard
    return enabled
