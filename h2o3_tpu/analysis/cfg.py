"""Intraprocedural control-flow graph with EXCEPTION edges (R022-R025).

Every prior rule generation is flow-insensitive: it sees which calls a
function makes, never which PATHS reach them. The paired-protocol leak
class (reserve without rollback, acquire without release on the
exception path) is invisible at that granularity — the closer is right
there in the function, just not on every path. This module supplies the
missing axis: a per-function CFG whose blocks are statements and whose
edges distinguish normal flow from exceptional flow:

  * every statement that contains a call, attribute access or subscript
    gets an EXCEPTION edge — to the enclosing try's handler dispatch
    when one exists, else to the synthetic RAISE exit (the implicit
    raise-to-caller path every Python statement carries);
  * `try`/`except`/`else`/`finally` lower faithfully: handler bodies,
    the else clause, and a `finally` body DUPLICATED onto every exit
    kind that crosses it (normal fall-through, return, break, continue,
    raise) — which is exactly why `finally: release()` proves closure on
    all paths without any special-casing in the rules;
  * `with` bodies propagate exceptions outward (a context manager's
    __exit__ is modeled by the RULES — a with-item opener is closed by
    construction — not by the graph);
  * loops carry back-edges, `break`/`continue` route through enclosing
    `finally` bodies, `while True:` has no fall-through exit.

Two synthetic exits terminate every path: EXIT (normal return or
fall-off-the-end) and RAISE (an exception escaping to the caller).  A
protocol is leak-free exactly when no path from an opener's NORMAL
successors reaches either exit without crossing a closer block.

Graphs are built lazily — only for functions a rule flags as candidates
(body mentions a registered opener) — and memoized on the engine.Module
cache, so the 25-rule run pays for CFGs on the handful of functions that
touch paired protocols, not the whole package.
"""

from __future__ import annotations

import ast

EXIT = -1      # normal return / fall off the end
RAISE = -2     # exception propagates to the caller


class Block:
    """One statement (or a synthetic dispatch point) in the graph."""

    __slots__ = ("bid", "stmt", "succs")

    def __init__(self, bid: int, stmt):
        self.bid = bid
        self.stmt = stmt          # ast stmt node, or None for synthetic
        self.succs = []           # [(block_id, "norm" | "exc")]

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


class CFG:
    def __init__(self, fn_node):
        self.fn = fn_node
        self.blocks: dict = {}         # bid -> Block
        self.entry = EXIT
        self.stmt_blocks: dict = {}    # id(stmt) -> [bid, ...] (finally
        #                                duplication makes this a list)

    def new(self, stmt=None) -> int:
        bid = len(self.blocks)
        self.blocks[bid] = Block(bid, stmt)
        if stmt is not None:
            self.stmt_blocks.setdefault(id(stmt), []).append(bid)
        return bid

    def edge(self, a: int, b: int, kind: str = "norm"):
        self.blocks[a].succs.append((b, kind))

    def escape_path(self, starts, closing_bids):
        """First escaping path from `starts` (block ids) to EXIT/RAISE
        that never enters a closing block — or None when every path is
        closed.  Returns (exit_kind, via) where exit_kind is "return" or
        "raise" and `via` is the line of the first exception edge taken
        (0 when the path is pure normal flow): the evidence the finding
        message names."""
        # pass 1: normal edges only — an early-return/fall-through leak
        # is the stronger evidence when both kinds exist
        seen: set = set()
        work = list(starts)
        while work:
            bid = work.pop()
            if bid == EXIT:
                return ("return", 0)
            if bid == RAISE or bid in closing_bids or bid in seen:
                continue
            seen.add(bid)
            work.extend(n for n, k in self.blocks[bid].succs
                        if k == "norm")
        # pass 2: all edges — the leak (if any) rides an exception edge;
        # `via` records the line of the first exception edge taken
        seen = set()
        work = [(b, 0) for b in starts]
        while work:
            bid, via = work.pop()
            if bid == EXIT:
                return ("return", via)
            if bid == RAISE:
                return ("raise", via)
            if bid in closing_bids or bid in seen:
                continue
            seen.add(bid)
            blk = self.blocks[bid]
            for nxt, kind in blk.succs:
                work.append((nxt, via if (kind == "norm" or via)
                             else blk.line))
        return None

    def reaches(self, starts, target_bids) -> bool:
        """Any path from `starts` into one of `target_bids`?"""
        seen: set = set()
        work = list(starts)
        while work:
            bid = work.pop()
            if bid in (EXIT, RAISE) or bid in seen:
                continue
            if bid in target_bids:
                return True
            seen.add(bid)
            work.extend(n for n, _k in self.blocks[bid].succs)
        return False

    def norm_succs(self, bid: int) -> list:
        return [n for n, k in self.blocks[bid].succs if k == "norm"]


# ---------------------------------------------------------------------------
# raising-statement classification
_RAISING = (ast.Call, ast.Attribute, ast.Subscript, ast.Await,
            ast.Yield, ast.YieldFrom)


def _expr_can_raise(expr) -> bool:
    if expr is None:
        return False
    return any(isinstance(n, _RAISING) for n in ast.walk(expr))


def _stmt_can_raise(st) -> bool:
    """Statement carries an implicit exception edge: it contains a call,
    attribute access or subscript (the ISSUE-19 vocabulary — plain
    name-to-name assignment cannot raise in any way worth an edge)."""
    if isinstance(st, (ast.Raise, ast.Assert)):
        return True
    if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
        return False          # the def itself; the body is another scope
    if isinstance(st, ast.AnnAssign):
        # the annotation is never evaluated in a function body
        return _expr_can_raise(st.value) or _expr_can_raise(st.target)
    for n in ast.iter_child_nodes(st):
        if isinstance(n, _RAISING) or _expr_can_raise(n):
            return True
    return False


def _is_catch_all(handler_type) -> bool:
    """`except:` / `except BaseException` / `except Exception` stop
    propagation for the protocol exceptions the lifecycle rules care
    about (nothing in this codebase raises bare BaseException), so the
    residual raise-to-caller edge is dropped for them."""
    if handler_type is None:
        return True
    names = []
    if isinstance(handler_type, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", ""))
                 for e in handler_type.elts]
    else:
        names = [getattr(handler_type, "id",
                         getattr(handler_type, "attr", ""))]
    return any(n in ("BaseException", "Exception") for n in names)


def _const_true(expr) -> bool:
    return isinstance(expr, ast.Constant) and bool(expr.value) is True


# ---------------------------------------------------------------------------
# builder
class _Ctx:
    """Continuation targets for the statement being lowered. Each is a
    zero-arg thunk returning a block id, memoized so one `finally` body
    is duplicated at most once per exit KIND (linear in nesting depth,
    never exponential)."""

    __slots__ = ("exc", "ret", "brk", "cont")

    def __init__(self, exc, ret, brk=None, cont=None):
        self.exc = exc
        self.ret = ret
        self.brk = brk
        self.cont = cont


def _memo(fn):
    cell = []

    def thunk():
        if not cell:
            cell.append(fn())
        return cell[0]
    return thunk


def build(fn_node) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef body."""
    g = CFG(fn_node)

    def lower_stmts(stmts, succ: int, ctx: _Ctx) -> int:
        entry = succ
        for st in reversed(stmts):
            entry = lower(st, entry, ctx)
        return entry

    def lower(st, succ: int, ctx: _Ctx) -> int:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            b = g.new(st)
            g.edge(b, succ)
            return b
        if isinstance(st, ast.Return):
            b = g.new(st)
            g.edge(b, ctx.ret())
            if _expr_can_raise(st.value):
                g.edge(b, ctx.exc(), "exc")
            return b
        if isinstance(st, ast.Raise):
            b = g.new(st)
            g.edge(b, ctx.exc(), "exc")
            return b
        if isinstance(st, ast.Break):
            b = g.new(st)
            g.edge(b, ctx.brk() if ctx.brk else EXIT)
            return b
        if isinstance(st, ast.Continue):
            b = g.new(st)
            g.edge(b, ctx.cont() if ctx.cont else EXIT)
            return b
        if isinstance(st, ast.If):
            b = g.new(st)
            g.edge(b, lower_stmts(st.body, succ, ctx))
            g.edge(b, lower_stmts(st.orelse, succ, ctx)
                   if st.orelse else succ)
            if _expr_can_raise(st.test):
                g.edge(b, ctx.exc(), "exc")
            return b
        if isinstance(st, ast.While):
            b = g.new(st)
            after = lower_stmts(st.orelse, succ, ctx) \
                if st.orelse else succ
            body_ctx = _Ctx(ctx.exc, ctx.ret,
                            brk=lambda: succ, cont=lambda: b)
            g.edge(b, lower_stmts(st.body, b, body_ctx))
            if not _const_true(st.test):
                g.edge(b, after)
            if _expr_can_raise(st.test):
                g.edge(b, ctx.exc(), "exc")
            return b
        if isinstance(st, (ast.For, ast.AsyncFor)):
            b = g.new(st)
            after = lower_stmts(st.orelse, succ, ctx) \
                if st.orelse else succ
            body_ctx = _Ctx(ctx.exc, ctx.ret,
                            brk=lambda: succ, cont=lambda: b)
            g.edge(b, lower_stmts(st.body, b, body_ctx))
            g.edge(b, after)
            g.edge(b, ctx.exc(), "exc")     # the iterator itself raises
            return b
        if isinstance(st, (ast.With, ast.AsyncWith)):
            b = g.new(st)
            g.edge(b, lower_stmts(st.body, succ, ctx))
            g.edge(b, ctx.exc(), "exc")     # ctx-expr / __enter__ raises
            return b
        if isinstance(st, ast.Try):
            return lower_try(st, succ, ctx)
        if isinstance(st, ast.Match):
            b = g.new(st)
            for case in st.cases:
                g.edge(b, lower_stmts(case.body, succ, ctx))
            g.edge(b, succ)                 # no case matched
            if _expr_can_raise(st.subject):
                g.edge(b, ctx.exc(), "exc")
            return b
        # simple statement
        b = g.new(st)
        g.edge(b, succ)
        if _stmt_can_raise(st):
            g.edge(b, ctx.exc(), "exc")
        return b

    def lower_try(st: ast.Try, succ: int, ctx: _Ctx) -> int:
        if st.finalbody:
            # every exit KIND that crosses the finally gets its own copy
            # of the finally body, continuing to the original target.
            # Exceptions raised inside the finally itself use the OUTER
            # context (they abandon the in-flight exit).
            fin_exc = _memo(lambda: lower_stmts(st.finalbody, ctx.exc(),
                                                ctx))
            fin_ret = _memo(lambda: lower_stmts(st.finalbody, ctx.ret(),
                                                ctx))
            fin_brk = _memo(lambda: lower_stmts(st.finalbody, ctx.brk(),
                                                ctx)) if ctx.brk else None
            fin_cont = _memo(lambda: lower_stmts(st.finalbody, ctx.cont(),
                                                 ctx)) if ctx.cont else None
            fin_norm = lower_stmts(st.finalbody, succ, ctx)
            inner = _Ctx(fin_exc, fin_ret, brk=fin_brk, cont=fin_cont)
            return lower_try_core(st, fin_norm, inner)
        return lower_try_core(st, succ, ctx)

    def lower_try_core(st: ast.Try, succ: int, ctx: _Ctx) -> int:
        if not st.handlers:
            return lower_stmts(st.body, succ, ctx)
        catch_all = any(_is_catch_all(h.type) for h in st.handlers)

        def make_dispatch():
            d = g.new()                     # synthetic handler dispatch
            for h in st.handlers:
                g.edge(d, lower_stmts(h.body, succ, ctx))
            if not catch_all:
                g.edge(d, ctx.exc(), "exc")  # unmatched type propagates
            return d

        dispatch = _memo(make_dispatch)
        body_ctx = _Ctx(dispatch, ctx.ret, brk=ctx.brk, cont=ctx.cont)
        after_body = lower_stmts(st.orelse, succ, ctx) \
            if st.orelse else succ
        return lower_stmts(st.body, after_body, body_ctx)

    base = _Ctx(exc=lambda: RAISE, ret=lambda: EXIT)
    body = getattr(fn_node, "body", [])
    g.entry = lower_stmts(body, EXIT, base)
    return g


def get(module, fn_node) -> CFG:
    """Build-or-fetch the CFG for `fn_node`, memoized on the Module the
    function was parsed from — candidate functions are re-queried by
    several rules (R022 openers, R024 caller checks) in one run."""
    cache = getattr(module, "_cfgs", None)
    if cache is None:
        cache = {}
        try:
            module._cfgs = cache
        except AttributeError:      # foreign module object: no memo
            return build(fn_node)
    got = cache.get(id(fn_node))
    if got is None:
        got = build(fn_node)
        cache[id(fn_node)] = got
    return got
