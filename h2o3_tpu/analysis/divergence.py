"""Runtime replay-divergence sanitizer (H2O3_DIVERGENCE=1|log).

The static effect rules (R018/R019, effects.py) prove the CODE can't
feed host-divergent values into replicated state; this sanitizer proves
the RUNTIME didn't: while a broadcast request executes, every
replicated-state mutation (DKV put/remove/atomic — hooked via
`kvstore._div_hook`, installed only when enabled) folds `(op, key,
value-digest)` into a per-request digest. The worker's digest rides the
ack frames it already sends (no extra round trip: `_replay_session`
attaches pending riders to the next ack, the coordinator's
`_recv_frame_at` peels them off), and the coordinator compares each
worker's digest against its own for the same seq. First mismatch names
the request path, seq, the first differing (key, op) entry and the
worker — `raise` mode turns the NEXT dispatched request into a
DivergenceError (raising inside the broadcaster's ack loop would be
swallowed as a worker excision, so the error is deferred to
`raise_if_pending()` in server dispatch); `log` mode only counts.

Metrics: h2o3_divergence_checks_total / h2o3_divergence_mismatches_total.

Digest caveat: jax device arrays are digested by type/shape only (no
device sync on the mutation path — a sanitizer must not perturb what it
observes); the (key, op) sequence plus host-side payload bytes is the
divergence signal. Same-key concurrent `atomic` digests are
order-dependent by design: the replay stream is serialized per worker,
so a mismatch there means the COORDINATOR interleaved differently —
which is itself a divergence.
"""

from __future__ import annotations

import hashlib
import threading

from h2o3_tpu.utils.env import env_str

_MAX_TRACK = 512        # per-seq summaries kept before dropping oldest
_MAX_ENTRIES = 128      # per-request mutation entries kept verbatim
_MAX_RIDERS = 64        # worker-side digests queued for the next ack

_mode = ""              # "" (off) | "log" | "raise"
_lock = threading.Lock()
_tls = threading.local()
_local: dict = {}       # seq -> coordinator summary
_remote: dict = {}      # seq -> {pid: worker summary} (rider beat local)
_rider_q: list = []     # worker side: summaries awaiting an ack frame
_pending = None         # first mismatch message awaiting raise_if_pending


class DivergenceError(RuntimeError):
    """Coordinator and a worker disagreed on the replicated-state
    mutations of one replayed request."""


def _counters():
    from h2o3_tpu.obs import metrics as _om
    return (_om.counter("h2o3_divergence_checks_total",
                        "replay divergence digest comparisons"),
            _om.counter("h2o3_divergence_mismatches_total",
                        "replay divergence digest mismatches"))


def env_mode() -> str:
    raw = env_str("H2O3_DIVERGENCE", "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return ""
    return "log" if raw == "log" else "raise"


def enable(mode: str = "raise"):
    global _mode
    from h2o3_tpu.core import kvstore
    _mode = mode
    kvstore._div_hook = _record


def disable():
    global _mode, _pending
    from h2o3_tpu.core import kvstore
    kvstore._div_hook = None
    _mode = ""
    _pending = None
    _tls.scope = None
    with _lock:
        _local.clear()
        _remote.clear()
        del _rider_q[:]


def active() -> bool:
    return bool(_mode)


# ---------------------------------------------------------------------------
# digests
def _value_digest(v, depth: int = 0) -> str:
    try:
        if v is None or isinstance(v, (bool, int, float, str, bytes)):
            r = repr(v) if not isinstance(v, bytes) else v
            if isinstance(r, str):
                r = r.encode("utf-8", "replace")
            return hashlib.sha1(r).hexdigest()[:8]
        import numpy as np
        if isinstance(v, np.ndarray):
            h = hashlib.sha1(f"{v.shape}{v.dtype}".encode())
            h.update(np.ascontiguousarray(v).tobytes())
            return h.hexdigest()[:8]
        if depth < 2 and isinstance(v, dict):
            parts = [f"{k!r}:{_value_digest(v[k], depth + 1)}"
                     for k in sorted(v, key=repr)[:32]]
            return hashlib.sha1(
                f"dict{len(v)}|{'|'.join(parts)}".encode()).hexdigest()[:8]
        if depth < 2 and isinstance(v, (list, tuple)):
            parts = [_value_digest(x, depth + 1) for x in v[:32]]
            return hashlib.sha1(
                f"seq{len(v)}|{'|'.join(parts)}".encode()).hexdigest()[:8]
        # jax arrays, frames, models: digest by TYPE — hashing device
        # payloads would force a host sync on the mutation path
        return f"t:{type(v).__name__}"
    except Exception:   # noqa: BLE001 — a digest must never break a put
        return "t:?"


def _record(op: str, key, value):
    """kvstore._div_hook: fold one replicated-state mutation into the
    thread's active request scope (no-op between requests)."""
    scope = getattr(_tls, "scope", None)
    if scope is None:
        return
    entry = f"{op}|{key}|{_value_digest(value)}"
    scope["n"] += 1
    scope["h"] = hashlib.sha1(
        (scope["h"] + "\n" + entry).encode()).hexdigest()[:16]
    if len(scope["e"]) < _MAX_ENTRIES:
        scope["e"].append(entry)


def _new_scope(seq: int, path: str) -> dict:
    return {"seq": int(seq), "path": path, "n": 0, "h": "", "e": []}


# ---------------------------------------------------------------------------
# coordinator side
def local_begin(seq: int, path: str):
    _tls.scope = _new_scope(seq, path)


def local_end():
    scope = getattr(_tls, "scope", None)
    _tls.scope = None
    if scope is None or not _mode:
        return
    with _lock:
        _local[scope["seq"]] = scope
        while len(_local) > _MAX_TRACK:
            _local.pop(next(iter(_local)))
        stashed = _remote.pop(scope["seq"], None)
    if stashed:
        for pid, summary in sorted(stashed.items(), key=lambda kv: repr(kv)):
            _compare(scope, pid, summary)


# ---------------------------------------------------------------------------
# worker side
def replay_begin(seq: int, path: str):
    _tls.scope = _new_scope(seq, path)


def replay_end():
    scope = getattr(_tls, "scope", None)
    _tls.scope = None
    if scope is None or not _mode:
        return
    with _lock:
        _rider_q.append({"seq": scope["seq"], "path": scope["path"],
                         "n": scope["n"], "h": scope["h"],
                         "e": scope["e"]})
        while len(_rider_q) > _MAX_RIDERS:
            _rider_q.pop(0)


def take_riders() -> list:
    with _lock:
        out, _rider_q[:] = _rider_q[:], []
    return out


def attach_riders(frame: dict) -> dict:
    """Piggyback pending replay digests on an outgoing ack frame —
    called by the worker's frame sends; a no-op when off or drained."""
    if _mode:
        riders = take_riders()
        if riders:
            frame["div"] = riders
    return frame


# ---------------------------------------------------------------------------
# comparison (coordinator, on ack receipt)
def note_remote(pid, riders):
    """Compare each rider against the coordinator's summary for that
    seq, or stash it if the local handler hasn't finished yet (the
    worker acks request N while the coordinator may still be executing
    it — both arrival orders are normal)."""
    if not _mode or not riders:
        return
    for summary in riders:
        try:
            seq = int(summary.get("seq"))
        except (TypeError, ValueError):
            continue
        with _lock:
            local = _local.get(seq)
            if local is None:
                _remote.setdefault(seq, {})[pid] = summary
                while len(_remote) > _MAX_TRACK:
                    _remote.pop(next(iter(_remote)))
        if local is not None:
            _compare(local, pid, summary)


def _compare(local: dict, pid, remote: dict):
    global _pending
    checks, mismatches = _counters()
    checks.inc()
    if local["h"] == remote.get("h", "") and \
            local["n"] == remote.get("n", -1):
        return
    mismatches.inc()
    le, re_ = local["e"], list(remote.get("e", ()))
    detail = "mutation counts differ"
    for i in range(max(len(le), len(re_))):
        a = le[i] if i < len(le) else "<none>"
        b = re_[i] if i < len(re_) else "<none>"
        if a != b:
            ao, ak = (a.split("|") + ["", ""])[:2]
            bo, bk = (b.split("|") + ["", ""])[:2]
            detail = (f"first differing mutation #{i}: coordinator "
                      f"{ao} key={ak!r} vs worker {bo} key={bk!r}")
            break
    msg = (f"replicated-state divergence on {local['path']!r} "
           f"(seq {local['seq']}) between coordinator and worker "
           f"pid={pid}: {detail} — coordinator ran "
           f"{local['n']} mutation(s) [digest {local['h'] or '-'}], "
           f"worker {remote.get('n', '?')} "
           f"[digest {remote.get('h', '') or '-'}]")
    from h2o3_tpu.utils import log as _ulog
    _ulog.err("%s", msg)
    if _mode == "raise" and _pending is None:
        _pending = msg


def raise_if_pending():
    """Surface the first recorded mismatch as DivergenceError — called
    from server dispatch BEFORE starting the next request, never from
    inside the broadcaster's send/ack loops (a raise there reads as a
    dead worker and excises it)."""
    global _pending
    if _pending is not None:
        msg, _pending = _pending, None
        raise DivergenceError(msg)
