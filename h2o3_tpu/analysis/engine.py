"""Analysis engine — findings, suppressions, baseline, orchestration.

The analyzer is AST-based and zero-dependency (stdlib only): it must run
in CI images and pre-commit hooks without importing jax or the package
under analysis. Every rule is derived from a defect class this repo
actually shipped (see rules_*.py docstrings); the engine is the part that
turns rule hits into actionable, machine-readable findings:

  * Finding — rule id, file:line, message, plus a line-content fingerprint
    so baselines survive unrelated edits shifting line numbers.
  * Inline suppression — a `# h2o3-ok: R003 <reason>` comment on the
    flagged line (or the line above, for multi-line statements) waives the
    listed rules at that site. The reason is mandatory by convention: a
    waiver without a why is a finding waiting to regress.
  * Baseline — grandfathered findings recorded in a JSON file
    (analysis_baseline.json); the tier-1 gate fails only on findings that
    are neither suppressed nor baselined, so new debt cannot land while
    old debt is paid down incrementally.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field


@dataclass
class Finding:
    rule: str                 # "R001".."R006"
    file: str                 # repo-relative path
    line: int
    message: str
    snippet: str = ""         # stripped source line (fingerprint input)
    suppressed: bool = False  # inline `# h2o3-ok:` waiver
    baselined: bool = False   # matched an analysis_baseline.json entry

    @property
    def fingerprint(self) -> str:
        """Stable identity across line-number drift: rule + file + the
        normalized content of the flagged line."""
        basis = f"{self.rule}:{self.file}:{' '.join(self.snippet.split())}"
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint,
                "suppressed": self.suppressed, "baselined": self.baselined}

    def __str__(self):
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclass
class Module:
    """One parsed source file handed to the rules.

    The parse happens once (load_modules); the two tree walks every rule
    used to redo — the child→parent map and the flat node list — are
    memoized here so N rules share one traversal instead of paying
    O(tree) each (the analyzer runs in pre-commit: wall-time is budget)."""
    path: str                 # absolute
    rel: str                  # repo-relative (finding/baseline identity)
    source: str
    tree: ast.AST
    lines: list = field(default_factory=list)
    _parents: dict = field(default=None, repr=False, compare=False)
    _nodes: list = field(default=None, repr=False, compare=False)

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def walk(self) -> list:
        """Flat ast.walk(tree) node list, computed once per module."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def parents(self) -> dict:
        """child node → parent node map, computed once per module."""
        if self._parents is None:
            self._parents = {c: p for p in self.walk()
                             for c in ast.iter_child_nodes(p)}
        return self._parents


_SUPPRESS_RE = re.compile(r"#\s*h2o3-ok:\s*([A-Z0-9,\s]+?)(?:\s+\S.*)?$")


def _suppressions(lines: list) -> dict:
    """{lineno: {rule, ...}} from `# h2o3-ok: R001[,R002] reason` comments.
    A waiver covers its own line and the line below it, so it can sit
    above a multi-line statement whose node starts on the next line."""
    out: dict = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        out.setdefault(i + 1, set()).update(rules)
    return out


def package_root() -> str:
    """The h2o3_tpu package directory (default analysis target)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def tests_root() -> str:
    """The repo's tests/ directory (analyzed under the relaxed profile)."""
    return os.path.join(repo_root(), "tests")


def _iter_py_files(paths) -> list:
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(out))


def load_modules(paths) -> list:
    root = repo_root()
    mods = []
    for path in _iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError) as ex:
            # an unparseable file is itself a finding-worthy defect, but
            # the compiler owns syntax errors; report and move on
            mods.append(Module(path, os.path.relpath(path, root),
                               "", ast.Module(body=[], type_ignores=[])))
            mods[-1].lines = [f"<unreadable: {ex}>"]
            continue
        m = Module(path, os.path.relpath(path, root), src, tree)
        m.lines = src.splitlines()
        mods.append(m)
    return mods


# Rules waived wholesale for test files: tests deliberately jit lambdas,
# call time.time() in fixtures, and seed impurity to prove the runtime
# handles it — R001/R004 are perf rules for production paths, R011's
# span census is a production-vocabulary concern (throwaway fixture
# spans are the point of a tracing test), R012's logging discipline
# is for records an operator must find later (a test printing its
# diagnostics is fine), and R013's socket deadlines are a production
# liveness concern (test fixtures connect to loopback listeners they
# themselves bound, with their own bounded retries and suite timeouts).
# R015 (host-sync taint on instrumented hot paths) and R016
# (replay-determinism) are production-invariant rules — test fixtures
# host-sync inside spans to assert results and seed nondeterminism into
# fake Broadcasters on purpose; R017's env census covers the package's
# config surface, while tests poke os.environ directly by design
# (monkeypatch.setenv round-trips).
# Everything else (locks, metrics, routes, R007-R010 concurrency)
# applies to tests too: a racy test harness or a leaked test thread
# flakes the suite.
# R018–R021 (replicated-state integrity) are likewise production-
# invariant rules: test fixtures register throwaway routes that mutate
# fixture DKVs, seed host-divergent values to prove the runtime
# sanitizer fires, and spin one-sided protocol stubs (a FakeWorker with
# no _collect_local) on purpose.
TEST_RELAXED = {"R001", "R004", "R011", "R012", "R013",
                "R015", "R016", "R017",
                "R018", "R019", "R020", "R021",
                # lifecycle + export rules: tests seed deliberate leaks
                # (to prove the leaktrack sanitizer fires) and call the
                # pair surfaces in half-open shapes by design
                "R022", "R023", "R024", "R025"}


def _is_test_file(rel: str) -> bool:
    r = rel.replace("\\", "/")
    return r.startswith("tests/") or "/tests/" in r


# {rule-or-pass: seconds} for the LAST analyze_modules call — the
# analyzer runs in pre-commit under a wall-time budget, so --json
# reports where the time went. Keys are "+"-joined rule ids per check
# function; functions marked SELF_TIMED (the shared callgraph pass)
# record their own finer-grained entries instead.
RULE_TIMINGS: dict = {}


def analyze_modules(mods: list, rules=None, only_files=None) -> list:
    """Run every rule over the parsed modules; returns findings with
    inline suppressions already applied (but baseline NOT applied).

    `only_files` (a set of repo-relative paths) scopes the OUTPUT to
    those files — the --changed-only mode: per-file rules skip other
    modules entirely, project rules still see the whole module set (a
    call graph over a partial project would miss cross-file edges) but
    report only into the scoped files."""
    import time as _time

    from h2o3_tpu.analysis import callgraph, rules_env, rules_jax, \
        rules_locks, rules_logging, rules_metrics, rules_pjit, \
        rules_protocol, rules_routes, rules_sockets, rules_spans
    findings: list = []
    RULE_TIMINGS.clear()
    if only_files is not None and not only_files:
        return []    # nothing in scope changed: every finding would be
        #              filtered out below — skip the analysis entirely
    per_file = [rules_jax.check, rules_locks.check, rules_logging.check,
                rules_sockets.check, rules_pjit.check]
    project = [rules_metrics.check, rules_routes.check, rules_spans.check,
               rules_env.check, rules_protocol.check, callgraph.check]
    if rules:
        wanted = set(rules)
        per_file = [f for f in per_file if f.RULES & wanted]
        project = [f for f in project if f.RULES & wanted]

    def _timed(rule_fn, arg):
        key = "+".join(sorted(rule_fn.RULES))
        t0 = _time.perf_counter()
        out = rule_fn(arg)
        if not getattr(rule_fn, "SELF_TIMED", False):
            RULE_TIMINGS[key] = RULE_TIMINGS.get(key, 0.0) + \
                (_time.perf_counter() - t0)
        return out

    for m in mods:
        if only_files is not None and m.rel not in only_files:
            continue
        for rule_fn in per_file:
            findings.extend(_timed(rule_fn, m))
    for rule_fn in project:
        findings.extend(_timed(rule_fn, mods))
    if rules:
        findings = [f for f in findings if f.rule in set(rules)]
    if only_files is not None:
        findings = [f for f in findings if f.file in only_files]
    findings = [f for f in findings
                if not (f.rule in TEST_RELAXED and _is_test_file(f.file))]
    # attach snippets + inline suppressions
    by_path = {m.rel: m for m in mods}
    sup_cache: dict = {}
    for f in findings:
        m = by_path.get(f.file)
        if m is None:
            continue
        f.snippet = f.snippet or m.snippet(f.line)
        if f.file not in sup_cache:
            sup_cache[f.file] = _suppressions(m.lines)
        if f.rule in sup_cache[f.file].get(f.line, ()):
            f.suppressed = True
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def analyze_paths(paths, rules=None) -> list:
    return analyze_modules(load_modules(paths), rules=rules)


def analyze_source(src: str, filename: str = "<fixture>",
                   rules=None) -> list:
    """Analyze a source string — the seeded-defect test entry point."""
    return analyze_sources({filename: src}, rules=rules)


def analyze_sources(sources: dict, rules=None) -> list:
    """Analyze {filename: source} strings as ONE project — the entry
    point for seeding cross-module defects (R007 lock-order cycles only
    exist in the composition of several files)."""
    mods = []
    for filename, src in sources.items():
        tree = ast.parse(src, filename=filename)
        m = Module(filename, filename, src, tree)
        m.lines = src.splitlines()
        mods.append(m)
    return analyze_modules(mods, rules=rules)


# ---------------------------------------------------------------------------
# baseline
def load_baseline(path: str) -> dict:
    """{fingerprint: note} from an analysis_baseline.json file."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e.get("note", "")
            for e in data.get("findings", [])}


def apply_baseline(findings: list, baseline: dict) -> list:
    for f in findings:
        if not f.suppressed and f.fingerprint in baseline:
            f.baselined = True
    return findings


def write_baseline(findings: list, path: str):
    """Grandfather every currently-unsuppressed finding (the one-time
    bootstrap; new findings after this still fail the gate)."""
    entries = []
    seen = set()
    for f in findings:
        if f.suppressed or f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        entries.append({"rule": f.rule, "file": f.file,
                        "fingerprint": f.fingerprint,
                        "snippet": f.snippet, "note": ""})
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2)
        fh.write("\n")


def unsuppressed(findings: list) -> list:
    return [f for f in findings if not f.suppressed and not f.baselined]


def run(paths=None, baseline_path=None, rules=None) -> list:
    """Full pipeline: parse, analyze, suppress, baseline. The tier-1 gate
    asserts `not unsuppressed(run(...))`."""
    if not paths:
        paths = [package_root()]
    findings = analyze_paths(paths, rules=rules)
    if baseline_path:
        apply_baseline(findings, load_baseline(baseline_path))
    return findings
