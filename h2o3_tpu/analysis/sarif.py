"""SARIF 2.1.0 emission — `python -m h2o3_tpu.analysis --sarif out.json`.

SARIF is the interchange format CI annotators (GitHub code scanning)
and editors consume; emitting it makes every R-rule finding a native
PR annotation instead of a log line someone has to grep. The mapping:

  * one `run` with the full rule catalog under `tool.driver.rules`
    (rule id, short description) so viewers render names, not ids;
  * one `result` per finding — `ruleId`, message, physical location
    (repo-relative URI + 1-based line), and the engine's content-hash
    fingerprint under `partialFingerprints` so SARIF consumers track a
    finding across line drift exactly like the JSON baseline does;
  * inline `# h2o3-ok:` waivers and baselined findings surface as SARIF
    `suppressions` (kind `inSource` / `external`) rather than being
    dropped: the annotator shows them struck-through instead of
    re-flagging them.

The output is deterministic (sorted keys, findings already sorted by
the engine) — the golden-file test diffs it byte-for-byte.
"""

from __future__ import annotations

RULE_SUMMARIES = {
    "R001": "jax.jit on a per-call lambda/closure: recompiles every "
            "invocation",
    "R002": "device→host sync under trace or inside a timeline span "
            "hot path",
    "R003": "attribute mutated both under its lock and bare",
    "R004": "impure value (time/random/global) captured at jit trace "
            "time",
    "R005": "metric-name drift vs the obs/METRICS.md census",
    "R006": "REST route capture groups vs handler signature drift",
    "R007": "lock-order cycle (direct or through any call chain)",
    "R008": "blocking operation reachable with a lock held",
    "R009": "donated buffer read after the jitted call consumed it",
    "R010": "thread/executor leak (no daemon/join/shutdown)",
    "R011": "span-name drift vs the obs/SPANS.md census",
    "R012": "print()/bare logging instead of the structured logger",
    "R013": "timeout-less socket wait",
    "R014": "raw jit/pjit dispatch not routed through the collective "
            "guard",
    "R015": "transitive device→host sync inside an instrumented span",
    "R016": "nondeterminism feeding replicated-state mutation in "
            "broadcast-replayed code",
    "R017": "env-config drift vs the analysis/ENV.md census "
            "(direct reads, non-literal names, duplicate declarations)",
    "R018": "replay-exempt route handler transitively mutates "
            "replicated state (coordinator-only mutation)",
    "R019": "host-identity source feeding replicated state in "
            "broadcast-replayed code (interprocedural)",
    "R020": "replay-channel protocol drift vs the deploy/PROTOCOL.md "
            "census (unhandled sends / dead handler arms)",
    "R021": "npz wire-format drift: writer and reader disagree on the "
            "plane/key set",
    "R022": "paired-protocol leak: an acquire whose release is not "
            "proven on every path, exception edges included",
    "R023": "control-flow exception swallowed by a broad handler on a "
            "dispatch/serving/replay path",
    "R024": "paired-protocol token discarded or leaked through a "
            "returning wrapper no caller closes",
    "R025": "traced-value control flow or callback in an exported "
            "scorer (portable-artifact contract)",
}


def to_sarif(findings: list) -> dict:
    """Findings (engine.Finding, post-suppression/baseline) → a SARIF
    2.1.0 log dict ready for json.dump."""
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.file.replace("\\", "/"),
                        "uriBaseId": "REPOROOT",
                    },
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "h2o3ContentHash/v1": f.fingerprint,
            },
        }
        if f.suppressed:
            res["suppressions"] = [{
                "kind": "inSource",
                "justification": "inline h2o3-ok waiver",
            }]
        elif f.baselined:
            res["suppressions"] = [{
                "kind": "external",
                "justification": "grandfathered in "
                                 "analysis_baseline.json",
            }]
        results.append(res)
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "h2o3_tpu.analysis",
                    "informationUri":
                        "https://example.invalid/h2o3_tpu/analysis",
                    "rules": [
                        {"id": rid,
                         "shortDescription": {"text": RULE_SUMMARIES[rid]}}
                        for rid in sorted(RULE_SUMMARIES)
                    ],
                },
            },
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {
                "REPOROOT": {"description": {
                    "text": "repository root (findings use "
                            "repo-relative paths)"}},
            },
            "results": results,
        }],
    }
