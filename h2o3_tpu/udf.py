"""User-defined functions — water/udf rebuilt for a single-controller runtime.

Reference: water/udf (CFuncRef/CFuncLoader, CDistributionFunc custom GBM
distributions, CMetricFunc custom model metrics) + h2o-extensions/jython-cfunc:
users upload jars of Java/Jython functions into the DKV and reference them as
"lang:keyname=ClassName" in `custom_distribution_func` /
`custom_metric_func` parameters.

TPU-native design: UDFs are Python objects whose array math is written in
jax.numpy — they are traced INTO the jitted training/scoring programs (no
interpreter callback per row; the reference pays a JVM/Jython call per row).
They register under the same DKV the frames/models live in, referenced as
"python:<key>" strings for h2o-py parameter parity."""

from __future__ import annotations

from h2o3_tpu.core.kvstore import DKV

_PREFIX = "udf_"


class CustomDistribution:
    """Custom GBM distribution (water/udf/CDistributionFunc analog).

    Subclass and override; all array math must be jax.numpy (it runs inside
    the jitted boosting programs):
      link_inv(F)      — inverse link: margin → prediction/probability
      grad_hess(F, y)  — pseudo-residual (gradient ascent dir) and hessian
      init_f0(ybar)    — initial margin from the weighted response mean
    """

    def link_inv(self, F):
        return F

    def grad_hess(self, F, y):
        raise NotImplementedError

    def init_f0(self, ybar: float) -> float:
        return float(ybar)


class CustomMetric:
    """Custom model metric (water/udf/CMetricFunc analog): map/reduce/metric
    with the same 3-phase contract as the reference."""

    name = "custom"

    def map(self, pred, y, w):
        """Phase 1: receives FULL column arrays (pred, y, w) and returns a
        tuple of components — either per-row arrays (length n, the reference
        per-row contract, vectorized) or already-reduced scalars. Per-row
        outputs are folded with reduce() pairwise on device; scalar outputs
        skip reduce and go straight to metric()."""
        raise NotImplementedError

    def reduce(self, l, r):
        return tuple(a + b for a, b in zip(l, r))

    def metric(self, agg) -> float:
        raise NotImplementedError


def register_udf(key: str, obj) -> str:
    """Register a UDF; returns the "python:<key>" reference string."""
    DKV.put(_PREFIX + key, obj)
    return f"python:{key}"


def resolve_udf(ref):
    """Accept a UDF object, a "python:key" reference, or a bare key."""
    if isinstance(ref, (CustomDistribution, CustomMetric)):
        return ref
    if not isinstance(ref, str):
        raise TypeError(f"not a UDF reference: {ref!r}")
    key = ref.split(":", 1)[1] if ":" in ref else ref
    obj = DKV.get(_PREFIX + key)
    if obj is None:
        raise KeyError(f"no UDF registered under {key!r}")
    return obj


def remove_udf(key: str):
    DKV.remove(_PREFIX + key)
