"""Client-bindings codegen — h2o-bindings analog (gen_python.py et al.)."""

from h2o3_tpu.bindings.gen import gen_python  # noqa: F401
