"""Shared serving-param placements — ONE HBM copy of a model's params.

The pre-mesh scorer cache traced a model's parameters (tree arrays, GLM
coefficients, net weights, centroids, …) into each per-bucket XLA
program as closure constants: N row-buckets × M models duplicated every
ensemble in HBM, and any model bigger than one host's HBM simply could
not ride the fast path. This store is the other half of the rebuild:

  * A model family exports a param PYTREE (`ModelBase._serving_params`)
    plus regex partition rules; `parallel.mesh.match_partition_rules`
    maps each leaf to a `PartitionSpec` and `mesh.shard_params` places
    it once as `NamedSharding`-committed device arrays.
  * Every compiled row-bucket program takes the placed pytree as its
    FIRST argument (not a baked constant), so all buckets — and on a
    multi-controller cloud, all hosts — share the same single copy.
  * Placements are REFCOUNTED by the cache entries that dispatch them:
    each resident (model, bucket) program holds one reference; the last
    eviction (LRU, stale-generation purge, model DELETE) frees the
    placement exactly once. `h2o3_scorer_params_bytes{model}` tracks the
    per-model occupancy, which is constant in the number of buckets.
  * A cloud-epoch bump (deploy/membership) rebuilds the mesh
    (`mesh.note_epoch`); placements record the epoch they were placed
    for and transparently re-place on the next dispatch.
"""

from __future__ import annotations

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.parallel import mesh as _mesh

PARAM_BYTES = _om.gauge(
    "h2o3_scorer_params_bytes",
    "resident HBM bytes of ONE shared serving-param copy per model "
    "(constant in the number of compiled row-buckets)")
PLACEMENTS = _om.counter(
    "h2o3_scorer_param_placements_total",
    "serving param pytrees placed on the mesh (one per model generation "
    "per cloud epoch; re-places after an epoch bump are counted too)")


class Placement:
    """One model generation's placed params: the device pytree, its
    PartitionSpec pytree, logical bytes, and the cloud epoch it was
    placed for (jax interns Mesh objects — same devices and axis names
    give the SAME Mesh back — so the epoch, not mesh identity, is the
    staleness signal)."""

    __slots__ = ("placed", "specs", "nbytes", "epoch", "refs")

    def __init__(self, placed, specs, nbytes, epoch):
        self.placed = placed
        self.specs = specs
        self.nbytes = nbytes
        self.epoch = epoch
        self.refs = 0


class ParamStore:
    """(model key, generation token) → refcounted Placement."""

    def __init__(self):
        self._lock = make_lock("serving.params")
        self._placements: dict = {}

    # -- placement ---------------------------------------------------------
    @staticmethod
    def _build_placement(model):
        """Compute a Placement WITHOUT the store lock held — the
        device_put of a large ensemble must not stall every other
        model's warm dispatches (which read the store per call). Returns
        None for families without a param export."""
        params = model._serving_params()
        if params is None:
            return None
        cld = _mesh.cloud()
        specs = _mesh.match_partition_rules(
            getattr(model, "_partition_rules", ()), params)
        placed = _mesh.shard_params(params, specs=specs, cld=cld)
        return Placement(placed, specs, _mesh.params_nbytes(placed),
                         cld.epoch)

    def _publish(self, key, p: "Placement") -> "Placement":
        """Install a freshly built Placement under the lock; a racing
        builder's copy wins first-publish (the loser's device arrays are
        GC'd). Returns the placement now in the store."""
        with self._lock:
            cur = self._placements.get(key)
            if cur is not None and cur.epoch == p.epoch:
                return cur
            if cur is not None:
                p.refs = cur.refs         # epoch re-place keeps the refs
            self._placements[key] = p
            PLACEMENTS.inc()
            PARAM_BYTES.set(p.nbytes, model=key[0])
            return p

    def acquire(self, model, token: int):
        """Place (or re-reference) the model's params; bumps the
        refcount. Called once per cache-entry build; each resident
        compiled bucket program holds exactly one reference. Returns the
        Placement, or None for families without a param export."""
        key = (model.key, token)
        with self._lock:
            p = self._placements.get(key)
            if p is not None:
                p.refs += 1
                return p
        built = self._build_placement(model)        # outside the lock
        if built is None:
            return None
        p = self._publish(key, built)
        with self._lock:
            p.refs += 1
        return p

    def reattach(self, model_key: str, token: int, p: "Placement"):
        """Re-install a placement an in-flight build acquired but a
        concurrent invalidate_key swept before the entry published —
        the entry's reference is live, so the store must know the
        placement again (or every dispatch would re-place one-shot)."""
        with self._lock:
            if (model_key, token) not in self._placements:
                self._placements[(model_key, token)] = p
                PARAM_BYTES.set(p.nbytes, model=model_key)

    def placed(self, model, token: int):
        """The CURRENT placed pytree for a dispatch — re-placing first
        when the mesh was rebuilt for a new cloud epoch (the old
        placement's arrays are laid out for a dead membership). Does not
        change the refcount; the calling cache entry already holds one."""
        key = (model.key, token)
        epoch = _mesh.cloud().epoch
        with self._lock:
            p = self._placements.get(key)
            if p is not None and p.epoch == epoch:
                return p.placed
        if p is not None:
            # stale epoch: rebuild outside the lock, publish (refs carry)
            built = self._build_placement(model)
            if built is not None:
                return self._publish(key, built).placed
            return None
        # Placement gone while a dispatch was in flight: the entry was
        # evicted/invalidated (retrain purge, model DELETE) between the
        # cache lookup and this call. Serve THIS request with a one-shot
        # placement that is never stored — storing it would re-register
        # the freed model with refs nothing will ever release (a
        # permanent HBM leak and a ghost gauge series for a deleted
        # model). One-shot placement is GC'd with the dispatch.
        params = model._serving_params()
        if params is None:
            return None
        return _mesh.shard_params(
            params,
            rules=getattr(model, "_partition_rules", ()))

    # -- release -----------------------------------------------------------
    def release(self, model_key: str, token: int):
        """One cache entry dropped its reference; the LAST release frees
        the placement (and its gauge series) exactly once."""
        with self._lock:
            p = self._placements.get((model_key, token))
            if p is None:
                return
            p.refs -= 1
            if p.refs <= 0:
                del self._placements[(model_key, token)]
                if not any(k[0] == model_key for k in self._placements):
                    PARAM_BYTES.remove(model=model_key)

    def invalidate_key(self, model_key: str):
        """Model DELETE: drop every generation's placement for the DKV
        key regardless of refcount (the cache drops its entries in the
        same breath — see ScorerCache.invalidate_key)."""
        with self._lock:
            for k in [k for k in self._placements if k[0] == model_key]:
                del self._placements[k]
            PARAM_BYTES.remove(model=model_key)

    def clear(self):
        with self._lock:
            keys = {k[0] for k in self._placements}
            self._placements.clear()
            for mk in keys:
                PARAM_BYTES.remove(model=mk)

    # -- introspection -----------------------------------------------------
    def bytes_for(self, model_key: str) -> int:
        with self._lock:
            return sum(p.nbytes for k, p in self._placements.items()
                       if k[0] == model_key)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(p.nbytes for p in self._placements.values())

    def by_model(self) -> dict:
        """{model_key: placement bytes} across resident generations —
        the /3/Usage HBM-attribution feed."""
        with self._lock:
            out: dict = {}
            for (mk, _tok), p in self._placements.items():
                out[mk] = out.get(mk, 0) + p.nbytes
            return out

    def resident(self) -> int:
        with self._lock:
            return len(self._placements)


PARAMS = ParamStore()

_om.gauge("h2o3_scorer_param_models",
          "model generations with a live shared serving-param placement",
          fn=lambda: float(PARAMS.resident()))
