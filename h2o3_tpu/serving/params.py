"""Shared serving-param placements — model params under the tier pager.

The pre-mesh scorer cache traced a model's parameters (tree arrays, GLM
coefficients, net weights, centroids, …) into each per-bucket XLA
program as closure constants: N row-buckets × M models duplicated every
ensemble in HBM, and any model bigger than one host's HBM simply could
not ride the fast path. This store is the other half of the rebuild:

  * A model family exports a param PYTREE (`ModelBase._serving_params`)
    plus regex partition rules; `parallel.mesh.match_partition_rules`
    maps each leaf to a `PartitionSpec` and `mesh.shard_params` places
    it once as `NamedSharding`-committed device arrays.
  * Every compiled row-bucket program takes the placed pytree as its
    FIRST argument (not a baked constant), so all buckets — and on a
    multi-controller cloud, all hosts — share the same single copy.
  * Placements are REFCOUNTED by the cache entries that dispatch them:
    each resident (model, bucket) program holds one reference; the last
    eviction (LRU, stale-generation purge, model DELETE) frees the
    placement exactly once. `h2o3_scorer_params_bytes{model}` tracks the
    per-model HBM occupancy, which is constant in the number of buckets.
  * A cloud-epoch bump (deploy/membership) rebuilds the mesh
    (`mesh.note_epoch`); placements record the epoch they were placed
    for and transparently re-place on the next dispatch.

Fleet-scale tiering (H2O-3's water/Cleaner.java memory manager, rebuilt
for the serving hot path): with `H2O3_SERVE_HBM_BUDGET_MB` set, a
placement's refcount keeps it REGISTERED but no longer keeps it
DEVICE-RESIDENT. Params ride the same three-tier ladder as chunk planes
(core/tiering.py):

    HBM (placed pytree)  ⇄  host canonical numpy  ⇄  npz under ice_root

  * PROMOTE is the ISSUE-11 placement primitive: the per-spec shard_fns
    from `mesh.make_shard_and_gather_fns` place the canonical host
    pytree; admission is reserved ATOMICALLY before any device_put
    lands (the ISSUE-6 in-flight-reservation discipline), so the
    `h2o3_scorer_params_bytes` sum can never exceed the budget even
    under concurrent cold faults.
  * DEMOTE is the matching gather_fns pass + `mesh._canon_host_leaf`
    (f64→f32, i64→i32) — the same canonicalization `shard_params`
    applies on the way in, so a demote→promote round trip is bit-exact.
  * EVICTION is same-tenant-first LRU: victims are chosen first among
    the faulting tenant's own cold placements, then cross-tenant in
    ascending `qos.eviction_standing` (heaviest QoS consumers first),
    then by the per-model hotness clock — one tenant's model churn
    cannot evict another tenant's hot set, and every eviction is
    CHARGED to the tenant whose fault forced it. `pin()` marks a
    model's placements never-victim (SLO hot sets).
  * `H2O3_SERVE_HOST_BUDGET_MB` bounds the host tier the same way;
    overflow spills to an npz artifact under ice_root (io/spill.py),
    freed exactly once on release/DELETE/retrain.

With no budget set, behavior is the pre-tiering fast path: eager
device placement at acquire, nothing demotes, no host mirrors.
"""

from __future__ import annotations

import itertools

import jax

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.parallel import mesh as _mesh
from h2o3_tpu.utils.env import env_int

# tier names (string-compatible with core.tiering's ladder)
TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_DISK = "disk"
_TIERS = (TIER_HBM, TIER_HOST, TIER_DISK)

PARAM_BYTES = _om.gauge(
    "h2o3_scorer_params_bytes",
    "HBM-resident bytes of ONE shared serving-param copy per model "
    "(constant in the number of compiled row-buckets; demoted "
    "placements leave the gauge — it is bounded by "
    "H2O3_SERVE_HBM_BUDGET_MB when set)")
PLACEMENTS = _om.counter(
    "h2o3_scorer_param_placements_total",
    "serving param pytrees placed on the mesh (one per model generation "
    "per cloud epoch; re-places after an epoch bump are counted too)")
PARAM_FAULTS = _om.counter(
    "h2o3_serve_param_faults_total",
    "model-param promotions into HBM by source tier — a cold model "
    "faulting in from its host mirror or ice_root npz artifact")
PARAM_EVICTIONS = _om.counter(
    "h2o3_serve_param_evictions_total",
    "model-param demotions by destination tier, charged to the tenant "
    "whose cold fault forced the eviction")


def _hbm_budget_bytes() -> int:
    """H2O3_SERVE_HBM_BUDGET_MB — byte budget for DEVICE-resident model
    params (0 = unbudgeted eager placement). Read per call so serving
    tests and operators can retune without a restart."""
    return env_int("H2O3_SERVE_HBM_BUDGET_MB", 0) * (1 << 20)


def _host_budget_bytes() -> int:
    """H2O3_SERVE_HOST_BUDGET_MB — byte budget for the host tier of
    demoted model params (0 = unbounded host tier)."""
    return env_int("H2O3_SERVE_HOST_BUDGET_MB", 0) * (1 << 20)


def _standing(principal: str) -> float:
    """Cross-tenant victim ordering key — qos.eviction_standing in
    [0, 1], lower = heavier consumer = evicted first. Looked up OUTSIDE
    the store lock (qos takes its own locks)."""
    try:
        from h2o3_tpu.serving import qos as _qos
        return _qos.eviction_standing(principal)
    except Exception:   # noqa: BLE001 — victim order must never fail
        return 1.0


class Placement:
    """One model generation's params, resident on exactly the tiers its
    non-None slots say: `placed` (device pytree), `host` (canonical
    numpy pytree), `path` (npz spill artifact). `specs` is the
    PartitionSpec pytree and `treedef` the param tree structure — both
    mesh-independent, so a placement can demote off one cloud epoch and
    promote onto the next (jax interns Mesh objects — same devices and
    axis names give the SAME Mesh back — so the epoch, not mesh
    identity, is the staleness signal). `tenant` is the principal that
    faulted it in last; `last` is the hotness-clock tick. `_io` is the
    per-placement transfer lock (one lockdep class), ordered BEFORE the
    store lock exactly like tiering.io → tiering.residency."""

    __slots__ = ("key", "placed", "specs", "host", "treedef", "path",
                 "nbytes", "epoch", "refs", "tenant", "last", "_io",
                 "_acct")

    def __init__(self, placed, specs, nbytes, epoch, host=None,
                 treedef=None):
        self.key = None
        self.placed = placed
        self.specs = specs
        self.host = host
        self.treedef = treedef
        self.path = None
        self.nbytes = nbytes
        self.epoch = epoch
        self.refs = 0
        self.tenant = "anonymous"
        self.last = 0
        self._io = make_lock("serving.params.io")
        self._acct = None

    @property
    def tier(self) -> str:
        """Best (fastest) tier this placement is resident on."""
        if self.placed is not None:
            return TIER_HBM
        if self.host is not None:
            return TIER_HOST
        return TIER_DISK


class ParamStore:
    """(model key, generation token) → refcounted, TIERED Placement."""

    def __init__(self):
        self._lock = make_lock("serving.params")
        self._placements: dict = {}
        self._pinned: set = set()
        self._bytes = {t: 0 for t in _TIERS}
        self._reserved = 0
        self._peak_hbm = 0
        self._ticks = itertools.count(1)
        self._fault_count = 0
        self._evictions_by_tenant: dict = {}

    # -- tenancy / clocks --------------------------------------------------
    @property
    def tiering_active(self) -> bool:
        return bool(_hbm_budget_bytes() or _host_budget_bytes())

    def _tick(self) -> int:
        return next(self._ticks)

    @staticmethod
    def _tenant() -> str:
        """The QoS principal of the request on this thread — the tenant
        a fault's evictions are charged to. Never called with the store
        lock held (qos/tracing take their own locks)."""
        try:
            from h2o3_tpu.obs import tracing as _tracing
            from h2o3_tpu.serving import qos as _qos
            return _qos.resolve_principal(_tracing.principal() or "")
        except Exception:   # noqa: BLE001 — attribution must not break serving
            return "anonymous"

    # -- accounting (presence-based, mirrors ChunkPager) -------------------
    def _account_locked(self, p: "Placement"):
        # h2o3-ok: R003 _locked helper — every caller holds self._lock
        present = (p.placed is not None, p.host is not None,
                   p.path is not None)
        prev = p._acct
        if prev is not None:
            for t, had in zip(_TIERS, prev):
                if had:
                    self._bytes[t] -= p.nbytes
        p._acct = present
        for t, has in zip(_TIERS, present):
            if has:
                self._bytes[t] += p.nbytes
        if present[0] and self._bytes[TIER_HBM] > self._peak_hbm:
            # h2o3-ok: R003 _locked helper — caller holds self._lock
            self._peak_hbm = self._bytes[TIER_HBM]
        self._gauge_locked(p.key[0])

    def _gauge_locked(self, model_key: str):
        # h2o3-ok: R003 _locked helper — every caller holds self._lock
        # (the per-series metric lock is a leaf, same as the pager's)
        total = sum(pp.nbytes for (mk, _t), pp in self._placements.items()
                    if mk == model_key and pp.placed is not None)
        PARAM_BYTES.set(total, model=model_key)

    def _forget_locked(self, p: "Placement"):
        # h2o3-ok: R003 _locked helper — every caller holds self._lock.
        # Un-account a placement leaving the store. Its in-memory
        # pytrees stay intact for in-flight holders (reattach/GC), but
        # the DISK artifact is owned by the store and freed exactly
        # once: the path is popped here and unlinked by the caller
        # outside the lock.
        prev = p._acct
        if prev is not None:
            for t, had in zip(_TIERS, prev):
                if had:
                    self._bytes[t] -= p.nbytes
        p._acct = None
        path, p.path = p.path, None
        return path

    def _registered_locked(self, p: "Placement") -> bool:
        # h2o3-ok: R003 _locked helper — every caller holds self._lock
        return p.key is not None and self._placements.get(p.key) is p

    # -- admission (ISSUE-6 in-flight reservation discipline) --------------
    def _try_reserve(self, nbytes: int, force: bool = False) -> bool:
        """Reserve HBM headroom BEFORE any device_put lands — resident
        + reserved never exceeds the budget, so concurrent cold faults
        cannot overshoot between transfer and accounting. `force` admits
        unconditionally (nothing left to demote — correctness over
        budget, exactly like the chunk pager)."""
        with self._lock:
            budget = _hbm_budget_bytes()
            if (force or not budget or
                    self._bytes[TIER_HBM] + self._reserved + nbytes
                    <= budget):
                self._reserved += nbytes
                return True
        return False

    def _release_reservation(self, nbytes: int):
        with self._lock:
            self._reserved -= nbytes

    # -- victim selection / eviction ---------------------------------------
    def _victim(self, tenant: str, exclude=None):
        """The next placement to demote for `tenant`'s fault: snapshot
        candidates under the lock, order OUTSIDE it (qos standing takes
        qos locks). Same-tenant cold placements go first, then other
        tenants in ascending QoS standing (heaviest consumers first),
        then coldest by the hotness clock — churn stays in its lane."""
        with self._lock:
            cands = [(p, p.tenant, p.last)
                     for k, p in self._placements.items()
                     if p.placed is not None and p is not exclude
                     and k[0] not in self._pinned]
        if not cands:
            return None

        def order(item):
            _p, owner, last = item
            if owner == tenant:
                return (0, 0.0, last)
            return (1, _standing(owner), last)
        cands.sort(key=order)
        return cands[0][0]

    def _make_room(self, incoming: int, tenant: str, exclude=None) -> bool:
        """Demote victims until `incoming` bytes fit under the HBM
        budget. False = nothing demotable (caller force-admits)."""
        budget = _hbm_budget_bytes()
        if not budget:
            return True
        while True:
            with self._lock:
                if (self._bytes[TIER_HBM] + self._reserved + incoming
                        <= budget):
                    return True
            vic = self._victim(tenant, exclude)
            if vic is None:
                return False
            self.demote(vic, charge=tenant)

    def demote(self, p: "Placement", charge: str | None = None,
               to_tier: str = TIER_HOST):
        """The DEMOTE primitive: gather the placed pytree back to host
        through `make_shard_and_gather_fns` gather_fns, canonicalize
        with `mesh._canon_host_leaf` (the same pass shard_params applies
        promoting — the bit-exact round-trip contract), drop the device
        copy; `to_tier="disk"` additionally spills the host pytree to an
        npz artifact under ice_root. The eviction is charged to the
        tenant whose fault forced it (`charge`), not the victim's owner."""
        tenant = charge if charge is not None else self._tenant()
        moved = False
        with p._io:
            if p.placed is not None:
                host = p.host
                if host is None:
                    host = self._gather_host(p)
                with self._lock:
                    p.host = host
                    p.placed = None
                    if self._registered_locked(p):
                        self._account_locked(p)
                moved = True
            if (to_tier == TIER_DISK and p.host is not None
                    and p.placed is None and p.path is None):
                from h2o3_tpu.io import spill as _spill
                leaves = jax.tree_util.tree_leaves(p.host)
                mk, tok = p.key if p.key is not None else ("params", 0)
                path = _spill.write_params(f"{mk}@{tok}", leaves)
                with self._lock:
                    p.path = path
                    p.host = None
                    if self._registered_locked(p):
                        self._account_locked(p)
                moved = True
        if moved:
            PARAM_EVICTIONS.inc(tier=to_tier, tenant=tenant)
            with self._lock:
                self._evictions_by_tenant[tenant] = \
                    self._evictions_by_tenant.get(tenant, 0) + 1

    @staticmethod
    def _gather_host(p: "Placement"):
        _shard_fns, gather_fns = _mesh.make_shard_and_gather_fns(p.specs)
        fetched = jax.tree_util.tree_map(lambda fn, leaf: fn(leaf),
                                         gather_fns, p.placed)
        return jax.tree_util.tree_map(_mesh._canon_host_leaf, fetched)

    def _spill_host_tier(self, tenant: str):
        """Enforce the host-tier budget after a fault/demote grew it:
        HBM-resident placements drop their (re-gatherable) host mirror
        first — free to reconstruct — then cold placements spill to
        disk, coldest first."""
        budget = _host_budget_bytes()
        if not budget:
            return
        while True:
            with self._lock:
                if self._bytes[TIER_HOST] <= budget:
                    return
                cands = [p for k, p in self._placements.items()
                         if p.host is not None and k[0] not in self._pinned]
                cands.sort(key=lambda pp: pp.last)
                vic = cands[0] if cands else None
            if vic is None:
                return
            if vic.placed is not None:
                with vic._io:
                    with self._lock:
                        if vic.placed is not None and vic.host is not None:
                            vic.host = None
                            if self._registered_locked(vic):
                                self._account_locked(vic)
            else:
                self.demote(vic, charge=tenant, to_tier=TIER_DISK)

    # -- promotion (fault) -------------------------------------------------
    def fault(self, p: "Placement"):
        """The PROMOTE primitive: place the canonical host pytree (read
        back from its npz artifact first when disk-resident) through the
        per-spec shard_fns, with admission reserved atomically BEFORE
        the device transfer starts. Mirrors ChunkPager.fault: reserve →
        transfer → account under the lock → release reservation; on a
        full device, demote victims and retry, force-admitting only
        when nothing is left to demote."""
        tenant = self._tenant()
        src = p.tier
        forced = False
        while True:
            with p._io:
                if p.placed is not None:
                    placed = p.placed
                    with self._lock:
                        p.last = self._tick()
                    return placed
                if self._try_reserve(p.nbytes, force=forced):  # h2o3-ok: R022 the commit CONVERTS the reservation to accounted bytes (self._reserved -= nbytes, reserved=False) inside its critical section; the finally releases exactly the uncommitted case — condition-variable pairing the path analysis cannot prove
                    stale_path = None
                    replaced_epoch = False
                    reserved = True
                    try:
                        host = p.host
                        if host is None:
                            from h2o3_tpu.io import spill as _spill
                            leaves = _spill.read_params(p.path)
                            host = jax.tree_util.tree_unflatten(
                                p.treedef, leaves)
                        cld = _mesh.cloud()
                        shard_fns, _g = _mesh.make_shard_and_gather_fns(
                            p.specs, cld)
                        placed = jax.tree_util.tree_map(
                            lambda fn, leaf: fn(leaf), shard_fns, host)
                        with self._lock:
                            p.placed = placed
                            replaced_epoch = p.epoch != cld.epoch
                            p.epoch = cld.epoch
                            p.host = host if self.tiering_active else None
                            stale_path, p.path = p.path, None
                            p.last = self._tick()
                            p.tenant = tenant
                            self._fault_count += 1
                            if self._registered_locked(p):
                                self._account_locked(p)
                            # convert the reservation to accounted bytes
                            # IN the commit's critical section, so
                            # admitted_bytes() (resident + reserved)
                            # never double-counts an in-flight fault at
                            # any observable instant
                            self._reserved -= p.nbytes
                            reserved = False
                    finally:
                        if reserved:
                            self._release_reservation(p.nbytes)
                    if stale_path is not None:
                        from h2o3_tpu.io import spill as _spill
                        _spill.delete_params(stale_path)
                    break
            forced = not self._make_room(p.nbytes, tenant, exclude=p)
        if src != TIER_HBM:
            PARAM_FAULTS.inc(tier=src)
        if replaced_epoch:
            PLACEMENTS.inc()    # epoch bump re-place (see _publish)
        self._spill_host_tier(tenant)
        return placed

    # -- placement ---------------------------------------------------------
    def _build_placement(self, model):
        """Compute a Placement WITHOUT the store lock held — the
        device_put of a large ensemble must not stall every other
        model's warm dispatches (which read the store per call). Returns
        None for families without a param export. Under a budget the
        build stops at the canonical HOST pytree (the demote
        primitive's output), so the initial device placement goes
        through the same reserved admission as any cold fault."""
        params = model._serving_params()
        if params is None:
            return None
        cld = _mesh.cloud()
        specs = _mesh.match_partition_rules(
            getattr(model, "_partition_rules", ()), params)
        treedef = jax.tree_util.tree_structure(params)
        if not self.tiering_active:
            placed = _mesh.shard_params(params, specs=specs, cld=cld)
            return Placement(placed, specs, _mesh.params_nbytes(placed),
                             cld.epoch, treedef=treedef)
        from h2o3_tpu.parallel import mrtask as _mrt
        host = jax.tree_util.tree_map(
            lambda leaf: _mesh._canon_host_leaf(
                _mrt.host_fetch(leaf) if isinstance(leaf, jax.Array)
                else leaf),
            params)
        return Placement(None, specs, _mesh.params_nbytes(host),
                         cld.epoch, host=host, treedef=treedef)

    def _publish(self, key, p: "Placement") -> "Placement":
        """Install a freshly built Placement under the lock; a racing
        builder's copy wins first-publish (the loser's arrays are
        GC'd). Returns the placement now in the store."""
        tenant = self._tenant()
        stale_path = None
        with self._lock:
            cur = self._placements.get(key)
            if cur is not None and cur.epoch == p.epoch:
                return cur
            if cur is not None:
                p.refs = cur.refs         # epoch re-place keeps the refs
                stale_path = self._forget_locked(cur)
            p.key = key
            p.tenant = tenant
            p.last = self._tick()
            self._placements[key] = p
            PLACEMENTS.inc()
            self._account_locked(p)
        if stale_path is not None:
            from h2o3_tpu.io import spill as _spill
            _spill.delete_params(stale_path)
        return p

    def acquire(self, model, token: int):
        """Place (or re-reference) the model's params; bumps the
        refcount. Called once per cache-entry build; each resident
        compiled bucket program holds exactly one reference. Returns the
        Placement, or None for families without a param export. Under a
        budget the first device placement rides `fault` (reserved
        admission, eviction on pressure)."""
        key = (model.key, token)
        with self._lock:
            p = self._placements.get(key)
            if p is not None:
                p.refs += 1
                p.last = self._tick()
                return p
        built = self._build_placement(model)        # outside the lock
        if built is None:
            return None
        p = self._publish(key, built)
        if p.placed is None:
            self.fault(p)
        with self._lock:
            p.refs += 1
        return p

    def reattach(self, model_key: str, token: int, p: "Placement"):
        """Re-install a placement an in-flight build acquired but a
        concurrent invalidate_key swept before the entry published —
        the entry's reference is live, so the store must know the
        placement again (or every dispatch would re-place one-shot)."""
        with self._lock:
            if (model_key, token) not in self._placements:
                p.key = (model_key, token)
                self._placements[(model_key, token)] = p
                self._account_locked(p)

    def placed(self, model, token: int):
        """The CURRENT placed pytree for a dispatch — faulting the
        placement back into HBM first when it was demoted, and
        re-placing when the mesh was rebuilt for a new cloud epoch (the
        old placement's arrays are laid out for a dead membership; the
        demote→fault hop gathers off the old mesh and places onto the
        new one, bit-exact by the canonicalization contract). Does not
        change the refcount; the calling cache entry already holds one."""
        key = (model.key, token)
        epoch = _mesh.cloud().epoch
        with self._lock:
            p = self._placements.get(key)
            if p is not None:
                p.last = self._tick()
                if p.placed is not None and p.epoch == epoch:
                    return p.placed
        if p is None or (p.placed is None and p.host is None
                         and p.path is None):
            # Placement gone while a dispatch was in flight: the entry
            # was evicted/invalidated (retrain purge, model DELETE)
            # between the cache lookup and this call — or swept with its
            # disk artifact already freed. Serve THIS request with a
            # one-shot placement that is never stored — storing it would
            # re-register the freed model with refs nothing will ever
            # release (a permanent HBM leak and a ghost gauge series for
            # a deleted model). One-shot placement is GC'd with the
            # dispatch.
            params = model._serving_params()
            if params is None:
                return None
            return _mesh.shard_params(
                params,
                rules=getattr(model, "_partition_rules", ()))
        if p.placed is not None and p.epoch != epoch:
            # stale epoch: gather off the old mesh, fault onto the new
            self.demote(p, charge=self._tenant())
        return self.fault(p)

    # -- pinning / explicit tier moves -------------------------------------
    def pin(self, model_key: str, on: bool = True):
        """Pin (or unpin) a model's placements against eviction — the
        tenant hot-set guard. Pinned placements still count against the
        budget; they are simply never victims."""
        with self._lock:
            if on:
                self._pinned.add(model_key)
            else:
                self._pinned.discard(model_key)

    def demote_key(self, model_key: str, to_tier: str = TIER_HOST):
        """Demote every device-resident placement of a model (tests,
        bench, and operator tooling)."""
        with self._lock:
            ps = [p for k, p in self._placements.items()
                  if k[0] == model_key]
        for p in ps:
            self.demote(p, to_tier=to_tier)

    # -- release -----------------------------------------------------------
    def release(self, model_key: str, token: int):
        """One cache entry dropped its reference; the LAST release frees
        the placement — every tier, exactly once (the npz artifact is
        unlinked outside the lock; device/host arrays free by GC)."""
        path = None
        with self._lock:
            p = self._placements.get((model_key, token))
            if p is None:
                return
            p.refs -= 1
            if p.refs <= 0:
                del self._placements[(model_key, token)]
                path = self._forget_locked(p)
                if not any(k[0] == model_key for k in self._placements):
                    PARAM_BYTES.remove(model=model_key)
                else:
                    self._gauge_locked(model_key)
        if path is not None:
            from h2o3_tpu.io import spill as _spill
            _spill.delete_params(path)

    def invalidate_key(self, model_key: str):
        """Model DELETE / retrain purge: drop every generation's
        placement for the DKV key regardless of refcount (the cache
        drops its entries in the same breath — see
        ScorerCache.invalidate_key), freeing all tiers exactly once."""
        paths = []
        with self._lock:
            for k in [k for k in self._placements if k[0] == model_key]:
                p = self._placements.pop(k)
                path = self._forget_locked(p)
                if path is not None:
                    paths.append(path)
            self._pinned.discard(model_key)
            PARAM_BYTES.remove(model=model_key)
        from h2o3_tpu.io import spill as _spill
        for path in paths:
            _spill.delete_params(path)

    def clear(self):
        paths = []
        with self._lock:
            keys = {k[0] for k in self._placements}
            for p in self._placements.values():
                path = self._forget_locked(p)
                if path is not None:
                    paths.append(path)
            self._placements.clear()
            self._pinned.clear()
            for mk in keys:
                PARAM_BYTES.remove(model=mk)
        from h2o3_tpu.io import spill as _spill
        for path in paths:
            _spill.delete_params(path)

    # -- introspection -----------------------------------------------------
    def bytes_for(self, model_key: str) -> int:
        """Logical bytes of the model's placements across all tiers."""
        with self._lock:
            return sum(p.nbytes for k, p in self._placements.items()
                       if k[0] == model_key)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(p.nbytes for p in self._placements.values())

    def by_model(self) -> dict:
        """{model_key: placement bytes} across resident generations —
        the /3/Usage HBM-attribution feed."""
        with self._lock:
            out: dict = {}
            for (mk, _tok), p in self._placements.items():
                out[mk] = out.get(mk, 0) + p.nbytes
            return out

    def by_model_tier(self) -> dict:
        """{model_key: {tier: bytes}} — which rung of the ladder each
        model's generations sit on."""
        with self._lock:
            out: dict = {}
            for (mk, _tok), p in self._placements.items():
                d = out.setdefault(mk, {t: 0 for t in _TIERS})
                d[p.tier] += p.nbytes
            return out

    def resident(self) -> int:
        with self._lock:
            return len(self._placements)

    def hbm_bytes(self) -> int:
        with self._lock:
            return self._bytes[TIER_HBM]

    def reserved_bytes(self) -> int:
        with self._lock:
            return self._reserved

    def admitted_bytes(self) -> int:
        """Resident + in-flight-reserved HBM bytes in ONE lock hold —
        the quantity the admission check bounds; ≤ budget at every
        instant (summing hbm_bytes() + reserved_bytes() from two
        separate calls can double-count a fault committing between
        them)."""
        with self._lock:
            return self._bytes[TIER_HBM] + self._reserved

    def tier_bytes(self) -> dict:
        with self._lock:
            return dict(self._bytes)

    def peak_hbm_bytes(self) -> int:
        with self._lock:
            return self._peak_hbm

    def reset_peak(self):
        with self._lock:
            self._peak_hbm = self._bytes[TIER_HBM]

    def stats(self) -> dict:
        with self._lock:
            return {
                "tier_bytes": dict(self._bytes),
                "reserved": self._reserved,
                "hbm_budget": _hbm_budget_bytes(),
                "host_budget": _host_budget_bytes(),
                "peak_hbm_bytes": self._peak_hbm,
                "faults": self._fault_count,
                "resident": len(self._placements),
                "pinned": sorted(self._pinned),
                "evictions_by_tenant": dict(self._evictions_by_tenant),
            }


PARAMS = ParamStore()

_om.gauge("h2o3_scorer_param_models",
          "model generations with a live shared serving-param placement",
          fn=lambda: float(PARAMS.resident()))


def _param_tier_series():
    return [({"tier": t}, float(b))
            for t, b in sorted(PARAMS.tier_bytes().items())]


_om.gauge("h2o3_serve_param_tier_bytes",
          "resident model-param bytes per tier of the serving ladder "
          "(hbm / host / disk)",
          fn=_param_tier_series)
