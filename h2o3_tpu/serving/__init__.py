"""Serving fast path: shape-bucketed compiled-scorer cache + micro-batched
REST scoring.

Entry points:
  * score_frame / score_frame_with_response — used by ModelBase.predict /
    _compute_metrics: recompile-free bucketed scoring, or None → legacy.
  * predict_via_rest — frame-based REST predictions routed through the
    micro-batch queue (concurrent requests coalesce into one dispatch).
  * score_payload — the lightweight row-payload scoring route: JSON rows
    in, per-row prediction dicts out, no DKV frame round-trip.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.serving.scorer_cache import (     # noqa: F401
    CACHE, FALLBACKS, Ineligible, model_token, prewarm, prewarm_all,
    prewarm_enabled, row_bucket, score_frame, score_frame_with_response,
    score_rows, stage_frame, stage_response, _fastpath_reason)
from h2o3_tpu.serving.params import PARAMS      # noqa: F401
from h2o3_tpu.serving.microbatch import (   # noqa: F401
    BATCHER, MicroBatcher, QueueFull)
from h2o3_tpu.serving import qos as _qos
from h2o3_tpu.serving.qos import (          # noqa: F401
    DeadlineExceeded, QuotaExceeded, RateLimited)
from h2o3_tpu.obs import usage as _usage


def _microbatch_eligible(model, nrows: int) -> bool:
    """Shared predicate for the two micro-batch entry points: models with
    a custom predict (isofor score frames, GLRM archetypes, …) own their
    output schema and must answer through model.predict; huge inputs and
    strike-parked models fall back too, as do multihost clouds for the
    few families WITHOUT a serving-param export (param-exporting
    families dispatch one SPMD program over the global mesh). Keep the
    frame route and the row-payload route agreeing on this."""
    from h2o3_tpu.serving import scorer_cache as _sc
    from h2o3_tpu.models.model import ModelBase
    return (type(model).predict is ModelBase.predict
            and _fastpath_reason(model, nrows) is None
            and not _sc._is_broken((model.key, model_token(model))))


def predict_via_rest(model, frame):
    """Micro-batched frame prediction for the REST layer. Ineligible
    inputs (huge frames, untraceable models) fall back to model.predict,
    which itself prefers the scorer cache."""
    from h2o3_tpu.serving import scorer_cache as _sc
    if not _microbatch_eligible(model, frame.nrows):
        # the HEAVY requests (oversized frames, custom-predict models,
        # multihost fallbacks) are exactly the ones a flooding tenant
        # leans on: QoS admission (deadline shed + token charge) applies
        # here too — only the queue-share cap is micro-batch-specific
        _qos.admit()
        return model.predict(frame)
    # shed BEFORE staging: a 503-bound request must not pay the
    # per-column decode + device_put only to be rejected at enqueue
    BATCHER.check_capacity()
    try:
        # frame adaptation + staging is the request's decode stage
        with _usage.stage("decode"):
            di = model._dinfo
            af = di.adapt(frame)
            raw = stage_frame(di, af, frame.nrows)
        out = BATCHER.score(model, raw, frame.nrows)
    except QueueFull:
        # backpressure is NOT degradation: falling back to model.predict
        # here would put the shed load right back on the stalled device.
        # Propagate so the REST layer answers 503 + Retry-After.
        raise
    except (RateLimited, QuotaExceeded, DeadlineExceeded):
        # QoS rejections likewise: a deadline-shed request scored on the
        # legacy path would pay the device for an answer nobody is
        # waiting for (and strike the model as broken on top)
        raise
    except Exception:   # noqa: BLE001 — serving must degrade, not 500
        _sc._note_failure((model.key, model_token(model)))
        FALLBACKS.inc(reason="trace-error")
        return model.predict(frame)
    return model._prediction_frame(out, frame.nrows)


def _cat_code(v, lut):
    if v is None or (isinstance(v, str) and v == ""):
        return np.nan
    if isinstance(v, str):
        return lut.get(v, np.nan)
    try:
        code = int(v)
    except (TypeError, ValueError):
        return np.nan
    return float(code) if 0 <= code < len(lut) else np.nan


def _num(v):
    if v is None or (isinstance(v, str) and v.strip() == ""):
        return np.nan
    try:
        return float(v)
    except (TypeError, ValueError):
        return np.nan


def payload_to_raw(model, rows, columns=None) -> np.ndarray:
    """JSON rows → (n, C_raw) staged f32 buffer in raw_columns() order.
    Rows are dicts {col: value} or lists aligned with `columns` (or with
    raw_columns() when columns is omitted). Categorical values may be
    level strings or in-domain integer codes; anything else is NA."""
    di = model._dinfo
    raw_cols = di.raw_columns()
    n = len(rows)
    raw = np.full((n, len(raw_cols)), np.nan, np.float32)
    if n == 0:
        return raw
    if isinstance(rows[0], dict):
        cells = {c: [r.get(c) for r in rows] for c in raw_cols}
    else:
        names = [str(c) for c in (columns or raw_cols)]
        pos = {c: names.index(c) for c in raw_cols if c in names}
        cells = {c: ([r[pos[c]] if pos[c] < len(r) else None for r in rows]
                     if c in pos else [None] * n)
                 for c in raw_cols}
    for j, c in enumerate(raw_cols):
        dom = di.domains.get(c)
        if dom is not None:
            lut = {str(l): float(i) for i, l in enumerate(dom)}
            raw[:, j] = [_cat_code(v, lut) for v in cells[c]]
        else:
            raw[:, j] = [_num(v) for v in cells[c]]
    return raw


def _payload_frame(model, raw: np.ndarray):
    """Rebuild a typed Frame from a staged raw buffer — the fallback for
    models the micro-batch fast path cannot serve (custom predict
    schemas, untraceable scorers, multihost)."""
    from h2o3_tpu.core.frame import Frame, Vec, T_CAT
    di = model._dinfo
    names, vecs = [], []
    for j, c in enumerate(di.raw_columns()):
        col = raw[:, j].astype(np.float64)
        mask = np.isnan(col)
        dom = di.domains.get(c)
        if dom is not None:
            vecs.append(Vec._from_floats(np.where(mask, 0.0, col), mask,
                                         T_CAT, np.asarray(dom, object)))
        else:
            vecs.append(Vec.from_numpy(col))
        names.append(c)
    return Frame(names, vecs)


def _frame_rows_to_dicts(pred) -> list:
    """Generic per-row dicts from a predictions Frame (whatever columns
    the model's predict emits: predict/p<level>, anomaly_score, Arch…)."""
    from h2o3_tpu.core.frame import T_CAT
    cols = []
    for name, vec in zip(pred.names, pred.vecs):
        vals = vec.to_numpy()
        if vec.type == T_CAT:
            dom = vec.domain
            cols.append((name, [None if np.isnan(v) else str(dom[int(v)])
                                for v in vals]))
        else:
            cols.append((name, [None if np.isnan(v) else float(v)
                                for v in vals]))
    return [{name: vals[i] for name, vals in cols}
            for i in range(pred.nrows)]


def score_payload(model, rows, columns=None) -> list:
    """Score raw JSON rows; returns one prediction dict per row. Models
    served by the base predict ride the micro-batch queue; custom-predict
    models (isofor/EIF/GLRM output schemas), untraceable scorers and
    multihost clouds go through a reconstructed Frame + model.predict so
    the route's answer always matches frame-based scoring."""
    from h2o3_tpu.serving import scorer_cache as _sc
    from h2o3_tpu.core.kvstore import DKV
    use_fast = _microbatch_eligible(model, len(rows))
    if use_fast:
        # shed before decoding the payload into a staging buffer
        BATCHER.check_capacity()
    else:
        # ineligible payloads still pay QoS admission (rate limit +
        # deadline shed) before any decode work — see predict_via_rest
        _qos.admit()
    with _usage.stage("decode"):
        raw = payload_to_raw(model, rows, columns)
    n = raw.shape[0]
    if n == 0:
        return []
    if use_fast:
        try:
            out = BATCHER.score(model, raw, n)
        except QueueFull:
            raise       # shed load at the REST edge (503), don't reroute
        except (RateLimited, QuotaExceeded, DeadlineExceeded):
            raise       # QoS rejections: 429/504, never a legacy re-score
        except Exception:   # noqa: BLE001 — degrade to the frame path
            _sc._note_failure((model.key, model_token(model)))
            FALLBACKS.inc(reason="trace-error")
            use_fast = False
    if use_fast:
        # same assembly as frame-based predict (_prediction_columns is
        # the single source of truth), just formatted as dicts
        cols = model._prediction_columns(np.asarray(out), n)
        preds = []
        for i in range(n):
            d = {}
            for name, vals, dom in cols:
                v = vals[i]
                if np.ndim(v):                      # multi-output rows
                    d[name] = [float(x) for x in v]
                elif np.isnan(v):
                    d[name] = None
                elif dom is not None:
                    d[name] = str(dom[int(v)])
                else:
                    d[name] = float(v)
            preds.append(d)
        return preds
    f = _payload_frame(model, raw)
    try:
        pred = model.predict(f)
    finally:
        DKV.remove(f.key)
    out_rows = _frame_rows_to_dicts(pred)
    DKV.remove(pred.key)
    return out_rows
