"""Micro-batching queue for REST scoring — one padded dispatch per bucket.

Concurrent `POST /3/Predictions/...` requests against the same model
coalesce into ONE device dispatch: the first arrival becomes the batch
leader, lingers a few milliseconds (H2O3_SCORE_LINGER_MS, default 2) for
followers, stacks every request's staged rows into one bucket-padded
buffer, runs the cached compiled scorer once, and fans the result rows
back out per request. Requests for different models (or different DKV
generations of the same key) never mix.

This converts serving throughput from O(dispatches == requests) to
O(dispatches == buckets): at high concurrency the accelerator sees a few
large padded batches instead of a stream of tiny ones.

Multi-tenant QoS (serving/qos.py): the single FIFO became per-principal
weighted-fair queues — requests coalesce only within their principal
(group key carries it), each tenant's occupancy of the global depth
bound is capped at its share, device slots are granted to ready
dispatches by deficit round-robin over configured weights, and a
request whose X-H2O3-Deadline-Ms budget elapsed is shed before staging
(entry) or skipped by its coalesced dispatch (a dead follower) — never
paid for on the device.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.deploy import chaos as _chaos
from h2o3_tpu.deploy import membership as _mb
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.obs import usage as _usage
from h2o3_tpu.obs.timeline import span as _span
from h2o3_tpu.serving import qos as _qos
from h2o3_tpu.serving import scorer_cache as _sc
from h2o3_tpu.utils.env import env_float, env_int

REQUESTS = _om.counter("h2o3_score_microbatch_requests_total",
                       "scoring requests entering the micro-batch queue")
DISPATCHES = _om.counter("h2o3_score_microbatch_dispatches_total",
                         "coalesced device dispatches leaving the queue")
REJECTED = _om.counter("h2o3_microbatch_rejected_total",
                       "scoring requests rejected by queue-depth "
                       "backpressure (HTTP 503 + Retry-After)")
WAIT_TIMEOUTS = _om.counter("h2o3_microbatch_wait_timeouts_total",
                            "follower requests whose bounded wait on the "
                            "batch leader expired (H2O3_SCORE_WAIT_S) — "
                            "a nonzero rate means dispatches are stalling")
BATCH_ROWS = _om.histogram("h2o3_score_microbatch_rows",
                           "real rows per coalesced dispatch",
                           buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                                    1024, 4096, 16384, 65536))
BATCH_SECONDS = _om.histogram(
    "h2o3_score_microbatch_seconds",
    "coalesced dispatch wall time (staging + device + readback); the "
    "exemplar carries one served request's trace id")

def _wait_s() -> float:
    """Follower safety timeout (seconds): the R008 rule forbids an
    unbounded Event.wait on the serving path — a leader that died between
    registration and dispatch must strand followers for a bounded time,
    not forever. Dispatch failures set per-request errors well before
    this fires; it is the backstop, not the control path."""
    return max(1.0, env_float("H2O3_SCORE_WAIT_S", 120.0))


class QueueFull(Exception):
    """Queue-depth backpressure: the caller should answer 503 with
    Retry-After rather than stacking another blocked thread. Raised
    instead of queueing so an overloaded accelerator sheds load at the
    REST edge (bounded memory, bounded thread count) — the ROADMAP's
    "micro-batch queue depth limit" gap."""

    def __init__(self, depth: int, limit: int, retry_after_s: int = 1):
        super().__init__(
            f"micro-batch queue full ({depth} pending >= limit {limit})")
        self.retry_after_s = retry_after_s


def _linger_s() -> float:
    return max(0.0, env_float("H2O3_SCORE_LINGER_MS", 2.0)) / 1e3


def _queue_depth_limit() -> int:
    """Max in-flight requests across all models (0 disables the bound).
    Default 512: at the default 2ms linger a healthy queue drains in a
    couple of dispatches, so hundreds of waiters means the device is
    stalled — shed rather than queue."""
    return env_int("H2O3_SCORE_QUEUE_DEPTH", 512)


class _Request:
    __slots__ = ("raw", "n", "event", "result", "error", "trace",
                 "principal", "deadline", "t_enqueue", "stages")

    def __init__(self, raw: np.ndarray, n: int):
        self.raw = raw
        self.n = n
        self.event = threading.Event()
        self.result = None
        self.error = None
        # latency decomposition: enqueue time anchors the per-request
        # queue-wait stage; the coalesced dispatch stamps its shared
        # stage timings (gate/decode/device/readback) here so the
        # submitting thread can merge them into its own waterfall
        self.t_enqueue = time.perf_counter()
        self.stages = None
        # submitting request's trace id: the coalesced dispatch span
        # links every parent trace it served
        self.trace = _tracing.current()
        # QoS context, captured on the submitting thread: the principal
        # keys the weighted-fair queue, and the deadline rides the
        # micro-batch so the coalesced dispatch can skip a follower
        # whose caller already gave up
        self.principal = _tracing.principal()
        self.deadline = _tracing.deadline()


class MicroBatcher:
    def __init__(self):
        self._lock = make_lock("microbatch")
        self._pending: dict = {}
        self._depth = 0       # in-flight requests (entered, not yet woken)
        self._queued: dict = {}   # principal -> in-flight request count

    def check_capacity(self):
        """Raise QueueFull when the in-flight bound is already hit — for
        callers to shed load BEFORE paying frame adaptation + staging.
        Also the QoS admission point (deadline shed → 504, token-bucket
        rate limit → 429, per-tenant queue share → 503): everything that
        can reject a request does so before the per-column decode.
        Advisory (no reservation): score() re-checks authoritatively."""
        _qos.admit()
        limit = _queue_depth_limit()
        principal = _tracing.principal()
        share_cap = _qos.tenant_share_cap(limit)
        with self._lock:
            if limit > 0 and self._depth >= limit:
                REJECTED.inc()
                raise QueueFull(self._depth, limit)
            held = self._share_held_locked(principal, limit, share_cap)
        if held is not None:
            self._share_rejected(principal, held, share_cap)

    def _share_held_locked(self, principal, limit, share_cap):
        """This principal's in-flight count when it is at/over its queue
        share (caller holds self._lock), else None. The one owner of the
        share-cap comparison for both admission sites."""
        if limit <= 0 or not principal:
            return None
        held = self._queued.get(principal, 0)   # h2o3-ok: R003 _locked helper — both callers hold self._lock
        return held if held >= share_cap else None

    @staticmethod
    def _share_rejected(principal, held, share_cap):
        """Share-cap rejection (→ 503): counters + raise, called OUTSIDE
        self._lock so the reject path never nests the metrics-registry
        lock inside the micro-batch lock in a new order."""
        REJECTED.inc()
        _qos.note_share_reject(principal)
        raise QueueFull(held, share_cap)

    def queued_by_principal(self) -> dict:
        """Snapshot of per-principal in-flight counts (the
        h2o3_qos_queue_depth{principal} gauge callback). LOCK-FREE
        (GIL-atomic dict copy), like the depth gauge: the callback runs
        under the metrics-registry lock while admission emits counters
        under the micro-batch lock — taking self._lock here would be
        the reverse order edge (lockdep inversion)."""
        return dict(self._queued)

    def score(self, model, raw: np.ndarray, n: int) -> np.ndarray:
        """Submit (n, C) staged raw rows; returns the (n, ...) host result
        for exactly these rows. Blocks until the coalesced dispatch lands.
        Raises QueueFull (→ HTTP 503) when the in-flight bound — or the
        submitting tenant's share of it — is hit.
        """
        REQUESTS.inc()
        req = _Request(np.asarray(raw[:n], np.float32), n)
        # token (not DKV version): requests only coalesce when they hold
        # the SAME model object, so a mid-stream overwrite can never mix
        # two generations in one dispatch. The PRINCIPAL is part of the
        # key: tenants never share a coalesced dispatch, so each group
        # charges exactly one tenant at the fair gate.
        key = (model.key, _sc.model_token(model), raw.shape[1],
               req.principal)
        limit = _queue_depth_limit()
        share_cap = _qos.tenant_share_cap(limit)
        share_held = None
        with self._lock:
            if limit > 0 and self._depth >= limit:
                REJECTED.inc()
                raise QueueFull(self._depth, limit)
            share_held = self._share_held_locked(req.principal, limit,
                                                 share_cap)
            if share_held is None:
                self._depth += 1
                if req.principal:
                    self._queued[req.principal] = \
                        self._queued.get(req.principal, 0) + 1
                group = self._pending.get(key)
                leader = group is None
                if leader:
                    group = self._pending[key] = []
                group.append(req)
        if share_held is not None:
            # deferred out of the lock: enqueue must be atomic with the
            # check, but the rejection counters must not emit under it
            self._share_rejected(req.principal, share_held, share_cap)
        _qos.note_interactive_start()
        try:
            out = self._await_result(model, key, req, leader)
            # fold the dispatch's stamped stage timings (queue/gate/
            # device/readback) into THIS thread's request waterfall —
            # followers inherit the breakdown the leader measured
            if req.stages:
                _usage.merge_stages(req.stages)
            return out
        finally:
            _qos.note_interactive_end()
            with self._lock:
                self._depth -= 1
                if req.principal:
                    left = self._queued.get(req.principal, 0) - 1
                    if left <= 0:
                        self._queued.pop(req.principal, None)
                    else:
                        self._queued[req.principal] = left

    def _await_result(self, model, key, req, leader) -> np.ndarray:
        if leader:
            batch = None
            try:
                linger = _linger_s()
                if linger > 0:
                    time.sleep(linger)
                with self._lock:
                    batch = self._pending.pop(key)
                self._dispatch(model, batch)
            except BaseException as ex:
                # the group must NEVER be orphaned: a leader failure
                # before the pop (or a non-Exception during dispatch)
                # would otherwise leave followers blocking on a dead
                # batch — and every later request joining it
                if batch is None:
                    with self._lock:
                        batch = self._pending.pop(key, None) or []
                err = ex if isinstance(ex, Exception) \
                    else RuntimeError(repr(ex))
                for r in batch:
                    if not r.event.is_set():
                        r.error = r.error or err
                        r.event.set()
                raise
        else:
            # watchdog-watched: a follower stuck behind a wedged leader
            # dispatch is a stall the sentinel should diagnose (cluster
            # JStack shows WHERE the leader is stuck) before the bounded
            # wait below turns it into a plain timeout — so the watch
            # deadline must undercut H2O3_SCORE_WAIT_S, after which this
            # context exits and the sentinel has nothing left to see
            from h2o3_tpu.obs import watchdog as _wd
            with _wd.watch("microbatch",
                           desc=f"follower wait {model.key}",
                           deadline_s=min(_wait_s() / 2,
                                          _wd._stall_s()),
                           trace=req.trace):
                ok = req.event.wait(timeout=_wait_s())
            if not ok:
                WAIT_TIMEOUTS.inc()
                raise TimeoutError(
                    "micro-batched scoring dispatch timed out "
                    f"after {_wait_s():g}s (H2O3_SCORE_WAIT_S)")
        if req.error is not None:
            raise req.error
        return req.result

    @staticmethod
    def _dispatch(model, batch):
        # chunk so one coalesced dispatch never exceeds the fast-path row
        # ceiling each request passed individually — 32×65k-row requests
        # must not fuse into one 2M-row bucket (new giant program, HBM
        # spike). A single request is already ≤ the cap by eligibility.
        cap = _sc._max_rows()
        chunks, cur, cur_rows = [], [], 0
        for r in batch:
            if cur and cur_rows + r.n > cap:
                chunks.append(cur)
                cur, cur_rows = [], 0
            cur.append(r)
            cur_rows += r.n
        chunks.append(cur)
        for chunk in chunks:
            MicroBatcher._dispatch_chunk(model, chunk)

    @staticmethod
    def _dispatch_chunk(model, batch):
        # deadline-aware shedding BEFORE staging or device dispatch: a
        # follower whose X-H2O3-Deadline-Ms budget elapsed while the
        # batch formed is answered 504 here — it contributes no rows, no
        # staging copy, and (when the whole chunk is dead) no dispatch
        # and no scorer compile at all. Gated off on multi-controller
        # runtimes: the workers replayed the broadcast and will join the
        # collective dispatch regardless, so the coordinator must too
        # (see qos.single_controller).
        now = time.monotonic()
        dead = [r for r in batch
                if _qos.deadline_dead(r.deadline, now)] \
            if _qos.single_controller() else []
        if dead:
            batch = [r for r in batch if not _qos.deadline_dead(r.deadline,
                                                                now)]
            for r in dead:
                r.error = _qos.DeadlineExceeded(now - r.deadline)
                r.event.set()
                _qos.SHED.inc(reason="batch")
        if not batch:
            return
        try:
            total = sum(r.n for r in batch)
            bucket = _sc.row_bucket(total)
            C = batch[0].raw.shape[1]
            # one coalesced dispatch serves N parent requests: the span
            # carries the leader's trace id AND links every follower's,
            # so each parent's GET /3/Trace/{id} shows this dispatch.
            # Trace-gated like scorer/mrtask spans: fully untraced
            # dispatches must not churn the bounded timeline ring
            links = sorted({r.trace for r in batch if r.trace})
            ctx = _span("microbatch.dispatch", rows=total,
                        requests=len(batch), links=links) \
                if links or _tracing.current() is not None \
                else contextlib.nullcontext()
            # weighted-fair gate: groups are single-principal (the key
            # carries it), so the whole chunk charges one tenant; under
            # device-slot contention grants follow deficit round-robin
            # over the configured weights. The queue-wait stage for every
            # request ends HERE (batch formed, dispatch starting); the
            # gate wait is its own stage.
            t_gate = time.perf_counter()
            took = _qos.GATE.acquire(batch[0].principal or _qos.ANONYMOUS,
                                     total)
            try:
                # timing reads live INSIDE the try: any statement between
                # acquire and the finally is a path that leaks the slot
                # if it raises (R022)
                t0 = time.perf_counter()
                gate_s = t0 - t_gate
                with ctx as sp, _usage.capture_stages() as shared:
                    with _usage.stage("decode"):
                        raw = np.full((bucket, C), np.nan, np.float32)
                        off = 0
                        for r in batch:
                            raw[off:off + r.n] = r.raw
                            off += r.n
                    # membership-aware dispatch: a scoring batch straddling
                    # a cloud-epoch bump (a worker excised mid-request)
                    # retries once with jittered backoff against the new
                    # epoch instead of failing all N coalesced requests.
                    # The chaos hook lets the fault harness fail a seeded
                    # dispatch deterministically.
                    def _score():
                        _chaos.maybe_raise("microbatch.dispatch",
                                           exc=_mb.EpochChanged)
                        return _sc.score_rows(model, raw, total,
                                              links=links)

                    out = _mb.retry_once(_score, op="microbatch")
                    # gate wait joins the captured decode/device/readback
                    # splits; the breakdown rides the dispatch span too
                    # (stamped before the span closes — the flight
                    # recorder snapshots at end)
                    shared["gate"] = shared.get("gate", 0.0) + gate_s
                    if sp is not None:
                        sp.attrs["stages"] = {k: round(v, 6)
                                              for k, v in shared.items()}
            finally:
                _qos.GATE.release(took)
            DISPATCHES.inc()
            # one served trace id rides each histogram as an OpenMetrics
            # exemplar, so a dispatch-latency spike resolves to a trace
            ex = links[0] if links else _tracing.current()
            BATCH_ROWS.observe(total, exemplar=ex)
            BATCH_SECONDS.observe(time.perf_counter() - t0, exemplar=ex)
            # stamp the waterfall onto every served request: queue wait
            # is per-request (enqueue → dispatch start); the gate wait
            # and captured decode/device/readback are chunk-shared —
            # each coalesced caller experienced that same wall time
            off = 0
            for r in batch:
                st = {"queue": max(0.0, t_gate - r.t_enqueue)}
                st.update(shared)
                r.stages = st
                r.result = out[off:off + r.n]
                off += r.n
        except Exception as ex:   # noqa: BLE001 — every waiter must wake
            for r in batch:
                r.error = ex
        finally:
            for r in batch:
                r.event.set()


BATCHER = MicroBatcher()

# module-level registration reading the module global: bound to whatever
# BATCHER currently is, not to the first instance ever constructed (the
# registry keeps the first fn per name, so an instance-bound closure
# would pin a replaced batcher and report its dead depth forever)
_om.gauge("h2o3_microbatch_queue_depth",
          "scoring requests currently inside the micro-batch queue",
          fn=lambda: float(BATCHER._depth))
