"""Compiled-scorer cache — the recompile-free, mesh-sharded serving fast
path.

Problem: every `Model.predict` used to trace + XLA-compile a fresh program
per unique row count (DataInfo.matrix jits a closure per call; several
algos jitted per-call lambdas inside `_score_matrix`). Serving latency was
dominated by compiles, not MXU time — the `h2o3_xla_compiles_total`
counter climbed once per request.

Design (hex/Model.java:1764 BigScore, re-keyed for XLA):
  * Rows are padded up to POWER-OF-TWO buckets (then to the mesh row
    granule), so any row count inside a bucket replays one resident
    program. Padded rows carry NaN raw values; predictions for them are
    garbage by construction and are trimmed host-side, while the metrics
    path stages a weight vector that is 0 on padding — padded rows can
    never poison predictions or aggregates.
  * ONE jitted program per cache key compiles the whole pipeline:
    raw staged columns → DataInfo.assemble_design (one-hot/standardize/
    impute/interactions) → the algo's scorer (tree gather loop, GLM
    link, DL forward, KMeans assign, NB posterior, …).
  * Model params ride as SHARED DEVICE ARGUMENTS, not baked constants:
    a family exporting `_serving_params()` has its param pytree mapped
    through regex partition rules (`parallel.mesh.match_partition_rules`)
    to `PartitionSpec`s and placed ONCE per model generation as
    `NamedSharding` device arrays (`serving.params.PARAMS`). Every
    row-bucket program of the model — and, on a multi-controller cloud,
    every host — dispatches against that single copy, so per-model HBM
    is constant in the number of buckets and multihost models ride the
    fast path instead of falling back. Families without a param export
    keep the legacy baked-constant build (single-host only).
  * Cache key = (model key, model-object generation token, raw column
    signature, dtype, bucket). The token is minted per model OBJECT
    (weakref map), so overwriting a DKV key with a retrained model — a
    different object — can never hit the old program, even when the
    overwrite races an in-flight request holding the old object. The
    param store is keyed by the same token: program invalidation and
    placement invalidation move together.
  * Staging is HOST-side (numpy decode of the packed Vec codecs) into a
    bucket-sized buffer + one `device_put` — neither ever compiles, which
    is what makes "3 row counts in one bucket == 1 compile" hold.
  * The staged device buffer is DONATED to the program (non-CPU backends),
    so steady-state scoring reuses the same HBM for staging instead of
    allocating fresh buffers per request. Placed params are never donated.
  * Every dispatch rides `parallel.compat.guarded_jit` — on host (CPU)
    meshes a scorer program over sharded args contains collectives, and
    an unguarded concurrent launch re-opens the ISSUE-10 XLA:CPU
    rendezvous hang (analyzer rule R014 rejects raw jit/pjit here).

  * With `H2O3_SERVE_HBM_BUDGET_MB` set, a cache entry's param
    REFERENCE no longer implies device RESIDENCY: placements ride the
    serving three-tier ladder (HBM ⇄ host ⇄ ice_root npz, see
    serving/params.py) and each dispatch's `PARAMS.placed()` faults a
    demoted model back in through reserved admission — the compiled
    program is byte-cheap and stays cached while its params page, so a
    cold model costs one device_put, never a recompile.

Env knobs:
  H2O3_SCORER_CACHE_SIZE      max resident programs (LRU; default 64)
  H2O3_SCORE_MIN_BUCKET       smallest row bucket (default 128)
  H2O3_SCORE_FASTPATH_MAX_ROWS  row-count ceiling for the fast path
                              (default 1<<20); larger batches take the
                              legacy sharded path whose compile amortizes
  H2O3_SCORER_PREWARM         1 → compile the smallest bucket (and place
                              params) on model publish AND on replacement
                              -worker join, so first requests warm-hit
  H2O3_SERVE_HBM_BUDGET_MB    byte budget for device-resident model
                              params (serving/params.py; 0 = eager,
                              unbudgeted placement)
  H2O3_SERVE_HOST_BUDGET_MB   byte budget for the host tier of demoted
                              params; overflow spills to ice_root
"""

from __future__ import annotations

import itertools
import threading
import weakref
from collections import OrderedDict

import jax
import numpy as np

from h2o3_tpu.analysis.lockdep import make_lock, make_rlock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import modelmon as _modelmon
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.obs import usage as _usage
from h2o3_tpu.obs.timeline import span as _span
from h2o3_tpu.parallel import compat as _compat
from h2o3_tpu.parallel import mesh as _mesh
from h2o3_tpu.parallel import mrtask as _mrt
from h2o3_tpu.serving.params import PARAMS
from h2o3_tpu.utils.env import env_bool, env_int

HITS = _om.counter("h2o3_scorer_cache_hits_total",
                   "compiled-scorer cache hits (no trace, no compile)")
MISSES = _om.counter("h2o3_scorer_cache_misses_total",
                     "compiled-scorer cache misses (one trace+compile each)")
EVICTIONS = _om.counter("h2o3_scorer_cache_evictions_total",
                        "compiled scorers dropped by the LRU bound")
FALLBACKS = _om.counter("h2o3_scorer_fallbacks_total",
                        "scoring requests that took the legacy path, "
                        "labeled by reason")
ROWS_SCORED = _om.counter("h2o3_score_rows_total",
                          "real (unpadded) rows scored via the fast path")


def _cache_size() -> int:
    return env_int("H2O3_SCORER_CACHE_SIZE", 64)


def _min_bucket() -> int:
    return env_int("H2O3_SCORE_MIN_BUCKET", 128)


def _max_rows() -> int:
    return env_int("H2O3_SCORE_FASTPATH_MAX_ROWS", 1 << 20)


def row_bucket(n: int) -> int:
    """Power-of-two bucket ≥ n (≥ the min bucket), rounded to the mesh row
    granule so the staged buffer row-shards evenly."""
    b = _min_bucket()
    while b < n:
        b <<= 1
    return _mesh.cloud().padded_rows(b)


# ---------------------------------------------------------------------------
# host-side decode of the packed Vec planes (no device programs, no compiles)
class Ineligible(Exception):
    """Raised during staging when a column cannot ride the fast path."""


def _decode_host(vec) -> np.ndarray:
    """(nrows,) f32 with NaN NAs decoded from a Vec's packed device plane.
    One device→host copy of the PACKED dtype; the codec math runs in numpy.

    Every device read here is jax.device_get — an EXPLICIT transfer — so
    the whole warm scoring path runs clean under
    jax.transfer_guard("disallow"), which only admits spelled-out
    transfers. The tier-1 sanitizer test holds the path to that bar; an
    np.asarray sneaking back in fails it.
    """
    from h2o3_tpu.core.frame import SparseVec
    n = vec.nrows
    if isinstance(vec, SparseVec):
        out = np.zeros(n, np.float32)
        rows = np.asarray(jax.device_get(vec.nz_rows))
        vals = np.asarray(jax.device_get(vec.nz_vals))
        keep = rows < n
        out[rows[keep]] = vals[keep]
        return out
    ch = getattr(vec, "_chunk", None)
    if ch is None:
        raise Ineligible(f"column type {vec.type!r} has no numeric staging")
    # tier-aware staging: resident host codec bytes are read in place
    # (zero transfers); an HBM-only chunk costs ONE explicit device_get
    # (transfer-guard-clean); a disk chunk loads to host WITHOUT faulting
    # the packed planes into HBM just to copy them back out
    data_h, mask_h = ch.staging_view()
    data = np.asarray(data_h)[:n]
    c = vec.codec
    if c.kind == "const":
        out = np.full(n, np.float32(c.const_val), np.float32)
    else:
        out = data.astype(np.float32)
        if c.bias:
            out = out + np.float32(c.bias)
    if mask_h is not None:
        m = np.asarray(mask_h)[:n]
        out = np.where(m != 0, np.float32(np.nan), out)
    return out


def stage_frame(dinfo, frame, rows: int) -> np.ndarray:
    """(rows, C_raw) f32 staging buffer: the ADAPTED frame's raw predictor
    columns in dinfo.raw_columns() order, NaN beyond frame.nrows."""
    cols = dinfo.raw_columns()
    raw = np.full((rows, len(cols)), np.nan, np.float32)
    n = frame.nrows
    for j, c in enumerate(cols):
        raw[:n, j] = _decode_host(frame.vec(c))
    return raw


def stage_response(dinfo, frame, rows: int):
    """(y, w) host vectors at bucket size: y NaN beyond n; w is 0 on
    padding rows AND rows with missing response (the BigScore skip-NA
    contract) so padded rows drop out of every weighted aggregate."""
    n = frame.nrows
    y = np.full(rows, np.nan, np.float32)
    y[:n] = _decode_host(frame.vec(dinfo.response_name))
    w = np.zeros(rows, np.float32)
    if dinfo.weights_name and dinfo.weights_name in frame.names:
        wv = _decode_host(frame.vec(dinfo.weights_name))
        w[:n] = np.where(np.isnan(wv), 0.0, wv)
    else:
        w[:n] = 1.0
    return y, np.where(np.isnan(y), 0.0, w)


# ---------------------------------------------------------------------------
# Per-model-object generation tokens. The cache key must pin the EXACT
# model object a program closed over; re-reading a DKV version at lookup
# time races with concurrent overwrites (thread A holds the old object,
# thread B re-puts the key, A would cache the old model under the new
# generation). A token minted per object travels with the object: an
# overwritten DKV key maps to a different object, hence a different
# token, and the stale program can never be hit again.
_TOKENS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_TOKEN_COUNTER = itertools.count(1)
_TOKEN_LOCK = make_lock("scorer_cache.tokens")


def model_token(model) -> int:
    with _TOKEN_LOCK:
        t = _TOKENS.get(model)
        if t is None:
            t = _TOKENS[model] = next(_TOKEN_COUNTER)
        return t


class _Program:
    """One resident compiled scorer: a callable taking the staged device
    rows. Param-sharing programs look up the CURRENT shared placement on
    every dispatch (a cloud-epoch mesh rebuild re-places transparently)
    and hold one param-store reference, released exactly once when the
    entry leaves the cache — however it leaves (LRU, stale-generation
    purge, model DELETE, clear)."""

    __slots__ = ("_jfn", "model_key", "token", "shares_params", "_model",
                 "placement")

    def __init__(self, jfn, model, token, shares_params, placement=None):
        self._jfn = jfn
        self._model = model
        self.model_key = model.key
        self.token = token
        self.shares_params = shares_params
        self.placement = placement

    def __call__(self, raw_dev):
        if self.shares_params:
            return self._jfn(PARAMS.placed(self._model, self.token),
                             raw_dev)
        return self._jfn(raw_dev)

    def release(self):
        if self.shares_params:
            PARAMS.release(self.model_key, self.token)


class ScorerCache:
    """LRU of compiled scorer programs, keyed by
    (model key, model-object token, raw column signature, dtype, bucket).
    """

    def __init__(self):
        self._lock = make_rlock("scorer_cache")
        self._entries: OrderedDict = OrderedDict()
        self._building: dict = {}   # key → per-key build lock
        _om.gauge("h2o3_scorer_cache_entries",
                  "compiled scorer programs currently resident",
                  fn=lambda: float(len(self._entries)))

    def program(self, model, bucket: int):
        return self.program_ex(model, bucket)[0]

    def program_ex(self, model, bucket: int):
        """(compiled fn, warm_hit) — warm_hit distinguishes the
        "scorer.warm_hit" vs "scorer.compile" span the dispatch records."""
        di = model._dinfo
        key = (model.key, model_token(model),
               tuple(di.raw_columns()), "float32", bucket)
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self._entries.move_to_end(key)
                HITS.inc()
                return fn, True
            # per-key build lock: concurrent cold misses for the same
            # program must compile ONCE — the second caller waits for the
            # first instead of paying a duplicate multi-second compile
            # one lockdep class for every per-key build lock: instances
            # differ, the ordering discipline is shared
            build_lock = self._building.setdefault(
                key, make_lock("scorer_cache.build"))
        with build_lock:
            with self._lock:
                fn = self._entries.get(key)
                if fn is not None:
                    self._entries.move_to_end(key)
                    HITS.inc()
                    return fn, True
            MISSES.inc()
            try:
                fn = self._build(model)
            except Exception:
                with self._lock:
                    self._building.pop(key, None)
                raise
            # publish while STILL holding the build lock: a queued
            # cold-miss thread must find the entry on its double-check,
            # not rebuild it
            with self._lock:
                self._building.pop(key, None)
                # purge other generations of this DKV key NOW rather than
                # waiting for LRU pressure: entries close over the model
                # object, so a retrain loop would otherwise pin dead
                # models (and their compiled executables) in memory
                stale = [k for k in self._entries
                         if k[0] == key[0] and k[1] != key[1]]
                for k in stale:
                    self._entries.pop(k).release()
                    EVICTIONS.inc()
                with _BROKEN_LOCK:
                    for k in [b for b in _BROKEN
                              if b[0] == key[0] and b[1] != key[1]]:
                        _BROKEN.pop(k, None)
                self._entries[key] = fn
                if fn.shares_params and fn.placement is not None:
                    # an invalidate_key that raced this build swept the
                    # placement the entry references — re-install it so
                    # dispatches don't degrade to one-shot re-placement
                    PARAMS.reattach(key[0], key[1], fn.placement)
                while len(self._entries) > _cache_size():
                    _, old = self._entries.popitem(last=False)
                    old.release()
                    EVICTIONS.inc()
        return fn, False

    @staticmethod
    def _build(model) -> "_Program":
        di = model._dinfo
        token = model_token(model)
        # donate the staged buffer: the program may alias its HBM for the
        # design matrix / outputs, so steady-state scoring does no fresh
        # allocation. CPU has no donation — gate it to avoid warnings.
        # The shared param pytree is NEVER donated.
        cpu = jax.default_backend() == "cpu"
        placement = PARAMS.acquire(model, token)
        if placement is not None:
            # mesh-sharded fast path: params enter as NamedSharding-placed
            # device args shared by every bucket of this model (and every
            # host); jit reads the committed shardings off the arrays, so
            # this is the pjit spelling without re-stating in_shardings
            def _score_p(params, raw):
                return model._score_with_params(params,
                                                di.assemble_design(raw))

            jfn = _compat.guarded_jit(
                _score_p, donate_argnums=() if cpu else (1,))
            return _Program(jfn, model, token, shares_params=True,
                            placement=placement)

        # legacy baked-constant build for families without a param
        # export: params trace in as closure constants, one copy PER
        # BUCKET — single-host only (see _fastpath_reason "multihost")
        def _score(raw):
            return model._score_matrix(di.assemble_design(raw))

        jfn = _compat.guarded_jit(
            _score, donate_argnums=() if cpu else (0,))
        return _Program(jfn, model, token, shares_params=False)

    def invalidate_key(self, model_key: str):
        """Drop every resident program (and failure strikes) for a DKV
        model key — called on model deletion so the cache's closures stop
        pinning the dead model. Releases each entry's param-store
        reference, then sweeps any placement left (a prewarm that placed
        params but lost its entry to LRU pressure mid-build). Other
        deletions are bounded by the LRU."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == model_key]:
                self._entries.pop(k).release()
                EVICTIONS.inc()
            with _BROKEN_LOCK:
                for b in [b for b in _BROKEN if b[0] == model_key]:
                    _BROKEN.pop(b, None)
            PARAMS.invalidate_key(model_key)

    def clear(self):
        with self._lock:
            for entry in self._entries.values():
                entry.release()
            self._entries.clear()
            PARAMS.clear()


CACHE = ScorerCache()

# (model key, token) → (consecutive failure count, last failure time).
# Three consecutive strikes PARK the model on the legacy path for a
# cooldown window rather than permanently: _is_broken short-circuits
# before any attempt, so without the cooldown no _note_success could ever
# run again and one bad burst (e.g. three co-batched timeouts during a
# single device stall) would disable the model for the process lifetime.
# After the cooldown one probe attempt is allowed — success clears the
# record, failure re-arms the window. A retrain mints a new token and
# starts clean; stale tokens are pruned on the next compile for the key.
_BROKEN: dict = {}
_BROKEN_LOCK = make_lock("scorer_cache.broken")
_BROKEN_STRIKES = 3
_BROKEN_COOLDOWN_S = 60.0


def _note_failure(key: tuple):
    import time as _time
    with _BROKEN_LOCK:
        count = _BROKEN.get(key, (0, 0.0))[0] + 1
        _BROKEN[key] = (count, _time.monotonic())


def _note_success(key: tuple):
    with _BROKEN_LOCK:
        _BROKEN.pop(key, None)


def _is_broken(key: tuple) -> bool:
    import time as _time
    with _BROKEN_LOCK:
        count, last = _BROKEN.get(key, (0, 0.0))
    if count < _BROKEN_STRIKES:
        return False
    return _time.monotonic() - last < _BROKEN_COOLDOWN_S


def _shares_params(model) -> bool:
    """True when the family exports a serving-param pytree — the
    mesh-sharded build with one shared HBM copy and multihost support."""
    try:
        return model._serving_params() is not None
    except Exception:   # noqa: BLE001 — an export bug falls back, not 500s
        return False


def _fastpath_reason(model, nrows: int):
    """None when the fast path applies, else a fallback-counter label."""
    di = getattr(model, "_dinfo", None)
    if di is None or not getattr(model, "key", None):
        return "no-dinfo"
    if jax.process_count() > 1 and not _shares_params(model):
        # only the legacy baked-constant build is host-local; families
        # exporting param pytrees dispatch one SPMD program over the
        # global mesh (params placed identically on every host by the
        # replay contract), so they stay on the fast path
        return "multihost"
    if nrows <= 0:
        return "empty"
    if nrows > _max_rows():
        return "too-large"
    if getattr(model, "_serving_fastpath", True) is False:
        return "model-opt-out"
    return None


def score_rows(model, raw: np.ndarray, n: int, links=()) -> np.ndarray:
    """Dispatch a staged (bucket, C) host buffer through the cached
    program. Returns the HOST result still at bucket length (rows beyond n
    are garbage; callers trim). `links` are additional trace ids served by
    this dispatch (micro-batch followers)."""
    import contextlib
    fn, warm = CACHE.program_ex(model, raw.shape[0])
    # compile spans ALWAYS record (rare, expensive, exactly what a trace
    # viewer needs); warm-hit spans only under an active trace — the
    # steady-state hot path pays nothing when nobody is looking
    if not warm or _tracing.current() is not None or links:
        attrs = {"bucket": raw.shape[0], "rows": n, "model": model.key}
        if links:
            attrs["links"] = list(links)
        ctx = _span("scorer.warm_hit" if warm else "scorer.compile",
                    **attrs)
    else:
        ctx = contextlib.nullcontext()
    # usage attribution: the scorer is the funnel layer that knows the
    # MODEL and row count, so its meter owns the charge (kind `score`);
    # the guarded jit's inner meter is suppressed. The device/readback
    # stage splits feed the request waterfall (micro-batch capture or
    # the caller's own recorder).
    with ctx, _usage.meter("score", model=model.key, rows=n):
        with _usage.stage("device"):
            out = fn(_mrt.device_put_rows(raw))
        ROWS_SCORED.inc(n)
        # device_get, not np.asarray: the result fetch is the one intended
        # device→host transfer on this path — keep it explicit so the
        # transfer-guard sanitizer admits it. A multi-controller result
        # whose shards live on other processes' devices gathers first (the
        # MRTask result-collection hop) — host_fetch owns that allgather.
        with _usage.stage("readback"):
            if isinstance(out, jax.Array) and not out.is_fully_addressable:
                host = np.asarray(_mrt.host_fetch(out))
            else:
                host = np.asarray(jax.device_get(out))
    # drift tap: fold the batch into the model's live sketch — pure
    # host-side numpy over the ALREADY-staged raw buffer and the host
    # result (zero extra device work); a no-op for unmonitored models
    # and guaranteed never to break scoring (modelmon owns the guard)
    _modelmon.observe(model, raw, host, n)
    return host


def _fast_scored(model, frame, with_response: bool):
    """Shared eligibility + strike accounting + staged dispatch for the
    two frame entry points. Returns the fast-path result or None (legacy
    path)."""
    reason = _fastpath_reason(model, frame.nrows)
    if reason is not None:
        FALLBACKS.inc(reason=reason)
        return None
    key = (model.key, model_token(model))
    if _is_broken(key):
        FALLBACKS.inc(reason="trace-error")
        return None
    try:
        di = model._dinfo
        af = di.adapt(frame)
        bucket = row_bucket(frame.nrows)
        raw = stage_frame(di, af, bucket)
        yw = stage_response(di, af, bucket) if with_response else None
        out = score_rows(model, raw, frame.nrows)
        _note_success(key)
        return (out, *yw) if with_response else out
    except Exception:   # noqa: BLE001 — fast path must never break scoring
        _note_failure(key)
        FALLBACKS.inc(reason="trace-error")
        from h2o3_tpu.utils import log as _log
        import traceback
        _log.warn(f"serving fast path failed for {key}: "
                  f"{traceback.format_exc(limit=3)}")
        return None


def score_frame(model, frame):
    """Fast-path scoring of a Frame: host result at bucket length, or None
    when the caller must take the legacy sharded path."""
    return _fast_scored(model, frame, with_response=False)


# ---------------------------------------------------------------------------
# Pre-warm on model publish (ROADMAP ISSUE-2 gap). Serving's first request
# to a fresh model pays the full trace+XLA compile; with
# H2O3_SCORER_PREWARM=1 the publish path compiles the MOST COMMON serving
# bucket — the minimum row bucket, where row-payload and small-frame
# requests land — in the background, so that first request records a
# warm-hit span instead.
PREWARMS = _om.counter(
    "h2o3_scorer_prewarm_total",
    "background scorer-cache pre-warm compiles completed on model "
    "publish (H2O3_SCORER_PREWARM=1)")


def prewarm_enabled() -> bool:
    return env_bool("H2O3_SCORER_PREWARM", False)


def prewarm(model, wait: bool = False):
    """Compile `model`'s minimum-bucket scorer in a background thread —
    placing the shared sharded params first for param-exporting families
    (the build acquires the placement), so a first request pays neither
    the placement device_put nor the XLA compile. Returns the Thread, or
    None when the model is fast-path ineligible. Failures are logged,
    counted as ordinary fallbacks by the first real request, and never
    break the publish."""
    if jax.process_count() > 1:
        # real multi-controller runtime: every process must dispatch
        # identical programs in identical (replay) order — a background
        # prewarm thread firing at its own time on one host would leave
        # an SPMD collective waiting for peers that never launch it.
        # First-request compiles ARE replay-ordered, so multihost clouds
        # warm on first use. Replacement workers joining the replay
        # channel run single-process jax (the dead slot is gone from the
        # fixed device runtime), so the join-path prewarm stays active.
        return None
    if _fastpath_reason(model, 1) is not None:
        return None
    bucket = row_bucket(1)

    def _run():
        try:
            di = model._dinfo
            raw = np.zeros((bucket, len(di.raw_columns())), np.float32)
            fn = CACHE.program(model, bucket)
            out = fn(_mrt.device_put_rows(raw))
            jax.block_until_ready(out)   # force the XLA compile NOW
            PREWARMS.inc()
        except Exception:   # noqa: BLE001 — prewarm must never break publish
            import traceback
            from h2o3_tpu.utils import log as _log
            _log.warn(f"scorer prewarm failed for {model.key}: "
                      f"{traceback.format_exc(limit=2)}")

    t = threading.Thread(target=_run, daemon=True,
                         name=f"scorer-prewarm-{model.key}")
    t.start()
    if wait:
        t.join(timeout=120.0)
    return t


def prewarm_all(wait: bool = False) -> int:
    """Prewarm every DKV-resident model's smallest-bucket scorer — the
    replacement-worker warm start (ISSUE-10 join path): a joiner that
    just replayed the coordinator's state snapshot places each model's
    shared params and compiles the smallest row bucket BEFORE its first
    live request, so the request records a warm hit instead of a
    multi-second compile. Returns how many prewarms were started."""
    from h2o3_tpu.core.kvstore import DKV
    threads = []
    for key in DKV.keys():
        # raw_get: this is a whole-registry SCAN — DKV.get would run the
        # tier-promotion hook and fault every disk-spilled frame's codec
        # bytes back into host RAM just to learn it is not a model
        m = DKV.raw_get(key)
        if getattr(m, "_dinfo", None) is None \
                or getattr(m, "key", None) != key:
            continue        # frames, vecs, misc DKV values — not models
        t = prewarm(m)
        if t is not None:
            threads.append(t)
    if wait:
        for t in threads:
            t.join(timeout=120.0)
    return len(threads)


def score_frame_with_response(model, frame):
    """(out, y, w) at bucket length for the metrics path, or None for the
    legacy path. w is 0 on padding and missing-response rows."""
    di = getattr(model, "_dinfo", None)
    if di is None or not di.response_name \
            or di.response_name not in frame.names:
        return None
    return _fast_scored(model, frame, with_response=True)
