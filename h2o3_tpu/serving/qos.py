"""Multi-tenant QoS — admission control for the serving path.

The north star is heavy traffic from millions of users, but until this
module the serving path had exactly one overload valve: the global
micro-batch depth bound (H2O3_SCORE_QUEUE_DEPTH → 503). One flooding
tenant filled that queue and starved every well-behaved caller — the
overload failure mode "The Tail at Scale" (Dean & Barroso, CACM 2013)
warns against, and the one the SRE Workbook's load-shedding chapter
prescribes per-client fairness for. This module is the per-client half:

  * **Principals.** The REST layer resolves every request to a principal
    (the authenticated Basic user; the stable ``anonymous`` bucket on an
    unauthenticated server — the QoS path never branches on auth mode)
    and stamps it into the obs TLS alongside the trace id
    (obs/tracing.set_principal). Everything below keys on it.
  * **Token buckets** (per tenant): H2O3_QOS_RATE_RPS requests/second
    with H2O3_QOS_BURST capacity, per-principal overrides in
    H2O3_QOS_RATES. Over-rate requests get **429 + Retry-After** — the
    caller is misbehaving — which is deliberately distinct from the
    capacity **503** (the *server* is saturated).
  * **Weighted-fair dispatch** (the micro-batcher's per-principal
    queues): when more coalesced dispatches are ready than
    H2O3_QOS_MAX_INFLIGHT device slots, the fair gate grants slots by
    deficit round-robin over H2O3_QOS_WEIGHTS (default equal), charging
    each grant its real row count — a flood of big batches from one
    tenant cannot starve another tenant's next dispatch.
  * **Queue share**: one principal may hold at most
    H2O3_QOS_TENANT_SHARE of the global depth bound, so a flood can
    never occupy the whole queue and 503 a newcomer's first request.
  * **Concurrent-job quotas**: H2O3_QOS_MAX_JOBS bounds RUNNING Jobs per
    principal, enforced where Job.start runs (nested jobs a build spawns
    internally are not double-counted).
  * **Priority lanes**: interactive scoring preempts batch work at the
    scheduler — an mrtask device dispatch issued from a Job thread
    defers (bounded by H2O3_QOS_BATCH_YIELD_S) while interactive
    requests are pending in the micro-batch queue. Never mid-batch: an
    in-flight device program always runs to completion.
  * **Deadline-aware shedding**: a request whose ``X-H2O3-Deadline-Ms``
    budget already elapsed is dropped with **504** *before* staging or
    device dispatch (h2o3_qos_shed_total{reason}); the deadline rides
    the micro-batch so a coalesced dispatch skips dead followers.

The uncontended path stays ≈ free: with one tenant under the in-flight
bound every check is a TLS read plus a couple of dict hits, the fair
gate takes its fast path, and no thread ever parks.

Env surface (all knobs declared here, R017-censused):
  H2O3_QOS               master switch (default on)
  H2O3_QOS_RATE_RPS      default per-tenant token rate (0 = unlimited)
  H2O3_QOS_BURST         token-bucket capacity (0 → max(1, 2×rate))
  H2O3_QOS_RATES         per-tenant rate overrides "alice:100,bob:5"
  H2O3_QOS_WEIGHTS       DRR weights "alice:4,bob:1" (default 1 each)
  H2O3_QOS_QUANTUM_ROWS  DRR quantum (rows added per round, default 2048)
  H2O3_QOS_MAX_INFLIGHT  device dispatch slots before the gate queues
  H2O3_QOS_GATE_WAIT_S   bounded wait for a slot (then fail open)
  H2O3_QOS_TENANT_SHARE  max fraction of the global queue one tenant
                         may hold (default 0.5; 1.0 disables)
  H2O3_QOS_MAX_JOBS      concurrent jobs per tenant (0 = unlimited)
  H2O3_QOS_BATCH_YIELD_S max per-dispatch batch-lane deferral
  H2O3_QOS_MAX_PRINCIPALS distinct principals tracked before folding
                         into the "_overflow" bucket (metric-cardinality
                         bound under credential churn)
"""

from __future__ import annotations

import math
import re
import threading
import time

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.utils.env import env_bool, env_float, env_int, env_str

ANONYMOUS = "anonymous"
OVERFLOW = "_overflow"


# ---------------------------------------------------------------------------
# exceptions → HTTP status mapping (api/server._route_inner)
class RateLimited(Exception):
    """Token bucket empty → HTTP 429 + Retry-After. The CALLER is over
    its configured rate — distinct from QueueFull's 503, where the
    SERVER is out of capacity."""

    def __init__(self, principal: str, retry_after_s: float):
        super().__init__(
            f"tenant {principal!r} is over its request rate "
            "(H2O3_QOS_RATE_RPS / H2O3_QOS_RATES)")
        self.principal = principal
        self.retry_after_s = max(1, int(math.ceil(retry_after_s)))


class QuotaExceeded(Exception):
    """Concurrent-job quota hit → HTTP 429 + Retry-After."""

    def __init__(self, principal: str, limit: int):
        super().__init__(
            f"tenant {principal!r} already runs {limit} concurrent "
            "jobs (H2O3_QOS_MAX_JOBS)")
        self.principal = principal
        self.retry_after_s = 1


class DeadlineExceeded(Exception):
    """The caller's X-H2O3-Deadline-Ms budget elapsed → HTTP 504. Raised
    BEFORE staging/device work — the whole point is to never spend
    accelerator time on an answer nobody is waiting for."""

    def __init__(self, overrun_s: float):
        super().__init__(
            f"request deadline elapsed {overrun_s * 1e3:.0f}ms ago "
            "(X-H2O3-Deadline-Ms)")
        self.overrun_s = overrun_s


# ---------------------------------------------------------------------------
# config (one accessor site per variable, R017)
def enabled() -> bool:
    """Master switch: H2O3_QOS=0 turns every mechanism in this module
    into a no-op (principals still resolve for metric labels)."""
    return env_bool("H2O3_QOS", True)


def _rate_rps() -> float:
    return env_float("H2O3_QOS_RATE_RPS", 0.0)


def _burst() -> float:
    return env_float("H2O3_QOS_BURST", 0.0)


def _rates_raw() -> str:
    return env_str("H2O3_QOS_RATES", "")


def _weights_raw() -> str:
    return env_str("H2O3_QOS_WEIGHTS", "")


def _quantum_rows() -> int:
    return max(1, env_int("H2O3_QOS_QUANTUM_ROWS", 2048))


def _max_inflight() -> int:
    return env_int("H2O3_QOS_MAX_INFLIGHT", 4)


def _gate_wait_s() -> float:
    return max(0.1, env_float("H2O3_QOS_GATE_WAIT_S", 30.0))


def tenant_share() -> float:
    return env_float("H2O3_QOS_TENANT_SHARE", 0.5)


def _max_jobs() -> int:
    return env_int("H2O3_QOS_MAX_JOBS", 0)


def _batch_yield_s() -> float:
    return env_float("H2O3_QOS_BATCH_YIELD_S", 0.5)


def _max_principals() -> int:
    return max(1, env_int("H2O3_QOS_MAX_PRINCIPALS", 256))


# ---------------------------------------------------------------------------
# metrics (declared once; per-principal label cardinality bounded by the
# principal fold below)
ADMITTED = _om.counter(
    "h2o3_qos_admitted_total",
    "requests admitted past QoS admission, by principal")
REJECTS = _om.counter(
    "h2o3_qos_rejected_total",
    "requests rejected by QoS admission, by principal and reason "
    "(rate = token bucket → 429; quota = concurrent-job cap → 429; "
    "share = per-tenant queue share → 503)")
SHED = _om.counter(
    "h2o3_qos_shed_total",
    "requests dropped because their X-H2O3-Deadline-Ms budget elapsed "
    "(→ 504), by where the corpse was found: entry = at the REST edge, "
    "admission = before staging, batch = a coalesced dispatch skipped "
    "the dead follower")
GATE_WAITS = _om.counter(
    "h2o3_qos_gate_waits_total",
    "coalesced dispatches that queued at the weighted-fair gate "
    "(device slots exhausted), by principal")
GATE_TIMEOUTS = _om.counter(
    "h2o3_qos_gate_timeouts_total",
    "fair-gate waits that hit H2O3_QOS_GATE_WAIT_S and failed OPEN "
    "(dispatched anyway) — nonzero means the device is badly stalled")
BATCH_YIELDS = _om.counter(
    "h2o3_qos_batch_yields_total",
    "batch-lane device dispatches (Job threads) that deferred to "
    "pending interactive scoring at the scheduler")
QOS_SECONDS = _om.histogram(
    "h2o3_qos_request_seconds",
    "scoring-request wall time by principal and status — the per-tenant "
    "SLI series; per-tenant SLO specs (obs/slo.py `principal` filter) "
    "burn against it")


def observe_request(seconds: float, exemplar, principal: str, status: str):
    """Record one scoring request in the per-tenant SLI histogram.
    Emitted through the module-level var so R005 censuses the label set
    (the REST layer's `_qos.QOS_SECONDS.observe(...)` attribute chain
    was invisible to the metric census)."""
    QOS_SECONDS.observe(seconds, exemplar=exemplar,
                        principal=principal, status=status)


# ---------------------------------------------------------------------------
# principal resolution (bounded label cardinality)
_SAFE_PRINCIPAL = re.compile(r"[0-9a-zA-Z_.\-@]{1,64}")
_KNOWN_LOCK = make_lock("qos.principals")
_known: set = set()


def resolve_principal(user) -> str:
    """Auth outcome → stable principal: the authenticated user name
    (sanitized — it becomes a metric label and crosses the federation
    merge), else the one shared ``anonymous`` bucket. Distinct
    principals beyond H2O3_QOS_MAX_PRINCIPALS fold into ``_overflow``
    so credential churn can't blow up metric cardinality or tenant
    state."""
    if not user:
        return ANONYMOUS
    s = str(user).strip()[:64]
    if not _SAFE_PRINCIPAL.fullmatch(s):
        s = re.sub(r"[^0-9a-zA-Z_.\-@]", "_", s)[:64]
        if not s:
            return ANONYMOUS
    with _KNOWN_LOCK:
        if s in _known:
            return s
        if len(_known) < _max_principals():
            _known.add(s)
            return s
    return OVERFLOW


def _parse_map(raw: str) -> dict:
    """"alice:4,bob:1" → {"alice": 4.0, "bob": 1.0}; junk entries are
    dropped (config typos must not crash admission)."""
    out = {}
    for part in raw.split(","):
        name, sep, val = part.strip().partition(":")
        if not sep or not name:
            continue
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


_weight_cache: tuple = ("", {})
_rate_cache: tuple = ("", {})


def weight(principal: str) -> float:
    """DRR weight for a principal (H2O3_QOS_WEIGHTS, default 1.0)."""
    global _weight_cache
    raw = _weights_raw()
    if raw != _weight_cache[0]:
        _weight_cache = (raw, _parse_map(raw))
    w = _weight_cache[1].get(principal, 1.0)
    return w if w > 0 else 1.0


def _rate_for(principal: str) -> float:
    global _rate_cache
    raw = _rates_raw()
    if raw != _rate_cache[0]:
        _rate_cache = (raw, _parse_map(raw))
    return _rate_cache[1].get(principal, _rate_rps())


# ---------------------------------------------------------------------------
# per-tenant token buckets (429 + Retry-After)
class _Bucket:
    __slots__ = ("tokens", "stamp", "rate", "burst")


_BUCKET_LOCK = make_lock("qos.tokens")
_buckets: dict = {}


def _bucket_burst(rate: float) -> float:
    b = _burst()
    return b if b > 0 else max(1.0, 2.0 * rate)


def charge_token(principal: str):
    """Take one token from the principal's bucket; raises RateLimited
    (→ 429) when empty, with Retry-After = time until the next token.
    Rate 0 (the default) means unlimited — no state is kept at all."""
    rate = _rate_for(principal)
    if rate <= 0:
        return
    burst = _bucket_burst(rate)
    now = time.monotonic()
    retry = None
    with _BUCKET_LOCK:
        b = _buckets.get(principal)
        if b is None:
            b = _buckets[principal] = _Bucket()
            b.tokens, b.stamp = burst, now
        b.rate, b.burst = rate, burst
        b.tokens = min(burst, b.tokens + (now - b.stamp) * rate)
        b.stamp = now
        if b.tokens < 1.0:
            retry = (1.0 - b.tokens) / rate
        else:
            b.tokens -= 1.0
    if retry is not None:
        REJECTS.inc(principal=principal, reason="rate")
        raise RateLimited(principal, retry)


def _token_series():
    """h2o3_qos_tokens{principal}: live bucket levels (refilled to the
    scrape instant so an idle tenant shows a full bucket)."""
    now = time.monotonic()
    with _BUCKET_LOCK:
        return [({"principal": p},
                 min(b.burst, b.tokens + (now - b.stamp) * b.rate))
                for p, b in sorted(_buckets.items())]


_om.gauge("h2o3_qos_tokens",
          "per-tenant token-bucket level (requests admissible right "
          "now before a 429)", fn=_token_series)


def _queue_series():
    """h2o3_qos_queue_depth{principal}: requests each tenant currently
    holds inside the micro-batch queue (the share-cap input)."""
    from h2o3_tpu.serving import microbatch as _mb
    return [({"principal": p}, float(n))
            for p, n in sorted(_mb.BATCHER.queued_by_principal().items())]


_om.gauge("h2o3_qos_queue_depth",
          "scoring requests inside the micro-batch queue, by principal",
          fn=_queue_series)


# ---------------------------------------------------------------------------
# multi-controller guard: on a multi-controller runtime every host
# replays each broadcast request and launches the SAME collective
# scoring program — a coordinator that refuses a request AFTER the
# broadcast (rate 429, share 503, mid-pipeline 504) while the workers
# dispatch it would leave them alone in the collective (rendezvous
# wedge). So on process_count() > 1 the only rejection points are the
# PRE-broadcast ones (entry deadline shed + edge admission, see
# api/server._route_inner); mid-pipeline sheds and the share cap gate
# themselves off here. Replay-channel clouds of single-process-jax
# hosts (elastic joiners) are unaffected: their scoring programs never
# rendezvous across hosts, so a divergent refusal only wastes one
# worker-side score.
_single_controller = None


def single_controller() -> bool:
    global _single_controller
    if _single_controller is None:
        import jax
        _single_controller = jax.process_count() == 1
    return _single_controller


# ---------------------------------------------------------------------------
# deadlines
def check_deadline(reason: str):
    """Shed the current request (504) when its deadline already elapsed.
    No deadline in the TLS → free pass."""
    d = _tracing.deadline()
    if d is None:
        return
    over = time.monotonic() - d
    if over > 0:
        SHED.inc(reason=reason)
        raise DeadlineExceeded(over)


def deadline_dead(deadline, now: float) -> bool:
    """Is an absolute monotonic deadline already blown? (micro-batch
    follower check — the TLS belongs to a different thread there)."""
    return deadline is not None and now > deadline


# ---------------------------------------------------------------------------
# admission (called from microbatch.check_capacity, i.e. BEFORE payload
# decode / frame staging): deadline shed + token charge. Internal
# callers with no request context pass through untouched — QoS is a
# REST-edge mechanism, and in-process library use must stay unchanged.
def admit():
    if not enabled():
        return
    if single_controller():
        # mid-pipeline deadline shed — gated off on multi-controller
        # runtimes where the workers already replayed the broadcast and
        # will dispatch the collective regardless (see single_controller)
        check_deadline("admission")
    if getattr(_QTLS, "edge_admitted", False):
        return      # the REST edge already charged, pre-broadcast
    p = _tracing.principal()
    if p is None:
        return
    charge_token(p)
    ADMITTED.inc(principal=p)


def edge_admit():
    """REST-edge admission for scoring routes (handlers marked
    server.scores), taken BEFORE the replay broadcast — the same
    pre-broadcast discipline as prepay_job_slot: a 429 raised after the
    broadcast would leave every worker dispatching a collective scoring
    program the coordinator refused (lone-host rendezvous wedge). The
    in-pipeline admit() sees the TLS flag and skips the double charge;
    end_request() clears it at request teardown."""
    admit()
    _QTLS.edge_admitted = True


def end_request():
    """Request teardown (api/server._route_inner finally): clear the
    edge-admission flag and release a prepaid job charge no Job
    adopted (the handler 4xx'd before Job.start)."""
    _QTLS.edge_admitted = False
    settle_prepaid_job_slot()


def tenant_share_cap(limit: int) -> int:
    """Max slots of the global queue depth bound one principal may hold
    (H2O3_QOS_TENANT_SHARE). A flood therefore saturates its share and
    starts eating 503s while headroom remains for everyone else — the
    SRE Workbook's per-client fairness for load shedding."""
    share = tenant_share()
    if not enabled() or share >= 1.0 or share <= 0.0 or limit <= 0 \
            or not single_controller():
        # multi-controller: a share-cap 503 fires AFTER the broadcast
        # (queue state is coordinator-local), which would strand the
        # workers' replayed collective — keep the pre-QoS behavior there
        return limit
    return max(1, int(limit * share))


def note_share_reject(principal: str):
    REJECTS.inc(principal=principal, reason="share")


def eviction_standing(principal: str) -> float:
    """A [0, 1] standing score for cross-tenant param eviction
    (serving/params.py victim ordering): token-bucket headroom × queue
    -share headroom. Lower = heavier consumer right now = that tenant's
    cold placements are demoted first when ANOTHER tenant faults and
    no same-tenant victim exists. A tenant with no QoS state (idle, or
    rate 0 = unlimited with an empty queue) scores 1.0 — last to lose
    its models to someone else's churn."""
    tok = 1.0
    rate = _rate_for(principal)
    if rate > 0:
        now = time.monotonic()
        with _BUCKET_LOCK:
            b = _buckets.get(principal)
            if b is not None and b.burst > 0:
                tok = min(b.burst,
                          b.tokens + (now - b.stamp) * b.rate) / b.burst
    share = 1.0
    try:
        from h2o3_tpu.serving import microbatch as _mb
        cap = tenant_share_cap(_mb._queue_depth_limit())
        if cap > 0:
            held = _mb.BATCHER.queued_by_principal().get(principal, 0)
            share = max(0.0, 1.0 - held / cap)
    except Exception:   # noqa: BLE001 — standing is advisory ordering only
        pass
    return max(0.0, min(1.0, tok * share))


# ---------------------------------------------------------------------------
# weighted-fair dispatch gate (deficit round-robin over principals)
class _Ticket:
    __slots__ = ("principal", "rows", "event", "granted")

    def __init__(self, principal: str, rows: int):
        self.principal = principal
        self.rows = max(1, int(rows))
        self.event = threading.Event()
        self.granted = False


class FairGate:
    """Bounds concurrently in-flight coalesced device dispatches at
    H2O3_QOS_MAX_INFLIGHT; excess dispatches park in per-principal
    queues and slots are granted by deficit round-robin: each grant
    round credits every waiting principal quantum×weight rows and the
    principal whose head ticket needs the fewest rounds wins (ties go
    round-robin), so over time granted ROWS converge to the weight
    ratio regardless of how many tickets a flood stacks up.

    Fast path (uncontended): one lock acquire, an int compare, no
    parking. Fail-open: a ticket that outwaits H2O3_QOS_GATE_WAIT_S
    dispatches anyway (counted) — fairness must never turn a slow
    device into a total outage.
    """

    def __init__(self):
        self._lock = make_lock("qos.gate")
        self._waiting: dict = {}     # principal -> list of _Ticket
        self._order: list = []       # round-robin order of waiting keys
        self._deficit: dict = {}     # principal -> credited rows
        self._inflight = 0

    # -- public -----------------------------------------------------------
    def acquire(self, principal: str, rows: int) -> bool:
        """Take a dispatch slot (blocks under contention). Returns True
        when a slot was taken — pass that token to release() in a
        finally. The token, not a re-read of the env, decides whether
        release decrements: flipping H2O3_QOS/H2O3_QOS_MAX_INFLIGHT
        while dispatches are in flight must not leak slots."""
        if not enabled():
            return False
        limit = _max_inflight()
        if limit <= 0:
            return False
        t = _Ticket(principal or ANONYMOUS, rows)
        with self._lock:
            if self._inflight < limit and not self._order:
                self._inflight += 1
                return True
            self._waiting.setdefault(t.principal, []).append(t)
            if t.principal not in self._deficit:
                self._deficit[t.principal] = 0.0
                self._order.append(t.principal)
        GATE_WAITS.inc(principal=t.principal)
        if t.event.wait(timeout=_gate_wait_s()):
            return True
        # timed out: fail open — withdraw the ticket if it is still
        # queued and take a slot anyway; if a grant raced the timeout,
        # the slot is already ours
        with self._lock:
            q = self._waiting.get(t.principal)
            if q is not None and t in q:
                q.remove(t)
                self._inflight += 1
            elif not t.granted:
                self._inflight += 1
        GATE_TIMEOUTS.inc()
        return True

    def release(self, took: bool = True):
        """Give a slot back. `took` is acquire()'s return value — a
        dispatch that never took a slot (QoS disabled at acquire time)
        must not decrement, and one that DID must decrement even if the
        env has been flipped off since."""
        if not took:
            return
        wake = []
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            limit = _max_inflight()
            if not enabled() or limit <= 0:
                # the gate was turned off mid-flight: drain every parked
                # waiter now instead of letting each fail open after the
                # full gate wait
                for q in self._waiting.values():
                    for t in q:
                        t.granted = True
                        wake.append(t)
                self._waiting.clear()
                self._order.clear()
                self._deficit.clear()
            else:
                while self._inflight < limit:
                    t = self._pick_locked()
                    if t is None:
                        break
                    self._inflight += 1
                    t.granted = True
                    wake.append(t)
        for t in wake:
            t.event.set()

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._waiting.values())

    def reset(self):
        with self._lock:
            for q in self._waiting.values():
                for t in q:
                    t.granted = True
                    t.event.set()
            self._waiting.clear()
            self._order.clear()
            self._deficit.clear()
            self._inflight = 0

    # -- DRR core ---------------------------------------------------------
    def _pick_locked(self) -> _Ticket | None:
        """Grant one ticket by deficit round-robin: find the principal
        whose head ticket needs the fewest whole quantum rounds to
        afford, credit every waiting principal that many rounds, charge
        the winner its rows. O(#waiting principals) per grant."""
        quantum = float(_quantum_rows())
        best = best_rounds = None
        for p in self._order:
            q = self._waiting.get(p)
            if not q:
                continue
            need = q[0].rows - self._deficit.get(p, 0.0)
            rounds = max(0, math.ceil(need / (quantum * weight(p))))
            if best_rounds is None or rounds < best_rounds:
                best, best_rounds = p, rounds
        if best is None:
            self._order.clear()   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
            self._deficit.clear()   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
            return None
        if best_rounds:
            for p in self._order:
                if self._waiting.get(p):
                    self._deficit[p] = (self._deficit.get(p, 0.0)   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
                                        + best_rounds * quantum * weight(p))
        t = self._waiting[best].pop(0)   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
        self._deficit[best] = self._deficit.get(best, 0.0) - t.rows   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
        # rotate the winner to the back so equal-rounds ties round-robin
        self._order.remove(best)   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
        if self._waiting.get(best):
            self._order.append(best)   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
        else:
            self._waiting.pop(best, None)   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
            self._deficit.pop(best, None)   # h2o3-ok: R003 _locked helper — only caller is release(), which holds self._lock
        return t


GATE = FairGate()


# ---------------------------------------------------------------------------
# priority lanes: interactive scoring preempts batch (Job-thread) device
# dispatches AT THE SCHEDULER — a batch dispatch about to launch defers
# while interactive requests are pending, bounded by
# H2O3_QOS_BATCH_YIELD_S; an in-flight device program is never aborted.
_LANE_COND = threading.Condition(make_lock("qos.lanes"))
_interactive_pending = 0

_QTLS = threading.local()


def in_job() -> bool:
    """Is this thread a Job worker (the batch lane)?"""
    return getattr(_QTLS, "in_job", False)


def note_interactive_start():
    global _interactive_pending
    with _LANE_COND:
        _interactive_pending += 1


def note_interactive_end():
    global _interactive_pending
    with _LANE_COND:
        _interactive_pending -= 1
        if _interactive_pending <= 0:
            _interactive_pending = max(0, _interactive_pending)
            _LANE_COND.notify_all()


def interactive_pending() -> int:
    return _interactive_pending


def batch_yield():
    """Called by the mrtask dispatch funnel just before launching a
    device program: a BATCH dispatch (Job thread) yields to pending
    interactive scoring. The racy lock-free fast-path read is deliberate
    — a stale zero just skips one yield, and the steady-state training
    loop pays a single int compare."""
    if _interactive_pending == 0 or not in_job() or not enabled():
        return
    limit = _batch_yield_s()
    if limit <= 0:
        return
    deadline = time.monotonic() + limit
    waited = False
    with _LANE_COND:
        while _interactive_pending > 0:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            waited = True
            _LANE_COND.wait(timeout=remaining)
    if waited:
        BATCH_YIELDS.inc()


# ---------------------------------------------------------------------------
# concurrent-job quotas (enforced where Job.start runs)
_JOBS_LOCK = make_lock("qos.jobs")
_job_counts: dict = {}


def acquire_job_slot():
    """Charge the current principal's concurrent-job quota. Returns the
    charge token to hand back to release_job_slot, or None when no
    charge applies (no request context, a nested job started from
    inside another counted job, quota unlimited, QoS off). Raises
    QuotaExceeded (→ 429) at the cap."""
    if not enabled():
        return None
    p = _tracing.principal()
    if p is None or in_job():
        return None
    limit = _max_jobs()
    if limit <= 0:
        return None
    over = False
    with _JOBS_LOCK:
        n = _job_counts.get(p, 0)
        if n >= limit:
            over = True
        else:
            _job_counts[p] = n + 1
    if over:
        REJECTS.inc(principal=p, reason="quota")
        raise QuotaExceeded(p, limit)
    return p


def release_job_slot(token):
    if token is None:
        return
    with _JOBS_LOCK:
        n = _job_counts.get(token, 0) - 1
        if n <= 0:
            _job_counts.pop(token, None)
        else:
            _job_counts[token] = n


def prepay_job_slot():
    """REST-layer quota charge for job-starting routes, taken BEFORE the
    replay broadcast: on a multi-host cloud the workers replay a request
    the moment the coordinator broadcasts it, so a quota rejection must
    happen before that point — a 429 AFTER the broadcast would leave the
    build running on every worker but not the coordinator (divergent DKV
    state, orphaned collectives). The charge parks in the request
    thread's TLS; the Job the handler starts ADOPTS it (and releases it
    at completion), and settle_prepaid_job_slot() at request teardown
    releases a charge no job consumed (handler 4xx'd first)."""
    token = acquire_job_slot()
    if token is not None:
        _QTLS.prepaid_job = token
    return token


def adopt_prepaid_job_slot():
    """Hand the request's prepaid charge (if any) to the Job that will
    own its release; returns None when nothing was prepaid."""
    tok = getattr(_QTLS, "prepaid_job", None)
    _QTLS.prepaid_job = None
    return tok


def settle_prepaid_job_slot():
    """Request teardown: release a prepaid charge no Job adopted."""
    release_job_slot(adopt_prepaid_job_slot())


def _jobs_series():
    with _JOBS_LOCK:
        return [({"principal": p}, float(n))
                for p, n in sorted(_job_counts.items())]


_om.gauge("h2o3_qos_active_jobs",
          "concurrently RUNNING jobs by principal (quota: "
          "H2O3_QOS_MAX_JOBS)", fn=_jobs_series)


class job_context:
    """Worker-thread context for Job._run: re-enters the launching
    request's principal (for metric attribution and so dispatches the
    job issues ride the BATCH lane) and marks the thread as in-job so
    nested Job.start calls skip the quota. Deadlines do NOT propagate —
    a build outlives its launching request's budget."""

    __slots__ = ("_principal", "_prev_p", "_prev_d", "_prev_flag")

    def __init__(self, principal):
        self._principal = principal

    def __enter__(self):
        self._prev_p = _tracing.set_principal(self._principal)
        self._prev_d = _tracing.set_deadline(None)
        self._prev_flag = getattr(_QTLS, "in_job", False)
        _QTLS.in_job = True
        return self

    def __exit__(self, *exc):
        _QTLS.in_job = self._prev_flag
        _tracing.set_deadline(self._prev_d)
        _tracing.set_principal(self._prev_p)
        return False


# ---------------------------------------------------------------------------
def reset():
    """Test hook: drop all tenant state (buckets, principals, quotas,
    gate queues, lane counters)."""
    global _interactive_pending
    _QTLS.edge_admitted = False
    _QTLS.prepaid_job = None
    with _BUCKET_LOCK:
        _buckets.clear()
    with _KNOWN_LOCK:
        _known.clear()
    with _JOBS_LOCK:
        _job_counts.clear()
    GATE.reset()
    with _LANE_COND:
        _interactive_pending = 0
        _LANE_COND.notify_all()
