"""h2o3_tpu — a TPU-native, JAX/XLA/Pallas-based rebuild of the H2O-3 ML platform.

Architecture (vs. the reference, /root/reference — H2O-3, a JVM cluster):

  * H2O's peer-to-peer cloud (water/Paxos.java) is replaced by a single-controller
    JAX runtime driving a `jax.sharding.Mesh` of TPU chips ("the cloud").
  * H2O's distributed K/V store (water/DKV.java) becomes a controller-side object
    registry whose values are sharded `jax.Array`s living in TPU HBM.
  * H2O's MRTask map/reduce over chunks (water/MRTask.java) becomes jitted,
    sharded computations whose reduces are XLA collectives over ICI.
  * H2O's Fluid-Vec data plane (water/fvec/) becomes a columnar Frame/Vec store
    of dtype-packed, row-sharded device arrays.

Public surface mirrors the reference's Python client (h2o-py/h2o/h2o.py).
"""

from h2o3_tpu.parallel.mesh import init, cloud, shutdown, cluster_info
from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.io.parser import import_file, parse_setup, upload_frame
from h2o3_tpu.core.jobs import Job

__version__ = "0.5.0"


def explain(models, frame, columns: int = 3, render: bool = False):
    """h2o.explain: figure bundle (SHAP summary, varimp, PDP, learning
    curve; cross-model heatmaps for lists) — see explain_plots.py."""
    from h2o3_tpu import explain_plots as EP
    return EP.explain(models, frame, columns=columns, render=render)


def explain_row(models, frame, row_index: int, columns: int = 3):
    """h2o.explain_row: per-row SHAP bars + ICE curves."""
    from h2o3_tpu import explain_plots as EP
    return EP.explain_row(models, frame, row_index, columns=columns)


def get_frame(key):
    """Fetch a Frame by key from the registry (h2o.get_frame)."""
    return DKV.get(key)


def get_model(key):
    """Fetch a Model by key from the registry (h2o.get_model)."""
    return DKV.get(key)


def remove(key):
    """Remove an object from the registry (h2o.remove)."""
    DKV.remove(key)


def ls():
    """List all registered keys (h2o.ls)."""
    return DKV.keys()


def save_model(model, path):
    """Binary model export (h2o.save_model)."""
    from h2o3_tpu.genmodel.mojo import save_model as _sm
    return _sm(model, path)


def load_model(path):
    """Binary model import (h2o.load_model)."""
    from h2o3_tpu.genmodel.mojo import load_model as _lm
    return _lm(path)


def import_mojo(path):
    """Load a scoring artifact (h2o.import_mojo → generic model)."""
    from h2o3_tpu.genmodel.mojo import MojoModel
    return MojoModel.load(path)


def create_frame(**kw):
    """Random frame generator (h2o.create_frame)."""
    from h2o3_tpu.utils.create_frame import create_frame as _cf
    return _cf(**kw)


def rapids(expr, session=None):
    """Evaluate a Rapids expression (h2o.rapids)."""
    from h2o3_tpu.rapids import rapids_exec
    return rapids_exec(expr, session)


def export_file(frame, path):
    """Frame snapshot export (h2o.export_file — .hex format here)."""
    from h2o3_tpu.io.persist import export_frame
    return export_frame(frame, path)


def automl(**kw):
    from h2o3_tpu.automl import H2OAutoML
    return H2OAutoML(**kw)


def quantile(frame, prob=None, combine_method="interpolate",
             weights_column=None):
    """Distributed quantiles (h2o.quantile → hex/quantile/Quantile.java).
    Returns a Frame: Probs column + one column per numeric input column."""
    from h2o3_tpu.models.quantile import frame_quantiles
    import numpy as np
    probs, cols = frame_quantiles(frame, prob,
                                  weights_column=weights_column,
                                  combine_method=combine_method)
    names = ["Probs"] + list(cols)
    data = [np.asarray(probs, np.float64)] + [cols[c] for c in cols]
    return Frame(names, [Vec.from_numpy(np.asarray(d, np.float64))
                         for d in data])
