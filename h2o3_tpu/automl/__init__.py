from h2o3_tpu.automl.automl import H2OAutoML, Leaderboard
