"""AutoML — ai/h2o/automl rebuilt: staged model plan + leaderboard + stacking.

Reference: AutoML.java:40 (lifecycle; planWork :347, learn :612),
ModelingStep/ModelingStepsExecutor (step state machine), modeling/
*StepsProvider (per-algo step definitions: XGBoost×3, GLM, DRF, GBM×5,
DeepLearning×3, XRT, 2 grids, 2 stacked ensembles), leaderboard/
Leaderboard.java (ranked by CV metric), events/EventLog.

TPU-native: the plan is a controller-side list of (name, builder-factory)
steps executed under the time/model budget; every step's chips-saturating
work is the underlying builder's jitted programs. The reference's XGBoost
steps map onto the native GBM histogram engine (the TPU build replaces the
xgboost4j JNI path outright — SURVEY §2.4).
"""

from __future__ import annotations

import time

import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.jobs import Job
from h2o3_tpu.core.kvstore import DKV


def _steps(seed: int):
    """The default modeling plan (modeling/*StepsProvider defaults)."""
    from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator as GLM
    from h2o3_tpu.models.tree.gbm import H2OGradientBoostingEstimator as GBM
    from h2o3_tpu.models.tree.drf import H2ORandomForestEstimator as DRF
    from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator as DL
    from h2o3_tpu.models.tree.xgboost import H2OXGBoostEstimator as XGB
    s = seed if seed and seed > 0 else 1
    return [
        # XGBoost steps (XGBoostStepsProvider defaults) on the native engine
        ("XGBoost_1", XGB, dict(ntrees=50, max_depth=10, min_rows=5, nbins=20,
                                learn_rate=0.3, sample_rate=0.8,
                                col_sample_rate_per_tree=0.8, seed=s)),
        ("XGBoost_2", XGB, dict(ntrees=50, max_depth=6, min_rows=10, nbins=20,
                                learn_rate=0.3, sample_rate=0.6,
                                col_sample_rate_per_tree=0.8, seed=s)),
        ("XGBoost_3", XGB, dict(ntrees=50, max_depth=15, min_rows=3, nbins=20,
                                learn_rate=0.3, sample_rate=0.8, seed=s)),
        ("GLM_1", GLM, dict(alpha=0.5, lambda_search=True, nlambdas=10,
                            max_iterations=20)),
        ("DRF_1", DRF, dict(ntrees=50, seed=s)),
        ("GBM_1", GBM, dict(ntrees=60, max_depth=6, min_rows=1,
                            learn_rate=0.1, sample_rate=0.8,
                            col_sample_rate_per_tree=0.8, seed=s)),
        ("GBM_2", GBM, dict(ntrees=60, max_depth=7, min_rows=10,
                            learn_rate=0.1, sample_rate=0.9, seed=s)),
        ("GBM_3", GBM, dict(ntrees=60, max_depth=8, min_rows=10,
                            learn_rate=0.1, seed=s)),
        ("GBM_4", GBM, dict(ntrees=60, max_depth=10, min_rows=10,
                            learn_rate=0.05, seed=s)),
        ("GBM_5", GBM, dict(ntrees=100, max_depth=15, min_rows=100,
                            learn_rate=0.05, sample_rate=0.6, seed=s)),
        ("DeepLearning_1", DL, dict(hidden=[64, 64], epochs=10, seed=s,
                                    mini_batch_size=128)),
        ("DeepLearning_2", DL, dict(hidden=[128], epochs=10, seed=s,
                                    mini_batch_size=128)),
        ("DeepLearning_3", DL, dict(hidden=[32, 32, 32], epochs=10, seed=s,
                                    mini_batch_size=128)),
        ("XRT_1", DRF, dict(ntrees=50, histogram_type="Random", seed=s)),
    ]


class Leaderboard:
    """leaderboard/Leaderboard.java: models ranked by CV metric — or by
    metrics on a held-out `leaderboard_frame` when one is supplied
    (Leaderboard.java scoring on the leaderboard frame)."""

    def __init__(self, sort_metric: str, decreasing: bool,
                 leaderboard_frame=None):
        self.sort_metric = sort_metric
        self.decreasing = decreasing
        self.leaderboard_frame = leaderboard_frame
        self.rows: list = []

    def add(self, name, model):
        if self.leaderboard_frame is not None:
            src = model._compute_metrics(self.leaderboard_frame)
        else:
            src = (model._output.cross_validation_metrics
                   or model._output.validation_metrics
                   or model._output.training_metrics)
        row = {"model_id": model.key, "step": name}
        for k in ("auc", "logloss", "mean_per_class_error", "rmse", "mse",
                  "pr_auc", "error", "mae"):
            v = getattr(src, k, None)
            if v is not None:
                row[k] = v
        self.rows.append((row, model))
        key = self.sort_metric
        self.rows.sort(key=lambda rm: rm[0].get(key, float("inf")),
                       reverse=self.decreasing)

    def as_list(self):
        return [r for r, _ in self.rows]

    @property
    def leader(self):
        return self.rows[0][1] if self.rows else None


class H2OAutoML:
    def __init__(self, max_models: int = 10, max_runtime_secs: float = 0.0,
                 seed: int = -1, nfolds: int = 5, sort_metric: str = "AUTO",
                 exclude_algos=None, include_algos=None, project_name=None,
                 balance_classes: bool = False,
                 keep_cross_validation_predictions: bool = True,
                 max_runtime_secs_per_model: float = 0.0,
                 recovery_dir: str | None = None,
                 preprocessing=None):
        self.max_models = max_models
        self.max_runtime_secs = max_runtime_secs
        self.max_runtime_secs_per_model = max_runtime_secs_per_model
        self.seed = seed
        self.nfolds = nfolds
        self.sort_metric = sort_metric
        self.exclude_algos = {a.lower() for a in (exclude_algos or [])}
        self.include_algos = ({a.lower() for a in include_algos}
                              if include_algos else None)
        self.project_name = project_name or DKV.make_key("automl")
        self.recovery_dir = recovery_dir
        # ai.h2o.automl.preprocessing.TargetEncoding: preprocessing=
        # ["target_encoding"] target-encodes high-cardinality categoricals
        # (cardinality >= 25, the reference's threshold) with CV-safe
        # kfold leakage handling before any model step runs
        self.preprocessing = [p.lower() for p in (preprocessing or [])]
        self.te_model = None
        DKV.put(self.project_name, self)
        self.leaderboard_obj = None
        self.event_log: list = []
        self.leader = None

    def _log(self, msg):
        self.event_log.append({"t": time.time(), "message": msg})

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, leaderboard_frame=None):
        assert y is not None and training_frame is not None
        is_cls = training_frame.vec(y).type == "enum"
        ncls = training_frame.vec(y).cardinality if is_cls else 1
        metric = self.sort_metric
        if metric in ("AUTO", None):
            metric = ("auc" if ncls == 2 else
                      "mean_per_class_error" if is_cls else "rmse")
        decreasing = metric in ("auc", "pr_auc", "accuracy", "f1")

        # ---- preprocessing: CV-safe target encoding ----------------------
        # (TargetEncoding.java: kfold strategy on the training frame with
        # the SAME fold column the model CVs on; plain strategy elsewhere)
        te_fold_col = None
        if "target_encoding" in self.preprocessing:
            x = x or [c for c in training_frame.names if c != y]
            (x, training_frame, validation_frame, leaderboard_frame,
             te_fold_col) = self._apply_target_encoding(
                x, y, training_frame, validation_frame, leaderboard_frame)
        # reset per-train state: a second train() on a frame without
        # high-card categoricals must not inherit run 1's fold column
        self._te_fold_col = te_fold_col

        lb = Leaderboard(metric.lower(), decreasing,
                         leaderboard_frame=leaderboard_frame)
        self.leaderboard_obj = lb
        t0 = time.time()
        built = 0
        se_candidates = []

        # recovery (Recovery.java:55 + -auto_recovery_dir H2O.java:411):
        # reload finished models of a killed run, skip their steps
        recovery = None
        recovered = set()
        if self.recovery_dir:
            from h2o3_tpu.io.persist import Recovery
            recovery = Recovery(self.recovery_dir)
            recovery.resume()
            recovered = set(recovery.recovered_model_keys())
            if training_frame is not None:
                recovery.checkpoint_frame(training_frame)

        def over_budget():
            return (self.max_runtime_secs
                    and time.time() - t0 > self.max_runtime_secs)

        def run_step(name, cls, params):
            nonlocal built
            p = dict(params)
            if te_fold_col is not None:
                # fold-consistent CV: models fold on the same assignment the
                # target encoder used for its out-of-fold encodings
                p["fold_column"] = te_fold_col
            else:
                p["nfolds"] = self.nfolds
            p["keep_cross_validation_predictions"] = True
            p["model_id"] = f"{self.project_name}_{name}"
            # per-model budget (AutoML.java time allocation): the smaller of
            # the per-model cap and the remaining global budget
            caps = [c for c in (self.max_runtime_secs_per_model,
                                (self.max_runtime_secs
                                 - (time.time() - t0)
                                 if self.max_runtime_secs else 0.0))
                    if c and c > 0]
            if caps:
                p["max_runtime_secs"] = max(1.0, min(caps))
            if p["model_id"] in recovered:
                m = DKV.get(p["model_id"])
                if m is not None:
                    self._log(f"recovered {name}")
                    lb.add(name, m)
                    se_candidates.append(m)
                    built += 1
                    return m
            try:
                self._log(f"building {name}")
                m = cls(**p)
                m.train(x=x, y=y, training_frame=training_frame,
                        validation_frame=validation_frame)
                lb.add(name, m)
                se_candidates.append(m)
                built += 1
                if recovery is not None:
                    recovery.checkpoint_model(m)
                return m
            except Exception as ex:  # noqa: BLE001 — a failed step is logged
                self._log(f"step {name} failed: {ex!r}")
                return None

        for name, cls, params in _steps(self.seed):
            algo = cls.algo
            if self.include_algos is not None and algo not in self.include_algos:
                continue
            if algo in self.exclude_algos:
                continue
            if self.max_models and built >= self.max_models:
                break
            if over_budget():
                self._log("time budget exhausted")
                break
            run_step(name, cls, params)

        # ---- grid steps (the two default grids of AutoML.java planWork:
        # GBM + DeepLearning random-discrete grids) ------------------------
        gbm_allowed = ("gbm" not in self.exclude_algos
                       and (self.include_algos is None
                            or "gbm" in self.include_algos))
        if (not over_budget() and gbm_allowed
                and (self.max_models == 0 or built < self.max_models)):
            self._run_grid_steps(lb, se_candidates, x, y, training_frame,
                                 validation_frame, t0, recovery)
            built = len(se_candidates)

        # ---- exploitation phase (ModelingStep.DynamicStep "exploitation
        # ratio": fine-tune the current best GBM with more, slower trees) --
        if not over_budget() and lb.leader is not None:
            self._run_exploitation(lb, se_candidates, x, y, training_frame,
                                   validation_frame, recovery)
        # Stacked ensembles (best-of-family + all) when ≥2 base models
        if len(se_candidates) >= 2 and "stackedensemble" not in self.exclude_algos:
            from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
            # best-of-family over CV-capable candidates only (the ensemble
            # needs every base model's fold predictions)
            best_of_family = {}
            cand = {m.key for m in se_candidates}
            for (row, m) in lb.rows:
                if m.key in cand:
                    best_of_family.setdefault(m.algo, m)
            for se_name, base in (
                    ("StackedEnsemble_BestOfFamily",
                     list(best_of_family.values())),
                    ("StackedEnsemble_AllModels", se_candidates)):
                if len(base) < 2:
                    continue
                try:   # one failed ensemble must not kill the other
                    self._log(f"building {se_name}")
                    se = H2OStackedEnsembleEstimator(
                        base_models=base,
                        model_id=f"{self.project_name}_{se_name}")
                    se.train(y=y, training_frame=training_frame)
                    lb.add(se_name, se)
                except Exception as ex:  # noqa: BLE001
                    self._log(f"{se_name} failed: {ex!r}")
        self.leader = lb.leader
        self._log(f"done: {built} base models; leader={lb.leader.key if lb.leader else None}")
        return self

    # ------------------------------------------------------------------
    # TargetEncoding.java: DEFAULT_CARDINALITY_THRESHOLD — only columns at
    # or above this many levels are worth encoding (low-card categoricals
    # one-hot fine)
    TE_CARDINALITY_THRESHOLD = 25

    def _apply_target_encoding(self, x, y, training_frame,
                               validation_frame, leaderboard_frame):
        """ai/h2o/automl/preprocessing/TargetEncoding.java: encode
        high-cardinality categorical predictors out-of-fold on the training
        frame (kfold strategy over a dedicated fold column, blended, with
        noise) and with the plain global encodings on validation /
        leaderboard frames. Returns the rewritten
        (x, train, valid, lb_frame, fold_column)."""
        from h2o3_tpu.core.frame import Vec
        from h2o3_tpu.models.target_encoder import H2OTargetEncoderEstimator
        te_cols = [c for c in x
                   if training_frame.vec(c).type == "enum"
                   and training_frame.vec(c).cardinality
                   >= self.TE_CARDINALITY_THRESHOLD]
        if not te_cols:
            self._log("target_encoding: no high-cardinality columns; skipped")
            return x, training_frame, validation_frame, leaderboard_frame, None
        if self.nfolds and self.nfolds >= 2:
            fold_col = "__automl_te_fold__"
            n = training_frame.nrows
            rng = np.random.default_rng(self.seed if self.seed > 0 else 0)
            folds = rng.permutation(n) % self.nfolds
            train2 = Frame(list(training_frame.names),
                           list(training_frame.vecs),
                           key=DKV.make_key("te_train"))
            train2[fold_col] = Vec.from_numpy(folds.astype(np.float64))
            te = H2OTargetEncoderEstimator(
                data_leakage_handling="kfold", blending=True,
                inflection_point=10.0, smoothing=20.0, noise=0.01,
                seed=self.seed if self.seed > 0 else 1,
                fold_column=fold_col, columns_to_encode=te_cols)
        else:
            # nfolds=0 disables CV: a synthetic 2-fold column here would
            # force fold-based CV on every model the run builds. Fall back
            # to leave-one-out, the non-kfold leakage strategy
            # (TargetEncoding.java LeaveOneOut) — no fold column at all.
            fold_col = None
            train2 = training_frame
            te = H2OTargetEncoderEstimator(
                data_leakage_handling="loo", blending=True,
                inflection_point=10.0, smoothing=20.0, noise=0.01,
                seed=self.seed if self.seed > 0 else 1,
                columns_to_encode=te_cols)
        te.train(x=x, y=y, training_frame=train2)
        self.te_model = te
        train_enc = te.transform(train2, as_training=True)
        valid_enc = (te.transform(validation_frame)
                     if validation_frame is not None else None)
        lb_enc = (te.transform(leaderboard_frame)
                  if leaderboard_frame is not None else None)
        # models see the encodings INSTEAD of the raw high-card columns
        x_enc = [c for c in x if c not in te_cols] \
            + [f"{c}_te" for c in te_cols]
        self._log(f"target_encoding: encoded {te_cols} "
                  f"(cardinalities {[training_frame.vec(c).cardinality for c in te_cols]})")
        return x_enc, train_enc, valid_enc, lb_enc, fold_col

    def _run_grid_steps(self, lb, se_candidates, x, y, training_frame,
                        validation_frame, t0, recovery):
        """The AutoML plan's grid steps: a RandomDiscrete GBM grid under
        the remaining time/model budget (AutoML.java planWork grids)."""
        from h2o3_tpu.models.grid import H2OGridSearch
        from h2o3_tpu.models.tree.gbm import H2OGradientBoostingEstimator
        budget_left = (self.max_runtime_secs - (time.time() - t0)
                       if self.max_runtime_secs else 0)
        if self.max_runtime_secs and budget_left < 5.0:
            return          # a sub-5s leftover cannot fit a model build
        room = (self.max_models - len(se_candidates)
                if self.max_models else 3)
        if room <= 0:
            return
        try:
            self._log("building GBM_grid_1")
            grid = H2OGridSearch(
                H2OGradientBoostingEstimator,
                hyper_params={"max_depth": [4, 7, 10],
                              "learn_rate": [0.05, 0.1],
                              "sample_rate": [0.6, 0.9]},
                grid_id=f"{self.project_name}_GBM_grid_1",
                search_criteria={"strategy": "RandomDiscrete",
                                 "max_models": min(room, 3),
                                 "max_runtime_secs": budget_left,
                                 "seed": self.seed},
                recovery_dir=self.recovery_dir)
            cv_kw = ({"fold_column": self._te_fold_col}
                     if getattr(self, "_te_fold_col", None)
                     else {"nfolds": self.nfolds})
            grid.train(x=x, y=y, training_frame=training_frame,
                       validation_frame=validation_frame,
                       keep_cross_validation_predictions=True,
                       ntrees=40, seed=self.seed if self.seed > 0 else 1,
                       **cv_kw)
            for i, m in enumerate(grid.models):
                lb.add(f"GBM_grid_1_model_{i}", m)
                se_candidates.append(m)
                if recovery is not None:
                    recovery.checkpoint_model(m)
        except Exception as ex:  # noqa: BLE001
            self._log(f"grid step failed: {ex!r}")

    def _run_exploitation(self, lb, se_candidates, x, y, training_frame,
                          validation_frame, recovery):
        """Exploitation: continue the best tree model with more trees via
        checkpoint restart (the learn-rate-annealing exploitation step of
        the reference plan).

        The continued model trains WITHOUT CV (a checkpoint restart cannot
        re-fold), so it only enters the leaderboard when ranking happens on
        a common held-out frame (leaderboard_frame / validation) — training
        metrics would compare optimistically against the others' CV
        metrics. It never joins se_candidates (no cv predictions)."""
        leader = lb.leader
        # gbm only: the xgboost estimator rejects `checkpoint` so its
        # continuation would fail on every run
        if getattr(leader, "algo", None) != "gbm":
            return
        holdout = (lb.leaderboard_frame is not None
                   or validation_frame is not None)
        if not holdout:
            self._log("exploitation skipped: no held-out frame to rank "
                      "a non-CV continuation fairly")
            return
        try:
            self._log("exploitation: continuing leader")
            cls = leader.__class__
            p = {k: v for k, v in leader.params.items() if v is not None}
            p["ntrees"] = int(p.get("ntrees") or 50) + 25
            p["checkpoint"] = leader.key
            p["model_id"] = f"{self.project_name}_{leader.algo}_exploit"
            p["nfolds"] = 0
            p.pop("keep_cross_validation_predictions", None)
            p.pop("keep_cross_validation_fold_assignment", None)
            m = cls(**p)
            m.train(x=x, y=y, training_frame=training_frame,
                    validation_frame=validation_frame)
            lb.add("exploitation", m)
            if recovery is not None:
                recovery.checkpoint_model(m)
        except Exception as ex:  # noqa: BLE001
            self._log(f"exploitation failed: {ex!r}")

    @property
    def leaderboard(self):
        import pandas as pd
        return pd.DataFrame(self.leaderboard_obj.as_list())

    def predict(self, test_data: Frame) -> Frame:
        if self.te_model is not None:
            # leader was trained on target-encoded columns: apply the same
            # (plain-strategy) encodings before scoring
            test_data = self.te_model.transform(test_data)
        return self.leader.predict(test_data)
