"""Chunk spill backend — the disk tier of the DKV chunk pager.

Reference: water/persist/PersistIce.java (Value byte[] spill files under
ice_root), water/Value.java mem/disk duality. Where persist.py snapshots
WHOLE frames (.hex, the FramePersist analog), this backend stores ONE
chunk plane-bundle per file: the packed codec bytes (dtype-packed data
plane + optional uint8 NA mask) exactly as the parser produced them, so a
disk→host promotion is a plain np.load with zero decode work and a
host→HBM promotion stays the same bulk device_put as any other fault.

Files live under the ice root (H2O3_TPU_ICE_ROOT, default
~/.h2o3_tpu_ice/chunks); the pager owns their lifetime — a chunk's spill
file is deleted when the chunk is promoted off disk or garbage-collected.
"""

from __future__ import annotations

import os
import re

import numpy as np

from h2o3_tpu.utils.env import env_str

_DEFAULT_ICE = os.path.join(os.path.expanduser("~"), ".h2o3_tpu_ice")
_ICE_ROOT = env_str("H2O3_TPU_ICE_ROOT", "") or _DEFAULT_ICE

_SAFE = re.compile(r"[^A-Za-z0-9_.-]")

# chunk keys are a per-process counter ("num#1", ...), so two processes
# sharing one ice root (two servers, parallel test workers) would clobber
# each other's files — every process spills into its own subdirectory
_PROC_TAG = f"p{os.getpid()}"


def get_ice_root() -> str:
    return _ICE_ROOT


def set_ice_root(path: str):
    """Point the spill tier somewhere else (tests use tmp dirs; the
    memory manager's `ice_root` attribute delegates here)."""
    global _ICE_ROOT
    _ICE_ROOT = str(path)


def chunk_dir() -> str:
    return os.path.join(_ICE_ROOT, "chunks", _PROC_TAG)


def write_chunk(key: str, data: np.ndarray, mask) -> str:
    """Persist one chunk's packed planes; returns the spill path.
    Uncompressed npz: the planes are already codec-packed, and spill
    bandwidth (not disk footprint) is what bounds demotion."""
    d = chunk_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{_SAFE.sub('_', key)}.npz")
    arrays = {"data": np.asarray(data)}
    if mask is not None:
        arrays["mask"] = np.asarray(mask)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)
    return path


def read_chunk(path: str):
    """(data, mask_or_None) packed host planes from a spill file."""
    with np.load(path, allow_pickle=False) as npz:
        data = npz["data"]
        mask = npz["mask"] if "mask" in npz.files else None
    return data, mask


def delete_chunk(path: str):
    try:
        os.unlink(path)
    except OSError:
        pass


# -- model-param artifacts (the disk rung of the serving param ladder) ----

def params_dir() -> str:
    return os.path.join(_ICE_ROOT, "params", _PROC_TAG)


def write_params(key: str, leaves) -> str:
    """Persist a param pytree's leaves (canonical host arrays, in
    tree-flatten order) as one npz artifact; returns the spill path.
    Same atomic tmp+rename discipline as chunk spill files."""
    d = params_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{_SAFE.sub('_', key)}.npz")
    arrays = {f"l{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)
    return path


def read_params(path: str) -> list:
    """The leaves back, in the order write_params received them."""
    with np.load(path, allow_pickle=False) as npz:
        return [npz[f"l{i}"] for i in range(len(npz.files))]


def delete_params(path: str):
    try:
        os.unlink(path)
    except OSError:
        pass
