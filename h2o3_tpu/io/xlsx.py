"""XLSX ingest — the XlsParser/POI capability of the reference
(h2o-parsers/h2o-orc-parser sibling `XlsParser.java` family) rebuilt on
the stdlib: an .xlsx file is a zip of XML parts, so no third-party
spreadsheet library is needed (none ships in this image).

Supported: the first worksheet, shared strings, inline strings, numeric
cells, blank cells → NA, first row as header when non-numeric (the same
header heuristic as the CSV setup guess). Legacy binary .xls (BIFF) is
loud-rejected with guidance — the reference parses it through POI, which
has no stdlib equivalent."""

from __future__ import annotations

import re
import zipfile
import xml.etree.ElementTree as ET
from typing import Optional

import numpy as np

_NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
_CELL_REF = re.compile(r"([A-Z]+)(\d+)")


def _col_index(ref: str) -> int:
    """'A'→0, 'Z'→25, 'AA'→26 …"""
    n = 0
    for ch in ref:
        n = n * 26 + (ord(ch) - 64)
    return n - 1


def _shared_strings(zf: zipfile.ZipFile) -> list:
    try:
        data = zf.read("xl/sharedStrings.xml")
    except KeyError:
        return []
    out = []
    for si in ET.fromstring(data).iter(f"{_NS}si"):
        out.append("".join(t.text or "" for t in si.iter(f"{_NS}t")))
    return out


def _first_sheet_name(zf: zipfile.ZipFile) -> str:
    names = [n for n in zf.namelist()
             if re.fullmatch(r"xl/worksheets/sheet\d+\.xml", n)]
    if not names:
        raise ValueError("xlsx contains no worksheets")
    return sorted(names, key=lambda n: int(re.findall(r"\d+", n)[0]))[0]


def read_xlsx_rows(path: str) -> list:
    """[[cell, …], …] with None for blanks; strings stay str, numbers
    float."""
    with zipfile.ZipFile(path) as zf:
        strings = _shared_strings(zf)
        sheet = ET.fromstring(zf.read(_first_sheet_name(zf)))
    rows = []
    for row in sheet.iter(f"{_NS}row"):
        cells: dict = {}
        for c in row.iter(f"{_NS}c"):
            ref = c.get("r", "")
            m = _CELL_REF.fullmatch(ref)
            ci = _col_index(m.group(1)) if m else len(cells)
            ctype = c.get("t", "n")
            v = c.find(f"{_NS}v")
            ist = c.find(f"{_NS}is")
            if ctype == "s" and v is not None:
                cells[ci] = strings[int(v.text)]
            elif ctype == "inlineStr" and ist is not None:
                cells[ci] = "".join(t.text or ""
                                    for t in ist.iter(f"{_NS}t"))
            elif ctype == "str" and v is not None:   # formula cached string
                cells[ci] = v.text
            elif ctype == "b" and v is not None:     # boolean
                cells[ci] = float(int(v.text))
            elif v is not None and v.text not in (None, ""):
                cells[ci] = float(v.text)
        if cells:
            width = max(cells) + 1
            rows.append([cells.get(j) for j in range(width)])
    return rows


def parse_xlsx(path: str, destination_frame: Optional[str] = None):
    """XLSX → Frame with the CSV path's typing rules (numeric / enum /
    NA), header detected when the first row is all-strings and a later
    row has a number."""
    from h2o3_tpu.core.frame import Frame, Vec
    rows = read_xlsx_rows(path)
    if not rows:
        raise ValueError(f"empty xlsx: {path}")
    ncol = max(len(r) for r in rows)
    rows = [r + [None] * (ncol - len(r)) for r in rows]
    first_all_str = all(isinstance(c, str) or c is None for c in rows[0])
    later_num = any(isinstance(c, float) for r in rows[1:] for c in r)
    header = first_all_str and later_num and len(rows) > 1
    names = ([str(c) if c is not None else f"C{j + 1}"
              for j, c in enumerate(rows[0])] if header
             else [f"C{j + 1}" for j in range(ncol)])
    body = rows[1:] if header else rows
    vecs = []
    for j in range(ncol):
        col = [r[j] for r in body]
        if any(isinstance(c, str) for c in col):
            vecs.append(Vec.from_numpy(np.asarray(
                [None if c is None else str(c) for c in col], object)))
        else:
            vecs.append(Vec.from_numpy(np.asarray(
                [np.nan if c is None else float(c) for c in col],
                np.float64)))
    return Frame(names, vecs, destination_frame)


def reject_legacy_xls(path: str, destination_frame=None):
    raise NotImplementedError(
        f"{path}: legacy binary .xls (BIFF) requires the reference's POI "
        "stack, which has no stdlib equivalent here — save the workbook "
        "as .xlsx (fully supported) or export to CSV")
