"""Persistence — water/persist/* + fault-tolerance Recovery rebuilt.

Reference: water/persist/PersistManager.java (URI-scheme dispatch: file/NFS/
HDFS/S3/GCS/HTTP), water/fvec/persist/FramePersist.java (.hex frame
snapshots), hex/faulttolerance/Recovery.java:55 (+ -auto_recovery_dir,
H2O.java:411): Grid/AutoML training state is persisted (frames + every
finished model) so a restarted cluster resumes the job.

TPU-native: frames serialize column-packed (the codec-packed host mirror of
HBM state) into one npz + JSON header; models reuse the binary pickle path
(device arrays → numpy). S3/HDFS/GCS schemes raise with guidance — the
cloud-connector dependencies aren't in this image; local/NFS paths cover the
recovery contract.
"""

from __future__ import annotations

import json
import os
import time
import zipfile

import numpy as np

from h2o3_tpu.core.frame import Codec, Frame, Vec
from h2o3_tpu.core.kvstore import DKV


def _stage_for_write(path: str) -> tuple:
    """Local staging target for a (possibly remote) export URI."""
    from h2o3_tpu.io import uri as _uri
    if _uri.is_remote(path):
        import tempfile
        fd, tmp = tempfile.mkstemp(suffix=".hex")
        os.close(fd)
        return tmp, path
    return path, None


def _finish_write(local: str, remote):
    if remote is not None:
        from h2o3_tpu.io import uri as _uri
        _uri.push_from_local(local, remote)


def _stage_for_read(path: str) -> str:
    from h2o3_tpu.io import uri as _uri
    return _uri.fetch_to_local(path)


# ===========================================================================
def export_frame(frame: Frame, path: str) -> str:
    """FramePersist.saveTo: snapshot a frame (packed columns, exact).
    URI schemes dispatch per PersistManager (file/gs/s3/memory)."""
    local, remote = _stage_for_write(path)
    path, _orig = local, path
    header = {"key": frame.key, "names": frame.names, "nrows": frame.nrows,
              "cols": []}
    arrays = {}
    for j, (n, v) in enumerate(zip(frame.names, frame.vecs)):
        from h2o3_tpu.core.frame import SparseVec
        is_sparse = isinstance(v, SparseVec)
        c = {"type": v.type, "codec": v.codec.kind, "bias": v.codec.bias,
             "const": None if v.codec.const_val != v.codec.const_val
             else v.codec.const_val,
             # has_mask is filled from the staged planes below — touching
             # v.mask here would fault a demoted chunk back into HBM
             "domain": v.levels(), "has_mask": False,
             "is_str": v.type == "str", "is_sparse": is_sparse}
        header["cols"].append(c)
        if is_sparse:
            # CXI-style persist: only the nonzero (row, value) pairs —
            # staging_view so exporting a demoted frame stays tier-cheap
            arrays[f"zr{j}"] = np.asarray(v._nzr_chunk.staging_view()[0])
            arrays[f"zv{j}"] = np.asarray(v._nzv_chunk.staging_view()[0])
        elif v.type == "str":
            data = v.host_data    # one device fetch+decode, not two
            arrays[f"s{j}"] = np.array([x if x is not None else ""
                                        for x in data])
            arrays[f"sm{j}"] = np.array([x is None for x in data])
        else:
            # staging_view: packed planes from the cheapest resident tier
            # — exporting a demoted frame must not fault it back into HBM
            data_h, mask_h = v._chunk.staging_view()
            c["has_mask"] = mask_h is not None
            arrays[f"d{j}"] = np.asarray(data_h)
            if mask_h is not None:
                arrays[f"m{j}"] = np.asarray(mask_h)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("header.json", json.dumps(header, default=float))
        import io as _io
        buf = _io.BytesIO()
        np.savez(buf, **arrays)
        zf.writestr("columns.npz", buf.getvalue())
    _finish_write(local, remote)
    return _orig


def import_frame(path: str, key=None) -> Frame:
    from h2o3_tpu.io import uri as _uri
    staged = _uri.is_remote(path)
    path = _stage_for_read(path)
    try:
        return _import_frame_local(path, key)
    finally:
        if staged:
            try:
                os.unlink(path)
            except OSError:
                pass


def _import_frame_local(path: str, key=None) -> Frame:
    import io as _io
    with zipfile.ZipFile(path) as zf:
        header = json.loads(zf.read("header.json"))
        npz = np.load(_io.BytesIO(zf.read("columns.npz")), allow_pickle=False)
        vecs = []
        from h2o3_tpu.parallel import mrtask as mr
        for j, c in enumerate(header["cols"]):
            if c.get("is_sparse"):
                from h2o3_tpu.core.frame import SparseVec
                vecs.append(SparseVec(npz[f"zr{j}"], npz[f"zv{j}"],
                                      header["nrows"], type=c["type"]))
                continue
            if c["is_str"]:
                s = npz[f"s{j}"].astype(object)
                m = npz[f"sm{j}"]
                s[m] = None
                vecs.append(Vec(None, Codec("const"), None,
                               header["nrows"], "str", host_data=s))
                continue
            codec = Codec(c["codec"], bias=c["bias"] or 0.0,
                          const_val=(c["const"] if c["const"] is not None
                                     else float("nan")))
            data_h = npz[f"d{j}"]
            mask_h = npz[f"m{j}"] if c["has_mask"] else None
            data = mr.device_put_rows(data_h)
            mask = mr.device_put_rows(mask_h) if mask_h is not None else None
            dom = (np.asarray(c["domain"], object)
                   if c["domain"] is not None else None)
            vecs.append(Vec(data, codec, mask, header["nrows"], c["type"],
                            dom, packed_host=data_h, packed_mask=mask_h))
    return Frame(header["names"], vecs, key or header["key"])


# ===========================================================================
class Recovery:
    """hex/faulttolerance/Recovery.java: job-level auto-checkpointing.

    Wrap a long-running multi-model job (grid / AutoML): every finished model
    and the referenced frames land in `recovery_dir`; `resume` reloads them
    so a restarted controller continues instead of starting over.
    """

    def __init__(self, recovery_dir: str):
        self.dir = recovery_dir
        os.makedirs(recovery_dir, exist_ok=True)
        self._manifest_path = os.path.join(recovery_dir, "manifest.json")

    def _manifest(self) -> dict:
        if os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                return json.load(f)
        return {"frames": {}, "models": [], "updated": 0}

    def _write(self, man):
        man["updated"] = time.time()
        with open(self._manifest_path, "w") as f:
            json.dump(man, f)

    def checkpoint_frame(self, frame: Frame):
        man = self._manifest()
        if frame.key not in man["frames"]:
            p = os.path.join(self.dir, f"frame_{frame.key}.hex")
            export_frame(frame, p)
            man["frames"][frame.key] = p
            self._write(man)

    def checkpoint_model(self, model):
        from h2o3_tpu.genmodel.mojo import save_model
        man = self._manifest()
        p = os.path.join(self.dir, f"model_{model.key}.bin")
        save_model(model, p)
        if model.key not in [m["key"] for m in man["models"]]:
            man["models"].append({"key": model.key, "path": p})
            self._write(man)

    def resume(self) -> dict:
        """Recovery.autoRecover: reload every persisted frame and model."""
        from h2o3_tpu.genmodel.mojo import load_model
        man = self._manifest()
        out = {"frames": [], "models": []}
        for key, p in man["frames"].items():
            if key not in DKV:
                out["frames"].append(import_frame(p, key))
        for m in man["models"]:
            if m["key"] not in DKV:
                out["models"].append(load_model(m["path"]))
        return out

    def recovered_model_keys(self) -> list:
        return [m["key"] for m in self._manifest()["models"]]
