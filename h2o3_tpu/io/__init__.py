from h2o3_tpu.io.parser import import_file, parse_setup, upload_frame
