"""URI-scheme storage dispatch — water/persist/PersistManager.java rebuilt.

Reference: PersistManager routes by URI scheme to Persist backends (local
FS/NFS eager, HTTP eager read-only, plus plugin modules S3/HDFS/GCS:
h2o-persist-s3, h2o-persist-hdfs, h2o-persist-gcs). Here:

  * file / bare paths -> local filesystem
  * http(s)://        -> eager read-only fetch (PersistEagerHTTP analog)
  * gs://             -> gcsfs (available in this image)
  * s3:// s3a://      -> fsspec if an s3 implementation is installed,
                         otherwise a clear installation hint
  * memory://         -> fsspec in-memory FS (testing)
  * hdfs://           -> routed through fsspec (pyarrow HDFS when present)

Everything materializes through a local staging file: frames/models are
small controller-side artifacts (the big arrays live in HBM), so eager
transfer matches the reference's eager backends.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import urllib.request

_REMOTE_SCHEMES = ("http://", "https://", "gs://", "s3://", "s3a://",
                   "hdfs://", "memory://")


def is_remote(path: str) -> bool:
    return path.startswith(_REMOTE_SCHEMES)


def _fs_for(path: str):
    import fsspec
    norm = path.replace("s3a://", "s3://")
    try:
        fs, rel = fsspec.core.url_to_fs(norm)
    except ImportError as e:
        raise NotImplementedError(
            f"persist backend for {path.split('://')[0]}:// needs an fsspec "
            f"implementation that is not installed ({e}); gs:// and "
            f"memory:// are available in this image") from e
    return fs, rel


def path_size(path: str) -> int:
    """Byte size of a local path or remote URI (HEAD content-length for
    http(s), fs.size for fsspec backends) — phase A of the distributed
    parse plans byte ranges over remote sources with this."""
    if not is_remote(path):
        return os.path.getsize(path)
    if path.startswith(("http://", "https://")):
        req = urllib.request.Request(path, method="HEAD")
        with urllib.request.urlopen(req) as r:
            ln = r.headers.get("Content-Length")
        if ln is None:
            raise OSError(f"no Content-Length for {path}")
        return int(ln)
    fs, rel = _fs_for(path)
    return int(fs.size(rel))


def supports_ranges(path: str) -> bool:
    """Whether `path` can serve byte-range reads (the chunked-parse
    prerequisite). Local files and fsspec backends always can; http(s)
    needs the server to advertise Accept-Ranges/Content-Length."""
    if not is_remote(path):
        return True
    if not path.startswith(("http://", "https://")):
        return True
    try:
        req = urllib.request.Request(path, method="HEAD")
        with urllib.request.urlopen(req) as r:
            accept = (r.headers.get("Accept-Ranges") or "").lower()
            has_len = r.headers.get("Content-Length") is not None
        return has_len and accept != "none"
    except Exception:   # noqa: BLE001 — probe failure: stage eagerly
        return False


def read_range(path: str, start: int, end: int) -> bytes:
    """Read bytes [start, end) from a local path or remote URI (HTTP
    Range request / fsspec cat_file) — phase B's remote chunk reader."""
    if end <= start:
        return b""
    if not is_remote(path):
        with open(path, "rb") as f:
            f.seek(start)
            return f.read(end - start)
    if path.startswith(("http://", "https://")):
        req = urllib.request.Request(
            path, headers={"Range": f"bytes={start}-{end - 1}"})
        with urllib.request.urlopen(req) as r:
            body = r.read()
            if r.status == 200 and start != 0:
                # server ignored the Range header: serve the slice so
                # the chunk contract still holds (wasteful but correct)
                return body[start:end]
            return body[: end - start]
    fs, rel = _fs_for(path)
    return fs.cat_file(rel, start=start, end=end)


def fetch_to_local(path: str, suffix: str = "") -> str:
    """Eager-read a (possibly remote) URI to a local staging file and
    return its path. Local paths pass through untouched."""
    if not is_remote(path):
        return path
    fd, tmp = tempfile.mkstemp(suffix=suffix or os.path.splitext(path)[1])
    os.close(fd)
    if path.startswith(("http://", "https://")):
        with urllib.request.urlopen(path) as r, open(tmp, "wb") as out:
            shutil.copyfileobj(r, out)
        return tmp
    fs, rel = _fs_for(path)
    fs.get_file(rel, tmp)
    return tmp


def push_from_local(local: str, path: str):
    """Upload a local staging file to a remote URI (export side)."""
    if not is_remote(path):
        if local != path:
            shutil.move(local, path)
        return path
    if path.startswith(("http://", "https://")):
        raise NotImplementedError(
            "http persist is eager READ-only (PersistEagerHTTP semantics); "
            "export to file/gs/s3 instead")
    fs, rel = _fs_for(path)
    fs.put_file(local, rel)
    os.unlink(local)
    return path


def exists(path: str) -> bool:
    if not is_remote(path):
        return os.path.exists(path)
    if path.startswith(("http://", "https://")):
        try:
            req = urllib.request.Request(path, method="HEAD")
            with urllib.request.urlopen(req):
                return True
        except Exception:
            return False
    fs, rel = _fs_for(path)
    return fs.exists(rel)
