"""URI-scheme storage dispatch — water/persist/PersistManager.java rebuilt.

Reference: PersistManager routes by URI scheme to Persist backends (local
FS/NFS eager, HTTP eager read-only, plus plugin modules S3/HDFS/GCS:
h2o-persist-s3, h2o-persist-hdfs, h2o-persist-gcs). Here:

  * file / bare paths -> local filesystem
  * http(s)://        -> eager read-only fetch (PersistEagerHTTP analog)
  * gs://             -> gcsfs (available in this image)
  * s3:// s3a://      -> fsspec if an s3 implementation is installed,
                         otherwise a clear installation hint
  * memory://         -> fsspec in-memory FS (testing)
  * hdfs://           -> routed through fsspec (pyarrow HDFS when present)

Everything materializes through a local staging file: frames/models are
small controller-side artifacts (the big arrays live in HBM), so eager
transfer matches the reference's eager backends.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import urllib.request

_REMOTE_SCHEMES = ("http://", "https://", "gs://", "s3://", "s3a://",
                   "hdfs://", "memory://")


def is_remote(path: str) -> bool:
    return path.startswith(_REMOTE_SCHEMES)


def _fs_for(path: str):
    import fsspec
    norm = path.replace("s3a://", "s3://")
    try:
        fs, rel = fsspec.core.url_to_fs(norm)
    except ImportError as e:
        raise NotImplementedError(
            f"persist backend for {path.split('://')[0]}:// needs an fsspec "
            f"implementation that is not installed ({e}); gs:// and "
            f"memory:// are available in this image") from e
    return fs, rel


def fetch_to_local(path: str, suffix: str = "") -> str:
    """Eager-read a (possibly remote) URI to a local staging file and
    return its path. Local paths pass through untouched."""
    if not is_remote(path):
        return path
    fd, tmp = tempfile.mkstemp(suffix=suffix or os.path.splitext(path)[1])
    os.close(fd)
    if path.startswith(("http://", "https://")):
        with urllib.request.urlopen(path) as r, open(tmp, "wb") as out:
            shutil.copyfileobj(r, out)
        return tmp
    fs, rel = _fs_for(path)
    fs.get_file(rel, tmp)
    return tmp


def push_from_local(local: str, path: str):
    """Upload a local staging file to a remote URI (export side)."""
    if not is_remote(path):
        if local != path:
            shutil.move(local, path)
        return path
    if path.startswith(("http://", "https://")):
        raise NotImplementedError(
            "http persist is eager READ-only (PersistEagerHTTP semantics); "
            "export to file/gs/s3 instead")
    fs, rel = _fs_for(path)
    fs.put_file(local, rel)
    os.unlink(local)
    return path


def exists(path: str) -> bool:
    if not is_remote(path):
        return os.path.exists(path)
    if path.startswith(("http://", "https://")):
        try:
            req = urllib.request.Request(path, method="HEAD")
            with urllib.request.urlopen(req):
                return True
        except Exception:
            return False
    fs, rel = _fs_for(path)
    return fs.exists(rel)
