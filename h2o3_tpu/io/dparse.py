"""Distributed 2-phase parse — the ParseDataset/MultiFileParseTask rebuild.

Reference: water/parser/ParseDataset.java:31,127,253 — phase 1 guesses the
setup on a sample; phase 2 is an MRTask over FILE CHUNKS (byte ranges)
whose per-chunk parsers emit NewChunks in parallel across the cluster;
categorical levels discovered per-chunk are merged cluster-wide and every
chunk's codes renumbered against the global domain
(ParseDataset.java:356-440 `MultiFileParseTask` + `EnumUpdateTask`).

TPU-native shape of the same idea — a cloud-wide, stage-overlapped
pipeline:

  phase A  chunk plan: every source split into ~`chunk_bytes` byte ranges
           aligned to line boundaries by the chunk contract (a range
           starts after its first newline, ends through the line
           straddling its end — each line parsed exactly once). Local
           files, HTTP/object-store URLs (io/uri range readers) and
           gzip/zip members (streaming decompress into line-aligned
           windows) all ride the same plan.
  phase B  parallel tokenize: each range → column-major doubles + string
           side table via the native tokenizer (GIL-released
           `fastcsv_parse_range`/`fastcsv_parse_bytes`), pooled with a
           bounded read-ahead so read/decompress overlaps tokenize.
           With a live cloud, chunks are deterministically fanned out
           over the replay channel (consistent hash over (path, start)):
           each host tokenizes its share and ships compact codec-byte
           planes back (the DKV re-home wire format — never decoded
           f32), while the coordinator parses its own share in parallel.
  phase C  merge: numeric columns concatenate; categorical columns do the
           EnumUpdateTask dance fully VECTORIZED — np.unique per-chunk
           levels → sorted global domain → searchsorted renumber (no
           per-row Python loops); time-column string fix-ups parse each
           unique token once and scatter. Packed columns land in the
           tier pager (born cold under a budget / H2O3_TPU_INGEST_COLD —
           no device_put spike), else `device_put` with the mesh row
           sharding.

The single-file `parse()` path in io/parser.py remains the fallback for
non-CSV formats (ARFF/SVMLight) and anything else the chunk plan cannot
express.

Env knobs (utils/env typed accessors, declared here):
  H2O3_PARSE_CHUNK_MB          chunk-plan granularity (default 64)
  H2O3_PARSE_WORKERS           tokenizer pool size (0 = one per core)
  H2O3_PARSE_READAHEAD         extra in-flight chunks beyond the pool
  H2O3_PARSE_FANOUT_TIMEOUT_S  per-wave deadline for remote parse shares
"""

from __future__ import annotations

import base64
import glob as _glob
import itertools
import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

from h2o3_tpu.core.frame import (Frame, T_CAT, T_NUM, T_STR, T_TIME,
                                 T_UUID, Vec)
from h2o3_tpu.io.parser import (NA_TOKENS, ParseSetup, _num_token,
                                _parse_time_ms, pack_span, parse_setup)
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs.timeline import span as _span
from h2o3_tpu.utils.env import env_float, env_int

DEFAULT_CHUNK_BYTES = 64 << 20

# per-stage ingest volume: read (remote range bytes fetched), decompress
# (bytes inflated out of gzip/zip members), tokenize (bytes handed to a
# tokenizer), pack (packed codec bytes landing in Vec planes), wire
# (codec-byte planes shipped back by fan-out workers)
INGEST_BYTES = _om.counter(
    "h2o3_ingest_bytes_total",
    "distributed-ingest pipeline volume by stage "
    "(read/decompress/tokenize/pack/wire)")
INGEST_ROWS = _om.counter(
    "h2o3_ingest_rows_total",
    "rows materialized into Frames by the distributed ingest pipeline")


def _chunk_bytes_default() -> int:
    """Chunk-plan granularity (H2O3_PARSE_CHUNK_MB, default 64MB — the
    FileVec chunk-size analog)."""
    return env_int("H2O3_PARSE_CHUNK_MB", 64) << 20


def _pool_workers(n_units: int) -> int:
    """Tokenizer pool size: H2O3_PARSE_WORKERS, 0 = one per core."""
    w = env_int("H2O3_PARSE_WORKERS", 0) or (os.cpu_count() or 1)
    return max(1, min(32, w, n_units))


def _readahead() -> int:
    """Extra chunks in flight beyond the pool — bounds raw-buffer memory
    while keeping read/decompress ahead of tokenize."""
    return max(1, env_int("H2O3_PARSE_READAHEAD", 4))


def _fanout_timeout_s() -> float:
    """WHOLE-WAVE deadline for the worker parse shares; a host that
    blows its slice forfeits the wave AND its remaining shares (the
    coordinator re-parses locally). The collect grants each worker its
    slice SEQUENTIALLY while holding the broadcast lock, so the per-
    worker slice is this value divided by the wave's host count —
    replayed REST traffic stalls at most ~this long per wave even when
    every worker is wedged."""
    return env_float("H2O3_PARSE_FANOUT_TIMEOUT_S", 30.0)


# wave budget: source bytes per worker per collect round, bounded so the
# base64 codec-plane ack stays well under the replay channel's 64MB
# frame cap. Worst case wire ≈ 2× source (incompressible f64 planes ≈
# 8B per ~9B token, plus the string planes of a text-heavy share ≈ its
# source bytes), ×4/3 base64 → 16MB source ≤ ~43MB ack
_WAVE_BUDGET = 16 << 20


# ---------------------------------------------------------------------------
def expand_paths(paths) -> list:
    """Accept a path, directory, glob pattern, remote URL, or list
    thereof (the h2o.import_file folder-import semantics:
    ImportFilesHandler)."""
    from h2o3_tpu.io import uri as _uri
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out = []
    for p in paths:
        p = os.fspath(p)
        if _uri.is_remote(p):
            out.append(p)
        elif os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")
                and os.path.isfile(os.path.join(p, f))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


def plan_chunks(paths: Sequence[str],
                chunk_bytes: Optional[int] = None) -> list:
    """Phase A: [(path, start, end, is_file_head)] byte-range plan over
    local files and remote URLs alike."""
    from h2o3_tpu.io import uri as _uri
    chunk_bytes = chunk_bytes or _chunk_bytes_default()
    plan = []
    for p in paths:
        size = _uri.path_size(p)
        n_chunks = max(1, -(-size // chunk_bytes))
        step = -(-size // n_chunks)
        for i in range(n_chunks):
            plan.append((p, i * step, min((i + 1) * step, size), i == 0))
    return plan


# ---------------------------------------------------------------------------
# phase B: tokenizers (native fast path + pure-python fallback)
def _rows_to_cols(rows, skip_header):
    """csv-module rows → [(numeric ndarray, {row: str})] per column."""
    if skip_header and rows:
        rows = rows[1:]
    ncol = max((len(r) for r in rows), default=0)
    cols = []
    for j in range(ncol):
        num = np.empty(len(rows), np.float64)
        smap = {}
        for i, r in enumerate(rows):
            t = r[j].strip() if j < len(r) else ""
            if t in NA_TOKENS:
                num[i] = np.nan
            else:
                try:
                    num[i] = float(t)
                except ValueError:
                    num[i] = np.nan
                    smap[i] = t
        cols.append((num, smap))
    return cols


def _tokenize_bytes_py(buf: bytes, sep: str, skip_header: bool,
                       skip_partial_first: bool = False):
    """Pure-python tokenizer over staged bytes (same chunk contract as
    the native `fastcsv_parse_bytes`)."""
    import csv
    import io as _io
    if skip_partial_first:
        nl = buf.find(b"\n")
        buf = buf[nl + 1:] if nl >= 0 else b""
        skip_header = False
    text = buf.decode("utf-8", "replace")
    rows = [r for r in csv.reader(_io.StringIO(text), delimiter=sep) if r]
    return _rows_to_cols(rows, skip_header)


def _tokenize_range_py(path: str, sep: str, skip_header: bool,
                       start: int, end: int):
    """Python fallback for one byte range (same chunk contract as the
    native parser); returns list of (numeric ndarray, {row: str})."""
    size = os.path.getsize(path)
    end = size if end < 0 else min(end, size)
    with open(path, "rb") as f:
        f.seek(end)
        ext = end
        while ext < size:
            b = f.read(1 << 16)
            if not b:
                break
            nl = b.find(b"\n")
            if nl >= 0:
                ext += nl + 1
                break
            ext += len(b)
        f.seek(start)
        buf = f.read(ext - start)
    return _tokenize_bytes_py(buf, sep, skip_header and start == 0,
                              skip_partial_first=start > 0)


def _tokenize_range(path, sep, skip_header, start, end):
    from h2o3_tpu.io import fastcsv
    if fastcsv.available():
        return fastcsv.parse_columns(path, sep, skip_header,
                                     start=start, end=end)
    return _tokenize_range_py(path, sep, skip_header, start, end)


def _tokenize_bytes(buf, sep, skip_header, skip_partial_first=False):
    from h2o3_tpu.io import fastcsv
    if fastcsv.available():
        return fastcsv.parse_bytes_columns(
            buf, sep, skip_header, skip_partial_first=skip_partial_first)
    return _tokenize_bytes_py(buf, sep, skip_header,
                              skip_partial_first=skip_partial_first)


def _read_remote_chunk(path: str, start: int, end: int) -> bytes:
    """Range-read one remote chunk plus enough slack to cover the line
    straddling `end` (the native extend-through-the-line step, done with
    HTTP/object-store range requests). EOF is detected from a SHORT
    read — no per-chunk size probe (a 10GB source at 64MB chunks would
    otherwise issue ~160 redundant HEADs across the fan-out)."""
    from h2o3_tpu.io import uri as _uri
    slack = 1 << 16
    buf = b""
    while True:
        lo = start + len(buf)          # fetch only the missing tail —
        hi = end + slack               # never re-download fetched bytes
        with _span("parse.read", start=lo, end=hi):
            part = _uri.read_range(path, lo, hi)
        INGEST_BYTES.inc(len(part), stage="read")
        eof = len(part) < hi - lo
        buf += part
        if len(buf) > end - start:
            nl = buf.find(b"\n", end - start)
            if nl >= 0:
                return buf[:nl + 1]    # cut through the straddling line
        if eof:
            return buf                 # no newline after end before EOF
        slack *= 4


def _tokenize_chunk(chunk, setup: ParseSetup):
    """One plan entry → [(num, smap)] per column (local or remote)."""
    from h2o3_tpu.io import uri as _uri
    path, start, end, head = chunk
    header = bool(setup.header and head)
    if _uri.is_remote(path):
        buf = _read_remote_chunk(path, start, end)
        return _tokenize_bytes(buf, setup.separator, header,
                               skip_partial_first=start > 0)
    return _tokenize_range(path, setup.separator, header, start, end)


def _pipelined(units, fn, workers: int):
    """Run `fn` over `units` with a bounded in-flight window, yielding
    results IN ORDER: the read/decompress producer stays `readahead`
    chunks ahead of the tokenizer pool, never further (bounds buffer
    memory for a 100GB source at a few chunks, not the whole file)."""
    if workers <= 1:
        for u in units:
            yield fn(u)
        return
    window = workers + _readahead()
    with ThreadPoolExecutor(workers) as ex:
        it = iter(units)
        pending = deque(ex.submit(fn, u)
                        for u in itertools.islice(it, window))
        while pending:
            res = pending.popleft().result()
            nxt = next(it, None)
            if nxt is not None:
                pending.append(ex.submit(fn, nxt))
            yield res


def _compressed_units(path: str, chunk_bytes: int):
    """Streaming-decompress a .gz/.zip member into line-aligned byte
    windows of ~chunk_bytes — compressed sources join the chunked
    pipeline via one sequential inflate pass instead of falling back to
    a whole-file sequential parse."""
    import gzip
    import zipfile
    if path.endswith(".gz"):
        stream = gzip.open(path, "rb")
    else:
        zf = zipfile.ZipFile(path)
        stream = zf.open(zf.namelist()[0])
    carry = b""
    first = True
    with stream:
        while True:
            with _span("parse.decompress", file=os.path.basename(path)):
                blk = stream.read(chunk_bytes)
            if not blk:
                break
            INGEST_BYTES.inc(len(blk), stage="decompress")
            buf = carry + blk
            nl = buf.rfind(b"\n")
            if nl < 0:
                carry = buf
                continue
            yield buf[:nl + 1], first
            first = False
            carry = buf[nl + 1:]
    if carry:
        yield carry, first


# ---------------------------------------------------------------------------
# fan-out: ship chunk shares over the replay channel (collect op
# "parse:<json>"), workers answer with compact codec-byte planes — the
# DKV re-home wire format (core/kvstore._plane_payload), never decoded
# f32, bit-exact by construction.
def _wire_pack_col(num: np.ndarray, smap: dict) -> dict:
    """Pack one chunk column for the wire — `_choose_codec` (the one
    narrowing-logic owner) with a LOSSLESS float policy layered on top:
    its f32 downgrade ships only when every value round-trips, raw f64
    otherwise, so the coordinator's merge sees bit-identical doubles to
    a local tokenize."""
    from h2o3_tpu.core.frame import _choose_codec
    from h2o3_tpu.core.kvstore import _plane_payload
    mask = np.isnan(num)
    has_na = bool(mask.any())
    packed, codec = _choose_codec(num, mask)
    kind, bias, cval = codec.kind, float(codec.bias), 0.0
    if kind in ("const", "i8", "i16", "i32") and bool(
            np.any((num == 0.0) & np.signbit(num) & ~mask)):
        # negative zero doesn't survive the integer/const round trip
        # (-0.0 - bias + bias = +0.0), and the merge keeps "-0" a
        # DISTINCT categorical level — ship raw f64 for these rare
        # columns so fanned-out parses stay bit-identical to local
        packed = num
        kind = "f64"
    if kind == "const":
        cval = float(codec.const_val)
        packed = np.zeros(0, np.int8)        # value rides in `c`
    elif kind == "f32" and not np.array_equal(
            packed.astype(np.float64), np.where(mask, 0.0, num),
            equal_nan=True):
        packed = num                         # f32 would lose bits
        kind = "f64"
    payload = _plane_payload(packed,
                             mask.astype(np.uint8) if has_na else None)
    out = {"p": base64.b64encode(payload).decode("ascii"),
           "k": kind, "b": bias, "c": cval, "n": int(len(num))}
    wire_len = len(payload)
    if smap:
        # string cells ship as npz planes too (rows/lens/utf-8 bytes) —
        # a JSON dict per cell would inflate text-heavy shares several×
        # past the replay channel's frame cap and get the worker
        # wrongly excised for answering with an oversized ack
        import io as _io
        rows = np.fromiter(smap.keys(), np.int64, len(smap))
        vals = [s.encode("utf-8") for s in smap.values()]
        lens = np.asarray([len(v) for v in vals], np.int32)
        blob = np.frombuffer(b"".join(vals), np.uint8)
        buf = _io.BytesIO()
        np.savez(buf, rows=rows, lens=lens, blob=blob)
        spay = buf.getvalue()
        out["s"] = base64.b64encode(spay).decode("ascii")
        wire_len += len(spay)
    INGEST_BYTES.inc(wire_len, stage="wire")
    return out


def _wire_restore_col(w: dict):
    """Inverse of _wire_pack_col → (float64 ndarray, {row: str})."""
    import io as _io
    from h2o3_tpu.core.kvstore import _plane_restore
    data, mask = _plane_restore(base64.b64decode(w["p"]))
    n = int(w["n"])
    kind = w["k"]
    if kind == "const":
        num = np.full(n, float(w.get("c", float("nan"))))
    else:
        num = data.astype(np.float64)
        if w.get("b"):
            num += float(w["b"])
    if mask is not None:
        num[mask.astype(bool)] = np.nan
    smap = {}
    if w.get("s"):
        with np.load(_io.BytesIO(base64.b64decode(w["s"])),
                     allow_pickle=False) as z:
            rows, lens = z["rows"], z["lens"]
            blob = z["blob"].tobytes()
        offs = np.concatenate([[0], np.cumsum(lens)])
        for i in range(len(rows)):
            smap[int(rows[i])] = blob[offs[i]:offs[i + 1]].decode(
                "utf-8", "replace")
    return num, smap


# hard bound on one parse ack's wire payload: whatever the wave-budget
# heuristic predicted, the ENCODED ack must stay under the replay
# channel's 64MB frame cap with headroom for JSON/HMAC framing — chunks
# that don't fit are simply left out of the answer and the coordinator
# re-parses them locally (the protocol already tolerates partial acks)
_ACK_WIRE_CAP = 44 << 20


def worker_parse_chunks(spec: dict) -> dict:
    """Worker side of the parse fan-out (multihost._collect_local
    `parse:` op): tokenize this host's chunk share — entries are
    [path, start, end, is_head, plan_index] — through the local pipeline
    and return wire-packed codec planes per plan index, truncated at
    _ACK_WIRE_CAP so a worst-case column mix (short f64 tokens) can
    never produce an oversized frame that gets this worker excised."""
    setup = ParseSetup(separator=spec.get("sep", ","),
                       header=bool(spec.get("header", True)))
    chunks = [tuple(c) for c in spec.get("chunks") or []]
    if not chunks:
        return {"chunks": {}}
    out = {}
    wire = 0
    for idx, cols in zip(
            [c[4] for c in chunks],
            _pipelined([c[:4] for c in chunks],
                       lambda c: _tokenize_chunk(c, setup),
                       _pool_workers(len(chunks)))):
        if wire >= _ACK_WIRE_CAP:
            continue            # drained, not returned: local fallback
        packed = [_wire_pack_col(num, smap) for num, smap in cols]
        wire += sum(len(w["p"]) + len(w.get("s") or "") for w in packed)
        out[str(idx)] = packed
    return {"chunks": out}


def _assign_chunks(plan, nodes):
    """Deterministic chunk → node map: consistent hash over
    (path, start) against the sorted live node set (the Key.java home
    hash reused for parse work) — replay-safe (R016): same plan + same
    membership ⇒ same assignment on every host, no RNG, no wall clock."""
    from h2o3_tpu.core.kvstore import HashRing
    ring = HashRing(nodes)
    return [ring.node_for(f"{c[0]}:{c[1]}") for c in plan]


def _fan_out_parse(bc, plan, assign, setup, results, done_flags):
    """Coordinator side: wave the worker shares over the replay channel
    (bounded per-wave payload so the base64 codec-plane acks stay under
    the frame cap), restoring codec planes into `results`. A worker that
    times out, errors or was excised mid-wave simply leaves its chunks
    unparsed — the caller re-runs them locally."""
    import json as _json
    pids = sorted(set(a for a in assign if a != 0))
    shares = {p: [i for i, a in enumerate(assign) if a == p]
              for p in pids}
    waves = []
    while any(shares.values()):
        wave = {}
        for p, idxs in shares.items():
            take, budget = [], 0
            while idxs:
                size = plan[idxs[0]][2] - plan[idxs[0]][1]
                if take and budget + size > _WAVE_BUDGET:
                    break      # bound holds: never overshoot by a chunk
                take.append(idxs.pop(0))
                budget += size
            if take:
                wave[p] = take
        waves.append(wave)
    forfeited: set = set()
    for wave in waves:
        wave = {p: idxs for p, idxs in wave.items()
                if p not in forfeited}
        if not wave:
            continue
        spec_shares = {str(p): [list(plan[i][:4]) + [i] for i in idxs]
                       for p, idxs in wave.items()}
        op = "parse:" + _json.dumps(
            {"sep": setup.separator, "header": bool(setup.header),
             "shares": spec_shares})
        with _span("parse.fanout", chunks=sum(map(len, wave.values())),
                   hosts=len(wave)):
            acks = bc.collect(
                op, timeout=_fanout_timeout_s() / max(1, len(wave)))
        answered = set()
        for ack in acks:
            if not ack or not isinstance(ack.get("parse"), dict):
                continue
            answered.add(ack.get("host"))
            for sidx, cols in (ack["parse"].get("chunks") or {}).items():
                i = int(sidx)
                results[i] = [_wire_restore_col(w) for w in cols]
                done_flags[i] = True
        # a worker that blew the wave deadline (or died) would stall
        # every later wave for the full timeout again while holding the
        # broadcast lock — drop its remaining shares to the local
        # fallback instead
        forfeited.update(p for p in wave if p not in answered)


def parse_files(paths, setup: Optional[ParseSetup] = None,
                destination_frame: Optional[str] = None,
                col_types: Optional[dict] = None,
                chunk_bytes: Optional[int] = None,
                workers: Optional[int] = None,
                broadcaster=None) -> Frame:
    """Phase B+C: byte-range-parallel multi-file parse to one Frame.

    With `broadcaster` (a live replay-channel coordinator), the chunk
    plan fans out cloud-wide: each worker tokenizes its consistent-hash
    share and ships codec-byte planes back while the coordinator parses
    its own share — the MultiFileParseTask shape. Without one, the full
    plan runs through the local bounded pipeline."""
    from h2o3_tpu.io import uri as _uri
    paths = expand_paths(paths)
    # remote compressed sources stage to local ONCE, up front: gzip/zip
    # need seekable local bytes for both setup sniffing and the
    # streaming inflate (range-reading raw gzip bytes and sniffing them
    # as CSV text would crash on the magic bytes)
    staged: list = []
    try:
        for i, p in enumerate(paths):
            if p.endswith((".gz", ".zip")) and _uri.is_remote(p):
                lp = _uri.fetch_to_local(p)
                staged.append(lp)
                paths[i] = lp
        return _parse_files_inner(paths, setup, destination_frame,
                                  col_types, chunk_bytes, workers,
                                  broadcaster)
    finally:
        for lp in staged:
            try:
                os.unlink(lp)
            except OSError:
                pass


def _parse_files_inner(paths, setup, destination_frame, col_types,
                       chunk_bytes, workers, broadcaster) -> Frame:
    setup = setup or _setup_for(paths[0])
    chunk_bytes = chunk_bytes or _chunk_bytes_default()
    if setup.parse_type != "CSV":
        # non-CSV (ARFF/SVMLight): sequential per-file parse + rbind
        from h2o3_tpu.io.parser import parse as _parse1
        frames = [_parse1(p, None if i else setup, None, col_types)
                  for i, p in enumerate(paths)]
        return _rbind_frames(frames, destination_frame)

    # live-worker set read ONCE: both the chunk-size cap and the
    # assignment must see the same membership (a worker joining between
    # two reads could be handed uncapped chunks whose ack blows the
    # frame cap)
    pids = broadcaster.live_pids() if broadcaster is not None else []
    if pids:
        # fan-out chunks must fit one wave (a chunk's codec-plane ack
        # has to stay under the replay channel's frame cap — shipping a
        # 64MB chunk would get the answering worker wrongly excised for
        # an oversized frame)
        chunk_bytes = min(chunk_bytes, _WAVE_BUDGET)

    plain = [p for p in paths if not p.endswith((".gz", ".zip"))]

    plan = plan_chunks(plain, chunk_bytes) if plain else []
    results: dict = {}
    if plan:
        done = [False] * len(plan)
        assign = [0] * len(plan)
        fan_thread = None
        if broadcaster is not None:
            if pids:
                assign = _assign_chunks(plan, [0] + pids)
                fan_thread = threading.Thread(
                    target=_fan_out_parse,
                    args=(broadcaster, plan, assign, setup, results,
                          done),
                    daemon=True, name="h2o3-parse-fanout")
                fan_thread.start()
        mine = [i for i, a in enumerate(assign) if a == 0]
        for i, cols in zip(
                mine,
                _pipelined([plan[i] for i in mine],
                           lambda c: _tokenize_chunk(c, setup),
                           workers or _pool_workers(len(mine) or 1))):
            results[i] = cols
            done[i] = True
        if fan_thread is not None:
            fan_thread.join()
            # any share a worker forfeited (timeout/excision) re-parses
            # locally so the frame always completes
            missing = [i for i in range(len(plan)) if not done[i]]
            for i, cols in zip(
                    missing,
                    _pipelined([plan[i] for i in missing],
                               lambda c: _tokenize_chunk(c, setup),
                               workers or _pool_workers(
                                   len(missing) or 1))):
                results[i] = cols
        for p, start, end, _h in plan:
            INGEST_BYTES.inc(end - start, stage="tokenize")

    # assemble in PATH order (plan indices are contiguous per plain
    # path; compressed members expand in place) — mixing .gz and plain
    # inputs must not reorder rows vs the paths the caller gave. Each
    # occurrence of a path is its own group (a new occurrence starts at
    # an is_file_head entry), so duplicated paths keep their positions.
    occ: dict = {}
    for i, entry in enumerate(plan):
        if entry[3]:
            occ.setdefault(entry[0], deque()).append([])
        occ[entry[0]][-1].append(i)
    chunks: list = []          # tokenized results, source order
    for p in paths:
        if not p.endswith((".gz", ".zip")):
            grp = occ[p].popleft() if occ.get(p) else []
            chunks.extend(results[i] for i in grp)
            continue
        chunks.extend(_parse_compressed(p, setup, chunk_bytes, workers))

    return _merge_chunks(chunks, setup, destination_frame, col_types)


def _parse_compressed(path: str, setup: ParseSetup, chunk_bytes: int,
                      workers) -> list:
    """Tokenize one LOCAL .gz/.zip member through the streaming
    pipeline (parse_files staged any remote compressed source before
    this runs — staging has exactly one owner)."""
    units = _compressed_units(path, chunk_bytes)
    return list(_pipelined(
        units,
        lambda u, _s=setup: _tokenize_bytes(
            u[0], _s.separator, bool(_s.header and u[1])),
        workers or _pool_workers(8)))


def _setup_for(path: str) -> ParseSetup:
    """parse_setup, staging a head sample locally for remote URLs
    (remote COMPRESSED paths never reach here — parse_files stages them
    whole first, and parse_setup handles local .gz/.zip itself)."""
    from h2o3_tpu.io import uri as _uri
    if not _uri.is_remote(path):
        return parse_setup(path)
    import tempfile
    want = 1 << 18
    head = _uri.read_range(path, 0, want)
    if len(head) >= want:
        # the sample cuts mid-line: a truncated final token must not
        # participate in type/column guessing (a half time-stamp would
        # flip the whole column to enum). A short read means EOF — the
        # whole file is the sample (and no size probe was needed).
        nl = head.rfind(b"\n")
        if nl >= 0:
            head = head[:nl + 1]
    fd, tmp = tempfile.mkstemp(suffix=".csv")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(head)
        return parse_setup(tmp)
    finally:
        os.unlink(tmp)


# ---------------------------------------------------------------------------
# phase C: vectorized merge
def _merge_chunks(chunks, setup, destination_frame, col_types) -> Frame:
    ncol = max((len(c) for c in chunks), default=0)
    names = list(setup.column_names)
    types = list(setup.column_types)
    while len(names) < ncol:
        names.append(f"C{len(names) + 1}")
        types.append(T_CAT)
    if col_types:
        for k, v in col_types.items():
            if k in names:
                types[names.index(k)] = v

    rows_per = [len(c[0][0]) if c else 0 for c in chunks]
    n = int(sum(rows_per))
    offs = np.concatenate([[0], np.cumsum(rows_per)]).astype(np.int64)

    # merge phase: vectorized host-array assembly, one column per pool
    # thread (the big numpy ops — concatenate, unique, searchsorted —
    # release the GIL, so columns merge in true parallel)
    def _merge_col(j):
        parts = [c[j] if j < len(c) else
                 (np.full(r, np.nan), {})
                 for c, r in zip(chunks, rows_per)]
        t = types[j]
        if t == T_NUM:
            return ("num", np.concatenate(
                [p[0] for p in parts]) if parts else np.empty(0))
        if t == T_TIME:
            return ("time", _merge_time(parts, offs))
        if t in (T_STR, T_UUID):
            toks = np.concatenate(
                [_chunk_tokens(*p) for p in parts]) if parts else \
                np.empty(0, object)
            return ("str" if t == T_STR else "uuid", toks)
        return ("cat", _merge_categorical(parts, n, offs))

    with _span("parse.merge", cols=ncol, chunks=len(chunks), rows=n):
        mw = _pool_workers(ncol or 1)
        if mw > 1:
            with ThreadPoolExecutor(mw) as ex:
                merged = list(ex.map(_merge_col, range(ncol)))
        else:
            merged = [_merge_col(j) for j in range(ncol)]
    # pack phase: merged host arrays → codec-packed Vec planes (born
    # cold into the tier pager under a budget / H2O3_TPU_INGEST_COLD)
    vecs = []
    with pack_span(cols=ncol):
        for kind, payload in merged:
            if kind == "num":
                vecs.append(Vec.from_numpy(payload, type=T_NUM))
            elif kind == "time":
                vecs.append(Vec.from_numpy(payload, type=T_TIME))
            elif kind == "str":
                vecs.append(Vec.from_numpy(payload, type=T_STR))
            elif kind == "uuid":
                from h2o3_tpu.core.frame import UuidVec
                vecs.append(UuidVec.encode(payload))
            else:
                codes, mask, domain = payload
                vecs.append(Vec._from_floats(codes, mask, T_CAT, domain))
    for v in vecs:
        ch = getattr(v, "_chunk", None)
        if ch is not None:
            INGEST_BYTES.inc(ch.nbytes, stage="pack")
    INGEST_ROWS.inc(n)
    return Frame(names[:ncol], vecs, destination_frame)


def _merge_time(parts, offs: np.ndarray) -> np.ndarray:
    """Time-column merge: numeric chunks concatenate; string tokens are
    batched — each UNIQUE token parses once, then scatters (the per-row
    `_parse_time_ms` dict loop was most of time-column ingest)."""
    num = np.concatenate([p[0] for p in parts]) if parts \
        else np.empty(0, np.float64)
    rows_l, vals = [], []
    for k, (_pnum, smap) in enumerate(parts):
        if smap:
            rows_l.append(np.fromiter(smap.keys(), np.int64,
                                      len(smap)) + offs[k])
            vals.extend(smap.values())
    if vals:
        uvals, inv = np.unique(np.asarray(vals, dtype=object),
                               return_inverse=True)
        parsed = np.empty(len(uvals), np.float64)
        for i, s in enumerate(uvals):
            try:
                parsed[i] = _parse_time_ms(s)
            except ValueError:
                parsed[i] = np.nan
        num[np.concatenate(rows_l)] = parsed[inv]
    return num


def _chunk_level_codes(num: np.ndarray, smap: dict):
    """One chunk column → (sorted unique token levels, int codes with
    -1 = NA). Numeric-looking tokens reconstruct through `_num_token`
    over the UNIQUE values only; per-row work is numpy gathers."""
    codes = np.full(len(num), -1, np.int64)
    nn = ~np.isnan(num)
    # negative zero: np.unique collapses -0.0 into 0.0, but the source
    # tokens "-0" and "0" are DISTINCT levels (_num_token keeps the
    # sign) — route -0.0 rows through the string side instead
    nz = nn & (num == 0.0) & np.signbit(num)
    if nz.any():
        nn = nn & ~nz
    u_num, inv = (np.unique(num[nn], return_inverse=True)
                  if nn.any() else (np.empty(0), np.empty(0, np.int64)))
    num_toks = np.asarray([_num_token(v) for v in u_num], dtype=object)
    if smap:
        srows = np.fromiter(smap.keys(), np.int64, len(smap))
        svals = np.asarray(list(smap.values()), dtype=object)
        u_str, sinv = np.unique(svals, return_inverse=True)
    else:
        srows = np.empty(0, np.int64)
        u_str = np.empty(0, object)
        sinv = np.empty(0, np.int64)
    parts = [num_toks, u_str]
    if nz.any():
        parts.append(np.asarray([_num_token(-0.0)], dtype=object))
    levels = np.unique(np.concatenate(parts)) \
        if any(len(p) for p in parts) else np.empty(0, object)
    if nn.any():
        codes[nn] = np.searchsorted(levels, num_toks)[inv]
    if len(srows):
        codes[srows] = np.searchsorted(levels, u_str)[sinv]
    if nz.any():
        codes[nz] = int(np.searchsorted(levels, _num_token(-0.0)))
    return levels, codes


def _chunk_tokens(num: np.ndarray, smap: dict) -> np.ndarray:
    """Reconstruct the token strings of a string/uuid chunk column
    (numeric-looking tokens came through as doubles; None = NA). Object
    gathers over unique values — no per-row Python loop."""
    levels, codes = _chunk_level_codes(num, smap)
    toks = np.empty(len(num), object)
    ok = codes >= 0
    toks[ok] = levels[codes[ok]]
    return toks


def _merge_categorical(parts, n: int, offs: np.ndarray):
    """Phase C cat merge (EnumUpdateTask), vectorized: per-chunk unique
    levels union into one sorted global domain (np.unique), each chunk's
    codes renumber through a searchsorted remap table — replaces the
    per-row Python dict loop that dominated categorical ingest.
    Returns (codes f64, NA mask, domain) for the pack phase."""
    per_chunk = [_chunk_level_codes(*p) for p in parts]
    all_levels = [lv for lv, _c in per_chunk if len(lv)]
    domain = np.unique(np.concatenate(all_levels)) if all_levels \
        else np.empty(0, object)
    codes = np.empty(n, np.float64)
    mask = np.zeros(n, bool)
    for k, (levels, ccodes) in enumerate(per_chunk):
        o = int(offs[k])
        e = o + len(ccodes)
        remap = np.searchsorted(domain, levels).astype(np.int64) \
            if len(levels) else np.empty(0, np.int64)
        na = ccodes < 0
        out = np.zeros(len(ccodes), np.float64)
        if len(levels):
            ok = ~na
            out[ok] = remap[ccodes[ok]]
        codes[o:e] = out
        mask[o:e] = na
    return codes, mask, domain


def _rbind_frames(frames, dest) -> Frame:
    """Row-bind parsed file frames with the same categorical domain merge
    as the chunked path (rapids `rbind` prim semantics)."""
    if len(frames) == 1:
        f = frames[0]
        return Frame(f.names, f.vecs, dest) if dest else f
    base = frames[0]
    vecs = []
    for j in range(base.ncols):
        vts = [f.vecs[j] for f in frames]
        if vts[0].type == T_STR:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.host_data for v in vts]), type=T_STR))
        elif vts[0].type == T_CAT:
            # searchsorted renumber, same as the chunked merge — the old
            # per-element list comprehension re-hashed every row through
            # a Python dict (quadratically worse than the path it backs
            # up for wide domains)
            doms = [np.asarray(v.levels() or [], dtype=object)
                    for v in vts]
            nonempty = [d for d in doms if len(d)]
            dom = np.unique(np.concatenate(nonempty)) if nonempty \
                else np.empty(0, object)
            cols = []
            for v, d in zip(vts, doms):
                c_np = v.to_numpy()
                remap = np.searchsorted(dom, d).astype(np.float64) \
                    if len(d) else np.empty(0, np.float64)
                out = np.full(len(c_np), np.nan)
                ok = ~np.isnan(c_np)
                if len(d):
                    out[ok] = remap[c_np[ok].astype(np.int64)]
                cols.append(out)
            merged = np.concatenate(cols)
            mask = np.isnan(merged)
            vecs.append(Vec._from_floats(
                np.where(mask, 0.0, merged), mask, T_CAT,
                np.asarray(dom, dtype=object)))
        else:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.to_numpy() for v in vts]),
                type=vts[0].type))
    return Frame(list(base.names), vecs, dest)


def import_files(paths, destination_frame: Optional[str] = None,
                 col_types: Optional[dict] = None,
                 chunk_bytes: Optional[int] = None,
                 workers: Optional[int] = None,
                 broadcaster=None) -> Frame:
    """h2o.import_file(path=folder/pattern/list/URL) analog on the
    distributed parse path."""
    return parse_files(paths, None, destination_frame, col_types,
                       chunk_bytes, workers, broadcaster=broadcaster)
