"""Distributed 2-phase parse — the ParseDataset/MultiFileParseTask rebuild.

Reference: water/parser/ParseDataset.java:31,127,253 — phase 1 guesses the
setup on a sample; phase 2 is an MRTask over FILE CHUNKS (byte ranges)
whose per-chunk parsers emit NewChunks in parallel across the cluster;
categorical levels discovered per-chunk are merged cluster-wide and every
chunk's codes renumbered against the global domain
(ParseDataset.java:356-440 `MultiFileParseTask` + `EnumUpdateTask`).

TPU-native shape of the same idea: tokenization is HOST work done by the
native C++ range parser (native/fastcsv.cpp `fastcsv_parse_range`) under a
thread pool — the ctypes call releases the GIL so ranges parse in true
parallel on however many cores the host (or each host of a multi-host
cloud) has. The two phases survive intact:

  phase A  chunk plan: every file split into ~`chunk_bytes` byte ranges
           aligned to line boundaries by the chunk contract (a range
           starts after its first newline, ends through the line
           straddling its end — each line parsed exactly once).
  phase B  parallel tokenize: each range → column-major doubles + string
           side table (no global state, no locks).
  phase C  merge: numeric columns concatenate; categorical columns do the
           EnumUpdateTask dance — per-chunk local level sets union into a
           sorted global domain, then each chunk's tokens renumber against
           it — and the packed codes `device_put` with the mesh row
           sharding (Vec._from_floats), so a multi-chip cloud receives the
           frame already row-sharded.

The single-file `parse()` path in io/parser.py remains the fallback for
compressed inputs and hosts without the native library.
"""

from __future__ import annotations

import glob as _glob
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

from h2o3_tpu.core.frame import (Frame, T_CAT, T_NUM, T_STR, T_TIME,
                                 T_UUID, Vec)
from h2o3_tpu.io.parser import (NA_TOKENS, ParseSetup, _num_token,
                                _parse_time_ms, parse_setup)

DEFAULT_CHUNK_BYTES = 64 << 20


# ---------------------------------------------------------------------------
def expand_paths(paths) -> list:
    """Accept a path, directory, glob pattern, or list thereof (the
    h2o.import_file folder-import semantics: ImportFilesHandler)."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")
                and os.path.isfile(os.path.join(p, f))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


def plan_chunks(paths: Sequence[str],
                chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> list:
    """Phase A: [(path, start, end, is_file_head)] byte-range plan."""
    plan = []
    for p in paths:
        size = os.path.getsize(p)
        n_chunks = max(1, -(-size // chunk_bytes))
        step = -(-size // n_chunks)
        for i in range(n_chunks):
            plan.append((p, i * step, min((i + 1) * step, size), i == 0))
    return plan


# ---------------------------------------------------------------------------
def _tokenize_range_py(path: str, sep: str, skip_header: bool,
                       start: int, end: int):
    """Python fallback for one byte range (same chunk contract as the
    native parser); returns list of (numeric ndarray, {row: str})."""
    import csv
    import io as _io
    size = os.path.getsize(path)
    end = size if end < 0 else min(end, size)
    with open(path, "rb") as f:
        f.seek(end)
        ext = end
        while ext < size:
            b = f.read(1 << 16)
            if not b:
                break
            nl = b.find(b"\n")
            if nl >= 0:
                ext += nl + 1
                break
            ext += len(b)
        f.seek(start)
        buf = f.read(ext - start)
    if start > 0:
        nl = buf.find(b"\n")
        buf = buf[nl + 1:] if nl >= 0 else b""
    text = buf.decode("utf-8", "replace")
    rows = [r for r in csv.reader(_io.StringIO(text), delimiter=sep) if r]
    if skip_header and start == 0 and rows:
        rows = rows[1:]
    ncol = max((len(r) for r in rows), default=0)
    cols = []
    for j in range(ncol):
        num = np.empty(len(rows), np.float64)
        smap = {}
        for i, r in enumerate(rows):
            t = r[j].strip() if j < len(r) else ""
            if t in NA_TOKENS:
                num[i] = np.nan
            else:
                try:
                    num[i] = float(t)
                except ValueError:
                    num[i] = np.nan
                    smap[i] = t
        cols.append((num, smap))
    return cols


def _tokenize_range(path, sep, skip_header, start, end):
    from h2o3_tpu.io import fastcsv
    if fastcsv.available():
        return fastcsv.parse_columns(path, sep, skip_header,
                                     start=start, end=end)
    return _tokenize_range_py(path, sep, skip_header, start, end)


# ---------------------------------------------------------------------------
def _chunk_tokens(num: np.ndarray, smap: dict) -> np.ndarray:
    """Reconstruct the token strings of a categorical/string chunk column
    (numeric-looking tokens came through as doubles)."""
    toks = np.empty(len(num), object)
    nn = ~np.isnan(num)
    # shortest round-trip reconstruction — '%g' truncated long numeric IDs
    toks[nn] = [_num_token(v) for v in num[nn]]
    for i, s in smap.items():
        toks[i] = s
    return toks


def parse_files(paths, setup: Optional[ParseSetup] = None,
                destination_frame: Optional[str] = None,
                col_types: Optional[dict] = None,
                chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                workers: Optional[int] = None) -> Frame:
    """Phase B+C: byte-range-parallel multi-file parse to one Frame."""
    paths = expand_paths(paths)
    setup = setup or parse_setup(paths[0])
    if setup.parse_type != "CSV" or any(
            p.endswith((".gz", ".zip")) for p in paths):
        # non-CSV / compressed: fall back to sequential per-file parse + rbind
        from h2o3_tpu.io.parser import parse as _parse1
        frames = [_parse1(p, None if i else setup, None, col_types)
                  for i, p in enumerate(paths)]
        return _rbind_frames(frames, destination_frame)

    plan = plan_chunks(paths, chunk_bytes)
    workers = workers or min(32, (os.cpu_count() or 1), len(plan))
    if workers > 1:
        with ThreadPoolExecutor(workers) as ex:
            chunks = list(ex.map(
                lambda c: _tokenize_range(c[0], setup.separator,
                                          setup.header and c[3],
                                          c[1], c[2]), plan))
    else:
        chunks = [_tokenize_range(c[0], setup.separator,
                                  setup.header and c[3], c[1], c[2])
                  for c in plan]

    ncol = max((len(c) for c in chunks), default=0)
    names = list(setup.column_names)
    types = list(setup.column_types)
    while len(names) < ncol:
        names.append(f"C{len(names) + 1}")
        types.append(T_CAT)
    if col_types:
        for k, v in col_types.items():
            if k in names:
                types[names.index(k)] = v

    rows_per = [len(c[0][0]) if c else 0 for c in chunks]
    n = int(sum(rows_per))
    offs = np.concatenate([[0], np.cumsum(rows_per)]).astype(np.int64)

    vecs = []
    for j in range(ncol):
        parts = [c[j] if j < len(c) else
                 (np.full(r, np.nan), {}) for c, r in zip(chunks, rows_per)]
        t = types[j]
        if t == T_NUM:
            vecs.append(Vec.from_numpy(
                np.concatenate([p[0] for p in parts]) if parts
                else np.empty(0), type=T_NUM))
        elif t == T_TIME:
            num = np.concatenate([p[0] for p in parts])
            for k, (pnum, smap) in enumerate(parts):
                for i, s in smap.items():
                    try:
                        num[offs[k] + i] = _parse_time_ms(s)
                    except ValueError:
                        num[offs[k] + i] = np.nan
            vecs.append(Vec.from_numpy(num, type=T_TIME))
        elif t == T_STR:
            toks = np.concatenate(
                [_chunk_tokens(*p) for p in parts]) if parts else \
                np.empty(0, object)
            vecs.append(Vec.from_numpy(toks, type=T_STR))
        elif t == T_UUID:
            from h2o3_tpu.core.frame import UuidVec
            toks = np.concatenate(
                [_chunk_tokens(*p) for p in parts]) if parts else \
                np.empty(0, object)
            vecs.append(UuidVec.encode(toks))
        else:
            vecs.append(_merge_categorical(parts, n, offs))
    return Frame(names[:ncol], vecs, destination_frame)


def _merge_categorical(parts, n: int, offs: np.ndarray) -> Vec:
    """Phase C cat merge (EnumUpdateTask): union per-chunk levels into one
    sorted global domain, renumber each chunk's codes against it."""
    locals_ = [_chunk_tokens(*p) for p in parts]
    levels = set()
    for toks in locals_:
        levels.update(str(t) for t in toks if t is not None)
    domain = np.asarray(sorted(levels), dtype=object)
    lookup = {s: i for i, s in enumerate(domain)}
    codes = np.empty(n, np.float64)
    mask = np.zeros(n, bool)
    for k, toks in enumerate(locals_):
        o = int(offs[k])
        for i, t in enumerate(toks):
            if t is None:
                codes[o + i] = 0.0
                mask[o + i] = True
            else:
                codes[o + i] = lookup[str(t)]
    return Vec._from_floats(codes, mask, T_CAT, domain)


def _rbind_frames(frames, dest) -> Frame:
    """Row-bind parsed file frames with the same categorical domain merge
    as the chunked path (rapids `rbind` prim semantics)."""
    if len(frames) == 1:
        f = frames[0]
        return Frame(f.names, f.vecs, dest) if dest else f
    base = frames[0]
    vecs = []
    for j in range(base.ncols):
        vts = [f.vecs[j] for f in frames]
        if vts[0].type == T_STR:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.host_data for v in vts]), type=T_STR))
        elif vts[0].type == T_CAT:
            dom = sorted({lv for v in vts for lv in (v.levels() or [])})
            lut = {lv: i for i, lv in enumerate(dom)}
            cols = []
            for v in vts:
                c_np = v.to_numpy()
                vdom = v.levels() or []
                cols.append(np.array(
                    [np.nan if np.isnan(x) else lut[vdom[int(x)]]
                     for x in c_np], np.float64))
            merged = np.concatenate(cols)
            mask = np.isnan(merged)
            vecs.append(Vec._from_floats(
                np.where(mask, 0.0, merged), mask, T_CAT,
                np.asarray(dom, dtype=object)))
        else:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.to_numpy() for v in vts]),
                type=vts[0].type))
    return Frame(list(base.names), vecs, dest)


def import_files(paths, destination_frame: Optional[str] = None,
                 col_types: Optional[dict] = None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 workers: Optional[int] = None) -> Frame:
    """h2o.import_file(path=folder/pattern/list) analog on the distributed
    parse path."""
    return parse_files(paths, None, destination_frame, col_types,
                       chunk_bytes, workers)
