"""Data ingest: 2-phase parse (setup guess → typed columnar load → HBM).

Reference: water/parser/ParseDataset.java:31,127 — phase 1 `ParseSetup.guessSetup`
sniffs separator/header/types on a sample; phase 2 `MultiFileParseTask` is an
MRTask over file chunks whose per-chunk parsers emit NewChunks, with
categorical levels merged cluster-wide then renumbered
(ParseDataset.java:356-440). Formats: CSV (CsvParser.java), ARFF
(ARFFParser.java), SVMLight (SVMLightParser.java), gzip/zip (ZipUtil.java).

TPU-native design: parsing is host work; the device is only involved at the
end (`device_put` of packed columns with a row sharding). Phase 2 here
tokenizes with a C-backed fast path when the native extension is built
(native/fastcsv.cpp), falling back to Python's csv module; column typing and
categorical renumbering happen once on the controller — there is no
cluster-wide level merge because there is one parse process.
"""

from __future__ import annotations

import gzip
import io
import math
import os
import zipfile
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from h2o3_tpu.core.frame import (Frame, T_CAT, T_NUM, T_STR, T_TIME,
                                 T_UUID, UuidVec, Vec)
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs.timeline import span as _span

# source bytes ingested, labeled by parse type (CSV/ARFF/SVMLight) — the
# /metrics view of ingest volume; the python-vs-native engine split lives
# in h2o3_fastcsv_bytes_total and the parse.tokenize span's engine attr
PARSE_BYTES = _om.counter("h2o3_parse_bytes_total",
                          "source bytes ingested by the 2-phase parser")
PARSE_ROWS = _om.counter("h2o3_parse_rows_total",
                         "rows materialized into Frames by the parser")

NA_TOKENS = {"", "NA", "N/A", "na", "NaN", "nan", "null", "NULL", "None", "?"}
_SEPARATORS = [",", "\t", ";", "|", " "]


def pack_span(**attrs):
    """The `parse.pack` stage span — one literal declaration site shared
    by the single-file path here and the chunked merge (io/dparse)."""
    return _span("parse.pack", **attrs)


# ---------------------------------------------------------------------------
@dataclass
class ParseSetup:
    """Result of phase-1 guessing (water/parser/ParseSetup.java)."""
    separator: str = ","
    header: bool = True
    column_names: list = field(default_factory=list)
    column_types: list = field(default_factory=list)  # "num"|"enum"|"str"|"time"
    parse_type: str = "CSV"  # CSV | ARFF | SVMLight
    na_strings: set = field(default_factory=lambda: set(NA_TOKENS))


def _open_text(path: str) -> io.TextIOBase:
    """Transparent gzip/zip handling (water/parser/ZipUtil.java)."""
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8", newline="")
    if path.endswith(".zip"):
        zf = zipfile.ZipFile(path)
        inner = zf.namelist()[0]
        return io.TextIOWrapper(zf.open(inner), encoding="utf-8", newline="")
    return open(path, "r", encoding="utf-8", newline="")


def _num_token(v: float) -> str:
    """Reconstruct the source token of a numeric-looking cat/str value.
    Shortest round-trip formatting: integral doubles print without a
    trailing '.0' (matching tokens like '1234567' or zip+4 codes) and
    distinct doubles never collide — unlike '%g', whose 6-sig-digit
    truncation folded '1234567' and '1234567.4' into one level."""
    v = float(v)
    if math.isfinite(v) and v == int(v) and abs(v) < 2 ** 53 \
            and not (v == 0.0 and math.copysign(1.0, v) < 0):
        return str(int(v))
    return repr(v)


def _is_num(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def parse_setup(path: str, sample_lines: int = 200) -> ParseSetup:
    """Phase 1: sniff separator, header, and column types from a sample."""
    with _span("parse.setup", file=os.path.basename(path)), \
            _open_text(path) as f:
        sample = [line.rstrip("\r\n") for _, line in zip(range(sample_lines), f)]
    sample = [l for l in sample if l]
    if not sample:
        raise ValueError(f"empty file: {path}")
    if sample[0].lstrip().startswith("@relation") or path.lower().endswith(".arff"):
        return _arff_setup(path)
    if path.lower().endswith(".svm") or path.lower().endswith(".svmlight"):
        return ParseSetup(parse_type="SVMLight")
    # separator: the one yielding a consistent, maximal column count
    best_sep, best_cols = ",", 1
    for sep in _SEPARATORS:
        counts = {len(_split(l, sep)) for l in sample[:50]}
        if len(counts) == 1:
            (c,) = counts
            if c > best_cols:
                best_sep, best_cols = sep, c
    sep = best_sep
    rows = [_split(l, sep) for l in sample]
    ncol = max(len(r) for r in rows)
    # header: first row all non-numeric, and some later row has a numeric
    first_nonnum = all(not _is_num(t) for t in rows[0] if t not in NA_TOKENS)
    later_num = any(_is_num(t) for r in rows[1:] for t in r)
    header = first_nonnum and later_num and len(rows) > 1
    names = ([t.strip('"') for t in rows[0]] if header
             else [f"C{i+1}" for i in range(ncol)])
    body = rows[1:] if header else rows
    types = _guess_types(body, ncol)
    return ParseSetup(separator=sep, header=header, column_names=names,
                      column_types=types)


def _split(line: str, sep: str) -> list:
    """Quote-aware split (CsvParser handles embedded separators in quotes)."""
    if '"' not in line:
        return line.split(sep)
    out, cur, q = [], [], False
    for ch in line:
        if ch == '"':
            q = not q
        elif ch == sep and not q:
            out.append("".join(cur)); cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _guess_types(rows: Sequence[Sequence[str]], ncol: int) -> list:
    types = []
    for j in range(ncol):
        col = [r[j].strip() for r in rows if j < len(r)]
        vals = [t for t in col if t not in NA_TOKENS]
        if not vals:
            types.append(T_NUM)
        elif all(_is_num(t) for t in vals):
            types.append(T_NUM)
        elif all(_looks_time(t) for t in vals[:20]) and vals:
            types.append(T_TIME)
        elif all(_looks_uuid(t) for t in vals[:20]) and vals:
            types.append(T_UUID)
        else:
            types.append(T_CAT)
    return types


_UUID_RE = None


def _looks_uuid(tok: str) -> bool:
    """ParseTime.attemptUUIDParse analog: 8-4-4-4-12 hex groups."""
    global _UUID_RE
    if _UUID_RE is None:
        import re as _re
        _UUID_RE = _re.compile(
            r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
            r"[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$")
    return bool(_UUID_RE.match(tok.strip()))


def _looks_time(tok: str) -> bool:
    if len(tok) < 8 or not tok[:4].isdigit():
        return False
    return ("-" in tok or "/" in tok) and any(c.isdigit() for c in tok)


# ---------------------------------------------------------------------------
def parse(path: str, setup: Optional[ParseSetup] = None,
          destination_frame: Optional[str] = None,
          col_types: Optional[dict] = None) -> Frame:
    """Phase 2: full tokenize → typed columns → packed sharded Vecs."""
    setup = setup or parse_setup(path)
    with _span("parse.file", file=os.path.basename(path),
               parse_type=setup.parse_type):
        f = _parse_dispatch(path, setup, destination_frame, col_types)
    try:
        PARSE_BYTES.inc(os.path.getsize(path), type=setup.parse_type)
    except OSError:
        pass
    PARSE_ROWS.inc(f.nrows)
    return f


def _parse_dispatch(path, setup, destination_frame, col_types) -> Frame:
    if setup.parse_type == "ARFF":
        return _parse_arff(path, setup, destination_frame)
    if setup.parse_type == "SVMLight":
        return _parse_svmlight(path, destination_frame)
    native = _native_parse(path, setup, destination_frame, col_types)
    if native is not None:
        return native
    # h2o3-ok: R011 same tokenize stage as io/fastcsv.py — two engines, engine= attr disambiguates
    with _span("parse.tokenize", engine="python_csv"):
        cols = _tokenize_csv(path, setup)
    names = list(setup.column_names)
    types = list(setup.column_types)
    # pad short rows / extend names if data is wider than the sample suggested
    while len(names) < len(cols):
        names.append(f"C{len(names)+1}")
        types.append(T_CAT)
    if col_types:
        for k, v in col_types.items():
            if k in names:
                types[names.index(k)] = v
    with pack_span(cols=len(cols)):
        vecs = [_column_to_vec(cols[j], types[j]) for j in range(len(cols))]
        return Frame(names[: len(vecs)], vecs, destination_frame)


def _tokenize_csv(path: str, setup: ParseSetup) -> list:
    """Return list of per-column python lists of token strings."""
    import csv
    cols: list[list] = []
    with _open_text(path) as f:
        rdr = csv.reader(f, delimiter=setup.separator)
        it = iter(rdr)
        if setup.header:
            next(it, None)
        for row in it:
            if not row:
                continue
            if len(cols) < len(row):
                depth = len(cols[0]) if cols else 0
                for _ in range(len(row) - len(cols)):
                    cols.append([""] * depth)
            for j in range(len(cols)):
                cols[j].append(row[j].strip() if j < len(row) else "")
    return cols


def _native_parse(path: str, setup: ParseSetup, dest, col_types):
    """C++ fast path (native/fastcsv.cpp): numeric columns arrive as doubles,
    categorical/string columns are rebuilt from the native string table."""
    if path.endswith((".gz", ".zip")):
        return None  # native path reads raw files; compressed → python path
    try:
        from h2o3_tpu.io import fastcsv
        if not fastcsv.available():
            return None
        cols = fastcsv.parse_columns(path, setup.separator, setup.header)
    except Exception:
        return None
    names = list(setup.column_names)
    types = list(setup.column_types)
    while len(names) < len(cols):
        names.append(f"C{len(names)+1}")
        types.append(T_CAT)
    if col_types:
        for k, v in col_types.items():
            if k in names:
                types[names.index(k)] = v
    vecs = []
    for j, (num, smap) in enumerate(cols):
        t = types[j] if j < len(types) else T_CAT
        if t == T_NUM:
            vecs.append(Vec.from_numpy(num, type=T_NUM))
        elif t == T_TIME:
            out = num.copy()
            for i, s in smap.items():
                try:
                    out[i] = _parse_time_ms(s)
                except ValueError:
                    out[i] = np.nan
            vecs.append(Vec.from_numpy(out, type=T_TIME))
        else:  # enum / str / uuid: reconstruct token strings
            # vectorized: _num_token over UNIQUE numeric values only,
            # object gathers for the rest (io/dparse._chunk_tokens)
            from h2o3_tpu.io.dparse import _chunk_tokens
            toks = _chunk_tokens(num, smap)
            if t == T_UUID:
                vecs.append(UuidVec.encode(toks))
            else:
                vecs.append(Vec.from_numpy(toks,
                                           type=T_STR if t == T_STR else None))
    return Frame(names[: len(vecs)], vecs, dest)


def _column_to_vec(tokens: list, vtype: str) -> Vec:
    n = len(tokens)
    if vtype == T_NUM or vtype == T_TIME:
        out = np.empty(n, np.float64)
        for i, t in enumerate(tokens):
            if t in NA_TOKENS:
                out[i] = np.nan
            else:
                try:
                    out[i] = float(t) if vtype == T_NUM else _parse_time_ms(t)
                except ValueError:
                    out[i] = np.nan
        return Vec.from_numpy(out, type=vtype)
    if vtype == T_STR:
        arr = np.array([None if t in NA_TOKENS else t for t in tokens], object)
        return Vec.from_numpy(arr, type=T_STR)
    if vtype == T_UUID:
        arr = np.array([None if t in NA_TOKENS or not _looks_uuid(t)
                        else t for t in tokens], object)
        return UuidVec.encode(arr)
    # enum; promote to str if nearly-unique (CsvParser enum→string promotion)
    arr = np.array([None if t in NA_TOKENS else t for t in tokens], object)
    uniq = {t for t in tokens if t not in NA_TOKENS}
    if n > 100 and len(uniq) > 0.95 * n:
        return Vec.from_numpy(arr, type=T_STR)
    return Vec.from_numpy(arr)


def _parse_time_ms(tok: str) -> float:
    from datetime import datetime
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%Y/%m/%d", "%m/%d/%Y",
                "%Y-%m-%dT%H:%M:%S"):
        try:
            return datetime.strptime(tok, fmt).timestamp() * 1000.0
        except ValueError:
            continue
    raise ValueError(tok)


# ---------------------------------------------------------------------------
# ARFF (water/parser/ARFFParser.java)
def _arff_setup(path: str) -> ParseSetup:
    names, types = [], []
    with _open_text(path) as f:
        for line in f:
            l = line.strip()
            if l.lower().startswith("@attribute"):
                parts = l.split(None, 2)
                names.append(parts[1].strip("'\""))
                t = parts[2].strip()
                if t.startswith("{"):
                    types.append(T_CAT)
                elif t.lower() in ("numeric", "real", "integer"):
                    types.append(T_NUM)
                elif t.lower() == "date":
                    types.append(T_TIME)
                else:
                    types.append(T_STR)
            elif l.lower().startswith("@data"):
                break
    return ParseSetup(separator=",", header=False, column_names=names,
                      column_types=types, parse_type="ARFF")


def _parse_arff(path: str, setup: ParseSetup, dest) -> Frame:
    rows = []
    with _open_text(path) as f:
        in_data = False
        for line in f:
            l = line.strip()
            if not in_data:
                if l.lower().startswith("@data"):
                    in_data = True
                continue
            if l and not l.startswith("%"):
                rows.append(_split(l, ","))
    ncol = len(setup.column_names)
    cols = [[r[j].strip() if j < len(r) else "" for r in rows] for j in range(ncol)]
    vecs = [_column_to_vec(cols[j], setup.column_types[j]) for j in range(ncol)]
    return Frame(setup.column_names, vecs, dest)


# ---------------------------------------------------------------------------
# SVMLight (water/parser/SVMLightParser.java) — densified on load
def _parse_svmlight(path: str, dest) -> Frame:
    """SVMLight ingest WITHOUT densifying (SVMLightParser.java →
    CXIChunk sparse chunks): feature columns land as SparseVecs holding
    only their nonzero (row, value) pairs; a 1M x 10k 0.1%-dense file
    stays ~nnz-sized in HBM instead of n*C."""
    from h2o3_tpu.core.frame import SparseVec
    targets = []
    ri, ci, vv = [], [], []
    max_idx = 0
    with _open_text(path) as f:
        for line in f:
            l = line.split("#")[0].strip()
            if not l:
                continue
            parts = l.split()
            i = len(targets)
            targets.append(float(parts[0]))
            for kv in parts[1:]:
                k, v = kv.split(":")
                k = int(k)
                ri.append(i)
                ci.append(k)
                vv.append(float(v))
                max_idx = max(max_idx, k)
    n = len(targets)
    ri = np.asarray(ri, np.int64)
    ci = np.asarray(ci, np.int64)
    vv = np.asarray(vv, np.float32)
    order = np.lexsort((ri, ci))          # group by column, rows sorted
    ri, ci, vv = ri[order], ci[order], vv[order]
    starts = np.searchsorted(ci, np.arange(max_idx + 2))
    names = ["target"] + [f"C{j+1}" for j in range(max_idx + 1)]
    vecs = [Vec.from_numpy(np.asarray(targets))]
    for j in range(max_idx + 1):
        s, e = starts[j], starts[j + 1]
        vecs.append(SparseVec(ri[s:e].astype(np.int32), vv[s:e], n))
    return Frame(names, vecs, dest)


# ---------------------------------------------------------------------------
def import_file(path: str, destination_frame: Optional[str] = None,
                col_types: Optional[dict] = None,
                header: Optional[bool] = None,
                sep: Optional[str] = None) -> Frame:
    """h2o.import_file analog: setup-guess then parse in one call.
    Columnar formats (parquet/ORC/feather/avro) dispatch to the Arrow-backed
    providers (io/columnar.py); text formats go through ParseSetup.
    Directories, glob patterns and path lists route to the distributed
    2-phase parse (io/dparse.py — MultiFileParseTask analog)."""
    from h2o3_tpu.io import uri as _uri
    if isinstance(path, (list, tuple)) or (
            isinstance(path, str) and not _uri.is_remote(path)
            and (os.path.isdir(path) or any(c in path for c in "*?["))):
        from h2o3_tpu.io import dparse
        setup = None
        if header is not None or sep is not None:
            first = dparse.expand_paths(path)[0]
            setup = parse_setup(first)
            if header is not None:
                setup.header = header
            if sep is not None:
                setup.separator = sep
        return dparse.parse_files(path, setup, destination_frame,
                                  col_types)
    staged = None
    if _uri.is_remote(path):
        # range-capable remote CSV sources ride the chunked plan — the
        # same byte-range pipeline as local files, no whole-file staging
        # (PersistEagerHTTP upgraded to ranged reads); columnar formats
        # and range-less servers fall back to the eager fetch below
        if header is None and sep is None \
                and _uri.supports_ranges(path) and not path.endswith(
                    (".parquet", ".orc", ".feather", ".avro", ".xlsx")):
            from h2o3_tpu.io import dparse
            try:
                return dparse.parse_files([path], None,
                                          destination_frame, col_types)
            except (OSError, NotImplementedError):
                # staging fallback ONLY for transport failures (the
                # server lied about ranges, fsspec backend missing) —
                # real parse bugs must surface, not silently re-download
                pass
        path = staged = _uri.fetch_to_local(path)
    try:
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        from h2o3_tpu.io import columnar
        colparser = columnar.sniff(path)
        if colparser is not None:
            return colparser(path, destination_frame)
        setup = parse_setup(path)
        if header is not None:
            setup.header = header
        if sep is not None:
            setup.separator = sep
        if path.endswith((".gz", ".zip")) and setup.parse_type == "CSV":
            # compressed CSV: one streaming inflate pass feeding the
            # chunked native pipeline (io/dparse) instead of the
            # sequential per-line python tokenizer
            from h2o3_tpu.io import dparse
            return dparse.parse_files([path], setup, destination_frame,
                                      col_types)
        return parse(path, setup, destination_frame, col_types)
    finally:
        if staged is not None:
            try:
                os.unlink(staged)
            except OSError:
                pass


def upload_frame(data, destination_frame: Optional[str] = None) -> Frame:
    """h2o.H2OFrame(python_obj) analog: ingest in-memory host data."""
    if isinstance(data, Frame):
        return data
    if isinstance(data, dict):
        return Frame.from_dict(data, destination_frame)
    if isinstance(data, np.ndarray):
        return Frame.from_numpy(data, key=destination_frame)
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return Frame.from_pandas(data, destination_frame)
    except ImportError:
        pass
    raise TypeError(f"cannot ingest {type(data)}")
