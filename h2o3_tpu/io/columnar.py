"""Columnar-format ingest: Parquet / ORC / Feather (Arrow-backed), Avro gated.

Reference: the pluggable parser SPI (water/parser/ParserService.java) with the
plugin parsers h2o-parsers/h2o-{parquet,orc,avro}-parser/ (Java parquet-mr /
Hive ORC / Avro readers emitting NewChunks). SURVEY.md §2.4 maps these to
"Arrow/parquet via C++-backed readers feeding host→HBM transfer" — pyarrow IS
that C++ reader (Arrow C++ under the hood); columns land as numpy and are
device_put row-sharded by the Frame store.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from h2o3_tpu.core.frame import Frame


def available_formats():
    out = {"parquet": False, "orc": False, "feather": False, "avro": False}
    try:
        import pyarrow  # noqa: F401
        out["parquet"] = True
        out["feather"] = True
        try:
            from pyarrow import orc  # noqa: F401
            out["orc"] = True
        except ImportError:
            pass
    except ImportError:
        pass
    try:
        import fastavro  # noqa: F401
        out["avro"] = True
    except ImportError:
        pass
    return out


def _table_to_frame(table, key: Optional[str]) -> Frame:
    """Arrow table → Frame columns. Dictionary/string → categorical,
    numeric → float64 + NA mask, bool → 0/1, timestamps → epoch ms."""
    import pyarrow as pa
    cols = {}
    for name in table.column_names:
        arr = table.column(name)
        t = arr.type
        if pa.types.is_dictionary(t):
            arr = arr.cast(pa.string())
            t = arr.type
        if pa.types.is_timestamp(t) or pa.types.is_date(t):
            ms = arr.cast(pa.timestamp("ms")).cast(pa.int64())
            np_col = ms.to_numpy(zero_copy_only=False).astype(np.float64)
            null = np.asarray(arr.is_null())
            np_col[null] = np.nan
            cols[name] = np_col
        elif pa.types.is_boolean(t) or pa.types.is_integer(t) or \
                pa.types.is_floating(t) or pa.types.is_decimal(t):
            np_col = arr.cast(pa.float64()).to_numpy(zero_copy_only=False)
            cols[name] = np.asarray(np_col, np.float64)
        else:  # strings and everything else → object (→ categorical Vec)
            py = arr.to_pylist()
            cols[name] = np.array([None if v is None else str(v) for v in py],
                                  object)
    return Frame.from_dict(cols, key)


def parse_parquet(path: str, key: Optional[str] = None) -> Frame:
    try:
        import pyarrow.parquet as pq
    except ImportError as e:
        raise RuntimeError("parquet ingest requires pyarrow (not available "
                           "in this image build)") from e
    return _table_to_frame(pq.read_table(path), key)


def parse_orc(path: str, key: Optional[str] = None) -> Frame:
    try:
        from pyarrow import orc
    except ImportError as e:
        raise RuntimeError("ORC ingest requires pyarrow.orc") from e
    return _table_to_frame(orc.ORCFile(path).read(), key)


def parse_feather(path: str, key: Optional[str] = None) -> Frame:
    try:
        import pyarrow.feather as feather
    except ImportError as e:
        raise RuntimeError("feather ingest requires pyarrow") from e
    return _table_to_frame(feather.read_table(path), key)


def parse_avro(path: str, key: Optional[str] = None) -> Frame:
    try:
        import fastavro
    except ImportError as e:
        raise RuntimeError(
            "Avro ingest requires fastavro, which is not in this image; "
            "convert to parquet/csv or install fastavro") from e
    with open(path, "rb") as fh:
        records = list(fastavro.reader(fh))
    cols: dict = {}
    for r in records:
        for k, v in r.items():
            cols.setdefault(k, []).append(v)
    np_cols = {}
    for k, vs in cols.items():
        if all(v is None or isinstance(v, (int, float, bool)) for v in vs):
            np_cols[k] = np.array([np.nan if v is None else float(v)
                                   for v in vs], np.float64)
        else:
            np_cols[k] = np.array([None if v is None else str(v)
                                   for v in vs], object)
    return Frame.from_dict(np_cols, key)


def _parse_xlsx(path, destination_frame=None):
    from h2o3_tpu.io.xlsx import parse_xlsx
    return parse_xlsx(path, destination_frame)


def _reject_xls(path, destination_frame=None):
    from h2o3_tpu.io.xlsx import reject_legacy_xls
    return reject_legacy_xls(path, destination_frame)


_EXT = {".parquet": parse_parquet, ".pqt": parse_parquet,
        ".orc": parse_orc, ".feather": parse_feather, ".avro": parse_avro,
        ".xlsx": _parse_xlsx, ".xls": _reject_xls}

_MAGIC = [(b"PAR1", parse_parquet), (b"ORC", parse_orc),
          (b"Obj\x01", parse_avro), (b"ARROW1", parse_feather)]


def sniff(path: str):
    """Return the columnar parser for this file, or None (→ text parsers).
    Extension first, then magic bytes (ParserService provider ranking)."""
    import os
    ext = os.path.splitext(path)[1].lower()
    if ext in _EXT:
        return _EXT[ext]
    try:
        with open(path, "rb") as fh:
            head = fh.read(8)
        for magic, fn in _MAGIC:
            if head.startswith(magic):
                return fn
    except OSError:
        pass
    return None
