"""ctypes bridge to the native CSV parser (native/fastcsv.cpp).

The reference's ingest hot loop is a JVM per-byte tokenizer
(water/parser/CsvParser.java); here it's a C++ pass exporting column-major
doubles + a string side table over a C ABI (no pybind11 in the image).
Build: `make -C native` (or scripts/build_native.sh); the Python parser falls
back to the csv module when the library is absent.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_str
from h2o3_tpu.obs.timeline import span as _span

# bytes handed to the native tokenizer (per byte-range call — the sum over
# ranges equals the file bytes, so this tracks true tokenizer throughput)
FASTCSV_BYTES = _om.counter("h2o3_fastcsv_bytes_total",
                            "bytes tokenized by the native CSV parser")

_LIB = None


def native_dir() -> str:
    """Directory holding the native .so builds (H2O3_NATIVE_DIR override;
    default <repo>/native). Declaration site for the variable — the
    TreeSHAP loader (models/tree/contrib) imports this helper."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return env_str("H2O3_NATIVE_DIR", "") or os.path.join(here, "native")


def _lib():
    global _LIB
    if _LIB is None:
        path = os.path.join(native_dir(), "libfastcsv.so")
        lib = ctypes.CDLL(path)
        lib.fastcsv_parse.restype = ctypes.c_void_p
        lib.fastcsv_parse.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                      ctypes.c_int]
        lib.fastcsv_parse_range.restype = ctypes.c_void_p
        lib.fastcsv_parse_range.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                            ctypes.c_long, ctypes.c_long,
                                            ctypes.c_int]
        lib.fastcsv_nrows.restype = ctypes.c_int64
        lib.fastcsv_nrows.argtypes = [ctypes.c_void_p]
        lib.fastcsv_ncols.restype = ctypes.c_int64
        lib.fastcsv_ncols.argtypes = [ctypes.c_void_p]
        lib.fastcsv_col_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.fastcsv_col_data.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastcsv_col_nstr.restype = ctypes.c_int64
        lib.fastcsv_col_nstr.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastcsv_col_na.restype = ctypes.c_int64
        lib.fastcsv_col_na.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastcsv_str_row.restype = ctypes.c_int64
        lib.fastcsv_str_row.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.fastcsv_str_val.restype = ctypes.c_char_p
        lib.fastcsv_str_val.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.fastcsv_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


def available() -> bool:
    try:
        _lib()
        return True
    except OSError:
        return False


def parse_columns(path: str, sep: str, header: bool,
                  start: int = 0, end: int = -1):
    """Returns list of (numeric ndarray, {row: str}) per column, for the
    byte range [start, end) (chunk-boundary semantics: a range at
    start > 0 begins after the first newline and runs through the line
    straddling `end` — the MultiFileParseTask chunk contract). The
    ctypes call releases the GIL, so ThreadPoolExecutor over ranges
    tokenizes in true parallel."""
    lib = _lib()
    try:
        span_bytes = (end if end >= 0 else os.path.getsize(path)) - start
    except OSError:
        span_bytes = 0
    with _span("parse.tokenize", engine="fastcsv", start=start, end=end):
        h = lib.fastcsv_parse_range(path.encode(), sep.encode(),
                                    start, end, 1 if header else 0)
    if not h:
        raise IOError(f"fastcsv failed on {path}")
    FASTCSV_BYTES.inc(max(span_bytes, 0))
    try:
        nrows = lib.fastcsv_nrows(h)
        ncols = lib.fastcsv_ncols(h)
        out = []
        for j in range(ncols):
            ptr = lib.fastcsv_col_data(h, j)
            arr = np.ctypeslib.as_array(ptr, shape=(nrows,)).copy()
            nstr = lib.fastcsv_col_nstr(h, j)
            smap = {}
            for i in range(nstr):
                smap[lib.fastcsv_str_row(h, j, i)] = \
                    lib.fastcsv_str_val(h, j, i).decode("utf-8", "replace")
            out.append((arr, smap))
        return out
    finally:
        lib.fastcsv_free(h)
