"""ctypes bridge to the native CSV parser (native/fastcsv.cpp).

The reference's ingest hot loop is a JVM per-byte tokenizer
(water/parser/CsvParser.java); here it's a C++ pass with an in-place
numeric fast path (exact Clinger fast-float + SWAR digit extraction, see
fastcsv.cpp) exporting column-major doubles + a string side table over a
C ABI (no pybind11 in the image). Two entry points feed the distributed
ingest pipeline (io/dparse.py): `parse_columns` for byte ranges of local
files (the native code does its own read, so pool threads overlap read
with tokenize) and `parse_bytes_columns` for caller-staged buffers
(streaming-decompressed gzip/zip windows, HTTP/object-store range reads).
Build: `make -C native` (or scripts/build_native.sh); the Python parser
falls back to the csv module when the library is absent.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_str
from h2o3_tpu.obs.timeline import span as _span

# bytes handed to the native tokenizer (per byte-range call — the sum over
# ranges equals the file bytes, so this tracks true tokenizer throughput)
FASTCSV_BYTES = _om.counter("h2o3_fastcsv_bytes_total",
                            "bytes tokenized by the native CSV parser")

_LIB = None


def native_dir() -> str:
    """Directory holding the native .so builds (H2O3_NATIVE_DIR override;
    default <repo>/native). Declaration site for the variable — the
    TreeSHAP loader (models/tree/contrib) imports this helper."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return env_str("H2O3_NATIVE_DIR", "") or os.path.join(here, "native")


def _lib():
    global _LIB
    if _LIB is None:
        path = os.path.join(native_dir(), "libfastcsv.so")
        lib = ctypes.CDLL(path)
        lib.fastcsv_parse.restype = ctypes.c_void_p
        lib.fastcsv_parse.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                      ctypes.c_int]
        lib.fastcsv_parse_range.restype = ctypes.c_void_p
        lib.fastcsv_parse_range.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                            ctypes.c_long, ctypes.c_long,
                                            ctypes.c_int]
        lib.fastcsv_parse_bytes.restype = ctypes.c_void_p
        lib.fastcsv_parse_bytes.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                            ctypes.c_char, ctypes.c_int,
                                            ctypes.c_int]
        lib.fastcsv_nrows.restype = ctypes.c_int64
        lib.fastcsv_nrows.argtypes = [ctypes.c_void_p]
        lib.fastcsv_ncols.restype = ctypes.c_int64
        lib.fastcsv_ncols.argtypes = [ctypes.c_void_p]
        lib.fastcsv_col_data.restype = ctypes.POINTER(ctypes.c_double)
        lib.fastcsv_col_data.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastcsv_col_nstr.restype = ctypes.c_int64
        lib.fastcsv_col_nstr.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastcsv_col_na.restype = ctypes.c_int64
        lib.fastcsv_col_na.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastcsv_str_row.restype = ctypes.c_int64
        lib.fastcsv_str_row.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.fastcsv_str_val.restype = ctypes.c_char_p
        lib.fastcsv_str_val.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.fastcsv_str_rows_ptr.restype = ctypes.POINTER(ctypes.c_int64)
        lib.fastcsv_str_rows_ptr.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int64]
        lib.fastcsv_str_lens_ptr.restype = ctypes.POINTER(ctypes.c_int32)
        lib.fastcsv_str_lens_ptr.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int64]
        lib.fastcsv_str_bytes_ptr.restype = ctypes.POINTER(ctypes.c_char)
        lib.fastcsv_str_bytes_ptr.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
        lib.fastcsv_str_bytes_len.restype = ctypes.c_int64
        lib.fastcsv_str_bytes_len.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
        lib.fastcsv_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


def available() -> bool:
    try:
        _lib()
        return True
    except OSError:
        return False


def _extract_columns(lib, h):
    """(numeric ndarray, {row: str}) per column from a parse handle.
    The string side table ships through the BULK export (three planes:
    rows / lens / concatenated bytes) — the old per-cell
    fastcsv_str_row/fastcsv_str_val pair cost two ctypes round trips per
    string cell, which dominated categorical-heavy ingest."""
    nrows = lib.fastcsv_nrows(h)
    ncols = lib.fastcsv_ncols(h)
    out = []
    for j in range(ncols):
        ptr = lib.fastcsv_col_data(h, j)
        arr = np.ctypeslib.as_array(ptr, shape=(nrows,)).copy() \
            if nrows else np.empty(0, np.float64)
        nstr = lib.fastcsv_col_nstr(h, j)
        smap = {}
        if nstr:
            rows = np.ctypeslib.as_array(
                lib.fastcsv_str_rows_ptr(h, j), shape=(nstr,))
            lens = np.ctypeslib.as_array(
                lib.fastcsv_str_lens_ptr(h, j), shape=(nstr,))
            blen = lib.fastcsv_str_bytes_len(h, j)
            raw = ctypes.string_at(lib.fastcsv_str_bytes_ptr(h, j), blen)
            offs = np.concatenate([[0], np.cumsum(lens)])
            for i in range(nstr):
                smap[int(rows[i])] = raw[offs[i]:offs[i + 1]].decode(
                    "utf-8", "replace")
        out.append((arr, smap))
    return out


def parse_columns(path: str, sep: str, header: bool,
                  start: int = 0, end: int = -1):
    """Returns list of (numeric ndarray, {row: str}) per column, for the
    byte range [start, end) (chunk-boundary semantics: a range at
    start > 0 begins after the first newline and runs through the line
    straddling `end` — the MultiFileParseTask chunk contract). The
    ctypes call releases the GIL, so ThreadPoolExecutor over ranges
    tokenizes in true parallel."""
    lib = _lib()
    try:
        span_bytes = (end if end >= 0 else os.path.getsize(path)) - start
    except OSError:
        span_bytes = 0
    with _span("parse.tokenize", engine="fastcsv", start=start, end=end):
        h = lib.fastcsv_parse_range(path.encode(), sep.encode(),
                                    start, end, 1 if header else 0)
    if not h:
        raise IOError(f"fastcsv failed on {path}")
    FASTCSV_BYTES.inc(max(span_bytes, 0))
    try:
        return _extract_columns(lib, h)
    finally:
        lib.fastcsv_free(h)


def parse_bytes_columns(buf: bytes, sep: str, header: bool,
                        skip_partial_first: bool = False):
    """Tokenize caller-staged bytes (a streaming-decompressed gzip/zip
    window, an HTTP range read) with the same chunk contract as
    `parse_columns`: `skip_partial_first` applies the start>0 half (the
    head up to the first newline belongs to the previous chunk);
    otherwise the buffer must hold whole lines. Same return shape."""
    lib = _lib()
    # h2o3-ok: R011 same tokenize stage as the range entry above — one engine, two native entry points
    with _span("parse.tokenize", engine="fastcsv_bytes", nbytes=len(buf)):
        h = lib.fastcsv_parse_bytes(buf, len(buf), sep.encode(),
                                    1 if header else 0,
                                    1 if skip_partial_first else 0)
    if not h:
        raise IOError("fastcsv failed on byte buffer")
    FASTCSV_BYTES.inc(len(buf))
    try:
        return _extract_columns(lib, h)
    finally:
        lib.fastcsv_free(h)
