"""Reference-format MOJO interop — read AND write genuine H2O-3 MOJO zips.

Format sources (all verified against the reference implementation):
  * container/model.ini: hex/genmodel/AbstractMojoWriter.java
    (writeModelInfo :150, writelnkv "key = value", [columns], [domains]
    "idx: count dNNN.txt", domains/dNNN.txt one level per line)
  * per-algo info keys: hex/tree/SharedTreeMojoWriter.java:32 (n_trees,
    n_trees_per_class, trees/tCC_TTT.bin blobs),
    hex/tree/gbm/GbmMojoWriter.java:29 (distribution, link_function,
    init_f, mojo_version 1.40)
  * tree byte format: hex/genmodel/algos/tree/SharedTreeMojoModel.java:129
    (scoreTree) — little-endian (ByteBufferWrapper nativeOrder):
      node  := nodeType:u8 colId:u16 [leaf if colId==0xFFFF: f32]
               naSplitDir:u8 (NaSplitDir.java: NAvsREST=1 NALeft=2
               NARight=3 Left=4 Right=5)
               payload (f32 splitVal | inline bitset)
               [leftSize:u8..u32 when left child is internal]
               leftSubtree rightSubtree
      nodeType bits: equal = nodeType & 12 (0 numeric, 8 = 32-bit inline
      bitset "fill2", 12 = offset bitset "fill3" [bitoff:u16 nbits:u32
      bytes]); lmask = nodeType & 51 in {0,1,2,3} = width-1 of leftSize,
      48 = left child is a 4-byte leaf; rmask 48<<2 in bits 0xC0 = right
      child is a leaf. Split semantics: d >= splitVal goes RIGHT; bitset
      contains((int)d) goes RIGHT (LSB-first bits,
      hex/genmodel/utils/GenmodelBitSet.java:contains); NaN (or
      out-of-range category) routes by leftward = naSplitDir in {2,4}.

Our engine's thresholds mean "x <= thr goes left"; the adjacent-float
conversion splitVal = nextafter(thr, +inf) (and back) makes write->score
round trips EXACT, not approximate.
"""

from __future__ import annotations

import math
import struct
import uuid as _uuid
import zipfile
from datetime import datetime, timezone

import numpy as np

NA_VS_REST = 1
NA_LEFT = 2
NA_RIGHT = 3


# ===========================================================================
# Tree serialization (CompressedTree byte layout)
def _write_node(out: bytearray, i: int, col, thr, nal, val, catbits,
                col_is_cat, ncat, nodes: int):
    """Append node i (heap layout) to `out`; returns nothing."""
    c = int(col[i]) if i < nodes else -1
    if c < 0:
        # root-is-leaf: full leaf record (nodeType, colId=0xFFFF, float)
        out += b"\x00\xff\xff"
        out += struct.pack("<f", float(val[i]))
        return
    kid_l, kid_r = 2 * i + 1, 2 * i + 2
    l_leaf = kid_l >= nodes or col[kid_l] < 0
    r_leaf = kid_r >= nodes or col[kid_r] < 0

    is_cat = bool(col_is_cat[c]) if col_is_cat is not None else False
    nb = int(ncat[c]) if (is_cat and ncat is not None) else 0
    use_fill2 = is_cat and nb <= 32

    # left subtree bytes (needed for the size field)
    left = bytearray()
    if l_leaf:
        left += struct.pack("<f", float(val[kid_l]) if kid_l < nodes
                            else float(val[i]))
    else:
        _write_node(left, kid_l, col, thr, nal, val, catbits, col_is_cat,
                    ncat, nodes)
    right = bytearray()
    if r_leaf:
        right += struct.pack("<f", float(val[kid_r]) if kid_r < nodes
                             else float(val[i]))
    else:
        _write_node(right, kid_r, col, thr, nal, val, catbits, col_is_cat,
                    ncat, nodes)

    if l_leaf:
        lmask = 48
        lsize_bytes = b""
    else:
        n = len(left)
        width = 1 if n < (1 << 8) else 2 if n < (1 << 16) else \
            3 if n < (1 << 24) else 4
        lmask = width - 1
        lsize_bytes = int(n).to_bytes(width, "little")
    rmask = 48 if r_leaf else 0
    equal = 0 if not is_cat else (8 if use_fill2 else 12)
    node_type = (lmask | equal | (rmask << 2)) & 0xFF
    out.append(node_type)
    out += struct.pack("<H", c)
    out.append(NA_LEFT if nal[i] else NA_RIGHT)
    if not is_cat:
        # ours: x <= thr left; H2O: x >= splitVal right => splitVal is the
        # adjacent float above thr (exact float round trip)
        sv = np.nextafter(np.float32(thr[i]), np.float32(np.inf))
        out += struct.pack("<f", float(sv))
    else:
        bits = _node_bits(catbits, i, nb)
        if use_fill2:
            out += bits[:4].ljust(4, b"\x00")
        else:
            nbits = nb
            out += struct.pack("<H", 0)           # bitoff
            out += struct.pack("<i", nbits)
            out += bits[: (nbits + 7) // 8].ljust((nbits + 7) // 8, b"\x00")
    out += lsize_bytes
    out += left
    out += right


def _node_bits(catbits, i, nb) -> bytes:
    """LSB-first byte string of the go-RIGHT category set for node i."""
    if catbits is None:
        return b"\x00" * ((nb + 7) // 8)
    words = np.asarray(catbits[i], np.uint32)
    return words.astype("<u4").tobytes()


def tree_to_h2o_bytes(ta, t: int, ncat=None, val_scale: float = 1.0) -> bytes:
    """Serialize tree t of a TreeArrays into the reference byte format.
    val_scale: GBM MOJO leaves store learn-rate-scaled contributions
    (the reference applies learn_rate during tree building); our
    TreeArrays keep raw Newton values and scale at scoring time."""
    out = bytearray()
    col = np.asarray(ta.col[t])
    thr = np.asarray(ta.thr[t], np.float32)
    nal = np.asarray(ta.na_left[t])
    val = np.asarray(ta.value[t], np.float32) * np.float32(val_scale)
    catbits = None if ta.catbits is None else np.asarray(ta.catbits[t])
    cic = None if ta.col_is_cat is None else np.asarray(ta.col_is_cat)
    _write_node(out, 0, col, thr, nal, val, catbits, cic, ncat,
                col.shape[0])
    return bytes(out)


# ===========================================================================
# Tree deserialization -> dense heap arrays
class _TreeParser:
    def __init__(self, b: bytes):
        self.b = b
        self.pos = 0

    def u1(self):
        v = self.b[self.pos]
        self.pos += 1
        return v

    def u2(self):
        v = struct.unpack_from("<H", self.b, self.pos)[0]
        self.pos += 2
        return v

    def i4(self):
        v = struct.unpack_from("<i", self.b, self.pos)[0]
        self.pos += 4
        return v

    def f4(self):
        v = struct.unpack_from("<f", self.b, self.pos)[0]
        self.pos += 4
        return v

    def skip(self, n):
        self.pos += n


def parse_h2o_tree(b: bytes, max_cat: int = 1024):
    """Decode one compressed tree into node dicts keyed by heap index."""
    nodes = {}

    def rec(p: _TreeParser, i: int, depth: int):
        node_type = p.u1()
        col = p.u2()
        if col == 0xFFFF:
            nodes[i] = ("leaf", p.f4())
            return depth
        nasd = p.u1()
        lmask = node_type & 51
        equal = node_type & 12
        rmask = (node_type & 0xC0) >> 2
        na_vs_rest = nasd == NA_VS_REST
        leftward = nasd in (NA_LEFT, 4)
        split_val = None
        bits = None
        bitoff = 0
        if not na_vs_rest:
            if equal == 0:
                split_val = p.f4()
            elif equal == 8:
                bits = p.b[p.pos: p.pos + 4]
                p.skip(4)
            else:
                bitoff = p.u2()
                nbits = p.i4()
                nbytes = (nbits + 7) // 8
                bits = p.b[p.pos: p.pos + nbytes]
                p.skip(nbytes)
        if lmask <= 3:
            p.skip(lmask + 1)        # left subtree size (recomputed)
        nodes[i] = ("split", col, leftward, na_vs_rest, split_val, bits,
                    bitoff)
        # left child
        if lmask == 48:
            nodes[2 * i + 1] = ("leaf", p.f4())
            dl = depth + 1
        else:
            dl = rec(p, 2 * i + 1, depth + 1)
        if rmask == 48:
            nodes[2 * i + 2] = ("leaf", p.f4())
            dr = depth + 1
        else:
            dr = rec(p, 2 * i + 2, depth + 1)
        return max(dl, dr)

    depth = rec(_TreeParser(b), 0, 0)
    return nodes, depth


def trees_to_arrays(tree_nodes, depth, n_features, cat_width=0):
    """Dense heap TreeArrays fields from a list of parsed trees."""
    from h2o3_tpu.models.tree.engine import TreeArrays
    T = len(tree_nodes)
    nnodes = 2 ** (depth + 1) - 1
    col = np.full((T, nnodes), -1, np.int32)
    thr = np.zeros((T, nnodes), np.float32)
    nal = np.zeros((T, nnodes), bool)
    val = np.zeros((T, nnodes), np.float32)
    W = max(1, (cat_width + 31) // 32)
    any_cat = False
    catbits = np.zeros((T, nnodes, W), np.uint32)
    col_is_cat = np.zeros(n_features, bool)
    big = np.float32(3.0e38)
    for t, nodes in enumerate(tree_nodes):
        for i, nd in nodes.items():
            if i >= nnodes:
                raise ValueError("tree deeper than declared depth")
            if nd[0] == "leaf":
                val[t, i] = nd[1]
                continue
            _, c, leftward, na_vs_rest, split_val, bits, bitoff = nd
            col[t, i] = c
            nal[t, i] = leftward
            if na_vs_rest:
                # all non-NA go left; NA routes right via nal=False
                thr[t, i] = big
                nal[t, i] = False
            elif split_val is not None:
                # H2O: x >= splitVal right  =>  our thr = prev float
                thr[t, i] = np.nextafter(np.float32(split_val),
                                         np.float32(-np.inf))
            else:
                any_cat = True
                col_is_cat[c] = True
                arr = np.frombuffer(bits.ljust(W * 4, b"\x00"),
                                    dtype="<u4")[:W].copy()
                if bitoff:
                    # shift the category ids up by bitoff
                    full = np.zeros(W * 32, bool)
                    raw = np.unpackbits(
                        np.frombuffer(bits, np.uint8), bitorder="little")
                    n = min(raw.size, W * 32 - bitoff)
                    full[bitoff: bitoff + n] = raw[:n]
                    arr = np.packbits(full, bitorder="little") \
                        .view("<u4")[:W].copy()
                catbits[t, i] = arr
    # leaf values for pruned interior slots stay 0; fill descendant values
    # of leaves so fixed-depth walks that overshoot stop at the leaf value
    for t, nodes in enumerate(tree_nodes):
        for i, nd in nodes.items():
            if nd[0] == "leaf":
                # propagate down the dense heap so a full-depth walk lands
                # on this value regardless of routing below a leaf
                stack = [i]
                while stack:
                    j = stack.pop()
                    if j != i:
                        val[t, j] = val[t, i]
                        col[t, j] = -1
                    kl, kr = 2 * j + 1, 2 * j + 2
                    if kl < nnodes:
                        stack += [kl, kr]
    return TreeArrays(
        col=col, thr=thr, na_left=nal, value=val, depth=depth,
        catbits=catbits if any_cat else None,
        col_is_cat=col_is_cat if any_cat else None)


# ===========================================================================
# Container: write
def export_h2o_mojo(model, path: str) -> str:
    """Write a reference-layout MOJO zip for a GBM/DRF model
    (hex/tree/SharedTreeMojoWriter.java + AbstractMojoWriter.java)."""
    di = model._dinfo
    algo = model.algo
    assert algo in ("gbm", "drf"), f"h2o-mojo export supports trees, not {algo}"
    multi = getattr(model, "_trees_k", None) is not None
    tlist = model._trees_k if multi else [model._trees]
    ntrees = tlist[0].ntrees
    tpc = len(tlist)

    feats = list(di.predictors)
    resp = di.response_name
    columns = feats + ([resp] if resp else [])
    domains = {}
    for ci, name in enumerate(columns):
        if name in (di.domains or {}):
            domains[ci] = list(di.domains[name])
    if resp and di.response_domain:
        domains[len(columns) - 1] = list(di.response_domain)
    nclasses = (len(di.response_domain) if di.response_domain else 1)

    dist = getattr(model, "_dist", "gaussian")
    link = {"bernoulli": "logit", "quasibinomial": "logit",
            "multinomial": "multinomial", "poisson": "log", "gamma": "log",
            "tweedie": "log"}.get(dist, "identity")
    f0 = model._f0 if not multi else 0.0
    cat_card = np.zeros(len(feats), np.int64)
    for j, name in enumerate(feats):
        if name in (di.cardinalities or {}):
            cat_card[j] = di.cardinalities[name]

    ini = ["[info]"]

    def kv(k, v):
        ini.append(f"{k} = {v}")

    kv("h2o_version", "3.46.0.99999")
    kv("mojo_version", "1.40")
    kv("license", "Apache License Version 2.0")
    kv("algo", algo)
    kv("algorithm", "Gradient Boosting Machine" if algo == "gbm"
        else "Distributed Random Forest")
    kv("endianness", "LITTLE_ENDIAN")
    kv("category", "Regression" if nclasses == 1 else
        ("Binomial" if nclasses == 2 else "Multinomial"))
    kv("uuid", str(_uuid.uuid4().int & ((1 << 63) - 1)))
    kv("supervised", "true")
    kv("n_features", len(feats))
    kv("n_classes", nclasses)
    kv("n_columns", len(columns))
    kv("n_domains", len(domains))
    kv("balance_classes", "false")
    kv("default_threshold", "0.5")
    kv("prior_class_distrib", "null")
    kv("model_class_distrib", "null")
    kv("timestamp", datetime.now(timezone.utc).isoformat())
    kv("n_trees", ntrees)
    kv("n_trees_per_class", tpc)
    kv("distribution", dist)
    kv("link_function", link)
    kv("init_f", float(f0))
    kv("offset_column", "null")

    ini.append("")
    ini.append("[columns]")
    ini += columns
    ini.append("")
    ini.append("[domains]")
    dom_files = []
    for di_idx, (ci, levels) in enumerate(sorted(domains.items())):
        ini.append(f"{ci}: {len(levels)} d{di_idx:03d}.txt")
        dom_files.append((f"domains/d{di_idx:03d}.txt", "\n".join(levels)))

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", "\n".join(ini) + "\n")
        for fn, content in dom_files:
            z.writestr(fn, content + "\n")
        lr = (float(model.params.get("learn_rate") or 1.0)
              if algo == "gbm" else 1.0)
        for cls, ta in enumerate(tlist):
            for t in range(ta.ntrees):
                b = tree_to_h2o_bytes(ta, t, ncat=cat_card, val_scale=lr)
                z.writestr(f"trees/t{cls:02d}_{t:03d}.bin", b)
    return path


# ===========================================================================
# Container: read
def _parse_ini(text: str):
    info, columns, domains = {}, [], {}
    section = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("["):
            section = line
            continue
        if section == "[info]":
            if "=" in line:
                k, v = line.split("=", 1)
                info[k.strip()] = v.strip()
        elif section == "[columns]":
            columns.append(line)
        elif section == "[domains]":
            ci, rest = line.split(":", 1)
            cnt, fname = rest.strip().split(" ", 1)
            domains[int(ci)] = (int(cnt), fname.strip())
    return info, columns, domains


class H2OMojoModel:
    """A reference-format MOJO loaded for scoring (GbmMojoModel /
    DrfMojoModel analog; scores with the TPU batch scorer)."""

    def __init__(self, info, columns, domains, trees_k, f0, dist, algo):
        self.info = info
        self.columns = columns
        self.domains = domains          # col index -> [levels]
        self.trees_k = trees_k          # list (per class) of TreeArrays
        self.f0 = f0
        self.dist = dist
        self.algo = algo
        self.n_features = int(info.get("n_features", len(columns) - 1))
        self.n_classes = int(info.get("n_classes", 1))

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """X (n, n_features) f32 with NaN NAs, categorical as level codes.
        Returns (n,) regression / (n, K) class probabilities."""
        from h2o3_tpu.models.tree import engine as E
        import jax.numpy as jnp
        Xj = jnp.asarray(X, jnp.float32)
        if self.algo == "drf":
            if self.n_classes <= 1:
                s = E.predict_ensemble(Xj, self.trees_k[0])
                return np.asarray(s) / self.trees_k[0].ntrees
            per = [np.asarray(E.predict_ensemble(Xj, ta)) / ta.ntrees
                   for ta in self.trees_k]
            if self.n_classes == 2 and len(per) == 1:
                p1 = 1.0 - per[0]     # DRF stores p(class0) votes
                P = np.stack([1 - p1, p1], 1)
            else:
                P = np.stack(per, 1)
                P = P / np.maximum(P.sum(1, keepdims=True), 1e-30)
            return P
        # GBM margins
        if self.n_classes <= 2:
            F = self.f0 + np.asarray(E.predict_ensemble(Xj, self.trees_k[0]))
            if self.n_classes == 2:
                p1 = 1.0 / (1.0 + np.exp(-F))
                return np.stack([1 - p1, p1], 1)
            if self.dist in ("poisson", "gamma", "tweedie"):
                return np.exp(F)
            return F
        Fs = [np.asarray(E.predict_ensemble(Xj, ta)) for ta in self.trees_k]
        M = np.stack(Fs, 1)
        M -= M.max(1, keepdims=True)
        P = np.exp(M)
        return P / P.sum(1, keepdims=True)


def import_h2o_mojo(path: str) -> H2OMojoModel:
    """Load a genuine H2O-3 MOJO zip (tree algos)."""
    with zipfile.ZipFile(path) as z:
        info, columns, domspec = _parse_ini(
            z.read("model.ini").decode("utf-8", "replace"))
        algo = info.get("algo", "gbm")
        if algo not in ("gbm", "drf"):
            raise NotImplementedError(
                f"reference-MOJO import supports tree models, got {algo}")
        mver = float(info.get("mojo_version", "1.40"))
        if mver < 1.2:
            raise NotImplementedError(
                f"mojo_version {mver} predates the v1.2 tree byte format")
        domains = {}
        for ci, (cnt, fname) in domspec.items():
            levels = z.read(f"domains/{fname}").decode(
                "utf-8", "replace").splitlines()
            domains[ci] = levels[:cnt]
        ntrees = int(info["n_trees"])
        tpc = int(info.get("n_trees_per_class", 1))
        n_features = int(info["n_features"])
        max_card = max([len(v) for v in domains.values()], default=0)
        groups = []
        for cls in range(tpc):
            parsed = []
            maxd = 1
            for t in range(ntrees):
                name = f"trees/t{cls:02d}_{t:03d}.bin"
                nodes, d = parse_h2o_tree(z.read(name))
                parsed.append(nodes)
                maxd = max(maxd, d)
            if maxd > 16:
                raise NotImplementedError(f"tree depth {maxd} > 16")
            groups.append(trees_to_arrays(parsed, maxd, n_features,
                                          cat_width=max(max_card, 32)))
    f0 = float(info.get("init_f", 0.0) if info.get("init_f") not in
               (None, "null") else 0.0)
    return H2OMojoModel(info, columns, domains, groups, f0,
                        info.get("distribution", "gaussian"), algo)
