"""Reference-format MOJO interop — read AND write genuine H2O-3 MOJO zips.

Format sources (all verified against the reference implementation):
  * container/model.ini: hex/genmodel/AbstractMojoWriter.java
    (writeModelInfo :150, writelnkv "key = value", [columns], [domains]
    "idx: count dNNN.txt", domains/dNNN.txt one level per line)
  * per-algo info keys: hex/tree/SharedTreeMojoWriter.java:32 (n_trees,
    n_trees_per_class, trees/tCC_TTT.bin blobs),
    hex/tree/gbm/GbmMojoWriter.java:29 (distribution, link_function,
    init_f, mojo_version 1.40)
  * tree byte format: hex/genmodel/algos/tree/SharedTreeMojoModel.java:129
    (scoreTree) — little-endian (ByteBufferWrapper nativeOrder):
      node  := nodeType:u8 colId:u16 [leaf if colId==0xFFFF: f32]
               naSplitDir:u8 (NaSplitDir.java: NAvsREST=1 NALeft=2
               NARight=3 Left=4 Right=5)
               payload (f32 splitVal | inline bitset)
               [leftSize:u8..u32 when left child is internal]
               leftSubtree rightSubtree
      nodeType bits: equal = nodeType & 12 (0 numeric, 8 = 32-bit inline
      bitset "fill2", 12 = offset bitset "fill3" [bitoff:u16 nbits:u32
      bytes]); lmask = nodeType & 51 in {0,1,2,3} = width-1 of leftSize,
      48 = left child is a 4-byte leaf; rmask 48<<2 in bits 0xC0 = right
      child is a leaf. Split semantics: d >= splitVal goes RIGHT; bitset
      contains((int)d) goes RIGHT (LSB-first bits,
      hex/genmodel/utils/GenmodelBitSet.java:contains); NaN (or
      out-of-range category) routes by leftward = naSplitDir in {2,4}.

Our engine's thresholds mean "x <= thr goes left"; the adjacent-float
conversion splitVal = nextafter(thr, +inf) (and back) makes write->score
round trips EXACT, not approximate.
"""

from __future__ import annotations

import math
import struct
import uuid as _uuid
import zipfile
from datetime import datetime, timezone

import numpy as np

NA_VS_REST = 1
NA_LEFT = 2
NA_RIGHT = 3


# ===========================================================================
# Tree serialization (CompressedTree byte layout)
def _write_node(out: bytearray, i: int, col, thr, nal, val, catbits,
                col_is_cat, ncat, nodes: int):
    """Append node i (heap layout) to `out`; returns nothing."""
    c = int(col[i]) if i < nodes else -1
    if c < 0:
        # root-is-leaf: full leaf record (nodeType, colId=0xFFFF, float)
        out += b"\x00\xff\xff"
        out += struct.pack("<f", float(val[i]))
        return
    kid_l, kid_r = 2 * i + 1, 2 * i + 2
    l_leaf = kid_l >= nodes or col[kid_l] < 0
    r_leaf = kid_r >= nodes or col[kid_r] < 0

    is_cat = bool(col_is_cat[c]) if col_is_cat is not None else False
    nb = int(ncat[c]) if (is_cat and ncat is not None) else 0
    use_fill2 = is_cat and nb <= 32

    # left subtree bytes (needed for the size field)
    left = bytearray()
    if l_leaf:
        left += struct.pack("<f", float(val[kid_l]) if kid_l < nodes
                            else float(val[i]))
    else:
        _write_node(left, kid_l, col, thr, nal, val, catbits, col_is_cat,
                    ncat, nodes)
    right = bytearray()
    if r_leaf:
        right += struct.pack("<f", float(val[kid_r]) if kid_r < nodes
                             else float(val[i]))
    else:
        _write_node(right, kid_r, col, thr, nal, val, catbits, col_is_cat,
                    ncat, nodes)

    if l_leaf:
        lmask = 48
        lsize_bytes = b""
    else:
        n = len(left)
        width = 1 if n < (1 << 8) else 2 if n < (1 << 16) else \
            3 if n < (1 << 24) else 4
        lmask = width - 1
        lsize_bytes = int(n).to_bytes(width, "little")
    rmask = 48 if r_leaf else 0
    equal = 0 if not is_cat else (8 if use_fill2 else 12)
    node_type = (lmask | equal | (rmask << 2)) & 0xFF
    out.append(node_type)
    out += struct.pack("<H", c)
    out.append(NA_LEFT if nal[i] else NA_RIGHT)
    if not is_cat:
        # ours: x <= thr left; H2O: x >= splitVal right => splitVal is the
        # adjacent float above thr (exact float round trip)
        sv = np.nextafter(np.float32(thr[i]), np.float32(np.inf))
        out += struct.pack("<f", float(sv))
    else:
        bits = _node_bits(catbits, i, nb)
        if use_fill2:
            out += bits[:4].ljust(4, b"\x00")
        else:
            nbits = nb
            out += struct.pack("<H", 0)           # bitoff
            out += struct.pack("<i", nbits)
            out += bits[: (nbits + 7) // 8].ljust((nbits + 7) // 8, b"\x00")
    out += lsize_bytes
    out += left
    out += right


def _node_bits(catbits, i, nb) -> bytes:
    """LSB-first byte string of the go-RIGHT category set for node i."""
    if catbits is None:
        return b"\x00" * ((nb + 7) // 8)
    words = np.asarray(catbits[i], np.uint32)
    return words.astype("<u4").tobytes()


def tree_to_h2o_bytes(ta, t: int, ncat=None, val_scale: float = 1.0) -> bytes:
    """Serialize tree t of a TreeArrays into the reference byte format.
    val_scale: GBM MOJO leaves store learn-rate-scaled contributions
    (the reference applies learn_rate during tree building); our
    TreeArrays keep raw Newton values and scale at scoring time."""
    out = bytearray()
    col = np.asarray(ta.col[t])
    thr = np.asarray(ta.thr[t], np.float32)
    nal = np.asarray(ta.na_left[t])
    val = np.asarray(ta.value[t], np.float32) * np.float32(val_scale)
    catbits = None if ta.catbits is None else np.asarray(ta.catbits[t])
    cic = None if ta.col_is_cat is None else np.asarray(ta.col_is_cat)
    _write_node(out, 0, col, thr, nal, val, catbits, cic, ncat,
                col.shape[0])
    return bytes(out)


# ===========================================================================
# Tree deserialization -> dense heap arrays
class _TreeParser:
    def __init__(self, b: bytes):
        self.b = b
        self.pos = 0

    def u1(self):
        v = self.b[self.pos]
        self.pos += 1
        return v

    def u2(self):
        v = struct.unpack_from("<H", self.b, self.pos)[0]
        self.pos += 2
        return v

    def i4(self):
        v = struct.unpack_from("<i", self.b, self.pos)[0]
        self.pos += 4
        return v

    def f4(self):
        v = struct.unpack_from("<f", self.b, self.pos)[0]
        self.pos += 4
        return v

    def skip(self, n):
        self.pos += n


def parse_h2o_tree(b: bytes, max_cat: int = 1024):
    """Decode one compressed tree into node dicts keyed by heap index."""
    nodes = {}

    def rec(p: _TreeParser, i: int, depth: int):
        node_type = p.u1()
        col = p.u2()
        if col == 0xFFFF:
            nodes[i] = ("leaf", p.f4())
            return depth
        nasd = p.u1()
        lmask = node_type & 51
        equal = node_type & 12
        rmask = (node_type & 0xC0) >> 2
        na_vs_rest = nasd == NA_VS_REST
        leftward = nasd in (NA_LEFT, 4)
        split_val = None
        bits = None
        bitoff = 0
        if not na_vs_rest:
            if equal == 0:
                split_val = p.f4()
            elif equal == 8:
                bits = p.b[p.pos: p.pos + 4]
                p.skip(4)
            else:
                bitoff = p.u2()
                nbits = p.i4()
                nbytes = (nbits + 7) // 8
                bits = p.b[p.pos: p.pos + nbytes]
                p.skip(nbytes)
        if lmask <= 3:
            p.skip(lmask + 1)        # left subtree size (recomputed)
        nodes[i] = ("split", col, leftward, na_vs_rest, split_val, bits,
                    bitoff)
        # left child
        if lmask == 48:
            nodes[2 * i + 1] = ("leaf", p.f4())
            dl = depth + 1
        else:
            dl = rec(p, 2 * i + 1, depth + 1)
        if rmask == 48:
            nodes[2 * i + 2] = ("leaf", p.f4())
            dr = depth + 1
        else:
            dr = rec(p, 2 * i + 2, depth + 1)
        return max(dl, dr)

    depth = rec(_TreeParser(b), 0, 0)
    return nodes, depth


def trees_to_arrays(tree_nodes, depth, n_features, cat_width=0):
    """Dense heap TreeArrays fields from a list of parsed trees."""
    from h2o3_tpu.models.tree.engine import TreeArrays
    T = len(tree_nodes)
    nnodes = 2 ** (depth + 1) - 1
    col = np.full((T, nnodes), -1, np.int32)
    thr = np.zeros((T, nnodes), np.float32)
    nal = np.zeros((T, nnodes), bool)
    val = np.zeros((T, nnodes), np.float32)
    W = max(1, (cat_width + 31) // 32)
    any_cat = False
    catbits = np.zeros((T, nnodes, W), np.uint32)
    col_is_cat = np.zeros(n_features, bool)
    big = np.float32(3.0e38)
    for t, nodes in enumerate(tree_nodes):
        for i, nd in nodes.items():
            if i >= nnodes:
                raise ValueError("tree deeper than declared depth")
            if nd[0] == "leaf":
                val[t, i] = nd[1]
                continue
            _, c, leftward, na_vs_rest, split_val, bits, bitoff = nd
            col[t, i] = c
            nal[t, i] = leftward
            if na_vs_rest:
                # all non-NA go left; NA routes right via nal=False
                thr[t, i] = big
                nal[t, i] = False
            elif split_val is not None:
                # H2O: x >= splitVal right  =>  our thr = prev float
                thr[t, i] = np.nextafter(np.float32(split_val),
                                         np.float32(-np.inf))
            else:
                any_cat = True
                col_is_cat[c] = True
                arr = np.frombuffer(bits.ljust(W * 4, b"\x00"),
                                    dtype="<u4")[:W].copy()
                if bitoff:
                    # shift the category ids up by bitoff
                    full = np.zeros(W * 32, bool)
                    raw = np.unpackbits(
                        np.frombuffer(bits, np.uint8), bitorder="little")
                    n = min(raw.size, W * 32 - bitoff)
                    full[bitoff: bitoff + n] = raw[:n]
                    arr = np.packbits(full, bitorder="little") \
                        .view("<u4")[:W].copy()
                catbits[t, i] = arr
    # leaf values for pruned interior slots stay 0; fill descendant values
    # of leaves so fixed-depth walks that overshoot stop at the leaf value
    for t, nodes in enumerate(tree_nodes):
        for i, nd in nodes.items():
            if nd[0] == "leaf":
                # propagate down the dense heap so a full-depth walk lands
                # on this value regardless of routing below a leaf
                stack = [i]
                while stack:
                    j = stack.pop()
                    if j != i:
                        val[t, j] = val[t, i]
                        col[t, j] = -1
                    kl, kr = 2 * j + 1, 2 * j + 2
                    if kl < nnodes:
                        stack += [kl, kr]
    return TreeArrays(
        col=col, thr=thr, na_left=nal, value=val, depth=depth,
        catbits=catbits if any_cat else None,
        col_is_cat=col_is_cat if any_cat else None)


# ===========================================================================
# Container: write
def export_h2o_mojo(model, path: str) -> str:
    """Write a reference-layout MOJO zip (AbstractMojoWriter.java layout;
    per-algo writers: SharedTreeMojoWriter, GlmMojoWriter,
    KMeansMojoWriter, DeeplearningMojoWriter)."""
    algo = model.algo
    if algo == "glm":
        return _export_glm_mojo(model, path)
    if algo == "kmeans":
        return _export_kmeans_mojo(model, path)
    if algo == "deeplearning":
        return _export_dl_mojo(model, path)
    return _export_tree_mojo(model, path)


def _export_tree_mojo(model, path: str) -> str:
    """GBM/DRF (hex/tree/SharedTreeMojoWriter.java)."""
    di = model._dinfo
    algo = model.algo
    assert algo in ("gbm", "drf"), f"h2o-mojo export supports trees, not {algo}"
    multi = getattr(model, "_trees_k", None) is not None
    tlist = model._trees_k if multi else [model._trees]
    ntrees = tlist[0].ntrees
    tpc = len(tlist)

    feats = list(di.predictors)
    resp = di.response_name
    columns = feats + ([resp] if resp else [])
    domains = {}
    for ci, name in enumerate(columns):
        if name in (di.domains or {}):
            domains[ci] = list(di.domains[name])
    if resp and di.response_domain:
        domains[len(columns) - 1] = list(di.response_domain)
    nclasses = (len(di.response_domain) if di.response_domain else 1)

    dist = getattr(model, "_dist", "gaussian")
    link = {"bernoulli": "logit", "quasibinomial": "logit",
            "multinomial": "multinomial", "poisson": "log", "gamma": "log",
            "tweedie": "log"}.get(dist, "identity")
    f0 = getattr(model, "_f0", 0.0) if not multi else 0.0
    cat_card = np.zeros(len(feats), np.int64)
    for j, name in enumerate(feats):
        if name in (di.cardinalities or {}):
            cat_card[j] = di.cardinalities[name]

    ini = ["[info]"]

    def kv(k, v):
        ini.append(f"{k} = {v}")

    kv("h2o_version", "3.46.0.99999")
    kv("mojo_version", "1.40")
    kv("license", "Apache License Version 2.0")
    kv("algo", algo)
    kv("algorithm", "Gradient Boosting Machine" if algo == "gbm"
        else "Distributed Random Forest")
    kv("endianness", "LITTLE_ENDIAN")
    kv("category", "Regression" if nclasses == 1 else
        ("Binomial" if nclasses == 2 else "Multinomial"))
    kv("uuid", str(_uuid.uuid4().int & ((1 << 63) - 1)))
    kv("supervised", "true")
    kv("n_features", len(feats))
    kv("n_classes", nclasses)
    kv("n_columns", len(columns))
    kv("n_domains", len(domains))
    kv("balance_classes", "false")
    kv("default_threshold", "0.5")
    kv("prior_class_distrib", "null")
    kv("model_class_distrib", "null")
    kv("timestamp", datetime.now(timezone.utc).isoformat())
    kv("n_trees", ntrees)
    kv("n_trees_per_class", tpc)
    kv("distribution", dist)
    kv("link_function", link)
    kv("init_f", float(f0))
    kv("offset_column", "null")

    ini.append("")
    ini.append("[columns]")
    ini += columns
    ini.append("")
    ini.append("[domains]")
    dom_files = []
    for di_idx, (ci, levels) in enumerate(sorted(domains.items())):
        ini.append(f"{ci}: {len(levels)} d{di_idx:03d}.txt")
        dom_files.append((f"domains/d{di_idx:03d}.txt", "\n".join(levels)))

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", "\n".join(ini) + "\n")
        for fn, content in dom_files:
            z.writestr(fn, content + "\n")
        lr = (float(model.params.get("learn_rate") or 1.0)
              if algo == "gbm" else 1.0)
        for cls, ta in enumerate(tlist):
            for t in range(ta.ntrees):
                b = tree_to_h2o_bytes(ta, t, ncat=cat_card, val_scale=lr)
                z.writestr(f"trees/t{cls:02d}_{t:03d}.bin", b)
    return path


# ===========================================================================
# Container: read
def _parse_ini(text: str):
    info, columns, domains = {}, [], {}
    section = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("["):
            section = line
            continue
        if section == "[info]":
            if "=" in line:
                k, v = line.split("=", 1)
                info[k.strip()] = v.strip()
        elif section == "[columns]":
            columns.append(line)
        elif section == "[domains]":
            ci, rest = line.split(":", 1)
            cnt, fname = rest.strip().split(" ", 1)
            domains[int(ci)] = (int(cnt), fname.strip())
    return info, columns, domains


class H2OMojoModel:
    """A reference-format MOJO loaded for scoring (GbmMojoModel /
    DrfMojoModel analog; scores with the TPU batch scorer)."""

    def __init__(self, info, columns, domains, trees_k, f0, dist, algo):
        self.info = info
        self.columns = columns
        self.domains = domains          # col index -> [levels]
        self.trees_k = trees_k          # list (per class) of TreeArrays
        self.f0 = f0
        self.dist = dist
        self.algo = algo
        self.n_features = int(info.get("n_features", len(columns) - 1))
        self.n_classes = int(info.get("n_classes", 1))

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """X (n, n_features) f32 with NaN NAs, categorical as level codes.
        Returns (n,) regression / (n, K) class probabilities."""
        from h2o3_tpu.models.tree import engine as E
        import jax.numpy as jnp
        Xj = jnp.asarray(X, jnp.float32)
        if self.algo == "drf":
            if self.n_classes <= 1:
                s = E.predict_ensemble(Xj, self.trees_k[0])
                return np.asarray(s) / self.trees_k[0].ntrees
            per = [np.asarray(E.predict_ensemble(Xj, ta)) / ta.ntrees
                   for ta in self.trees_k]
            if self.n_classes == 2 and len(per) == 1:
                p1 = 1.0 - per[0]     # DRF stores p(class0) votes
                P = np.stack([1 - p1, p1], 1)
            else:
                P = np.stack(per, 1)
                P = P / np.maximum(P.sum(1, keepdims=True), 1e-30)
            return P
        # GBM margins
        if self.n_classes <= 2:
            F = self.f0 + np.asarray(E.predict_ensemble(Xj, self.trees_k[0]))
            if self.n_classes == 2:
                p1 = 1.0 / (1.0 + np.exp(-F))
                return np.stack([1 - p1, p1], 1)
            if self.dist in ("poisson", "gamma", "tweedie"):
                return np.exp(F)
            return F
        Fs = [np.asarray(E.predict_ensemble(Xj, ta)) for ta in self.trees_k]
        M = np.stack(Fs, 1)
        M -= M.max(1, keepdims=True)
        P = np.exp(M)
        return P / P.sum(1, keepdims=True)


def import_h2o_mojo(path: str) -> H2OMojoModel:
    """Load a genuine H2O-3 MOJO zip (tree algos)."""
    with zipfile.ZipFile(path) as z:
        info, columns, domspec = _parse_ini(
            z.read("model.ini").decode("utf-8", "replace"))
        algo = info.get("algo", "gbm")
        if algo not in ("gbm", "drf"):
            raise NotImplementedError(
                f"reference-MOJO import supports tree models, got {algo}")
        mver = float(info.get("mojo_version", "1.40"))
        if mver < 1.2:
            raise NotImplementedError(
                f"mojo_version {mver} predates the v1.2 tree byte format")
        domains = {}
        for ci, (cnt, fname) in domspec.items():
            levels = z.read(f"domains/{fname}").decode(
                "utf-8", "replace").splitlines()
            domains[ci] = levels[:cnt]
        ntrees = int(info["n_trees"])
        tpc = int(info.get("n_trees_per_class", 1))
        n_features = int(info["n_features"])
        max_card = max([len(v) for v in domains.values()], default=0)
        groups = []
        for cls in range(tpc):
            parsed = []
            maxd = 1
            for t in range(ntrees):
                name = f"trees/t{cls:02d}_{t:03d}.bin"
                nodes, d = parse_h2o_tree(z.read(name))
                parsed.append(nodes)
                maxd = max(maxd, d)
            if maxd > 16:
                raise NotImplementedError(f"tree depth {maxd} > 16")
            groups.append(trees_to_arrays(parsed, maxd, n_features,
                                          cat_width=max(max_card, 32)))
    f0 = float(info.get("init_f", 0.0) if info.get("init_f") not in
               (None, "null") else 0.0)
    return H2OMojoModel(info, columns, domains, groups, f0,
                        info.get("distribution", "gaussian"), algo)


# ===========================================================================
# Non-tree writers (GlmMojoWriter / KMeansMojoWriter / DeeplearningMojoWriter)
def _ini_header(algo, algorithm, category, nclasses, columns, n_features,
                supervised=True):
    """Common [info] block (AbstractMojoWriter.writeModelInfo)."""
    ini = ["[info]"]

    def kv(k, v):
        ini.append(f"{k} = {v}")

    kv("h2o_version", "3.46.0.99999")
    kv("mojo_version", "1.00")
    kv("license", "Apache License Version 2.0")
    kv("algo", algo)
    kv("algorithm", algorithm)
    kv("endianness", "LITTLE_ENDIAN")
    kv("category", category)
    kv("uuid", str(_uuid.uuid4().int & ((1 << 63) - 1)))
    kv("supervised", "true" if supervised else "false")
    kv("n_features", n_features)
    kv("n_classes", nclasses)
    kv("n_columns", len(columns))
    kv("balance_classes", "false")
    kv("default_threshold", "0.5")
    kv("timestamp", datetime.now(timezone.utc).isoformat())
    return ini, kv


def _arr(vals):
    """Arrays.toString encoding readkv round-trips: "[a, b, c]"."""
    return "[" + ", ".join(repr(float(v)) if isinstance(v, float)
                           else str(v) for v in vals) + "]"


def _finish_zip(path, ini, columns, domains_by_ci):
    ini.append("")
    ini.append("[columns]")
    ini += columns
    ini.append("")
    ini.append("[domains]")
    dom_files = []
    for di_idx, (ci, levels) in enumerate(sorted(domains_by_ci.items())):
        ini.append(f"{ci}: {len(levels)} d{di_idx:03d}.txt")
        dom_files.append((f"domains/d{di_idx:03d}.txt", "\n".join(levels)))
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", "\n".join(ini) + "\n")
        for fn, content in dom_files:
            z.writestr(fn, content + "\n")
    return path


def _glm_layout(model):
    """(columns cats-first, beta per-class in genmodel layout, cat meta).

    GlmMojoModel.glmScore0 applies beta to RAW values: indicator betas by
    catOffsets (use_all_factor_levels=true here — our one-hot keeps every
    level), then raw numerics, intercept last; standardization is baked
    out of the betas exactly like the reference writer does."""
    di = model._dinfo
    assert not (di.inter_pairs or di.inter_catcat or di.inter_catnum), \
        "reference GLM MOJO export does not cover interaction columns"
    cats, nums = list(di.cat_cols), list(di.num_cols)
    cat_offsets = [0]
    for c in cats:
        cat_offsets.append(cat_offsets[-1] + di.cardinalities[c])
    fam = model._state.family
    if fam == "multinomial":
        std = model._coefficients_std          # {name: [K betas]}
        K = len(di.response_domain)
        P = cat_offsets[-1] + len(nums) + 1
        beta = np.zeros(K * P)
        for k in range(K):
            j = 0
            icept = std["Intercept"][k]
            for c in cats:
                for lvl in di.domains[c]:
                    beta[k * P + j] = std[f"{c}.{lvl}"][k]
                    j += 1
            for c in nums:
                b = std[c][k]
                if di.standardize:
                    s = max(di.sigmas[c], 1e-10)
                    beta[k * P + j] = b / s
                    icept -= b * di.means[c] / s
                else:
                    beta[k * P + j] = b
                j += 1
            beta[k * P + P - 1] = icept
    else:
        raw = model._coefficients
        # sparse fits keep STANDARDIZED betas in _coefficients
        # (glm.py skips de-standardization there) — bake the scale out
        # here so the MOJO's raw-space contract holds
        destd = bool(di.standardize) and getattr(model, "_sparse_fit",
                                                 False)
        beta = np.zeros(cat_offsets[-1] + len(nums) + 1)
        j = 0
        icept = raw["Intercept"]
        for c in cats:
            for lvl in di.domains[c]:
                beta[j] = raw[f"{c}.{lvl}"]
                j += 1
        for c in nums:
            b = raw[c]
            if destd:
                s = max(di.sigmas[c], 1e-10)
                beta[j] = b / s
                icept -= b * di.means[c] / s
            else:
                beta[j] = b
            j += 1
        beta[-1] = icept
    return cats, nums, cat_offsets, beta


def _export_glm_mojo(model, path: str) -> str:
    """hex/glm GlmMojoWriter: beta + cat offsets + link in [info]."""
    di = model._dinfo
    st = model._state
    assert st.family in ("gaussian", "binomial", "poisson", "gamma",
                         "tweedie", "multinomial"), \
        f"reference GLM MOJO export: unsupported family {st.family}"
    cats, nums, cat_offsets, beta = _glm_layout(model)
    resp = di.response_name
    columns = cats + nums + ([resp] if resp else [])
    nclasses = len(di.response_domain) if di.response_domain else 1
    category = ("Binomial" if nclasses == 2 else
                "Multinomial" if nclasses > 2 else "Regression")
    ini, kv = _ini_header("glm", "Generalized Linear Model", category,
                          nclasses, columns, len(cats) + len(nums))
    kv("use_all_factor_levels", "true")
    kv("cats", len(cats))
    # NA categoricals: the engine scores them as an all-zero indicator
    # row; imputing the (out-of-range) cardinality makes GlmMojoModel's
    # `ival < catOffsets[i+1]` guard skip the beta — zero contribution,
    # exactly the engine's semantics
    kv("cat_modes", _arr([di.cardinalities[c] for c in cats]))
    kv("cat_offsets", _arr(cat_offsets))
    kv("nums", len(nums))
    kv("num_means", _arr([float(di.means[c]) for c in nums]))
    kv("mean_imputation", "true" if di.impute_missing else "false")
    kv("beta", _arr([float(b) for b in beta]))
    kv("family", st.family)
    kv("link", st.link)
    kv("tweedie_link_power",
       float(model.params.get("tweedie_link_power") or 0.0))
    domains = {ci: list(di.domains[c]) for ci, c in enumerate(cats)}
    if resp and di.response_domain:
        domains[len(columns) - 1] = list(di.response_domain)
    return _finish_zip(path, ini, columns, domains)


def _export_kmeans_mojo(model, path: str) -> str:
    """hex/kmeans KMeansMojoWriter: centers + standardization in [info]."""
    di = model._dinfo
    assert not di.cat_cols, \
        "reference KMeans MOJO export covers numeric frames (categorical " \
        "columns go through the one-hot design here, which the genmodel " \
        "row codec does not mirror)"
    nums = list(di.num_cols)
    centers = np.asarray(model._centroids, np.float64)
    ini, kv = _ini_header("kmeans", "K-means", "Clustering", 1, nums,
                          len(nums), supervised=False)
    std = bool(model.params.get("standardize"))
    kv("standardize", "true" if std else "false")
    if std:
        kv("standardize_means", _arr([float(di.means[c]) for c in nums]))
        kv("standardize_mults",
           _arr([1.0 / max(float(di.sigmas[c]), 1e-10) for c in nums]))
        kv("standardize_modes", _arr([-1] * len(nums)))
    kv("center_num", centers.shape[0])
    for i in range(centers.shape[0]):
        kv(f"center_{i}", _arr([float(v) for v in centers[i]]))
    return _finish_zip(path, ini, nums, {})


def _export_dl_mojo(model, path: str) -> str:
    """hex/deeplearning DeeplearningMojoWriter: per-layer weight/bias
    arrays + input normalization in [info]."""
    di = model._dinfo
    act = str(model.params.get("activation") or "Rectifier")
    assert "Maxout" not in act, \
        "reference DL MOJO export: Maxout weight layout not covered"
    assert not model.params.get("autoencoder"), \
        "reference DL MOJO export covers supervised nets"
    params = [(np.asarray(W, np.float64), np.asarray(b, np.float64))
              for W, b in model._params_net]
    cats, nums = list(di.cat_cols), list(di.num_cols)
    # GenModel.setCats clamps NA (and out-of-range) categories onto the
    # LAST level of each factor; the engine scores NA cats as an all-zero
    # indicator. Export an explicit extra "NA" level per factor with a
    # ZERO weight row so both scorers agree exactly.
    cat_offsets = [0]
    for c in cats:
        cat_offsets.append(cat_offsets[-1] + di.cardinalities[c] + 1)
    if cats:
        W0, b0 = params[0]
        rows = []
        pos = 0
        for c in cats:
            card = di.cardinalities[c]
            rows.append(W0[pos: pos + card])
            rows.append(np.zeros((1, W0.shape[1])))      # the NA slot
            pos += card
        rows.append(W0[pos:])                            # numeric rows
        params[0] = (np.vstack(rows), b0)
    if not di.standardize and nums:
        # no norm arrays means genmodel maps a missing numeric to RAW 0,
        # while the engine imputes the training mean. Shift inputs by the
        # means (norm_sub=mean, norm_mul=1) and fold the shift into the
        # first-layer bias so non-missing rows are untouched and missing
        # ones land on the mean — exact on both sides.
        W0, b0 = params[0]
        means = np.array([float(di.means[c]) for c in nums])
        noff = cat_offsets[-1]
        params[0] = (W0, b0 + means @ W0[noff: noff + len(nums)])
    resp = di.response_name
    columns = cats + nums + ([resp] if resp else [])
    nclasses = len(di.response_domain) if di.response_domain else 1
    category = ("Binomial" if nclasses == 2 else
                "Multinomial" if nclasses > 2 else "Regression")
    ini, kv = _ini_header("deeplearning", "Deep Learning", category,
                          nclasses, columns, len(cats) + len(nums))
    units = [params[0][0].shape[0]] + [b.shape[0] for _, b in params]
    kv("mini_batch_size", 1)
    kv("nums", len(nums))
    kv("cats", len(cats))
    kv("cat_offsets", _arr(cat_offsets))
    if di.standardize:
        kv("norm_sub", _arr([float(di.means[c]) for c in nums]))
        kv("norm_mul",
           _arr([1.0 / max(float(di.sigmas[c]), 1e-10) for c in nums]))
    else:
        # bias-folded mean shift (see above): missing -> post-norm 0 ==
        # the training mean, non-missing values reproduce exactly
        kv("norm_sub", _arr([float(di.means[c]) for c in nums]))
        kv("norm_mul", _arr([1.0] * len(nums)))
    kv("norm_resp_mul", "null")
    kv("norm_resp_sub", "null")
    kv("use_all_factor_levels", "true")
    kv("activation", act)
    kv("mean_imputation", "true" if di.impute_missing else "false")
    kv("cat_modes", _arr([di.cardinalities[c] for c in cats]))
    kv("distribution", "bernoulli" if nclasses == 2 else
       "multinomial" if nclasses > 2 else "gaussian")
    kv("neural_network_sizes", _arr(units))
    kv("hidden_dropout_ratios", _arr([]))
    for li, (W, b) in enumerate(params):
        kv(f"bias_layer{li}", _arr([float(v) for v in b]))
        # genmodel weight layout is (out, in) row-major; ours is (in, out)
        kv(f"weight_layer{li}",
           _arr([float(v) for v in W.T.reshape(-1)]))
    domains = {ci: list(di.domains[c]) for ci, c in enumerate(cats)}
    if resp and di.response_domain:
        domains[len(columns) - 1] = list(di.response_domain)
    return _finish_zip(path, ini, columns, domains)


# ===========================================================================
# Non-tree oracles: bit-faithful score0 re-implementations
def _parse_arr(s, dtype=float):
    s = s.strip()
    if s in ("null", "[]", ""):
        return np.array([], np.float64 if dtype is float else np.int64)
    vals = [x.strip() for x in s.strip("[]").split(",") if x.strip()]
    return np.array([dtype(v) for v in vals],
                    np.float64 if dtype is float else np.int64)


class H2OGlmMojoOracle:
    """GlmMojoModel/GlmMultinomialMojoModel.glmScore0 re-implemented
    exactly (float64, same eta accumulation order per class)."""

    def __init__(self, info):
        self.beta = _parse_arr(info["beta"])
        self.cat_offsets = _parse_arr(info.get("cat_offsets", "[]"), int)
        self.cats = int(info.get("cats", 0))
        self.nums = int(info.get("nums", 0))
        self.num_means = _parse_arr(info.get("num_means", "[]"))
        self.cat_modes = _parse_arr(info.get("cat_modes", "[]"), int)
        self.mean_imputation = info.get("mean_imputation") == "true"
        self.use_all = info.get("use_all_factor_levels", "true") == "true"
        self.family = info.get("family", "gaussian")
        self.link = info.get("link", "identity")
        self.tweedie_link_power = float(
            info.get("tweedie_link_power") or 0.0)
        self.n_classes = int(info.get("n_classes", 1))

    def _link_eval(self, eta):
        if self.link in ("identity", "family_default"):
            return eta
        if self.link == "logit":
            return 1.0 / (1.0 + np.exp(-eta))
        if self.link == "log":
            return np.exp(eta)
        if self.link == "inverse":
            xx = np.where(np.abs(eta) < 1e-5, np.sign(eta) * 1e-5, eta)
            return 1.0 / xx
        if self.link == "ologit":
            return 1.0 / (1.0 + np.exp(-eta))
        if self.link == "tweedie":
            # GenModel.GLM_tweedieInv
            p = self.tweedie_link_power
            if p == 0:
                return np.maximum(2e-16, np.exp(eta))
            return np.power(eta, 1.0 / p)
        raise NotImplementedError(self.link)

    def predict_raw(self, X):
        """X (n, cats+nums): cat level codes then raw numerics."""
        X = np.array(X, np.float64, copy=True)
        if self.mean_imputation:
            for i in range(self.cats):
                X[np.isnan(X[:, i]), i] = self.cat_modes[i]
            for i in range(self.nums):
                j = self.cats + i
                X[np.isnan(X[:, j]), j] = self.num_means[i]
        n = X.shape[0]
        if self.family == "multinomial":
            K = self.n_classes
            P = len(self.beta) // K
            etas = np.zeros((n, K))
            for k in range(K):
                b = self.beta[k * P:(k + 1) * P]
                etas[:, k] = self._eta(X, b)
            m = np.maximum(etas.max(1), 0.0)       # reference max_row
            #                                         starts at 0
            E = np.exp(etas - m[:, None])
            return E / E.sum(1, keepdims=True)
        mu = self._link_eval(self._eta(X, self.beta))
        if self.family in ("binomial", "fractionalbinomial"):
            return np.stack([1.0 - mu, mu], 1)
        return mu

    def _eta(self, X, beta):
        n = X.shape[0]
        eta = np.zeros(n)
        noff = (self.cat_offsets[self.cats] - self.cats
                if self.cats else 0)
        for i in range(self.cats):
            raw = X[:, i]
            # un-imputed NaN contributes nothing (engine zero-row parity)
            raw = np.where(np.isnan(raw), -(1 << 30), raw)
            ival = raw.astype(np.int64) + (0 if self.use_all else -1)
            ival = ival + self.cat_offsets[i]
            ok = (ival < self.cat_offsets[i + 1]) & \
                (ival >= self.cat_offsets[i])
            if not self.use_all:
                ok &= X[:, i] != 0
            eta += np.where(ok, beta[np.clip(ival, 0, len(beta) - 1)], 0.0)
        for i in range(self.cats, len(beta) - 1 - noff):
            eta += beta[noff + i] * X[:, self.cats + (i - self.cats)]
        return eta + beta[-1]


class H2OKMeansMojoOracle:
    """KMeansMojoModel.score0: Kmeans_preprocessData + KMeans_closest."""

    def __init__(self, info):
        self.standardize = info.get("standardize") == "true"
        k = int(info["center_num"])
        self.centers = np.stack([_parse_arr(info[f"center_{i}"])
                                 for i in range(k)])
        if self.standardize:
            self.means = _parse_arr(info["standardize_means"])
            self.mults = _parse_arr(info["standardize_mults"])
            self.modes = _parse_arr(info["standardize_modes"], int)

    def predict_raw(self, X):
        X = np.array(X, np.float64, copy=True)
        if self.standardize:
            for i in range(X.shape[1]):
                if self.modes[i] == -1:
                    na = np.isnan(X[:, i])
                    X[na, i] = self.means[i]
                    X[:, i] = (X[:, i] - self.means[i]) * self.mults[i]
                else:
                    X[np.isnan(X[:, i]), i] = self.modes[i]
        d2 = ((X[:, None, :] - self.centers[None, :, :]) ** 2).sum(-1)
        return d2.argmin(1)


class H2ODlMojoOracle:
    """DeeplearningMojoModel.score0: one-hot cats, normalized nums,
    dense layers with the stored activation, softmax for classifiers."""

    def __init__(self, info):
        self.cats = int(info.get("cats", 0))
        self.nums = int(info.get("nums", 0))
        self.cat_offsets = _parse_arr(info.get("cat_offsets", "[]"), int)
        self.norm_sub = _parse_arr(info.get("norm_sub", "[]"))
        self.norm_mul = _parse_arr(info.get("norm_mul", "[]"))
        self.cat_modes = _parse_arr(info.get("cat_modes", "[]"), int)
        self.mean_imputation = info.get("mean_imputation") == "true"
        self.activation = info.get("activation", "Rectifier")
        self.units = _parse_arr(info["neural_network_sizes"], int)
        self.n_classes = int(info.get("n_classes", 1))
        self.layers = []
        li = 0
        while f"weight_layer{li}" in info:
            W = _parse_arr(info[f"weight_layer{li}"])
            b = _parse_arr(info[f"bias_layer{li}"])
            nin, nout = self.units[li], self.units[li + 1]
            # stored (out, in) row-major -> back to (in, out)
            self.layers.append((W.reshape(nout, nin).T, b))
            li += 1

    def _act(self, z):
        if "Rectifier" in self.activation:
            return np.maximum(z, 0.0)
        if "Tanh" in self.activation:
            return np.tanh(z)
        raise NotImplementedError(self.activation)

    def predict_raw(self, X):
        X = np.array(X, np.float64, copy=True)
        n = X.shape[0]
        ncat_in = int(self.cat_offsets[-1]) if self.cats else 0
        H = np.zeros((n, ncat_in + self.nums))
        for i in range(self.cats):
            codes = X[:, i]
            # GenModel.setCats: NaN -> the extra trailing NA level;
            # out-of-range clamps onto that same last slot
            idx = np.where(np.isnan(codes), self.cat_offsets[i + 1] - 1,
                           np.nan_to_num(codes) + self.cat_offsets[i])
            idx = np.minimum(idx, self.cat_offsets[i + 1] - 1).astype(np.int64)
            H[np.arange(n), idx] = 1.0
        for i in range(self.nums):
            v = X[:, self.cats + i]
            if len(self.norm_sub):
                v = (v - self.norm_sub[i]) * self.norm_mul[i]
            H[:, ncat_in + i] = np.nan_to_num(v)
        for W, b in self.layers[:-1]:
            H = self._act(H @ W + b)
        W, b = self.layers[-1]
        out = H @ W + b
        if self.n_classes >= 2:
            out = out - out.max(1, keepdims=True)
            E = np.exp(out)
            return E / E.sum(1, keepdims=True)
        return out[:, 0]


_ORACLES = {"glm": H2OGlmMojoOracle, "kmeans": H2OKMeansMojoOracle,
            "deeplearning": H2ODlMojoOracle}


def import_h2o_mojo_any(path: str):
    """Dispatch loader: tree MOJOs go through the TPU batch scorer
    (import_h2o_mojo); GLM/KMeans/DL go to the exact-score0 oracles."""
    with zipfile.ZipFile(path) as z:
        info, _, _ = _parse_ini(z.read("model.ini").decode("utf-8",
                                                           "replace"))
    algo = info.get("algo", "gbm")
    if algo in _ORACLES:
        return _ORACLES[algo](info)
    return import_h2o_mojo(path)
