"""MOJO-style scoring artifacts — h2o-genmodel rebuilt.

Reference: h2o-genmodel/ (MojoModel.java, GenModel.java, per-algo readers
hex/genmodel/algos/*, EasyPredictModelWrapper row API): a MOJO is a zip of
model metadata + binary payload that scores with zero cluster dependencies;
writers live beside each algo (*MojoWriter.java).

This build's artifact keeps the same contract — a self-contained zip
(model.ini-style JSON metadata + npz payloads) scoreable with numpy alone —
with the same algo coverage (trees/GLM/KMeans/DL/NB/PCA/GLRM). Byte-level
compatibility with the reference's zip layout is not attempted: the scoring
JAR ecosystem is JVM-side; the parity surface here is save → load → identical
predictions without a running cloud.
"""

from __future__ import annotations

import io
import json
import pickle
import zipfile

import numpy as np

from h2o3_tpu.core.kvstore import DKV

MAGIC = "h2o3_tpu_mojo/1"


# ===========================================================================
def export_mojo(model, path: str) -> str:
    """Model.getMojo(): serialize the learned state + scoring metadata."""
    algo = model.algo
    di = model._dinfo
    meta = {
        "magic": MAGIC, "algo": algo, "model_id": model.key,
        "params": {k: v for k, v in model.params.items()
                   if isinstance(v, (int, float, str, bool, list, type(None)))},
        "predictors": di.predictors if di else [],
        "feature_names": di.feature_names if di else [],
        "cat_cols": di.cat_cols if di else [],
        "num_cols": di.num_cols if di else [],
        "domains": {k: list(v) for k, v in (di.domains or {}).items()} if di else {},
        "response_domain": di.response_domain if di else None,
        "means": di.means if di else {},
        "sigmas": di.sigmas if di else {},
        "standardize": di.standardize if di else False,
        "cat_mode": di.cat_mode if di else "onehot",
    }
    arrays = {}
    if algo in ("gbm", "xgboost", "drf", "isolationforest"):
        if getattr(model, "_trees_k", None) is not None:
            meta["nclass_trees"] = len(model._trees_k)
            meta["depth"] = model._trees_k[0].depth
            for c, ta in enumerate(model._trees_k):
                arrays[f"col_{c}"] = np.asarray(ta.col)
                arrays[f"thr_{c}"] = np.asarray(ta.thr)
                arrays[f"nal_{c}"] = np.asarray(ta.na_left)
                arrays[f"val_{c}"] = np.asarray(ta.value)
            meta["f0"] = np.asarray(model._f0).tolist()
        else:
            ta = model._trees
            meta["depth"] = ta.depth
            arrays["col_0"] = np.asarray(ta.col)
            arrays["thr_0"] = np.asarray(ta.thr)
            arrays["nal_0"] = np.asarray(ta.na_left)
            arrays["val_0"] = np.asarray(ta.value)
            if algo in ("gbm", "xgboost"):
                meta["f0"] = float(model._f0)
                meta["dist"] = model._dist
            if algo == "isolationforest":
                meta["min_len"] = model._min_len
                meta["max_len"] = model._max_len
        if algo in ("gbm", "xgboost"):
            meta["dist"] = model._dist
            meta["learn_rate"] = float(model.params["learn_rate"])
        if algo == "drf":
            meta["nclasses"] = model.nclasses
    elif algo == "glm":
        arrays["beta"] = np.asarray(model._state.beta)
        meta["family"] = model._state.family
        meta["link"] = model._state.link
    elif algo == "kmeans":
        arrays["centers"] = np.asarray(model._centroids)
    elif algo == "deeplearning":
        for i, (W, b) in enumerate(model._params_net):
            arrays[f"W_{i}"] = np.asarray(W)
            arrays[f"b_{i}"] = np.asarray(b)
        meta["n_layers"] = len(model._params_net)
        meta["activation"] = model.params.get("activation")
        meta["loss_kind"] = model._loss_kind
        meta["autoencoder"] = bool(model.params.get("autoencoder"))
    elif algo == "naivebayes":
        arrays["priors"] = model._priors
        for i, t in enumerate(model._cat_probs):
            arrays[f"cat_{i}"] = t
        for i, m in enumerate(model._num_mean):
            arrays[f"nmean_{i}"] = m
            arrays[f"nsd_{i}"] = model._num_sd[i]
        meta["cat_idx"] = list(model._cat_idx)
        meta["num_idx"] = list(model._num_idx)
    elif algo == "pca":
        arrays["rotation"] = model._rotation
        arrays["mean"] = model._mean
        arrays["sd"] = model._sd
        meta["transform"] = model._transform
    elif algo == "glrm":
        arrays["archetypes"] = model._B
    else:
        raise NotImplementedError(f"MOJO export for {algo}")
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("model.json", json.dumps(meta, default=float))
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        zf.writestr("payload.npz", buf.getvalue())
    return path


# ===========================================================================
class MojoModel:
    """Standalone scorer (hex/genmodel/MojoModel + EasyPredictModelWrapper):
    numpy-only, no cloud, no jax required at score time."""

    def __init__(self, meta: dict, arrays: dict):
        self.meta = meta
        self.arrays = arrays
        self.algo = meta["algo"]

    @staticmethod
    def load(path: str) -> "MojoModel":
        with zipfile.ZipFile(path) as zf:
            meta = json.loads(zf.read("model.json"))
            assert meta.get("magic") == MAGIC, "not an h2o3_tpu MOJO"
            npz = np.load(io.BytesIO(zf.read("payload.npz")))
            arrays = {k: npz[k] for k in npz.files}
        return MojoModel(meta, arrays)

    # ---- row → model-space matrix (GenModel data prep) -------------------
    def _row_to_matrix(self, rows) -> np.ndarray:
        m = self.meta
        if isinstance(rows, dict):
            rows = [rows]
        n = len(rows)
        if m["cat_mode"] == "label":
            X = np.full((n, len(m["predictors"])), np.nan)
            for i, r in enumerate(rows):
                for j, c in enumerate(m["predictors"]):
                    v = r.get(c)
                    if v is None:
                        continue
                    if c in m["domains"]:
                        dom = m["domains"][c]
                        X[i, j] = dom.index(str(v)) if str(v) in dom else np.nan
                    else:
                        X[i, j] = float(v)
            return X
        cols = []
        for i, r in enumerate(rows):
            row = []
            for c in m["cat_cols"]:
                dom = m["domains"][c]
                oh = [0.0] * len(dom)
                v = r.get(c)
                if v is not None and str(v) in dom:
                    oh[dom.index(str(v))] = 1.0
                row.extend(oh)
            for c in m["num_cols"]:
                v = r.get(c)
                x = np.nan if v is None else float(v)
                if m["standardize"]:
                    mu = m["means"].get(c, 0.0)
                    sd = max(m["sigmas"].get(c, 1.0) or 1.0, 1e-10)
                    x = 0.0 if np.isnan(x) else (x - mu) / sd
                elif np.isnan(x):
                    x = m["means"].get(c, 0.0)
                row.append(x)
            cols.append(row)
        return np.asarray(cols, np.float64)

    # ---- scoring ---------------------------------------------------------
    def _walk_trees(self, X, c_idx=0):
        col = self.arrays[f"col_{c_idx}"]
        thr = self.arrays[f"thr_{c_idx}"]
        nal = self.arrays[f"nal_{c_idx}"]
        val = self.arrays[f"val_{c_idx}"]
        T = col.shape[0]
        n = X.shape[0]
        out = np.zeros(n)
        depth = self.meta["depth"]
        for t in range(T):
            node = np.zeros(n, np.int64)
            for _ in range(depth):
                c = col[t][node]
                leafish = c < 0
                cc = np.maximum(c, 0)
                x = X[np.arange(n), cc]
                isna = np.isnan(x)
                right = np.where(isna, ~nal[t][node], x > thr[t][node])
                child = 2 * node + 1 + right.astype(np.int64)
                node = np.where(leafish, node, child)
            out += val[t][node]
        return out

    def predict(self, data):
        """EasyPredictModelWrapper.predict: dict row(s) → prediction dict."""
        X = self._row_to_matrix(data)
        m = self.meta
        algo = self.algo
        if algo in ("gbm", "xgboost"):
            if "nclass_trees" in m:
                K = m["nclass_trees"]
                F = np.stack([m["f0"][c] + m["learn_rate"] *
                              self._walk_trees(X, c) for c in range(K)], 1)
                eF = np.exp(F - F.max(1, keepdims=True))
                P = eF / eF.sum(1, keepdims=True)
                return self._cls_out(P)
            F = m["f0"] + m["learn_rate"] * self._walk_trees(X)
            if m["dist"] in ("bernoulli", "quasibinomial"):
                p = 1 / (1 + np.exp(-F))
                return self._cls_out(np.stack([1 - p, p], 1))
            if m["dist"] in ("poisson", "gamma", "tweedie"):
                return {"predict": np.exp(F)}
            return {"predict": F}
        if algo == "drf":
            if "nclass_trees" in m:
                K = m["nclass_trees"]
                P = np.stack([self._walk_trees(X, c) /
                              self.arrays["col_0"].shape[0]
                              for c in range(K)], 1)
                P = np.clip(P, 0, 1)
                P /= np.maximum(P.sum(1, keepdims=True), 1e-10)
                return self._cls_out(P)
            mean = self._walk_trees(X) / self.arrays["col_0"].shape[0]
            if m["response_domain"]:
                p = np.clip(mean, 0, 1)
                return self._cls_out(np.stack([1 - p, p], 1))
            return {"predict": mean}
        if algo == "isolationforest":
            ml = self._walk_trees(X) / self.arrays["col_0"].shape[0]
            span = max(m["max_len"] - m["min_len"], 1e-12)
            return {"predict": (m["max_len"] - ml) / span, "mean_length": ml}
        if algo == "glm":
            beta = self.arrays["beta"]
            Xi = np.column_stack([np.nan_to_num(X), np.ones(len(X))])
            if m["family"] == "multinomial":
                F = Xi @ beta.T
                eF = np.exp(F - F.max(1, keepdims=True))
                return self._cls_out(eF / eF.sum(1, keepdims=True))
            eta = Xi @ beta
            link = m["link"]
            mu = (eta if link == "identity" else
                  1 / (1 + np.exp(-np.clip(eta, -40, 40)))
                  if link == "logit" else
                  np.exp(np.clip(eta, -700, 700)) if link == "log"
                  else 1.0 / eta)
            if m["family"] in ("binomial", "quasibinomial"):
                return self._cls_out(np.stack([1 - mu, mu], 1))
            return {"predict": mu}
        if algo == "kmeans":
            C = self.arrays["centers"]
            d = ((np.nan_to_num(X)[:, None, :] - C[None]) ** 2).sum(-1)
            return {"cluster": d.argmin(1)}
        if algo == "deeplearning":
            h = np.nan_to_num(X)
            nl = m["n_layers"]
            act = (m.get("activation") or "Rectifier").lower()
            for i in range(nl):
                z = h @ self.arrays[f"W_{i}"] + self.arrays[f"b_{i}"]
                if i < nl - 1:
                    if "maxout" in act:
                        z = z.reshape(z.shape[0], -1, 2).max(2)
                    elif "tanh" in act:
                        z = np.tanh(z)
                    else:
                        z = np.maximum(z, 0)
                h = z
            if m.get("autoencoder"):
                return {"reconstruction": h}
            if m["loss_kind"] == "ce":
                eF = np.exp(h - h.max(1, keepdims=True))
                return self._cls_out(eF / eF.sum(1, keepdims=True))
            return {"predict": h[:, 0]}
        if algo == "pca":
            x = np.nan_to_num(X)
            t = m["transform"]
            if t in ("DEMEAN", "STANDARDIZE"):
                x = x - self.arrays["mean"]
            if t in ("DESCALE", "STANDARDIZE", "NORMALIZE"):
                x = x / self.arrays["sd"]
            return {"scores": x @ self.arrays["rotation"]}
        raise NotImplementedError(self.algo)

    def _cls_out(self, P):
        dom = self.meta["response_domain"]
        idx = P.argmax(1)
        return {"predict": np.array([dom[i] for i in idx], object),
                "probs": P, "domain": dom}


# ===========================================================================
# Binary model save/load (water/api/ModelsHandler exportBinaryModel)
class _ModelPickler(pickle.Pickler):
    """Device arrays are converted to host numpy on serialization — a saved
    model must load without a TPU attached (Model.exportBinaryModel)."""

    def reducer_override(self, obj):
        try:
            import jax
            if isinstance(obj, jax.Array):
                return (np.asarray, (np.asarray(obj),))
        except ImportError:
            pass
        return NotImplemented


def save_model(model, path: str) -> str:
    with open(path, "wb") as f:
        _ModelPickler(f, protocol=5).dump(model)
    return path


def load_model(path: str):
    with open(path, "rb") as f:
        m = pickle.load(f)
    DKV.put(m.key, m)
    return m
