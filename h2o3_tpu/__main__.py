"""`python -m h2o3_tpu` — the `java -jar h2o.jar` analog.

Parses the OptArgs-style CLI (water/H2O.java:327: -port, -name, -ip,
-basic_auth/-hash_login file, -ssl, -nthreads …), forms the cloud (one
host or a jax.distributed multi-host launch via deploy/multihost env
vars), and serves REST + Flow until interrupted."""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="h2o3-tpu",
        description="Start an h2o3-tpu node (REST + Flow on one host; "
                    "multi-host when H2O3_COORDINATOR_ADDRESS is set)")
    ap.add_argument("-port", "--port", type=int, default=54321)
    ap.add_argument("-ip", "--ip", default=None,
                    help="bind address (default loopback; 0.0.0.0 when "
                         "-bind_all)")
    ap.add_argument("-name", "--name", default=None,
                    help="cloud name (water.H2O -name)")
    ap.add_argument("-bind_all", action="store_true",
                    help="listen on every interface (requires auth or "
                         "H2O3_INSECURE_BIND_ALL=1)")
    ap.add_argument("-basic_auth", "--auth_file", default=None,
                    help="user:password lines file (-hash_login analog)")
    ap.add_argument("-ssl_cert", default=None)
    ap.add_argument("-ssl_key", default=None)
    ap.add_argument("-n_rows_shards", type=int, default=None,
                    help="mesh rows axis (default: all devices)")
    ap.add_argument("-n_model_shards", type=int, default=1)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)

    from h2o3_tpu.utils import config as _cfg
    if args.name:
        _cfg.set_property("cloud.name", args.name)
    if args.bind_all:
        _cfg.set_property("api.bind_all", True)
    if args.auth_file:
        _cfg.set_property("api.auth_file", args.auth_file)
    if args.ssl_cert:
        _cfg.set_property("api.ssl_cert", args.ssl_cert)
    if args.ssl_key:
        _cfg.set_property("api.ssl_key", args.ssl_key)

    from h2o3_tpu.deploy import multihost
    if multihost.is_multihost():
        multihost.serve(args.port, n_rows_shards=args.n_rows_shards,
                        n_model_shards=args.n_model_shards)
        return 0

    import h2o3_tpu
    cloud = h2o3_tpu.init(n_rows_shards=args.n_rows_shards,
                          n_model_shards=args.n_model_shards)
    from h2o3_tpu.api.server import H2OServer
    srv = H2OServer(args.port, host=args.ip)
    print(f"h2o3-tpu cloud up: {cloud.n_devices} device shard(s); "
          f"REST + Flow on :{srv.port}")
    try:
        srv.start(background=False)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
