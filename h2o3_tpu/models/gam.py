"""GAM — hex/gam/GAM.java: generalized additive models via spline basis + GLM.

Reference: GAM builds cubic-regression-spline basis columns for each
`gam_columns` predictor (GamSplines/CubicRegressionSpline — the
value-at-knots parametrization of Wood §4.1.2), appends them to the design
matrix with the TRUE curvature penalty matrix S = Dᵀ B⁻¹ D (∫f″² over the
knot range, banded D/B from knot spacings), centers each basis block for
identifiability against the intercept, then delegates the fit to GLM with
the per-block penalty (scaled by `scale`).

TPU-native: basis construction and the (num_knots²) penalty assembly are
host work; the fit is the GLM IRLS path (device Gram matmuls) with the
penalty folded into the normal equations (glm.py `quadratic_penalty`).
With one gaussian gam column, knots at the data points and scale=λ this
reproduces the classical smoothing spline exactly (tested against
scipy.interpolate.make_smoothing_spline)."""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model import ModelBase


def crs_design_and_penalty(x: np.ndarray, knots: np.ndarray):
    """Cubic regression spline in the value-at-knots parametrization
    (Wood 2006 §4.1.2; GamSplines/CubicRegressionSpline semantics).

    Returns (X, S): X (n, K) maps knot values γ to f(x_i); S (K, K) is the
    exact curvature penalty ∫ f″(t)² dt = γᵀSγ with S = Dᵀ B⁻¹ D."""
    k = np.asarray(knots, np.float64)
    K = len(k)
    h = np.diff(k)                                   # (K-1,)
    # banded D (K-2, K) and B (K-2, K-2)
    D = np.zeros((K - 2, K))
    B = np.zeros((K - 2, K - 2))
    for i in range(K - 2):
        D[i, i] = 1.0 / h[i]
        D[i, i + 1] = -1.0 / h[i] - 1.0 / h[i + 1]
        D[i, i + 2] = 1.0 / h[i + 1]
        B[i, i] = (h[i] + h[i + 1]) / 3.0
        if i + 1 < K - 2:
            B[i, i + 1] = B[i + 1, i] = h[i + 1] / 6.0
    Binv_D = np.linalg.solve(B, D)                   # (K-2, K)
    S = D.T @ Binv_D                                 # (K, K) penalty
    # F maps values γ to second derivatives m at ALL knots (natural BC:
    # zero curvature at the end knots)
    F = np.zeros((K, K))
    F[1:-1] = Binv_D

    xc = np.nan_to_num(np.asarray(x, np.float64), nan=float(np.mean(k)))
    xc = np.clip(xc, k[0], k[-1])                    # natural-spline clamp
    j = np.clip(np.searchsorted(k, xc, side="right") - 1, 0, K - 2)
    hj = h[j]
    am = (k[j + 1] - xc) / hj
    ap = (xc - k[j]) / hj
    cm = ((k[j + 1] - xc) ** 3 / hj - hj * (k[j + 1] - xc)) / 6.0
    cp = ((xc - k[j]) ** 3 / hj - hj * (xc - k[j])) / 6.0
    n = len(xc)
    X = np.zeros((n, K))
    X[np.arange(n), j] += am
    X[np.arange(n), j + 1] += ap
    X += cm[:, None] * F[j] + cp[:, None] * F[j + 1]
    return X, S


def _centering_transform(X: np.ndarray):
    """Identifiability constraint Σᵢ f(xᵢ) = 0 (the reference centers each
    gam block so it cannot absorb the intercept): Z = null space of 1ᵀX."""
    c = X.sum(axis=0, keepdims=True)                 # (1, K)
    # householder-style: full SVD null space of the 1xK constraint
    _, _, vt = np.linalg.svd(c, full_matrices=True)
    return vt[1:].T                                  # (K, K-1)


class H2OGeneralizedAdditiveEstimator(ModelBase):
    algo = "gam"
    _defaults = dict(H2OGeneralizedLinearEstimator._defaults)
    _defaults.update({"gam_columns": None, "num_knots": None,
                      "scale": None, "bs": None, "spline_orders": None})

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        self.params.update(kw)
        gam_cols = self.params.get("gam_columns") or []
        gam_cols = [c[0] if isinstance(c, list) else c for c in gam_cols]
        nk = self.params.get("num_knots") or [6] * len(gam_cols)
        scales = self.params.get("scale") or [1.0] * len(gam_cols)
        frame = training_frame
        self._gam_cols = gam_cols
        self._knots = {}
        self._Z = {}
        self._S = {}
        self._basis_names = {}
        aug = self._augment(frame, gam_cols, nk, fit=True)
        vaug = None
        if validation_frame is not None:
            vaug = self._augment(validation_frame, gam_cols, nk, fit=False)
        xx = list(x) if x is not None else [c for c in frame.names if c != y]
        xx = [c for c in xx if c not in gam_cols] + \
            [n for c in gam_cols for n in self._basis_names[c]]
        glm_params = {k: v for k, v in self.params.items()
                      if k in H2OGeneralizedLinearEstimator._defaults
                      or k in H2OGeneralizedLinearEstimator._COMMON}
        # named penalty blocks: the GLM indexes them into ITS OWN expanded
        # design (and applies the standardization rescale), so
        # interactions/weights/offset params can never desynchronize the
        # penalty from the design matrix
        glm_params["quadratic_penalty"] = [
            (self._basis_names[c],
             (float(scales[ci]) if ci < len(scales) else 1.0)
             * (self._Z[c].T @ self._S[c] @ self._Z[c]))
            for ci, c in enumerate(gam_cols)]
        self._glm = H2OGeneralizedLinearEstimator(**glm_params)
        self._glm.train(x=xx, y=y, training_frame=aug,
                        validation_frame=vaug)
        self.key = self.params.get("model_id") or self._glm.key + "_gam"
        self._output = self._glm._output
        self._dinfo = self._glm._dinfo
        from h2o3_tpu.core.kvstore import DKV
        DKV.put(self.key, self)
        return self

    def _augment(self, frame: Frame, gam_cols, nk, fit: bool) -> Frame:
        names, vecs = list(frame.names), list(frame.vecs)
        out = Frame(names, vecs)
        for ci, c in enumerate(gam_cols):
            xcol = frame.vec(c).to_numpy()
            if fit:
                k = int(nk[ci]) if ci < len(nk) else 6
                qs = np.linspace(0.0, 1.0, k)
                knots = np.unique(np.nanquantile(xcol, qs))
                if len(knots) < 3:
                    raise ValueError(
                        f"gam column {c!r} has {len(knots)} distinct "
                        "knot value(s); a cubic regression spline needs "
                        ">= 3 (constant or near-constant column — drop "
                        "it from gam_columns)")
                self._knots[c] = knots
            B, S = crs_design_and_penalty(xcol, self._knots[c])
            if fit:
                self._S[c] = S
                self._Z[c] = _centering_transform(B)
                self._basis_names[c] = [
                    f"{c}_gam{j}" for j in range(self._Z[c].shape[1])]
            Bz = B @ self._Z[c]
            for j, bn in enumerate(self._basis_names[c]):
                out[bn] = Bz[:, j]
        return out

    def predict(self, test_data: Frame) -> Frame:
        aug = self._augment(test_data, self._gam_cols,
                            self.params.get("num_knots") or [], fit=False)
        return self._glm.predict(aug)

    def model_performance(self, test_data=None):
        if test_data is None:
            return self._output.training_metrics
        aug = self._augment(test_data, self._gam_cols, [], fit=False)
        return self._glm._compute_metrics(aug)

    def coef(self):
        return self._glm.coef()
