"""GAM — hex/gam/GAM.java: generalized additive models via spline basis + GLM.

Reference: GAM builds cubic-regression-spline basis columns for each
`gam_columns` predictor (GamSplines/, MatrixFrameUtils/), appends them to the
design matrix with a smoothness penalty, then delegates the fit to GLM.

TPU-native: the basis expansion is a host-side construction of extra columns
(small: num_knots per gam column); the fit is the GLM IRLS path (device Gram
matmuls). The smoothness penalty enters as per-column L2 scaling
(scale_tp_penalty approximation of the reference's penalty matrix).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.model import ModelBase


def _cr_spline_basis(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """Natural cubic regression spline basis (GamSplines CubicRegressionSpline):
    truncated-power natural spline with K knots → K columns."""
    K = len(knots)
    d = np.zeros((len(x), K))
    xc = np.nan_to_num(x, nan=np.nanmean(x))

    def omega(z, k):
        return np.where(z > k, (z - k) ** 3, 0.0)

    denom = knots[-1] - knots[0] or 1.0
    base = [np.ones_like(xc), xc]
    for j in range(K - 2):
        t = (omega(xc, knots[j]) - omega(xc, knots[-1])) / denom \
            - (omega(xc, knots[-2]) - omega(xc, knots[-1])) / denom * \
            (knots[-1] - knots[j]) / (knots[-1] - knots[-2])
        base.append(t)
    return np.column_stack(base[:K])


class H2OGeneralizedAdditiveEstimator(ModelBase):
    algo = "gam"
    _defaults = dict(H2OGeneralizedLinearEstimator._defaults)
    _defaults.update({"gam_columns": None, "num_knots": None,
                      "scale": None, "bs": None, "spline_orders": None})

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        self.params.update(kw)
        gam_cols = self.params.get("gam_columns") or []
        gam_cols = [c[0] if isinstance(c, list) else c for c in gam_cols]
        nk = self.params.get("num_knots") or [6] * len(gam_cols)
        frame = training_frame
        self._gam_cols = gam_cols
        self._knots = {}
        self._basis_names = {}
        aug, vaug = self._augment(frame, gam_cols, nk, fit=True), None
        if validation_frame is not None:
            vaug = self._augment(validation_frame, gam_cols, nk, fit=False)
        xx = list(x) if x is not None else [c for c in frame.names if c != y]
        xx = [c for c in xx if c not in gam_cols] + \
            [n for c in gam_cols for n in self._basis_names[c]]
        glm_params = {k: v for k, v in self.params.items()
                      if k in H2OGeneralizedLinearEstimator._defaults
                      or k in H2OGeneralizedLinearEstimator._COMMON}
        self._glm = H2OGeneralizedLinearEstimator(**glm_params)
        self._glm.train(x=xx, y=y, training_frame=aug,
                        validation_frame=vaug)
        self.key = self.params.get("model_id") or self._glm.key + "_gam"
        self._output = self._glm._output
        self._dinfo = self._glm._dinfo
        from h2o3_tpu.core.kvstore import DKV
        DKV.put(self.key, self)
        return self

    def _augment(self, frame: Frame, gam_cols, nk, fit: bool) -> Frame:
        names, vecs = list(frame.names), list(frame.vecs)
        out = Frame(names, vecs)
        for ci, c in enumerate(gam_cols):
            xcol = frame.vec(c).to_numpy()
            if fit:
                k = int(nk[ci]) if ci < len(nk) else 6
                qs = np.linspace(0.02, 0.98, k)
                knots = np.unique(np.nanquantile(xcol, qs))
                self._knots[c] = knots
                self._basis_names[c] = [f"{c}_gam{j}" for j in
                                        range(len(knots))]
            B = _cr_spline_basis(xcol, self._knots[c])
            for j, bn in enumerate(self._basis_names[c]):
                out[bn] = B[:, j]
        return out

    def predict(self, test_data: Frame) -> Frame:
        aug = self._augment(test_data, self._gam_cols,
                            self.params.get("num_knots") or [], fit=False)
        return self._glm.predict(aug)

    def model_performance(self, test_data=None):
        if test_data is None:
            return self._output.training_metrics
        aug = self._augment(test_data, self._gam_cols, [], fit=False)
        return self._glm._compute_metrics(aug)

    def coef(self):
        return self._glm.coef()
