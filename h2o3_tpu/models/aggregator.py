"""Aggregator — hex/aggregator/Aggregator.java: exemplar-based compression.

Reference: single-pass exemplar assignment — a row joins an existing exemplar
if within a distance threshold (scaled by target_num_exemplars), else becomes
a new exemplar; counts kept per exemplar.

TPU-native: distance checks against the current exemplar set are batched
device matmuls; the sequential admission loop runs over mini-batches (the
reference is also sequential per chunk).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


class H2OAggregatorEstimator(ModelBase):
    algo = "aggregator"
    supervised = False
    _defaults = {
        "target_num_exemplars": 5000, "rel_tol_num_exemplars": 0.5,
        "transform": "NORMALIZE",
    }

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = np.asarray(di.matrix(frame))[: frame.nrows]
        X = np.nan_to_num(X)
        sd = X.std(axis=0)
        X = X / np.where(sd > 0, sd, 1.0)
        n, p = X.shape
        target = int(self.params["target_num_exemplars"])
        # radius heuristic: volume argument (reference uses iterative tuning)
        from math import sqrt
        span = X.max(axis=0) - X.min(axis=0)
        diam = float(np.linalg.norm(span))
        radius = diam / max(target ** (1.0 / max(p, 1)), 2.0) * 0.5
        lo_tol = self.params["rel_tol_num_exemplars"]
        for _ in range(8):  # tune radius toward the exemplar budget
            ex_idx, counts = self._sweep(X, radius)
            k = len(ex_idx)
            if abs(k - target) <= lo_tol * target or k == n:
                break
            radius *= (k / max(target, 1)) ** (1.0 / max(p, 1))
        self._exemplar_rows = ex_idx
        out_cols = {f: frame.vec(f).to_numpy()[ex_idx] for f in frame.names
                    if frame.vec(f).type != "str"}
        out_cols["counts"] = counts.astype(np.float64)
        of = Frame.from_dict(out_cols)
        self._output_frame_key = of.key
        self._output.model_summary = {"num_exemplars": len(ex_idx),
                                      "radius": radius}

    @staticmethod
    def _sweep(X, radius):
        n = X.shape[0]
        ex: list = [0]
        counts = [1]
        r2 = radius * radius
        B = 4096
        Xj = jnp.asarray(X)

        @_compat.guard_collective

        @jax.jit
        def dists(batch, E):
            return ((batch[:, None, :] - E[None]) ** 2).sum(-1)

        i = 1
        while i < n:
            j = min(i + B, n)
            n_snap = len(ex)
            E = jnp.asarray(X[ex])
            d = np.asarray(dists(Xj[i:j], E))   # (batch, n_snap)
            batch_new: list = []                # exemplars admitted this batch
            for bi in range(j - i):
                row = d[bi]
                m = int(np.argmin(row))
                best = row[m]
                if batch_new:                   # also check in-batch exemplars
                    d2 = ((X[i + bi] - X[batch_new]) ** 2).sum(-1)
                    m2 = int(np.argmin(d2))
                    if d2[m2] < best:
                        best = d2[m2]
                        m = n_snap + m2
                if best <= r2:
                    counts[m] += 1
                else:
                    batch_new.append(i + bi)
                    ex.append(i + bi)
                    counts.append(1)
            i = j
        return np.asarray(ex), np.asarray(counts)

    def aggregated_frame(self) -> Frame:
        from h2o3_tpu.core.kvstore import DKV
        return DKV.get(self._output_frame_key)

    def predict(self, test_data):
        raise NotImplementedError("Aggregator produces a frame, not predictions")
