"""ModelBuilder / Model framework — hex/ModelBuilder.java + hex/Model.java.

Reference: hex/ModelBuilder.java (param validation `init(expensive)` :1319,
n-fold CV orchestration `computeCrossValidation` :597, Driver :228),
hex/Model.java (score :1764, BigScore MRTask :2077, per-row score0 :2244,
adaptTestForTrain), hex/DataInfo.java:23 (row codec: one-hot expansion,
standardization, NA imputation).

TPU-native design:
  * A builder's Driver is a controller loop launching jitted device programs;
    "BigScore" is one jitted batch scorer over the row-sharded matrix — there
    is no per-row score0; scoring is vectorized by construction.
  * DataInfo becomes a matrix-builder: it materializes the (padded_rows ×
    nfeatures) f32 design matrix ONCE per train/score (one-hot on device via
    jax.nn.one_hot, standardization/imputation fused in the same jit).
  * CV builds fold models sequentially on the controller (each a full-mesh
    jitted program — the TPU analog of H2O building CV models in parallel on
    idle cluster CPU is keeping the chips busy with one model at a time).
"""

from __future__ import annotations

import copy
import math
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_CAT, T_NUM
from h2o3_tpu.core.jobs import Job
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.models import metrics as M
from h2o3_tpu.parallel import mesh as _mesh
from h2o3_tpu.parallel import compat as _compat


# ===========================================================================
class DataInfo:
    """Design-matrix codec (hex/DataInfo.java:23).

    cat_mode:
      * "onehot" — expand categoricals to indicator columns (GLM/DL/KMeans/PCA)
      * "label"  — keep categorical codes as one numeric column (tree algos,
                   which bin them natively)
    """

    def __init__(self, frame: Frame, x: Sequence[str], y: Optional[str],
                 cat_mode: str = "onehot", standardize: bool = False,
                 impute_missing: bool = True, weights: Optional[str] = None,
                 offset: Optional[str] = None,
                 interactions: Optional[Sequence[str]] = None):
        self.cat_mode = cat_mode
        self.standardize = standardize
        self.impute_missing = impute_missing
        self.response_name = y
        self.weights_name = weights
        self.offset_name = offset
        self.predictors = [c for c in x if c != y and frame.vec(c).type != "str"]
        self.cat_cols = [c for c in self.predictors if frame.vec(c).type == T_CAT]
        self.num_cols = [c for c in self.predictors if c not in self.cat_cols]
        self.domains = {c: list(frame.vec(c).domain) for c in self.cat_cols}
        self.cardinalities = {c: len(self.domains[c]) for c in self.cat_cols}
        # response metadata
        self.response_domain = None
        if y is not None and frame.vec(y).type == T_CAT:
            self.response_domain = list(frame.vec(y).domain)
        # normalization stats from the TRAINING frame
        self.means = {c: frame.vec(c).mean() for c in self.num_cols}
        self.sigmas = {c: frame.vec(c).sigma() or 1.0 for c in self.num_cols}
        # interactions (hex/DataInfo.java interactions / makeInteraction /
        # InteractionWrappedVec): pairwise interaction columns over the
        # listed predictors. num x num -> product column (standardized with
        # its own training stats); cat x cat -> interaction categorical
        # whose indicator block spans the level CROSS; cat x num -> one
        # numeric column per level of the categorical (the wrapped-vec
        # expansion: num value in the active level's slot, 0 elsewhere).
        self.inter_pairs: list = []      # (num_a, num_b, name)
        self.inter_catcat: list = []     # (cat_a, cat_b, name)
        self.inter_catnum: list = []     # (cat_a, num_b, name)
        if interactions:
            if cat_mode != "onehot":
                raise ValueError(
                    "interactions are only supported with the one-hot "
                    "design matrix (GLM-family models)")
            # dedupe, order-preserving: a repeated entry would emit a
            # degenerate self-pair product
            interactions = list(dict.fromkeys(interactions))
            unknown = [c for c in interactions if c not in self.predictors]
            if unknown:
                raise ValueError(
                    f"interactions reference unknown predictors: "
                    f"{unknown} (GLM interaction-column validation)")
            import itertools as _it
            for a, b in _it.combinations(interactions, 2):
                a_cat, b_cat = a in self.cat_cols, b in self.cat_cols
                if a_cat and b_cat:
                    cross = (self.cardinalities[a] * self.cardinalities[b])
                    if cross > 10_000:
                        raise ValueError(
                            f"categorical interaction {a}x{b} expands to "
                            f"{cross} indicator columns (cap 10000)")
                    self.inter_catcat.append((a, b, f"{a}_{b}"))
                elif a_cat or b_cat:
                    ca, nb = (a, b) if a_cat else (b, a)
                    self.inter_catnum.append((ca, nb, f"{ca}:{nb}"))
                else:
                    name = f"{a}:{b}"
                    self.inter_pairs.append((a, b, name))
                    prod = (frame.vec(a).as_f32()[: frame.nrows]
                            * frame.vec(b).as_f32()[: frame.nrows])
                    pn = np.asarray(prod, np.float64)
                    ok = pn[~np.isnan(pn)]
                    self.means[name] = float(ok.mean()) if len(ok) else 0.0
                    self.sigmas[name] = float(ok.std(ddof=1)) or 1.0 \
                        if len(ok) > 1 else 1.0
        # expanded feature names (coefficient_names order: cats first like H2O)
        self.feature_names: list[str] = []
        if cat_mode == "onehot":
            for c in self.cat_cols:
                self.feature_names += [f"{c}.{l}" for l in self.domains[c]]
            self.feature_names += self.num_cols
            self.feature_names += [n for _, _, n in self.inter_pairs]
            for a, b, name in self.inter_catcat:
                self.feature_names += [
                    f"{name}.{la}_{lb}" for la in self.domains[a]
                    for lb in self.domains[b]]
            for a, b, name in self.inter_catnum:
                self.feature_names += [f"{a}.{la}:{b}"
                                       for la in self.domains[a]]
        else:
            self.feature_names = list(self.predictors)

    @property
    def n_features(self) -> int:
        return len(self.feature_names)

    # ---- device-side matrix build --------------------------------------
    def raw_columns(self) -> list:
        """Column order of the RAW (pre-expansion) staging matrix consumed
        by assemble_design: cat codes first, then numerics — the serving
        fast path stages exactly these into its bucket buffer."""
        if self.cat_mode == "label":
            return list(self.predictors)
        return self.cat_cols + self.num_cols

    def _assemble(self, raw_cat, raw_num):
        """Expand raw columns into the design matrix (one-hot, standardize,
        impute, interactions). Pure traceable jnp — callable eagerly, under
        matrix()'s jit, or inside a serving scorer program."""
        cards = tuple(self.cardinalities[c] for c in self.cat_cols)
        means = np.array([self.means[c] for c in self.num_cols], np.float32)
        sigmas = np.array([max(self.sigmas[c], 1e-10)
                           for c in self.num_cols], np.float32)
        standardize = self.standardize
        inter_idx = tuple(
            (self.num_cols.index(a), self.num_cols.index(b),
             np.float32(self.means[n]),
             np.float32(max(self.sigmas[n], 1e-10)))
            for a, b, n in self.inter_pairs)
        catcat_idx = tuple(
            (self.cat_cols.index(a), self.cat_cols.index(b),
             self.cardinalities[a], self.cardinalities[b])
            for a, b, _ in self.inter_catcat)
        catnum_idx = tuple(
            (self.cat_cols.index(a), self.num_cols.index(b),
             self.cardinalities[a], np.float32(self.means[b]),
             np.float32(max(self.sigmas[b], 1e-10)))
            for a, b, _ in self.inter_catnum)
        parts = []
        if raw_cat is not None:
            for j, k in enumerate(cards):
                col = raw_cat[:, j]
                code = jnp.where(jnp.isnan(col), -1, col).astype(jnp.int32)
                parts.append(jax.nn.one_hot(code, k, dtype=jnp.float32))
        if raw_num is not None:
            x = raw_num
            if standardize:
                x = (x - means) / sigmas
            if self.impute_missing:
                fill = jnp.zeros_like(means) if standardize else means
                x = jnp.where(jnp.isnan(x), fill, x)
            parts.append(x)
        for (ia, ib, im, isg) in inter_idx:
            p = raw_num[:, ia] * raw_num[:, ib]     # RAW product
            if standardize:
                p = (p - im) / isg
            if self.impute_missing:
                p = jnp.where(jnp.isnan(p),
                              0.0 if standardize else im, p)
            parts.append(p[:, None])
        for (ia, ib, ka, kb) in catcat_idx:
            # interaction categorical: indicator over the level cross;
            # NA in either factor -> all-zero row (InteractionWrappedVec)
            ca = raw_cat[:, ia]
            cb = raw_cat[:, ib]
            bad = jnp.isnan(ca) | jnp.isnan(cb)
            code = jnp.where(
                bad, -1,
                jnp.nan_to_num(ca) * kb + jnp.nan_to_num(cb)
            ).astype(jnp.int32)
            parts.append(jax.nn.one_hot(code, ka * kb,
                                        dtype=jnp.float32))
        for (ia, ib, ka, im, isg) in catnum_idx:
            # cat x num wrapped vec: num value in the active level slot
            ca = raw_cat[:, ia]
            code = jnp.where(jnp.isnan(ca), -1, ca).astype(jnp.int32)
            x = raw_num[:, ib]
            if standardize:
                x = (x - im) / isg
            if self.impute_missing:
                x = jnp.where(jnp.isnan(x), 0.0 if standardize else im,
                              x)
            parts.append(jax.nn.one_hot(code, ka, dtype=jnp.float32)
                         * x[:, None])
        return jnp.concatenate(parts, axis=1)

    def assemble_design(self, raw):
        """raw (rows, len(raw_columns())) f32 NaN-NA → design matrix.
        Traceable; the serving scorer cache compiles it together with the
        model's _score_matrix into ONE program per (model, bucket)."""
        if self.cat_mode == "label":
            return raw
        ncat = len(self.cat_cols)
        raw_cat = raw[:, :ncat] if ncat else None
        raw_num = raw[:, ncat:] if self.num_cols else None
        return self._assemble(raw_cat, raw_num)

    def __getstate__(self):
        # the jit wrapper is derived state, rebuilt on demand; never pickled
        state = dict(self.__dict__)
        state.pop("_assemble_jit", None)
        return state

    def matrix(self, frame: Frame) -> jax.Array:
        """(padded, n_features) f32 row-sharded design matrix. NaN padding rows
        remain NaN in "label" mode; in onehot mode NAs are imputed/zeroed and
        callers must use weights() to exclude padding."""
        frame = self.adapt(frame)
        if self.cat_mode == "label":
            return frame.matrix(self.predictors)
        raw_cat = frame.matrix(self.cat_cols) if self.cat_cols else None
        raw_num = frame.matrix(self.num_cols) if self.num_cols else None
        # ONE jit wrapper per DataInfo: a fresh jax.jit(self._assemble)
        # here would have a new identity (and empty trace cache) per call
        # — the same per-call recompile hazard fixed in weights()/engine
        fn = self.__dict__.get("_assemble_jit")
        if fn is None:
            out_sh = _mesh.cloud().rows_sharding(2)
            fn = self._assemble_jit = _compat.guard_collective(
                jax.jit(self._assemble, out_shardings=out_sh))
        return fn(raw_cat, raw_num)

    def response(self, frame: Frame) -> jax.Array:
        """(padded,) f32 response; class index for categorical; NaN padding."""
        return frame.matrix([self.response_name])[:, 0]

    def weights(self, frame: Frame) -> jax.Array:
        """(padded,) f32 observation weights; 0 on padding rows and rows with
        missing response (the BigScore skip-NA contract)."""
        if self.weights_name:
            w = frame.matrix([self.weights_name])[:, 0]
            w = jnp.where(jnp.isnan(w), 0.0, w)
        else:
            w = jnp.ones(frame.padded_len, jnp.float32)
        # n is a traced scalar: the old closure-over-n jit had a fresh
        # function identity per call and recompiled on every invocation
        return _mask_padding(w, frame.nrows)

    def offset(self, frame: Frame):
        if not self.offset_name:
            return None
        o = frame.matrix([self.offset_name])[:, 0]
        return jnp.where(jnp.isnan(o), 0.0, o)

    # ---- test-frame adaptation (Model.adaptTestForTrain) ----------------
    def adapt(self, frame: Frame) -> Frame:
        """Remap categorical domains to training domains; add missing columns
        as all-NA. Returns the original frame when nothing needs adapting."""
        needed = list(self.predictors)
        if self.response_name and self.response_name in frame.names:
            needed.append(self.response_name)
        for extra in (self.weights_name, self.offset_name):
            if extra and extra in frame.names:
                needed.append(extra)
        changed = False
        names, vecs = [], []
        for c in needed:
            if c not in frame.names:
                v = Vec.from_numpy(np.full(frame.nrows, np.nan))
                changed = True
            else:
                v = frame.vec(c)
                want = self.domains.get(c) or (
                    self.response_domain if c == self.response_name else None)
                if v.type == T_CAT and want is not None and v.levels() != want:
                    v = _remap_domain(v, want)
                    changed = True
                elif v.type == T_CAT and want is None and c in self.num_cols:
                    # train saw numeric, test has cat → NA out
                    v = Vec.from_numpy(np.full(frame.nrows, np.nan))
                    changed = True
            names.append(c)
            vecs.append(v)
        if not changed and names == frame.names[: len(names)]:
            return frame
        f = Frame(names, vecs)
        DKV.remove(f.key)  # adaptation product is transient, not registered
        return f


@_compat.guard_collective


@jax.jit
def _mask_padding(w, n):
    """Zero weights on padding rows; n traced, so one compile per shape."""
    idx = jnp.arange(w.shape[0])
    return jnp.where(idx < n, w, 0.0)


def _fold_custom_metric(udf, mapped):
    """Apply the CMetricFunc 3-phase contract (water/udf): map emits per-row
    component tuples; reduce is an associative combiner folded down to the
    final aggregate. Vectorized: pairwise binary-tree halving, so jnp-math
    combiners run on device. Pre-aggregated scalars pass through unchanged."""
    tup = mapped if isinstance(mapped, tuple) else (mapped,)
    if jnp.asarray(tup[0]).ndim == 0:
        return mapped                      # map already produced the aggregate
    comps = tuple(jnp.atleast_1d(jnp.asarray(c)) for c in tup)
    while comps[0].shape[0] > 1:
        n = comps[0].shape[0]
        even = n - (n % 2)
        red = udf.reduce(tuple(c[0:even:2] for c in comps),
                         tuple(c[1:even:2] for c in comps))
        red = tuple(jnp.atleast_1d(jnp.asarray(a)) for a in red)
        if n % 2:
            red = tuple(jnp.concatenate([a, c[-1:]])
                        for a, c in zip(red, comps))
        comps = red
    agg = tuple(c[0] for c in comps)
    return agg if isinstance(mapped, tuple) else agg[0]


def _remap_domain(v: Vec, want: list) -> Vec:
    lookup = {l: i for i, l in enumerate(want)}
    src = v.to_numpy()
    dom = v.domain
    out = np.full(len(src), np.nan)
    for i, code in enumerate(src):
        if not math.isnan(code):
            out[i] = lookup.get(str(dom[int(code)]), np.nan)
    return Vec._from_floats(np.where(np.isnan(out), 0.0, out),
                            np.isnan(out), T_CAT, np.asarray(want, object))


# ===========================================================================
@dataclass
class ModelOutput:
    """hex/Model.Output analog: everything the training run learned."""
    model_id: str = ""
    algo: str = ""
    names: list = field(default_factory=list)
    domains: dict = field(default_factory=dict)
    response_domain: Optional[list] = None
    training_metrics: Optional[object] = None
    validation_metrics: Optional[object] = None
    cross_validation_metrics: Optional[object] = None
    scoring_history: list = field(default_factory=list)
    model_summary: dict = field(default_factory=dict)
    variable_importances: Optional[list] = None
    run_time_ms: int = 0
    cv_predictions_key: Optional[str] = None
    cv_fold_assignment_key: Optional[str] = None


class ModelBase:
    """Shared estimator/model surface (mirrors h2o-py H2OEstimator)."""

    algo = "base"
    supervised = True
    _defaults: dict = {}
    _COMMON = {
        "model_id": None, "seed": -1, "nfolds": 0, "weights_column": None,
        "offset_column": None, "fold_assignment": "AUTO", "fold_column": None,
        "keep_cross_validation_predictions": False,
        "keep_cross_validation_fold_assignment": False,
        "ignored_columns": None, "ignore_const_cols": True,
        "max_runtime_secs": 0.0, "standardize": True,
        "categorical_encoding": "AUTO", "distribution": "AUTO",
        "checkpoint": None, "export_checkpoints_dir": None,
        "custom_metric_func": None, "custom_distribution_func": None,
    }

    def __init__(self, **params):
        self.params = dict(self._COMMON)
        self.params.update(self._defaults)
        unknown = set(params) - set(self.params)
        if unknown:
            raise ValueError(f"{self.algo}: unknown parameters {sorted(unknown)}")
        self.params.update(params)
        self._output: Optional[ModelOutput] = None
        self._dinfo: Optional[DataInfo] = None
        self.key: Optional[str] = None

    # ---- public training entrypoint (H2OEstimator.train) ----------------
    def train(self, x=None, y=None, training_frame=None, validation_frame=None,
              **overrides) -> "ModelBase":
        self.params.update(overrides)
        frame = training_frame
        assert isinstance(frame, Frame), "training_frame must be a Frame"
        if self.supervised:
            assert y is not None, f"{self.algo} requires a response column y"
        x = self._resolve_predictors(frame, x, y)
        self._dinfo = self._make_data_info(frame, x, y)
        self.key = self.params.get("model_id") or DKV.make_key(self.algo)
        self._output = ModelOutput(model_id=self.key, algo=self.algo,
                                   names=list(x),
                                   domains=self._dinfo.domains,
                                   response_domain=self._dinfo.response_domain)
        job = Job(description=f"{self.algo} on {frame.key}", dest=self.key)
        t0 = time.time()
        mrs = float(self.params.get("max_runtime_secs") or 0.0)
        if mrs > 0:
            job.deadline = t0 + mrs
        # early stopping scores the validation frame when one is given
        # (ScoreKeeper uses validation metrics over training metrics)
        self._valid_for_scoring = validation_frame

        def work(job: Job):
            if int(self.params["nfolds"] or 0) > 1 or self.params.get("fold_column"):
                self._run_cross_validation(frame, x, y, job)
            self._fit(frame, job)
            self._score_train_valid(frame, validation_frame)
            self._output.run_time_ms = int(1000 * (time.time() - t0))
            # release validation scoring state: the margins/design matrix
            # would otherwise pin device memory for the model's lifetime
            # (and a retrain on this instance must never see stale state)
            self._vstate = None
            self._valid_for_scoring = None
            return self

        job.start(work, background=False)
        job.join()
        # drift baseline: profile the training distribution (features +
        # predictions) and register the model for live monitoring BEFORE
        # publish, so a retrain rotates generations before any request
        # can score the new one (modelmon owns the try/except — a failed
        # profile must never fail the train)
        from h2o3_tpu.obs import modelmon as _modelmon
        _modelmon.install_baseline(self, frame)
        DKV.put(self.key, self)
        # optional serving pre-warm on publish (H2O3_SCORER_PREWARM=1):
        # compile the most common row bucket in the background so the
        # first real request warm-hits instead of paying the compile
        from h2o3_tpu import serving
        if serving.prewarm_enabled():
            serving.prewarm(self)
        return self

    def _resolve_predictors(self, frame, x, y):
        if x is None:
            skip = {y, self.params.get("weights_column"),
                    self.params.get("offset_column"),
                    self.params.get("fold_column")}
            skip |= set(self.params.get("ignored_columns") or [])
            x = [c for c in frame.names if c not in skip]
        else:
            x = [frame.names[i] if isinstance(i, int) else i for i in x]
        if self.params.get("ignore_const_cols"):
            # SparseVec reuses the "const" codec for its implicit zeros:
            # it is constant only when it has NO nonzeros at all
            x = [c for c in x
                 if frame.vec(c).type == "str"
                 or getattr(frame.vec(c), "nnz", 0) > 0
                 or not (frame.vec(c).codec.kind == "const"
                         and frame.vec(c).na_cnt() == 0)]
        return x

    def _make_data_info(self, frame, x, y) -> DataInfo:
        return DataInfo(frame, x, y,
                        cat_mode=self._cat_mode(),
                        standardize=bool(self.params.get("standardize")),
                        weights=self.params.get("weights_column"),
                        offset=self.params.get("offset_column"),
                        interactions=self.params.get("interactions"))

    def _cat_mode(self) -> str:
        return "onehot"

    # ---- algo hooks ------------------------------------------------------
    def _fit(self, frame: Frame, job: Job):
        raise NotImplementedError

    def _score_matrix(self, X: jax.Array):
        """Batch score0: return regression preds (n,) or class probs (n,K)."""
        raise NotImplementedError

    # ---- mesh-sharded serving params -------------------------------------
    # Families list the instance attributes whose (pytree-of-arrays)
    # values should enter the serving scorer as SHARED DEVICE ARGUMENTS
    # instead of baked closure constants: the serving param store places
    # them once per model generation (NamedSharding over the cloud mesh,
    # PartitionSpecs from the regex rules below) and every row-bucket
    # program dispatches against that single HBM copy. Attributes that
    # are missing or None are skipped (e.g. `_trees` vs `_trees_k`
    # depending on the trained distribution). Anything the scorer
    # CONCRETIZES at trace time (float(self._f0[c]), static index lists)
    # must stay OUT of this tuple — it traces as a constant like before.
    _serving_param_attrs: tuple = ()
    # ((regex, PartitionSpec), ...) matched against '/'-joined leaf paths
    # ("_trees/value", "_params_net/0/0", …) by mesh.match_partition_rules;
    # first match wins, unmatched leaves and scalars replicate.
    _partition_rules: tuple = ()

    def _serving_params(self):
        """Param pytree for the serving fast path, or None when this
        family's scorer must close over its state (legacy baked build)."""
        attrs = self._serving_param_attrs
        if not attrs:
            return None
        p = {a: getattr(self, a, None) for a in attrs}
        p = {a: v for a, v in p.items() if v is not None}
        return p or None

    # ---- DKV lifecycle hooks ---------------------------------------------
    def _on_remove(self):
        """DKV.remove(model key): drop the model's serving residency —
        compiled programs, shared param placements on EVERY tier (HBM,
        host mirror, ice_root npz) — exactly once. Runs outside the
        `dkv` mutex (kvstore contract), so cache/param locks never nest
        under it. Idempotent: the REST DELETE handler calls
        CACHE.invalidate_key in the same breath."""
        if not self.key:
            return
        try:
            from h2o3_tpu import serving
            serving.CACHE.invalidate_key(self.key)
        except Exception:   # noqa: BLE001 — removal must not fail the DKV op
            pass
        # per-model observability series leave /metrics exactly once:
        # drift sketches + gauges (modelmon) and the usage ledger's
        # attribution rows/counters. Both are idempotent no-ops when the
        # model was never monitored/charged.
        try:
            from h2o3_tpu.obs import modelmon as _mm
            _mm.forget(self.key)
        except Exception:   # noqa: BLE001
            pass
        try:
            from h2o3_tpu.obs import usage as _usage
            _usage.forget_model(self.key)
        except Exception:   # noqa: BLE001
            pass

    def _on_replace(self):
        """A retrain overwriting this key frees the old generation's
        serving tiers like a remove — but KEEPS the monitoring series:
        modelmon retains the outgoing generation's live sketch for the
        shadow-compare (rotation happened in install_baseline), and the
        usage ledger keeps attributing to the key across generations."""
        if not self.key:
            return
        try:
            from h2o3_tpu import serving
            serving.CACHE.invalidate_key(self.key)
        except Exception:   # noqa: BLE001 — removal must not fail the DKV op
            pass

    def _score_with_params(self, params, X):
        """_score_matrix with `params` (a `_serving_params()`-shaped
        pytree, possibly of tracers) standing in for the exported
        attributes. The default grafts the params onto a SHALLOW COPY of
        the model and runs the family's own `_score_matrix` — the same
        code path as legacy scoring, so fast-path and legacy predictions
        are bit-identical by construction. The copy keeps concurrent
        legacy scorers (reading concrete attrs off `self`) safe while a
        build thread traces."""
        clone = copy.copy(self)
        for a, v in params.items():
            setattr(clone, a, v)
        return type(self)._score_matrix(clone, X)

    # ---- scoring / metrics ----------------------------------------------
    @property
    def _is_classifier(self) -> bool:
        return self.supervised and self._dinfo.response_domain is not None

    @property
    def nclasses(self) -> int:
        d = self._dinfo.response_domain if self._dinfo else None
        return len(d) if d else 1

    def predict(self, test_data: Frame) -> Frame:
        out = self._score_host(test_data)
        return self._prediction_frame(out, test_data.nrows)

    def _score_host(self, test_data: Frame) -> np.ndarray:
        """Score a frame and fetch the result to host in ONE device→host
        transfer. Serving-sized frames ride the compiled-scorer cache (no
        recompile per row count); large frames take the legacy sharded
        path, whose compile cost amortizes over the batch."""
        from h2o3_tpu import serving
        from h2o3_tpu.parallel import mrtask as _mrt
        out = serving.score_frame(self, test_data)
        if out is None:
            X = self._dinfo.matrix(test_data)
            out = _mrt.host_fetch(self._score_matrix(X))
        return out

    def _prediction_columns(self, out: np.ndarray, n: int) -> list:
        """Host-side prediction column assembly — the ONE place that maps
        raw scores to (name, float64 values, domain-or-None) columns.
        Shared by _prediction_frame and the REST row-payload route, so
        the two serving answers can never diverge. The classifier path
        slices every p<level> column out of the ONE fetched copy — there
        is exactly one device→host transfer per predict."""
        if self._is_classifier:
            probs = np.asarray(out, np.float64)[:n]
            pred = probs.argmax(axis=1).astype(np.float64)
            dom = self._dinfo.response_domain
            cols = [("predict", pred, dom)]
            cols += [(f"p{lvl}", probs[:, k], None)
                     for k, lvl in enumerate(dom)]
            return cols
        return [("predict", np.asarray(out, np.float64)[:n], None)]

    def _prediction_frame(self, out: np.ndarray, n: int) -> Frame:
        """Build the predictions Frame from host scores."""
        names, vecs = [], []
        for name, vals, dom in self._prediction_columns(out, n):
            if dom is not None:
                vecs.append(Vec._from_floats(vals, np.zeros(n, bool),
                                             T_CAT, np.asarray(dom, object)))
            else:
                vecs.append(Vec.from_numpy(vals))
            names.append(name)
        return Frame(names, vecs)

    def model_performance(self, test_data: Optional[Frame] = None):
        if test_data is None:
            return self._output.training_metrics
        return self._compute_metrics(test_data)

    def _compute_metrics(self, frame: Frame):
        from h2o3_tpu import serving
        di = self._dinfo
        fast = serving.score_frame_with_response(self, frame)
        if fast is not None:
            # bucketed fast path: host (bucket,)-shaped y/w with w=0 on
            # padding AND missing-response rows — padded rows can never
            # poison the aggregates
            out, y, w = fast
        else:
            X = di.matrix(frame)
            y = di.response(frame)
            w = di.weights(frame)
            w = jnp.where(jnp.isnan(y), 0.0, w)
            out = self._score_matrix(X)
        m = self._metrics_from_preds(y, out, w)
        cmf = self.params.get("custom_metric_func")
        if cmf and m is not None:
            # water/udf CMetricFunc 3-phase contract, traced in one program
            from h2o3_tpu.udf import resolve_udf
            udf = resolve_udf(cmf)
            # rows with w=0 (padding / missing response) must not poison the
            # aggregate: neutralize y there (0·NaN would propagate)
            ysafe = jnp.where(w > 0, jnp.nan_to_num(y), 0.0)
            agg = _fold_custom_metric(udf, udf.map(jnp.nan_to_num(out),
                                                   ysafe, w))
            m.custom_metric = {"name": udf.name,
                               "value": float(udf.metric(agg))}
        return m

    def _metrics_from_preds(self, y, out, w):
        if not self.supervised:
            return None
        if self._is_classifier and self.nclasses == 2:
            return M.binomial_metrics(y, out[:, 1], w,
                                      domain=self._dinfo.response_domain)
        if self._is_classifier:
            return M.multinomial_metrics(y, out, w,
                                         domain=self._dinfo.response_domain)
        return M.regression_metrics(y, out, w)

    def _score_train_valid(self, frame, valid):
        if not self.supervised:
            return
        self._output.training_metrics = self._compute_metrics(frame)
        if valid is not None:
            self._output.validation_metrics = self._compute_metrics(valid)

    # ---- cross-validation (ModelBuilder.computeCrossValidation :597) -----
    def _run_cross_validation(self, frame: Frame, x, y, job: Job):
        nfolds = int(self.params["nfolds"] or 0)
        fold_col = self.params.get("fold_column")
        n = frame.nrows
        if fold_col:
            fa = frame.vec(fold_col).to_numpy().astype(int)
            folds = sorted(set(fa.tolist()))
        else:
            seed = int(self.params.get("seed") or -1)
            rng = np.random.default_rng(seed if seed > 0 else None)
            if self.params.get("fold_assignment", "AUTO") in ("AUTO", "Random"):
                fa = rng.integers(0, nfolds, size=n)
            elif self.params["fold_assignment"] == "Modulo":
                fa = np.arange(n) % nfolds
            else:  # Stratified — per-class modulo on shuffled order
                yv = frame.vec(y).to_numpy()
                fa = np.zeros(n, int)
                for cls in np.unique(yv[~np.isnan(yv)]):
                    idx = np.where(yv == cls)[0]
                    rng.shuffle(idx)
                    fa[idx] = np.arange(len(idx)) % nfolds
            folds = list(range(nfolds))
        host = frame.to_numpy()
        col_data = {c: host[:, j] for j, c in enumerate(frame.names)}
        cat_doms = {c: frame.vec(c).domain for c in frame.names
                    if frame.vec(c).type == T_CAT}
        holdout_pred = None
        cv_models = []
        for fi, f in enumerate(folds):
            tr_idx = fa != f
            te_idx = ~tr_idx
            tr = _subframe(frame, col_data, cat_doms, tr_idx)
            te = _subframe(frame, col_data, cat_doms, te_idx)
            mb = self.__class__(**{k: v for k, v in self.params.items()
                                   if k not in ("nfolds", "model_id",
                                                "fold_column")})
            mb.params["nfolds"] = 0
            # the budget is shared by ALL folds + the final build — give
            # each fold what remains of the parent deadline, not a fresh
            # full allowance (ModelBuilder CV time allocation)
            if job.deadline is not None:
                mb.params["max_runtime_secs"] = max(
                    1.0, job.deadline - time.time())
            mb.train(x=x, y=y, training_frame=tr)
            cv_models.append(mb)
            pf = mb.predict(te)
            if holdout_pred is None:
                ncols_p = pf.ncols
                holdout_pred = np.full((n, ncols_p), np.nan)
            holdout_pred[te_idx] = pf.to_numpy()
            for k in (tr.key, te.key, pf.key):
                DKV.remove(k)
            job.update(0.5 * (fi + 1) / len(folds), f"CV fold {fi+1}")
        # CV metrics on the combined holdout predictions
        yv = self._dinfo.response(frame)
        w = self._dinfo.weights(frame)
        pad = frame.padded_len
        if self._is_classifier:
            probs = np.zeros((pad, self.nclasses), np.float32)
            probs[:n] = holdout_pred[:, 1:]
            out = jnp.asarray(probs)
        else:
            pr = np.zeros(pad, np.float32)
            pr[:n] = holdout_pred[:, 0]
            out = jnp.asarray(pr)
        self._output.cross_validation_metrics = self._metrics_from_preds(yv, out, w)
        self._cv_models = cv_models
        if self.params.get("keep_cross_validation_predictions"):
            cvp = Frame.from_numpy(holdout_pred[:, 1:] if self._is_classifier
                                   else holdout_pred)
            self._output.cv_predictions_key = cvp.key
        if self.params.get("keep_cross_validation_fold_assignment"):
            cvf = Frame.from_numpy(fa.astype(np.float64))
            self._output.cv_fold_assignment_key = cvf.key

    # ---- introspection ---------------------------------------------------
    def auc(self, valid=False):
        m = (self._output.validation_metrics if valid
             else self._output.training_metrics)
        return getattr(m, "auc", None)

    def logloss(self, valid=False):
        m = (self._output.validation_metrics if valid
             else self._output.training_metrics)
        return getattr(m, "logloss", None)

    def mse(self, valid=False):
        m = (self._output.validation_metrics if valid
             else self._output.training_metrics)
        return getattr(m, "mse", None)

    def rmse(self, valid=False):
        m = (self._output.validation_metrics if valid
             else self._output.training_metrics)
        return getattr(m, "rmse", None)

    @property
    def model_id(self):
        return self.key

    def summary(self):
        return self._output.model_summary if self._output else {}

    def scoring_history(self):
        return self._output.scoring_history if self._output else []

    def varimp(self, use_pandas=False):
        vi = self._output.variable_importances if self._output else None
        if vi and use_pandas:
            import pandas as pd
            return pd.DataFrame(vi)
        return vi

    # ---- explanation surface (h2o-py explain module) ---------------------
    def partial_plot(self, frame, cols=None, nbins: int = 20, plot=False):
        """h2o model.partial_plot: PDP tables for the given columns."""
        from h2o3_tpu import explain_data as EX
        cols = cols or [r["variable"] for r in (self.varimp() or [])[:2]] \
            or self._dinfo.predictors[:2]
        return [EX.partial_dependence(self, frame, c, nbins=nbins)
                for c in cols]

    def permutation_importance(self, frame, metric="AUTO", n_repeats=1,
                               seed=42):
        """h2o model.permutation_importance (PermutationVarImp.java)."""
        from h2o3_tpu import explain_data as EX
        return EX.permutation_varimp(self, frame, metric=metric,
                                     n_repeats=n_repeats, seed=seed)

    def ice_plot(self, frame, column, nbins: int = 20):
        """ICE figure (h2o-py model.ice_plot renders matplotlib)."""
        from h2o3_tpu import explain_plots as EP
        return EP.ice_plot(self, frame, column, nbins=nbins)

    def pd_plot(self, frame, column, nbins: int = 20):
        from h2o3_tpu import explain_plots as EP
        return EP.pd_plot(self, frame, column, nbins=nbins)

    def varimp_plot(self, num_of_features: int = 10):
        from h2o3_tpu import explain_plots as EP
        return EP.varimp_plot(self, num_of_features=num_of_features)

    def shap_summary_plot(self, frame, top_n: int = 20):
        from h2o3_tpu import explain_plots as EP
        return EP.shap_summary_plot(self, frame, top_n=top_n)

    def shap_explain_row_plot(self, frame, row_index: int, top_n: int = 10):
        from h2o3_tpu import explain_plots as EP
        return EP.shap_explain_row_plot(self, frame, row_index,
                                        top_n=top_n)

    def learning_curve_plot(self):
        from h2o3_tpu import explain_plots as EP
        return EP.learning_curve_plot(self)

    def explain(self, frame, columns: int = 3):
        from h2o3_tpu import explain_plots as EP
        return EP.explain(self, frame, columns=columns)

    # ---- export (h2o-genmodel surface) -----------------------------------
    def download_mojo(self, path: str, format: str = "native") -> str:
        """format="native": this framework's npz-zip artifact.
        format="h2o3": genuine reference-layout MOJO zip (tree models) that
        the stock h2o-genmodel JAR scores unmodified
        (hex/tree/SharedTreeMojoWriter.java layout)."""
        if format == "h2o3":
            from h2o3_tpu.genmodel.h2o_mojo import export_h2o_mojo
            return export_h2o_mojo(self, path)
        from h2o3_tpu.genmodel.mojo import export_mojo
        return export_mojo(self, path)

    save_mojo = download_mojo

    def download_pojo(self, path: str) -> str:
        """Generate a dependency-free Java scoring class
        (water/util/JCodeGen.java analog)."""
        from h2o3_tpu.genmodel.pojo import export_pojo
        return export_pojo(self, path)

    def save_model_details(self, path: str) -> str:
        import json
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, default=str)
        return path

    def to_dict(self):
        o = self._output
        d = {
            "model_id": self.key, "algo": self.algo,
            "params": {k: v for k, v in self.params.items() if v is not None},
            "training_metrics": o.training_metrics.to_dict() if o and o.training_metrics else None,
            "validation_metrics": o.validation_metrics.to_dict() if o and o.validation_metrics else None,
            "model_summary": o.model_summary if o else {},
        }
        # ModelOutputSchemaV3 extras the clients read off the model JSON:
        # varimp table, GLM coefficients, KMeans centers
        if o and o.variable_importances:
            d["variable_importances"] = o.variable_importances
        if o and o.scoring_history:
            d["scoring_history"] = o.scoring_history
        out = {}
        if getattr(self, "_coefficients", None):
            out["coefficients_table"] = self._coefficients
            out["coefficients_std"] = getattr(self, "_coefficients_std",
                                              None)
        if getattr(self, "_centroids", None) is not None:
            out["centers"] = np.asarray(self._centroids,
                                        np.float64).tolist()
        if out:
            d["output"] = out
        return d


def _subframe(frame: Frame, col_data, cat_doms, idx: np.ndarray) -> Frame:
    """Row-subset a frame on the host (CV fold splitting)."""
    names, vecs = [], []
    for c in frame.names:
        v = frame.vec(c)
        if v.type == "str":
            vecs.append(Vec.from_numpy(v.host_data[idx], type="str"))
        else:
            col = col_data[c][idx]
            mask = np.isnan(col)
            vecs.append(Vec._from_floats(np.where(mask, 0.0, col), mask,
                                         v.type, cat_doms.get(c)))
        names.append(c)
    return Frame(names, vecs)
