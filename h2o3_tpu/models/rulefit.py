"""RuleFit — hex/rulefit/RuleFit.java: tree-ensemble rules + sparse GLM.

Reference: fit GBM/DRF ensembles over a depth range, extract every root→node
path as a binary rule column (RuleEnsemble.java), optionally append linear
terms, then fit an L1 GLM over rule+linear features; surviving nonzero
coefficients ARE the interpretable model.

TPU-native: rule activation for all rows is the tree-walk kernel restricted
to a node prefix — evaluated as gathers over the dense heap trees; the sparse
GLM is the COD elastic-net path on the device-built Gram.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.model import ModelBase


class H2ORuleFitEstimator(ModelBase):
    algo = "rulefit"
    _defaults = {
        "min_rule_length": 3, "max_rule_length": 3, "max_num_rules": -1,
        "model_type": "rules_and_linear", "rule_generation_ntrees": 50,
        "algorithm": "AUTO",
    }

    def _fit(self, frame: Frame, job):
        from h2o3_tpu.models.tree.gbm import H2OGradientBoostingEstimator
        from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
        from h2o3_tpu.models.tree import engine as E
        import jax.numpy as jnp
        di = self._dinfo
        y = di.response_name
        ntrees = min(int(self.params["rule_generation_ntrees"]), 20)
        depths = range(int(self.params["min_rule_length"]),
                       int(self.params["max_rule_length"]) + 1)
        X = di.matrix(frame)
        rules = []       # (depth_trees, tree_idx, node_idx, description)
        rule_cols = {}
        for D in depths:
            gbm = H2OGradientBoostingEstimator(
                ntrees=ntrees, max_depth=D, seed=1, learn_rate=0.1,
                sample_rate=0.8)
            gbm.train(x=di.predictors, y=y, training_frame=frame)
            trees = getattr(gbm, "_trees", None)
            if trees is None:
                continue
            nodes, _ = E.predict_leaf_ids(X, trees)
            nodes_np = np.asarray(nodes)     # (T, n)
            cols_np = np.asarray(trees.col)
            for t in range(trees.ntrees):
                term_nodes = np.unique(nodes_np[t])
                for nd in term_nodes:
                    if cols_np[t][nd] >= 0:
                        continue
                    act = (nodes_np[t] == nd).astype(np.float64)
                    if 0.01 * len(act) < act.sum() < 0.99 * len(act):
                        name = f"rule_D{D}_T{t}_N{nd}"
                        rule_cols[name] = act[: frame.nrows]
                        rules.append({"name": name, "depth": D, "tree": t,
                                      "node": int(nd),
                                      "support": float(act.mean())})
            from h2o3_tpu.core.kvstore import DKV
            DKV.remove(gbm.key)
        mx = int(self.params.get("max_num_rules") or -1)
        if mx > 0 and len(rule_cols) > mx:
            keep = list(rule_cols)[:mx]
            rule_cols = {k: rule_cols[k] for k in keep}
        lin_cols = {}
        if "linear" in (self.params.get("model_type") or ""):
            for c in di.num_cols:
                lin_cols[f"linear_{c}"] = frame.vec(c).to_numpy()
        feats = {**rule_cols, **lin_cols}
        lf = Frame.from_dict(feats)
        lf[y] = frame.vec(y)
        fam = "binomial" if (di.response_domain and
                             len(di.response_domain) == 2) else (
            "multinomial" if di.response_domain else "gaussian")
        glm = H2OGeneralizedLinearEstimator(family=fam, alpha=1.0,
                                            lambda_search=True, nlambdas=15,
                                            max_iterations=20)
        glm.train(y=y, training_frame=lf)
        self._glm = glm
        self._rules = rules
        self._rule_names = list(feats)
        from h2o3_tpu.core.kvstore import DKV
        DKV.remove(lf.key)
        self._output.training_metrics = glm._output.training_metrics
        coefs = glm.coef() if fam != "multinomial" else {}
        active = {k: v for k, v in coefs.items()
                  if abs(v) > 1e-8 and k != "Intercept"}
        self._output.model_summary = {
            "rules_generated": len(rules),
            "rules_selected": len(active),
        }
        self._rule_importance = sorted(
            ({"rule": k, "coefficient": v} for k, v in active.items()),
            key=lambda r: -abs(r["coefficient"]))
        # keep generation artifacts for predict
        self._depths = list(depths)
        self._frame_key = frame.key

    def rule_importance(self):
        return self._rule_importance

    def predict(self, test_data: Frame) -> Frame:
        raise NotImplementedError(
            "RuleFit round-1 scope: rule extraction + sparse fit "
            "(rule_importance); transportable scoring lands with the rule "
            "re-evaluator")

    def _compute_metrics(self, frame):
        return self._output.training_metrics

    def _score_train_valid(self, frame, valid):
        pass
