"""Generic model — hex/generic/: import a MOJO as a first-class in-cluster
model (scoreable via the normal predict path / REST)."""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.genmodel.mojo import MojoModel


class H2OGenericEstimator:
    algo = "generic"

    def __init__(self, path: str = None, model_key: str = None):
        self.params = {"path": path}
        self.key = model_key or DKV.make_key("generic")
        self._scorer: MojoModel | None = None
        if path:
            self._scorer = MojoModel.load(path)
            DKV.put(self.key, self)

    def train(self, training_frame=None, **kw):
        path = kw.get("path") or self.params.get("path")
        self._scorer = MojoModel.load(path)
        DKV.put(self.key, self)
        return self

    @property
    def original_algo(self):
        return self._scorer.algo if self._scorer else None

    def predict(self, test_data: Frame) -> Frame:
        sc = self._scorer
        m = sc.meta
        rows = []
        host = {c: test_data.vec(c) for c in test_data.names}
        for i in range(test_data.nrows):
            row = {}
            for c in m["predictors"]:
                if c not in host:
                    row[c] = None
                    continue
                v = host[c]
                if v.type == "enum":
                    code = v.to_numpy()[i]
                    row[c] = None if np.isnan(code) else v.domain[int(code)]
                elif v.type == "str":
                    row[c] = v.host_data[i]
                else:
                    x = v.to_numpy()[i]
                    row[c] = None if np.isnan(x) else float(x)
            rows.append(row)
        out = sc.predict(rows)
        cols = {}
        if "probs" in out:
            dom = out["domain"]
            cols["predict"] = out["predict"]
            for k, lvl in enumerate(dom):
                cols[f"p{lvl}"] = out["probs"][:, k]
        elif "cluster" in out:
            cols["predict"] = out["cluster"].astype(np.float64)
        else:
            for k, v in out.items():
                cols[k if k != "predict" else "predict"] = v
        return Frame.from_dict(cols)
