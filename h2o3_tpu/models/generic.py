"""Generic model — hex/generic/: import a MOJO as a first-class in-cluster
model (scoreable via the normal predict path / REST).

Accepts BOTH artifact families:
  * this framework's own npz-zip MOJOs (genmodel/mojo.py), and
  * genuine reference-format H2O-3 MOJO zips (model.ini + trees/*.bin,
    hex/genmodel layout) via genmodel/h2o_mojo.py.
"""

from __future__ import annotations

import zipfile

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.genmodel.mojo import MojoModel


def _is_reference_mojo(path: str) -> bool:
    try:
        with zipfile.ZipFile(path) as z:
            return "model.ini" in z.namelist()
    except Exception:
        return False


class H2OGenericEstimator:
    algo = "generic"

    def __init__(self, path: str = None, model_key: str = None):
        self.params = {"path": path}
        self.key = model_key or DKV.make_key("generic")
        self._scorer: MojoModel | None = None
        self._ref = None                 # reference-format H2OMojoModel
        if path:
            self._load(path)
            DKV.put(self.key, self)

    def _load(self, path: str):
        if _is_reference_mojo(path):
            from h2o3_tpu.genmodel.h2o_mojo import import_h2o_mojo
            self._ref = import_h2o_mojo(path)
        else:
            self._scorer = MojoModel.load(path)

    def train(self, training_frame=None, **kw):
        path = kw.get("path") or self.params.get("path")
        self._load(path)
        DKV.put(self.key, self)
        return self

    @property
    def original_algo(self):
        if self._ref is not None:
            return self._ref.algo
        return self._scorer.algo if self._scorer else None

    def predict(self, test_data: Frame) -> Frame:
        if self._ref is not None:
            return self._predict_reference(test_data)
        sc = self._scorer
        m = sc.meta
        rows = []
        # materialize each predictor column ONCE (to_numpy/host_data are
        # device readbacks — per-row access would be O(n) each)
        cols_host = {}
        for c in m["predictors"]:
            if c not in test_data.names:
                cols_host[c] = None
                continue
            v = test_data.vec(c)
            cols_host[c] = (v.type, v.host_data if v.type == "str"
                            else v.to_numpy(), v.domain)
        for i in range(test_data.nrows):
            row = {}
            for c in m["predictors"]:
                ch = cols_host[c]
                if ch is None:
                    row[c] = None
                    continue
                vtype, data, dom = ch
                if vtype == "enum":
                    code = data[i]
                    row[c] = None if np.isnan(code) else dom[int(code)]
                elif vtype == "str":
                    row[c] = data[i]
                else:
                    x = data[i]
                    row[c] = None if np.isnan(x) else float(x)
            rows.append(row)
        out = sc.predict(rows)
        cols = {}
        if "probs" in out:
            dom = out["domain"]
            cols["predict"] = out["predict"]
            for k, lvl in enumerate(dom):
                cols[f"p{lvl}"] = out["probs"][:, k]
        elif "cluster" in out:
            cols["predict"] = out["cluster"].astype(np.float64)
        else:
            for k, v in out.items():
                cols[k if k != "predict" else "predict"] = v
        return Frame.from_dict(cols)

    # ---- reference-format MOJO scoring path ------------------------------
    def _predict_reference(self, test_data: Frame) -> Frame:
        mm = self._ref
        n = test_data.nrows
        feats = mm.columns[: mm.n_features]
        X = np.full((n, mm.n_features), np.nan, np.float32)
        for j, cname in enumerate(feats):
            if cname not in test_data.names:
                continue
            v = test_data.vec(cname)
            x = np.asarray(v.to_numpy(), np.float32)[:n]
            if v.type == "enum" and j in mm.domains:
                # remap frame levels onto the mojo's domain order
                remap = {lv: k for k, lv in enumerate(mm.domains[j])}
                codes = np.full(n, np.nan, np.float32)
                for k, lv in enumerate(v.domain):
                    codes[x == k] = remap.get(lv, np.nan)
                x = codes
            X[:, j] = x
        out = mm.predict_raw(X)
        resp_dom = mm.domains.get(len(mm.columns) - 1)
        if out.ndim == 2 and resp_dom:
            pred = np.argmax(out, axis=1).astype(np.float64)
            cols = {"predict": pred}
            for k, lvl in enumerate(resp_dom[: out.shape[1]]):
                cols[f"p{lvl}"] = out[:, k].astype(np.float64)
            return Frame.from_dict(cols)
        return Frame.from_dict({"predict": np.asarray(out, np.float64)
                                .reshape(n)})
