"""Naive Bayes — hex/naivebayes/NaiveBayes.java: one-pass conditional tables.

Reference: per-class priors + per-feature conditionals (categorical: Laplace-
smoothed count tables; numeric: per-class Gaussian mean/sd) computed in a
single MRTask; scoring is a log-space sum.

TPU-native design: all tables come from segment-sums keyed by class in one
jitted pass (psum across shards); scoring is a batched log-density matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.model import ModelBase, DataInfo
from h2o3_tpu.parallel import compat as _compat


class H2ONaiveBayesEstimator(ModelBase):
    algo = "naivebayes"
    _defaults = {
        "laplace": 0.0, "min_sdev": 0.001, "eps_sdev": 0.0,
        "min_prob": 0.001, "eps_prob": 0.0, "compute_metrics": True,
    }

    def _cat_mode(self):
        return "label"

    def _make_data_info(self, frame, x, y):
        return DataInfo(frame, x, y, cat_mode="label", standardize=False,
                        impute_missing=False,
                        weights=self.params.get("weights_column"))

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)     # label-encoded cats, NaN NAs
        y = di.response(frame)
        w = di.weights(frame)
        w = jnp.where(jnp.isnan(y), 0.0, w)
        K = self.nclasses
        yi = jnp.where(jnp.isnan(y), 0, y).astype(jnp.int32)
        lap = float(self.params.get("laplace") or 0.0)
        cat_idx = [i for i, c in enumerate(di.predictors) if c in di.cat_cols]
        num_idx = [i for i, c in enumerate(di.predictors) if c not in di.cat_cols]
        cards = [di.cardinalities[di.predictors[i]] for i in cat_idx]

        @_compat.guard_collective

        @jax.jit
        def tables(X, yi, w):
            prior = jax.ops.segment_sum(w, yi, num_segments=K)
            outs = []
            for j, card in zip(cat_idx, cards):
                col = X[:, j]
                ok = ~jnp.isnan(col)
                code = jnp.where(ok, col, 0).astype(jnp.int32)
                idx = yi * card + code
                cnt = jax.ops.segment_sum(jnp.where(ok, w, 0.0), idx,
                                          num_segments=K * card)
                outs.append(cnt.reshape(K, card))
            nsum, nssq, ncnt = [], [], []
            for j in num_idx:
                col = X[:, j]
                ok = ~jnp.isnan(col)
                wv = jnp.where(ok, w, 0.0)
                cv = jnp.where(ok, col, 0.0)
                nsum.append(jax.ops.segment_sum(wv * cv, yi, num_segments=K))
                nssq.append(jax.ops.segment_sum(wv * cv * cv, yi,
                                                num_segments=K))
                ncnt.append(jax.ops.segment_sum(wv, yi, num_segments=K))
            return prior, outs, nsum, nssq, ncnt

        prior, cat_cnt, nsum, nssq, ncnt = tables(X, yi, w)
        prior = np.asarray(prior, np.float64)
        self._priors = prior / prior.sum()
        self._cat_idx = cat_idx
        self._num_idx = num_idx
        self._cat_probs = []
        for cnt, card in zip(cat_cnt, cards):
            c = np.asarray(cnt, np.float64) + lap
            self._cat_probs.append(c / c.sum(axis=1, keepdims=True))
        min_sd = float(self.params.get("min_sdev") or 1e-3)
        self._num_mean, self._num_sd = [], []
        for s, q, c in zip(nsum, nssq, ncnt):
            s, q, c = (np.asarray(v, np.float64) for v in (s, q, c))
            m = s / np.maximum(c, 1e-30)
            var = q / np.maximum(c, 1e-30) - m * m
            sd = np.sqrt(np.maximum(var * c / np.maximum(c - 1, 1), min_sd ** 2))
            self._num_mean.append(m)
            self._num_sd.append(sd)
        self._output.model_summary = {
            "nclasses": K, "priors": self._priors.tolist(), "laplace": lap}

    def _score_matrix(self, X):
        K = self.nclasses
        logp = jnp.log(jnp.asarray(np.maximum(self._priors, 1e-300),
                                   jnp.float32))[None, :]
        parts = jnp.tile(logp, (X.shape[0], 1))
        min_prob = float(self.params.get("min_prob") or 1e-3)
        for t, j in enumerate(self._cat_idx):
            tbl = jnp.asarray(np.log(np.maximum(self._cat_probs[t], min_prob)),
                              jnp.float32)          # (K, card)
            col = X[:, j]
            ok = ~jnp.isnan(col)
            code = jnp.where(ok, col, 0).astype(jnp.int32)
            contrib = tbl.T[code]                    # (n, K)
            parts = parts + jnp.where(ok[:, None], contrib, 0.0)
        for t, j in enumerate(self._num_idx):
            m = jnp.asarray(self._num_mean[t], jnp.float32)[None, :]
            sd = jnp.asarray(self._num_sd[t], jnp.float32)[None, :]
            col = X[:, j][:, None]
            ok = ~jnp.isnan(X[:, j])
            ll = -0.5 * jnp.log(2 * jnp.pi * sd * sd) \
                - (col - m) ** 2 / (2 * sd * sd)
            parts = parts + jnp.where(ok[:, None], ll, 0.0)
        return jax.nn.softmax(parts, axis=1)
