"""Naive Bayes — hex/naivebayes/NaiveBayes.java: one-pass conditional tables.

Reference: per-class priors + per-feature conditionals (categorical: Laplace-
smoothed count tables; numeric: per-class Gaussian mean/sd) computed in a
single MRTask; scoring is a log-space sum.

TPU-native design: all tables come from segment-sums keyed by class in one
jitted pass (psum across shards); scoring is a batched log-density matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.models.model import ModelBase, DataInfo
from h2o3_tpu.parallel import compat as _compat


class H2ONaiveBayesEstimator(ModelBase):
    algo = "naivebayes"
    # mesh-sharded serving: the staged log-probability tables (not the
    # raw counts — those are concretized into tables host-side) ride as
    # shared device args. Staged lazily, so export forces the staging.
    _serving_param_attrs = ("_score_tab",)
    _defaults = {
        "laplace": 0.0, "min_sdev": 0.001, "eps_sdev": 0.0,
        "min_prob": 0.001, "eps_prob": 0.0, "compute_metrics": True,
    }

    def _serving_params(self):
        if getattr(self, "_priors", None) is None:
            return None
        self._stage_score_tables()
        return super()._serving_params()

    def _cat_mode(self):
        return "label"

    def _make_data_info(self, frame, x, y):
        return DataInfo(frame, x, y, cat_mode="label", standardize=False,
                        impute_missing=False,
                        weights=self.params.get("weights_column"))

    def _fit(self, frame: Frame, job):
        # a retrain on this instance must rebuild the staged scoring
        # tables from the NEW fit — the cache would otherwise freeze the
        # first fit's priors into every later prediction
        self._score_tab = None
        di = self._dinfo
        X = di.matrix(frame)     # label-encoded cats, NaN NAs
        y = di.response(frame)
        w = di.weights(frame)
        w = jnp.where(jnp.isnan(y), 0.0, w)
        K = self.nclasses
        yi = jnp.where(jnp.isnan(y), 0, y).astype(jnp.int32)
        lap = float(self.params.get("laplace") or 0.0)
        cat_idx = [i for i, c in enumerate(di.predictors) if c in di.cat_cols]
        num_idx = [i for i, c in enumerate(di.predictors) if c not in di.cat_cols]
        cards = [di.cardinalities[di.predictors[i]] for i in cat_idx]

        @_compat.guard_collective

        @jax.jit
        def tables(X, yi, w):
            prior = jax.ops.segment_sum(w, yi, num_segments=K)
            outs = []
            for j, card in zip(cat_idx, cards):
                col = X[:, j]
                ok = ~jnp.isnan(col)
                code = jnp.where(ok, col, 0).astype(jnp.int32)
                idx = yi * card + code
                cnt = jax.ops.segment_sum(jnp.where(ok, w, 0.0), idx,
                                          num_segments=K * card)
                outs.append(cnt.reshape(K, card))
            nsum, nssq, ncnt = [], [], []
            for j in num_idx:
                col = X[:, j]
                ok = ~jnp.isnan(col)
                wv = jnp.where(ok, w, 0.0)
                cv = jnp.where(ok, col, 0.0)
                nsum.append(jax.ops.segment_sum(wv * cv, yi, num_segments=K))
                nssq.append(jax.ops.segment_sum(wv * cv * cv, yi,
                                                num_segments=K))
                ncnt.append(jax.ops.segment_sum(wv, yi, num_segments=K))
            return prior, outs, nsum, nssq, ncnt

        prior, cat_cnt, nsum, nssq, ncnt = tables(X, yi, w)
        prior = np.asarray(prior, np.float64)
        self._priors = prior / prior.sum()
        self._cat_idx = cat_idx
        self._num_idx = num_idx
        self._cat_probs = []
        for cnt, card in zip(cat_cnt, cards):
            c = np.asarray(cnt, np.float64) + lap
            self._cat_probs.append(c / c.sum(axis=1, keepdims=True))
        min_sd = float(self.params.get("min_sdev") or 1e-3)
        self._num_mean, self._num_sd = [], []
        for s, q, c in zip(nsum, nssq, ncnt):
            s, q, c = (np.asarray(v, np.float64) for v in (s, q, c))
            m = s / np.maximum(c, 1e-30)
            var = q / np.maximum(c, 1e-30) - m * m
            sd = np.sqrt(np.maximum(var * c / np.maximum(c - 1, 1), min_sd ** 2))
            self._num_mean.append(m)
            self._num_sd.append(sd)
        self._output.model_summary = {
            "nclasses": K, "priors": self._priors.tolist(), "laplace": lap}

    def _stage_score_tables(self):
        """Host-staged scoring tables, cached on the instance: the same
        numpy math the scorer used to run at trace time (f64 clip/log,
        then f32 cast), hoisted OUT of the trace so the tables can ride
        the mesh-sharded fast path as shared device arguments. The
        serving clone swaps in a TRACED version of this dict; the `get`
        below then returns tracers and the scorer stays pure jnp."""
        tab = self.__dict__.get("_score_tab")
        if tab is not None:
            return tab
        min_prob = float(self.params.get("min_prob") or 1e-3)
        sds = [np.asarray(s, np.float32) for s in self._num_sd]
        # EVERY param-only transcendental (the log of priors, cat tables
        # and the gaussian normalizer) is computed HERE, on the host:
        # left in the trace, XLA would constant-fold it in the baked
        # build but evaluate it with runtime kernels in the shared-param
        # build — transcendentals are not correctly rounded, so the two
        # programs could differ by an ULP. Staged tables make the baked,
        # shared-param and eager paths read literally the same numbers.
        tab = self._score_tab = {
            "log_prior": np.log(np.maximum(self._priors, 1e-300)
                                ).astype(np.float32),
            "log_cat": [np.log(np.maximum(p, min_prob)).astype(np.float32)
                        for p in self._cat_probs],
            "mean": [np.asarray(m, np.float32) for m in self._num_mean],
            "gauss_log": [np.float32(-0.5)
                          * np.log(np.float32(2 * np.pi) * s * s)
                          for s in sds],
            # reciprocal staged too: a division by a CONSTANT variance
            # invites XLA's multiply-by-reciprocal rewrite, which the
            # shared-param build (runtime divisor) would not get — a
            # pre-staged multiply keeps the two programs op-for-op equal
            "inv_two_var": [np.float32(1.0) / (np.float32(2.0) * s * s)
                            for s in sds],
        }
        return tab

    def _score_matrix(self, X):
        tab = self._stage_score_tables()
        logp = jnp.asarray(tab["log_prior"])[None, :]
        parts = jnp.tile(logp, (X.shape[0], 1))
        for t, j in enumerate(self._cat_idx):
            tbl = jnp.asarray(tab["log_cat"][t])     # (K, card)
            col = X[:, j]
            ok = ~jnp.isnan(col)
            code = jnp.where(ok, col, 0).astype(jnp.int32)
            contrib = tbl.T[code]                    # (n, K)
            parts = parts + jnp.where(ok[:, None], contrib, 0.0)
        for t, j in enumerate(self._num_idx):
            m = jnp.asarray(tab["mean"][t])[None, :]
            inv2v = jnp.asarray(tab["inv_two_var"][t])[None, :]
            glog = jnp.asarray(tab["gauss_log"][t])[None, :]
            col = X[:, j][:, None]
            ok = ~jnp.isnan(X[:, j])
            ll = glog - (col - m) ** 2 * inv2v
            parts = parts + jnp.where(ok[:, None], ll, 0.0)
        return jax.nn.softmax(parts, axis=1)
