"""Segment models — hex/segments/SegmentModelsBuilder.java: one model per
data segment (distinct combination of segment-column values)."""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame
from h2o3_tpu.core.kvstore import DKV


class SegmentModels:
    def __init__(self, results: list):
        self._results = results

    def as_list(self):
        return self._results

    def __len__(self):
        return len(self._results)


def train_segments(estimator_cls, params: dict, segment_columns, x=None,
                   y=None, training_frame: Frame = None) -> SegmentModels:
    """ModelBuilder.trainSegments: split the frame by segment columns, train
    one model per segment; failures recorded per segment (not fatal)."""
    f = training_frame
    seg_cols = [segment_columns] if isinstance(segment_columns, str) \
        else list(segment_columns)
    seg_data = [f.vec(c).to_numpy() for c in seg_cols]
    seg_doms = [f.vec(c).levels() for c in seg_cols]
    keys = list(zip(*seg_data))
    uniq = sorted(set(keys), key=lambda t: tuple(-1 if v != v else v
                                                 for v in t))
    host = f.to_numpy()
    results = []
    from h2o3_tpu.models.model import _subframe
    col_data = {c: host[:, j] for j, c in enumerate(f.names)}
    cat_doms = {c: f.vec(c).domain for c in f.names
                if f.vec(c).type == "enum"}
    for seg in uniq:
        idx = np.array([k == seg for k in keys])
        label = {c: (seg_doms[i][int(seg[i])] if seg_doms[i] is not None
                     and seg[i] == seg[i] else seg[i])
                 for i, c in enumerate(seg_cols)}
        try:
            sub = _subframe(f, col_data, cat_doms, idx)
            m = estimator_cls(**params)
            m.train(x=x, y=y, training_frame=sub)
            results.append({"segment": label, "model": m.key,
                            "status": "SUCCEEDED", "nrows": int(idx.sum())})
            DKV.remove(sub.key)
        except Exception as ex:  # noqa: BLE001 — per-segment failure recorded
            results.append({"segment": label, "model": None,
                            "status": "FAILED", "error": repr(ex)})
    return SegmentModels(results)
