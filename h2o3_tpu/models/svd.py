"""SVD — hex/svd/SVD.java: distributed singular value decomposition.

Reference: power iteration with a distributed Gram MRTask (svd/SVD.java),
methods GramSVD / Power / Randomized.

TPU-native: the Gram XᵀX is one sharded matmul; eigh of the small (p×p) Gram
gives V and σ directly (GramSVD); U = XVσ⁻¹ is one more sharded matmul.
Power/Randomized collapse into the same exact path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


@_compat.guard_collective


@jax.jit
def _gram_xtx(X):
    return X.T @ X


@_compat.guard_collective


@jax.jit
def _right_multiply(X, M):
    """U = X·(V·σ⁻¹) as one resident program — the per-call jit(lambda)
    it replaces recompiled on every fit (R001)."""
    return X @ M


class H2OSingularValueDecompositionEstimator(ModelBase):
    algo = "svd"
    supervised = False
    # mesh-sharded serving: right singular vectors + stats as shared args
    _serving_param_attrs = ("_v", "_mean", "_sd")
    _defaults = {
        "nv": 1, "transform": "NONE", "svd_method": "GramSVD",
        "max_iterations": 1000, "keep_u": True,
    }

    def _make_data_info(self, frame, x, y):
        from h2o3_tpu.models.model import DataInfo
        return DataInfo(frame, x, y, cat_mode="onehot", standardize=False,
                        impute_missing=True)

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        k = int(self.params["nv"])
        transform = (self.params.get("transform") or "NONE").upper()
        Xz = jnp.where(jnp.isnan(X), 0.0, X) * (w[:, None] > 0)
        wsum = float(np.asarray(w.sum()))
        mean = np.asarray((w[:, None] * Xz).sum(axis=0)) / max(wsum, 1e-30)
        var = np.asarray((w[:, None] * (Xz - mean) ** 2).sum(axis=0)) / \
            max(wsum - 1, 1)
        sd = np.sqrt(np.maximum(var, 1e-30))
        if transform in ("DEMEAN", "STANDARDIZE"):
            Xz = Xz - jnp.asarray(mean, jnp.float32) * (w[:, None] > 0)
        if transform in ("DESCALE", "STANDARDIZE", "NORMALIZE"):
            Xz = Xz / jnp.asarray(sd, jnp.float32)
        G = _gram_xtx(Xz)
        Gn = np.asarray(G, np.float64)
        evals, evecs = np.linalg.eigh(Gn)
        order = np.argsort(-evals)
        evals = np.clip(evals[order][:k], 0, None)
        V = evecs[:, order][:, :k]
        d = np.sqrt(evals)
        self._v = V
        self._d = d
        self._transform = transform
        self._mean, self._sd = mean, sd
        if self.params.get("keep_u"):
            dinv = np.where(d > 1e-12, 1.0 / np.maximum(d, 1e-12), 0.0)
            U = np.asarray(_right_multiply(
                Xz, jnp.asarray(V * dinv[None, :],
                                jnp.float32)))[: frame.nrows]
            uf = Frame([f"u{j+1}" for j in range(k)],
                       [Vec.from_numpy(U[:, j].astype(np.float64))
                        for j in range(k)])
            self._u_key = uf.key
        self._output.model_summary = {
            "nv": k, "d": d.tolist(), "method": "GramSVD",
        }

    def _score_matrix(self, X):
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        if self._transform in ("DEMEAN", "STANDARDIZE"):
            Xz = Xz - jnp.asarray(self._mean, jnp.float32)
        if self._transform in ("DESCALE", "STANDARDIZE", "NORMALIZE"):
            Xz = Xz / jnp.asarray(self._sd, jnp.float32)
        return Xz @ jnp.asarray(self._v, jnp.float32)

    def predict(self, test_data: Frame) -> Frame:
        S = np.asarray(self._score_matrix(self._dinfo.matrix(test_data)))
        S = S[: test_data.nrows]
        return Frame([f"svd{j+1}" for j in range(S.shape[1])],
                     [Vec.from_numpy(S[:, j].astype(np.float64))
                      for j in range(S.shape[1])])

    def d(self):
        return self._d

    def v(self):
        return self._v

    def u(self) -> Frame:
        from h2o3_tpu.core.kvstore import DKV
        return DKV.get(self._u_key)
