"""Grid search — hex/grid/GridSearch.java + HyperSpaceWalker.java.

Reference: GridSearch.java:69 (driver; `_parallelism` :73), cartesian and
RandomDiscrete hyperspace walkers, grid keyed in DKV, failure tolerance (a
failed model doesn't kill the grid), and recovery: with `recovery_dir` set
every finished model is auto-checkpointed (hex/faulttolerance/Recovery.java:55
+ GridSearch recovery) and a restarted controller resumes the grid where it
died instead of rebuilding finished models.

TPU-native: `parallelism` (GridSearch.java:73) builds N models concurrently
from controller threads — XLA async dispatch interleaves their device
programs (and compile time overlaps host-side), which is the model-parallel
axis the reference exposes; the walker logic is a faithful port. Failed
builds are recorded and skipped like the reference.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from h2o3_tpu.core.kvstore import DKV

# Concurrent multi-replica dispatch on a host (CPU) mesh hangs at the
# XLA collective rendezvous; the serialization that used to live here as
# a private module lock is now owned by the shared dispatch layer
# (parallel/compat): every JIT launch takes the fine-grained
# host_collective_guard (launch→block_until_ready), and whole trains
# take compat.train_guard — still end-to-end on host meshes, because a
# training body's EAGER ops on sharded arrays (row slicing → gather
# collectives) cannot be call-site-guarded. Accelerator runtimes keep
# full overlap; on host meshes host-side work between a train's device
# launches still overlaps OTHER guarded dispatch (serving, rapids),
# just not other trains.


class H2OGridSearch:
    def __init__(self, model, hyper_params: dict, grid_id=None,
                 search_criteria=None, parallelism: int = 1,
                 recovery_dir: str | None = None):
        # `model` may be an estimator class or an instance carrying defaults
        if isinstance(model, type):
            self._cls = model
            self._base_params = {}
        else:
            self._cls = model.__class__
            self._base_params = {k: v for k, v in model.params.items()
                                 if v is not None}
        self.hyper_params = hyper_params
        self.grid_id = grid_id or DKV.make_key("grid")
        self.search_criteria = dict(search_criteria or {"strategy": "Cartesian"})
        self.models: list = []
        self.failures: list = []
        self.parallelism = max(1, int(parallelism))
        self.recovery_dir = recovery_dir
        self._lock = threading.Lock()
        DKV.put(self.grid_id, self)

    # ------------------------------------------------------------------
    def _combos(self):
        keys = sorted(self.hyper_params)
        values = [self.hyper_params[k] for k in keys]
        strat = self.search_criteria.get("strategy", "Cartesian")
        combos = [dict(zip(keys, c)) for c in itertools.product(*values)]
        if strat == "RandomDiscrete":
            seed = int(self.search_criteria.get("seed", -1))
            if seed <= 0 and self.recovery_dir:
                # recovery skips combos BY INDEX: the shuffle must reproduce
                # across a restart, so derive a stable seed from the grid id
                import zlib
                seed = zlib.crc32(self.grid_id.encode()) or 1
            rng = np.random.default_rng(seed if seed > 0 else None)
            rng.shuffle(combos)
            mx = self.search_criteria.get("max_models")
            if mx:
                combos = combos[: int(mx)]
        return combos

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, **kw):
        max_secs = float(self.search_criteria.get("max_runtime_secs", 0) or 0)
        t0 = time.time()

        # recovery (Recovery.java:55): persist inputs up-front, reload any
        # models a previous (killed) run already finished, skip their combos
        recovery = None
        recovered = set()
        if self.recovery_dir:
            from h2o3_tpu.io.persist import Recovery
            recovery = Recovery(self.recovery_dir)
            recovery.resume()
            # only THIS grid's models: the recovery dir may be shared with
            # a surrounding AutoML run (its base models live there too)
            prefix = f"{self.grid_id}_model_"
            recovered = {k for k in recovery.recovered_model_keys()
                         if k.startswith(prefix)}
            for key in recovered:
                prev = DKV.get(key)
                if prev is not None and prev.key not in \
                        {m.key for m in self.models}:
                    with self._lock:
                        self.models.append(prev)
            if training_frame is not None:
                recovery.checkpoint_frame(training_frame)
            if validation_frame is not None:
                recovery.checkpoint_frame(validation_frame)

        def build(i, combo):
            if max_secs and time.time() - t0 > max_secs:
                return                     # budget elapsed while queued
            model_id = f"{self.grid_id}_model_{i}"
            if model_id in recovered:
                return                     # finished before the restart
            params = dict(self._base_params)
            params.update(kw)
            params.update(combo)
            params["model_id"] = model_id
            try:
                m = self._cls(**params)
                from h2o3_tpu.parallel import compat as _compat
                with _compat.train_guard():
                    m.train(x=x, y=y, training_frame=training_frame,
                            validation_frame=validation_frame)
                with self._lock:
                    self.models.append(m)
                if recovery is not None:
                    recovery.checkpoint_model(m)
            except Exception as ex:  # noqa: BLE001 — grid tolerates failures
                with self._lock:
                    self.failures.append({"params": combo,
                                          "error": repr(ex)})

        combos = self._combos()
        if self.parallelism <= 1:
            for i, combo in enumerate(combos):
                if max_secs and time.time() - t0 > max_secs:
                    break
                build(i, combo)
            return self
        # model-parallel axis (GridSearch._parallelism): concurrent builds
        with ThreadPoolExecutor(max_workers=self.parallelism) as pool:
            futs = []
            for i, combo in enumerate(combos):
                if max_secs and time.time() - t0 > max_secs:
                    break
                futs.append(pool.submit(build, i, combo))
            for f in futs:
                f.result()
        return self

    # ------------------------------------------------------------------
    def get_grid(self, sort_by: str = "auc", decreasing=None):
        """Models sorted by a metric (Grid.getModels + Leaderboard sort)."""
        if decreasing is None:
            decreasing = sort_by in ("auc", "pr_auc", "r2", "accuracy", "f1")

        def metric(m):
            src = (m._output.cross_validation_metrics
                   or m._output.validation_metrics
                   or m._output.training_metrics)
            v = getattr(src, sort_by, None)
            return v if v is not None else float("inf")

        return sorted(self.models, key=metric, reverse=decreasing)

    @property
    def model_ids(self):
        return [m.key for m in self.models]

    def __len__(self):
        return len(self.models)
