"""Parameter documentation attached to every estimator class —
the h2o-py generated-docstring surface (h2o-bindings gen_python.py emits
one documented property per parameter; here one shared table renders a
parameter section into each estimator's __doc__ at import, so
``help(H2OGradientBoostingEstimator)`` reads like the reference's).

Descriptions are condensed from the reference schema help strings
(water/api/API.java help= annotations across */ModelParametersSchemaV3).
"""

from __future__ import annotations

PARAM_DOCS = {
    # shared ModelBuilder surface (ModelParametersSchemaV3)
    "model_id": "Destination key for the model (auto-generated when None).",
    "seed": "RNG seed for sampling/initialization; -1 = time-based.",
    "nfolds": "Number of cross-validation folds (0 = none).",
    "fold_assignment": "CV fold scheme: AUTO, Random, Modulo, Stratified.",
    "fold_column": "Column holding explicit fold indices for CV.",
    "keep_cross_validation_predictions":
        "Retain per-fold holdout predictions (needed for stacking).",
    "keep_cross_validation_fold_assignment":
        "Retain the fold-assignment frame.",
    "weights_column": "Observation weights column.",
    "offset_column": "Per-row model offset column (GLM/GBM margins).",
    "ignored_columns": "Columns excluded from training.",
    "ignore_const_cols": "Drop constant columns before training.",
    "max_runtime_secs": "Wall-clock budget for the build (0 = unlimited).",
    "standardize": "Standardize numeric columns to zero mean/unit variance.",
    "categorical_encoding": "Categorical handling (AUTO = algo default).",
    "distribution": "Loss family (AUTO resolves from the response type).",
    "checkpoint": "Model key to resume training from.",
    "export_checkpoints_dir": "Directory receiving per-iteration exports.",
    "custom_metric_func": "UDF computing an extra scoring metric.",
    "custom_distribution_func": "UDF loss (gradient/link) for boosting.",
    # tree family (SharedTreeV3 + GBMV3/DRFV3)
    "ntrees": "Number of trees (TOTAL, including a checkpoint's).",
    "max_depth": "Maximum tree depth.",
    "min_rows": "Minimum observation weight in a leaf.",
    "learn_rate": "Boosting shrinkage (GBM/XGBoost eta).",
    "sample_rate": "Row sample rate per tree.",
    "col_sample_rate": "Column sample rate per split level.",
    "col_sample_rate_per_tree": "Column sample rate per tree.",
    "nbins": "Histogram bins for numeric splits.",
    "nbins_cats": "Histogram bins for categorical splits.",
    "nbins_top_level": "Root-level bins (halve per level to nbins).",
    "min_split_improvement": "Minimum relative SE improvement to split.",
    "histogram_type": "Binning scheme (AUTO/UniformAdaptive/QuantilesGlobal).",
    "score_tree_interval": "Score every this-many trees.",
    "stopping_rounds": "Early-stop after this many non-improving scores.",
    "stopping_metric": "Metric driving early stopping.",
    "stopping_tolerance": "Relative improvement below which to stop.",
    "monotone_constraints": "Per-column {+1,-1} monotonicity constraints.",
    "calibrate_model": "Fit a Platt calibration model on holdout data.",
    "balance_classes": "Over/under-sample to balance class counts.",
    "mtries": "Columns tried per split (DRF; -1 = sqrt(p)).",
    "binomial_double_trees": "DRF: build one tree per class for binomial.",
    "reg_lambda": "L2 regularization on leaf weights (XGBoost lambda).",
    "reg_alpha": "L1 regularization on leaf weights (XGBoost alpha).",
    "booster": "gbtree or dart.",
    "rate_drop": "DART: per-iteration tree dropout rate.",
    "one_drop": "DART: always drop at least one tree.",
    "skip_drop": "DART: probability of skipping dropout entirely.",
    "tree_method": "hist (the TPU engine implements hist semantics).",
    "scale_pos_weight": "Positive-class gradient weight (imbalance).",
    # GLM family (GLMV3)
    "family": "Response family (gaussian, binomial, poisson, ...).",
    "link": "Link function (family_default resolves canonically).",
    "solver": "IRLSM, L_BFGS, COORDINATE_DESCENT or AUTO.",
    "alpha": "Elastic-net mixing (0 = ridge, 1 = lasso).",
    "lambda_": "Regularization strength (list = explicit path).",
    "lambda_search": "Fit a full regularization path.",
    "nlambdas": "Path length when lambda_search is on.",
    "lambda_min_ratio": "Smallest lambda as a ratio of lambda_max.",
    "beta_constraints": "Frame of per-coefficient bounds.",
    "compute_p_values": "Compute z/p-values (unpenalized fits).",
    "remove_collinear_columns": "Drop collinear columns before fitting.",
    "intercept": "Fit an intercept term.",
    "prior": "Prior probability of class 1 (binomial offset).",
    "tweedie_variance_power": "Tweedie variance power.",
    "tweedie_link_power": "Tweedie link power.",
    "interactions": "Columns whose pairwise interactions enter the design.",
    "max_iterations": "Solver iteration cap.",
    "objective_epsilon": "Relative objective convergence threshold.",
    "beta_epsilon": "Coefficient-change convergence threshold (IRLSM).",
    # DL family (DeepLearningV3)
    "hidden": "Hidden-layer sizes, e.g. [200, 200].",
    "epochs": "Passes over the training frame.",
    "activation": "Rectifier, Tanh, Maxout (+WithDropout variants).",
    "rho": "ADADELTA decay factor.",
    "epsilon": "ADADELTA smoothing constant.",
    "rate": "Learning rate (when adaptive_rate is off).",
    "momentum_start": "Initial momentum (plain SGD).",
    "input_dropout_ratio": "Dropout on the input layer.",
    "hidden_dropout_ratios": "Per-hidden-layer dropout.",
    "l1": "L1 weight penalty.",
    "l2": "L2 weight penalty.",
    "max_w2": "Squared-norm cap per neuron's incoming weights.",
    "autoencoder": "Train an autoencoder instead of a supervised net.",
    "mini_batch_size": "Rows per SGD minibatch.",
    "adaptive_rate": "Use ADADELTA instead of fixed-rate SGD.",
    # KMeans / PCA / dimensionality
    "k": "Number of clusters / components.",
    "init": "Initialization scheme (PlusPlus, Furthest, Random, User).",
    "estimate_k": "Find k up to the given maximum.",
    "user_points": "Frame of user-supplied initial centers.",
    "transform": "Column transform (NONE/STANDARDIZE/NORMALIZE/...).",
    "pca_method": "GramSVD / Power / Randomized.",
    # misc families
    "ntrees_isolation": "Isolation trees.",
    "sample_size": "Rows per isolation tree.",
    "laplace": "Naive Bayes Laplace smoothing.",
    "min_sdev": "Naive Bayes minimum per-feature std deviation.",
    "gamma": "Kernel width (PSVM) / min split loss (XGBoost alias).",
    "hyper_param": "SVM penalty C.",
    "kernel_type": "SVM kernel (gaussian via random Fourier features).",
    "rank_ratio": "ICF/feature-map rank as a fraction of n.",
    "min_word_freq": "Word2Vec vocabulary frequency floor.",
    "vec_size": "Word2Vec embedding width.",
    "window_size": "Word2Vec context window.",
    "sent_sample_rate": "Word2Vec frequent-word downsampling.",
    "epochs_w2v": "Word2Vec passes.",
    "stratify_by": "CoxPH strata columns.",
    "ties": "CoxPH tie handling (efron or breslow).",
    "num_knots": "GAM spline knots per column.",
    "gam_columns": "Columns receiving spline bases.",
    "scale": "GAM smoothing penalty scale.",
    "metalearner_algorithm": "Stacked-ensemble combiner algorithm.",
    "base_models": "Stacked-ensemble base model keys.",
    "data_leakage_handling": "Target encoding strategy (none/loo/kfold).",
    "blending": "Target encoding: shrink level means toward the prior.",
    "inflection_point": "TE blending inflection point (rows).",
    "smoothing": "TE blending smoothing.",
    "noise": "TE uniform noise half-width applied in training.",
}


def document(cls) -> None:
    """Append a generated parameter section to an estimator's __doc__."""
    params = dict(getattr(cls, "_COMMON", {}), **getattr(cls, "_defaults", {}))
    if not params:
        return
    lines = ["", "Parameters", "----------"]
    for name in sorted(params):
        desc = PARAM_DOCS.get(name)
        dflt = params[name]
        lines.append(f"{name} : default {dflt!r}")
        if desc:
            lines.append(f"    {desc}")
    cls.__doc__ = (cls.__doc__ or cls.__name__) + "\n" + "\n".join(lines)
