"""Stacked Ensemble — hex/ensemble/StackedEnsemble.java + Metalearners.java.

Reference: base models trained with keep_cross_validation_predictions; the
"level-one" frame is the column-bound CV holdout predictions; a metalearner
(default GLM with non-negative weights for regression/binomial) is trained on
it; scoring = metalearner over base-model predictions.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.kvstore import DKV
from h2o3_tpu.models.model import ModelBase


class H2OStackedEnsembleEstimator(ModelBase):
    algo = "stackedensemble"
    _defaults = {
        "base_models": None, "metalearner_algorithm": "AUTO",
        "metalearner_nfolds": 0, "metalearner_params": None,
    }

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        base = self.params.get("base_models") or []
        base = [DKV.get(b) if isinstance(b, str) else b for b in base]
        if not base:
            raise ValueError("stackedensemble requires base_models")
        self._base = base
        # level-one frame from CV holdout predictions
        cols = {}
        for m in base:
            cvk = m._output.cv_predictions_key
            if cvk is None:
                raise ValueError(
                    f"base model {m.key} lacks keep_cross_validation_predictions")
            cvp = DKV.get(cvk)
            arr = cvp.to_numpy()
            if self._is_classifier and self.nclasses == 2:
                cols[f"{m.key}"] = arr[:, -1]      # P(class1)
            elif self._is_classifier:
                for k in range(arr.shape[1]):
                    cols[f"{m.key}_p{k}"] = arr[:, k]
            else:
                cols[f"{m.key}"] = arr[:, 0]
        cols[di.response_name] = frame.vec(di.response_name).to_numpy() \
            if frame.vec(di.response_name).type != "enum" else None
        yv = frame.vec(di.response_name)
        l1 = Frame.from_dict({k: v for k, v in cols.items() if v is not None})
        l1[di.response_name] = yv
        # metalearner (Metalearners.java default: GLM)
        algo = (self.params.get("metalearner_algorithm") or "AUTO").lower()
        mp = dict(self.params.get("metalearner_params") or {})
        if algo in ("auto", "glm"):
            from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
            mp.setdefault("lambda_", 0.0)
            if not self._is_classifier or self.nclasses == 2:
                mp.setdefault("non_negative", True)
            meta = H2OGeneralizedLinearEstimator(**mp)
        elif algo == "gbm":
            from h2o3_tpu.models.tree.gbm import H2OGradientBoostingEstimator
            meta = H2OGradientBoostingEstimator(**mp)
        elif algo == "drf":
            from h2o3_tpu.models.tree.drf import H2ORandomForestEstimator
            meta = H2ORandomForestEstimator(**mp)
        elif algo == "deeplearning":
            from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
            meta = H2ODeepLearningEstimator(**mp)
        else:
            raise ValueError(f"metalearner {algo}")
        meta.train(y=di.response_name, training_frame=l1)
        self._meta = meta
        DKV.remove(l1.key)
        self._output.model_summary = {
            "base_models": [m.key for m in base],
            "metalearner": meta.algo,
        }

    def _level_one(self, test: Frame) -> Frame:
        cols = {}
        for m in self._base:
            p = m.predict(test)
            arr = p.to_numpy()
            if self._is_classifier and self.nclasses == 2:
                cols[f"{m.key}"] = arr[:, -1]
            elif self._is_classifier:
                for k in range(arr.shape[1] - 1):
                    cols[f"{m.key}_p{k}"] = arr[:, 1 + k]
            else:
                cols[f"{m.key}"] = arr[:, 0]
            DKV.remove(p.key)
        return Frame.from_dict(cols)

    def predict(self, test_data: Frame) -> Frame:
        l1 = self._level_one(test_data)
        out = self._meta.predict(l1)
        DKV.remove(l1.key)
        return out

    def _compute_metrics(self, frame: Frame):
        l1 = self._level_one(frame)
        l1[self._dinfo.response_name] = frame.vec(self._dinfo.response_name)
        m = self._meta._compute_metrics(l1)
        DKV.remove(l1.key)
        return m
