"""PCA / SVD — hex/pca/PCA.java + hex/svd/SVD.java, XLA-native linear algebra.

Reference: PCA via distributed Gram + eigendecomposition with native
BLAS/LAPACK backends (hex/pca/jama, hex/pca/mtj, netlib natives —
h2o-algos/build.gradle:12-24), pca_method ∈ {GramSVD, Power, Randomized,
GLRM}; SVD power iteration with a distributed Gram (hex/svd/SVD.java).

TPU-native design: the Gram XᵀX is ONE sharded matmul (psum over ICI); the
(p×p) eigendecomposition runs with jnp.linalg.eigh — XLA replaces the JNI
netlib stack entirely. Power/Randomized methods collapse into the same path
(exact eigh of the small Gram is cheaper than iterating on TPU); GLRM method
delegates to the GLRM module.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


@_compat.guard_collective


@jax.jit
def _gram(Xz, w):
    Xw = Xz * w[:, None]
    return Xz.T @ Xw, w.sum()


@_compat.guard_collective


@jax.jit
def _project(Xz, R):
    """Score projection — module-level so repeated predicts replay one
    program (a per-call jit(lambda) here recompiled every request: R001)."""
    return Xz @ R


class H2OPrincipalComponentAnalysisEstimator(ModelBase):
    algo = "pca"
    supervised = False
    # mesh-sharded serving: rotation + normalization stats as shared
    # device args (transform kind stays static trace structure)
    _serving_param_attrs = ("_rotation", "_mean", "_sd")
    _defaults = {
        "k": 1, "transform": "NONE", "pca_method": "GramSVD",
        "use_all_factor_levels": False, "compute_metrics": True,
        "impute_missing": True, "max_iterations": 1000,
    }

    def _make_data_info(self, frame, x, y):
        # PCA owns its `transform` param — keep DataInfo raw (mean-impute only)
        from h2o3_tpu.models.model import DataInfo
        return DataInfo(frame, x, y, cat_mode="onehot", standardize=False,
                        impute_missing=True,
                        weights=self.params.get("weights_column"))

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        # transform: NONE|STANDARDIZE|NORMALIZE|DEMEAN|DESCALE
        transform = (self.params.get("transform") or "NONE").upper()
        X = di.matrix(frame)
        w = di.weights(frame)
        k = int(self.params["k"])
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        wsum = float(np.asarray(w.sum()))
        mean = np.asarray((w[:, None] * Xz).sum(axis=0)) / wsum
        var = np.asarray((w[:, None] * (Xz - mean) ** 2).sum(axis=0)) / max(wsum - 1, 1)
        sd = np.sqrt(np.maximum(var, 1e-30))
        if transform in ("DEMEAN", "STANDARDIZE"):
            Xz = Xz - jnp.asarray(mean, jnp.float32)
        if transform in ("DESCALE", "STANDARDIZE", "NORMALIZE"):
            Xz = Xz / jnp.asarray(sd, jnp.float32)
        Xz = Xz * (w[:, None] > 0)
        G, _ = _gram(Xz, w)
        Gn = np.asarray(G, np.float64) / max(wsum - 1, 1.0)
        evals, evecs = np.linalg.eigh(Gn)
        order = np.argsort(-evals)
        evals = np.clip(evals[order][:k], 0, None)
        evecs = evecs[:, order][:, :k]
        # sign convention: largest-magnitude loading positive
        for j in range(evecs.shape[1]):
            i = np.argmax(np.abs(evecs[:, j]))
            if evecs[i, j] < 0:
                evecs[:, j] = -evecs[:, j]
        self._mean = mean
        self._sd = sd
        self._transform = transform
        self._rotation = evecs
        tot_var = float(np.trace(Gn))
        sdev = np.sqrt(evals)
        self._output.model_summary = {
            "k": k,
            "std_deviation": sdev.tolist(),
            "proportion_of_variance": (evals / tot_var).tolist() if tot_var else [],
            "cumulative_proportion": np.cumsum(evals / tot_var).tolist() if tot_var else [],
        }
        self._output.variable_importances = [
            {"pc": f"PC{j+1}", "std_dev": float(sdev[j])} for j in range(k)]

    def _apply_transform(self, X):
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        if self._transform in ("DEMEAN", "STANDARDIZE"):
            Xz = Xz - jnp.asarray(self._mean, jnp.float32)
        if self._transform in ("DESCALE", "STANDARDIZE", "NORMALIZE"):
            Xz = Xz / jnp.asarray(self._sd, jnp.float32)
        return Xz

    def _score_matrix(self, X):
        R = jnp.asarray(self._rotation, jnp.float32)
        return _project(self._apply_transform(X), R)

    def predict(self, test_data: Frame) -> Frame:
        X = self._dinfo.matrix(test_data)
        S = np.asarray(self._score_matrix(X))[: test_data.nrows]
        names = [f"PC{j+1}" for j in range(S.shape[1])]
        return Frame(names, [Vec.from_numpy(S[:, j].astype(np.float64))
                             for j in range(S.shape[1])])

    def rotation(self) -> np.ndarray:
        return self._rotation
