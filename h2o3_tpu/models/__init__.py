"""Model builders (the hex.* algorithm layer rebuilt TPU-native)."""

from h2o3_tpu.models.kmeans import H2OKMeansEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.tree.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.tree.drf import H2ORandomForestEstimator
from h2o3_tpu.models.tree.isofor import H2OIsolationForestEstimator
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator
from h2o3_tpu.models.naive_bayes import H2ONaiveBayesEstimator
from h2o3_tpu.models.svd import H2OSingularValueDecompositionEstimator
from h2o3_tpu.models.aggregator import H2OAggregatorEstimator
from h2o3_tpu.models.ensemble import H2OStackedEnsembleEstimator
from h2o3_tpu.models.grid import H2OGridSearch
from h2o3_tpu.models.target_encoder import H2OTargetEncoderEstimator
from h2o3_tpu.models.word2vec import H2OWord2vecEstimator
from h2o3_tpu.models.coxph import H2OCoxProportionalHazardsEstimator
from h2o3_tpu.models.extended_isofor import H2OExtendedIsolationForestEstimator
from h2o3_tpu.models.gam import H2OGeneralizedAdditiveEstimator
from h2o3_tpu.models.rulefit import H2ORuleFitEstimator
from h2o3_tpu.models.generic import H2OGenericEstimator
from h2o3_tpu.models.segments import train_segments, SegmentModels
from h2o3_tpu.models.psvm import H2OSupportVectorMachineEstimator
from h2o3_tpu.models.tree.xgboost import H2OXGBoostEstimator
from h2o3_tpu.models.infogram import H2OInfogram

# generated parameter docs (h2o-bindings gen_python.py docstring surface)
from h2o3_tpu.models.param_docs import document as _document

ESTIMATORS = {
    "kmeans": H2OKMeansEstimator,
    "glm": H2OGeneralizedLinearEstimator,
    "gbm": H2OGradientBoostingEstimator,
    "drf": H2ORandomForestEstimator,
    "isolationforest": H2OIsolationForestEstimator,
    "deeplearning": H2ODeepLearningEstimator,
    "pca": H2OPrincipalComponentAnalysisEstimator,
    "glrm": H2OGeneralizedLowRankEstimator,
    "naivebayes": H2ONaiveBayesEstimator,
    "svd": H2OSingularValueDecompositionEstimator,
    "aggregator": H2OAggregatorEstimator,
    "stackedensemble": H2OStackedEnsembleEstimator,
    "targetencoder": H2OTargetEncoderEstimator,
    "word2vec": H2OWord2vecEstimator,
    "coxph": H2OCoxProportionalHazardsEstimator,
    "extendedisolationforest": H2OExtendedIsolationForestEstimator,
    "gam": H2OGeneralizedAdditiveEstimator,
    "rulefit": H2ORuleFitEstimator,
    "generic": H2OGenericEstimator,
    "psvm": H2OSupportVectorMachineEstimator,
    "xgboost": H2OXGBoostEstimator,
}

for _cls in set(ESTIMATORS.values()):
    _document(_cls)
