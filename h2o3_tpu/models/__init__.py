"""Model builders (the hex.* algorithm layer rebuilt TPU-native)."""

from h2o3_tpu.models.kmeans import H2OKMeansEstimator
from h2o3_tpu.models.glm import H2OGeneralizedLinearEstimator
from h2o3_tpu.models.tree.gbm import H2OGradientBoostingEstimator
from h2o3_tpu.models.tree.drf import H2ORandomForestEstimator
from h2o3_tpu.models.tree.isofor import H2OIsolationForestEstimator
from h2o3_tpu.models.deeplearning import H2ODeepLearningEstimator
from h2o3_tpu.models.pca import H2OPrincipalComponentAnalysisEstimator
from h2o3_tpu.models.glrm import H2OGeneralizedLowRankEstimator
from h2o3_tpu.models.naive_bayes import H2ONaiveBayesEstimator

ESTIMATORS = {
    "kmeans": H2OKMeansEstimator,
    "glm": H2OGeneralizedLinearEstimator,
    "gbm": H2OGradientBoostingEstimator,
    "drf": H2ORandomForestEstimator,
    "isolationforest": H2OIsolationForestEstimator,
    "deeplearning": H2ODeepLearningEstimator,
    "pca": H2OPrincipalComponentAnalysisEstimator,
    "glrm": H2OGeneralizedLowRankEstimator,
    "naivebayes": H2ONaiveBayesEstimator,
}
