"""Target Encoding — h2o-extensions/target-encoder (ai.h2o.targetencoding).

Reference: TargetEncoder.java — per categorical column, replace levels by the
(blended) mean response computed with a leakage-control strategy:
  * "none"       — global per-level means
  * "loo"        — leave-one-out (row's own response excluded)
  * "kfold"      — means computed out-of-fold
Blending shrinks small-level means toward the prior:
  λ = 1 / (1 + exp(-(n - k) / f))  (inflection_point k, smoothing f).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_CAT


class H2OTargetEncoderEstimator:
    algo = "targetencoder"

    def __init__(self, data_leakage_handling="none", blending=False,
                 inflection_point=10.0, smoothing=20.0, noise=0.0,
                 seed=-1, fold_column=None, columns_to_encode=None):
        self.params = dict(data_leakage_handling=data_leakage_handling.lower(),
                           blending=blending,
                           inflection_point=inflection_point,
                           smoothing=smoothing, noise=noise, seed=seed,
                           fold_column=fold_column,
                           columns_to_encode=columns_to_encode)
        self._encodings: dict = {}
        self._prior = 0.0
        self._y = None

    def train(self, x=None, y=None, training_frame=None, **kw):
        f = training_frame
        self._y = y
        yv = f.vec(y)
        yn = yv.to_numpy()
        if yv.type == T_CAT:
            if len(yv.levels()) != 2:
                raise ValueError("target encoding supports numeric or binary response")
        ok = ~np.isnan(yn)
        self._prior = float(yn[ok].mean())
        cols = self.params["columns_to_encode"] or [
            c for c in (x or f.names)
            if c != y and f.vec(c).type == T_CAT]
        self._cols = [c if isinstance(c, str) else f.names[c] for c in cols]
        fold_col = self.params["fold_column"]
        folds = None
        if fold_col and fold_col in f.names and \
                self.params["data_leakage_handling"] == "kfold":
            folds = f.vec(fold_col).to_numpy().astype(int)
            self._nfolds = int(folds.max()) + 1
        for c in self._cols:
            v = f.vec(c)
            codes = v.to_numpy()
            dom = v.levels()
            nd = len(dom)
            sel = ok & ~np.isnan(codes)
            ci = codes[sel].astype(np.int64)
            sums = np.bincount(ci, weights=yn[sel], minlength=nd)
            cnts = np.bincount(ci, minlength=nd).astype(np.float64)
            enc = {"domain": dom, "sums": sums, "counts": cnts}
            if folds is not None:
                # per-fold sums/counts in one bincount pass over the
                # joint (fold, level) key: the kfold encoding of a row
                # is total minus its own fold's contribution
                key = folds[sel] * nd + ci
                fs = np.bincount(key, weights=yn[sel],
                                 minlength=self._nfolds * nd)
                fc = np.bincount(key, minlength=self._nfolds * nd)
                enc["fold_sums"] = fs.reshape(self._nfolds, nd)
                enc["fold_counts"] = fc.reshape(self._nfolds,
                                                nd).astype(np.float64)
            self._encodings[c] = enc
        return self

    def _encode_col(self, c, codes, yn=None, folds=None):
        enc = self._encodings[c]
        sums, cnts = enc["sums"].copy(), enc["counts"].copy()
        out = np.full(len(codes), self._prior)
        mode = self.params["data_leakage_handling"]
        blend = self.params["blending"]
        k = self.params["inflection_point"]
        fsm = self.params["smoothing"]

        def blended(s, n):
            if n <= 0:
                return self._prior
            mean = s / n
            if not blend:
                return mean
            lam = 1.0 / (1.0 + np.exp(-(n - k) / fsm))
            return lam * mean + (1 - lam) * self._prior

        fold_s = enc.get("fold_sums")
        for i, code in enumerate(codes):
            if np.isnan(code):
                continue
            lvl = int(code)
            s, n = sums[lvl], cnts[lvl]
            if mode == "leave_one_out" or mode == "loo":
                if yn is not None and not np.isnan(yn[i]):
                    s, n = s - yn[i], n - 1
            elif mode == "kfold" and folds is not None and fold_s is not None:
                fo = folds[i]
                s = s - fold_s[fo, lvl]
                n = n - enc["fold_counts"][fo, lvl]
            out[i] = blended(s, n)
        noise = self.params["noise"]
        if noise and yn is not None:
            seed = self.params["seed"]
            rng = np.random.default_rng(seed if seed > 0 else None)
            out = out + rng.uniform(-noise, noise, len(out))
        return out

    def transform(self, frame: Frame, as_training=False) -> Frame:
        names, vecs = list(frame.names), list(frame.vecs)
        yn = frame.vec(self._y).to_numpy() if (
            as_training and self._y in frame.names) else None
        fold_col = self.params["fold_column"]
        folds = None
        if as_training and fold_col and fold_col in frame.names and \
                self.params["data_leakage_handling"] == "kfold":
            folds = frame.vec(fold_col).to_numpy().astype(int)
        out = Frame(names, vecs)
        for c in self._cols:
            if c not in frame.names:
                continue
            codes = frame.vec(c).to_numpy()
            enc_col = self._encode_col(c, codes, yn=yn, folds=folds)
            out[f"{c}_te"] = enc_col
        return out
