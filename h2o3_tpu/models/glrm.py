"""GLRM — hex/glrm/GLRM.java: low-rank X ≈ A·B via alternating minimization.

Reference: GLRM alternating updates of the archetype matrix Y (k×p, shared)
and per-row X coefficients with pluggable losses/regularizers; used both for
dimensionality reduction and missing-value imputation.

TPU-native design: with quadratic loss + L2 regularizers the alternating
steps are closed-form ridge solves: A = XBᵀ(BBᵀ+γI)⁻¹ (row-sharded matmul),
B = (AᵀA+γI)⁻¹AᵀX (k×k solve on controller, AᵀX a psum-reduced matmul). Other
losses fall back to gradient steps. NAs contribute zero loss via a weight
mask (no imputation needed — the reference's key GLRM property).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


class H2OGeneralizedLowRankEstimator(ModelBase):
    algo = "glrm"
    supervised = False
    _defaults = {
        "k": 1, "loss": "Quadratic", "regularization_x": "None",
        "regularization_y": "None", "gamma_x": 0.0, "gamma_y": 0.0,
        "max_iterations": 1000, "init": "PlusPlus", "transform": "NONE",
        "recover_svd": False, "min_step_size": 1e-4,
    }

    def _make_data_info(self, frame, x, y):
        # GLRM owns its `transform` handling and trains on OBSERVED entries
        # only — no standardization or NA imputation in the design matrix.
        from h2o3_tpu.models.model import DataInfo
        return DataInfo(frame, x, y, cat_mode="onehot", standardize=False,
                        impute_missing=False,
                        weights=self.params.get("weights_column"))

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        k = int(self.params["k"])
        max_it = min(int(self.params["max_iterations"]), 300)
        gx = float(self.params.get("gamma_x") or 0.0)
        gy = float(self.params.get("gamma_y") or 0.0)
        seed = int(self.params.get("seed") or -1)
        rng = np.random.default_rng(seed if seed > 0 else 7)
        obs = (~jnp.isnan(X)) & (w[:, None] > 0)   # observed-entry mask
        M = obs.astype(jnp.float32)
        Xz = jnp.where(obs, X, 0.0)
        n, p = X.shape
        B = jnp.asarray(rng.normal(0, 0.1, (k, p)), jnp.float32)
        A = jnp.zeros((n, k), jnp.float32)

        @_compat.guard_collective

        @jax.jit
        def step_A(Xz, M, B):
            # exact masked per-row ridge: A_r = (B·diag(m_r)·Bᵀ+γI)⁻¹ B(m_r·x_r)
            # batched k×k solves — tiny per row, vmapped on device
            G = jnp.einsum("ki,ni,li->nkl", B, M, B) \
                + (gx + 1e-6) * jnp.eye(k)[None]
            rhs = (Xz * M) @ B.T
            return jax.vmap(jnp.linalg.solve)(G, rhs)

        @_compat.guard_collective

        @jax.jit
        def step_B(Xz, M, A):
            # exact masked per-column ridge over archetypes
            G = jnp.einsum("nk,ni,nl->ikl", A, M, A) \
                + (gy + 1e-6) * jnp.eye(k)[None]
            rhs = (A.T @ (Xz * M)).T                  # (p, k)
            return jax.vmap(jnp.linalg.solve)(G, rhs).T

        @_compat.guard_collective

        @jax.jit
        def objective(Xz, M, A, B):
            R = (Xz - A @ B) * M
            return (R * R).sum() + gx * (A * A).sum() + gy * (B * B).sum()

        prev = np.inf
        history = []
        for it in range(max_it):
            A = step_A(Xz, M, B)
            B = step_B(Xz, M, A)
            obj = float(objective(Xz, M, A, B))
            history.append({"iteration": it, "objective": obj})
            job.update(0.1 + 0.8 * (it + 1) / max_it, f"iter {it}")
            if abs(prev - obj) < float(self.params["min_step_size"]) * max(1.0, abs(prev)):
                break
            prev = obj
        self._A = A
        self._B = np.asarray(B)
        self._objective = obj
        self._output.scoring_history = history
        self._output.model_summary = {"k": k, "objective": obj,
                                      "iterations": it + 1}

    def _score_matrix(self, X):
        # project new rows onto the archetypes (exact masked ridge per row)
        k = self._B.shape[0]
        B = jnp.asarray(self._B)
        gx = float(self.params.get("gamma_x") or 0.0)
        obs = ~jnp.isnan(X)
        M = obs.astype(jnp.float32)
        Xz = jnp.where(obs, X, 0.0)
        G = jnp.einsum("ki,ni,li->nkl", B, M, B) + (gx + 1e-6) * jnp.eye(k)[None]
        rhs = (Xz * M) @ B.T
        return jax.vmap(jnp.linalg.solve)(G, rhs)

    def predict(self, test_data: Frame) -> Frame:
        # bucketed compiled-scorer cache via _score_host (legacy for big n)
        A = np.asarray(self._score_host(test_data))
        A = A[: test_data.nrows]
        return Frame([f"Arch{j+1}" for j in range(A.shape[1])],
                     [Vec.from_numpy(A[:, j].astype(np.float64))
                      for j in range(A.shape[1])])

    def reconstruct(self, test_data: Frame) -> Frame:
        """Impute/reconstruct: Â·B in the original column space."""
        A = self._score_matrix(self._dinfo.matrix(test_data))
        R = np.asarray(A @ jnp.asarray(self._B))[: test_data.nrows]
        names = [f"reconstr_{c}" for c in self._dinfo.feature_names]
        return Frame(names, [Vec.from_numpy(R[:, j].astype(np.float64))
                             for j in range(R.shape[1])])

    def archetypes(self) -> np.ndarray:
        return self._B
