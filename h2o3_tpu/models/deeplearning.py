"""DeepLearning — hex/deeplearning rebuilt as synchronous allreduce SGD.

Reference: hex/deeplearning/DeepLearning.java, DeepLearningTask.java:17
(per-row fwd/bwd :101, Hogwild lock-free updates into node-local weights,
reduce = model averaging :180), Neurons.java (Rectifier/Tanh/Maxout ± dropout),
DeepLearningModelInfo.java (flat weight vector), adaptive rate = ADADELTA
(rho/epsilon), momentum ramp for plain SGD, l1/l2, input dropout.

TPU-native design (BASELINE.json: "Hogwild → synchronous ICI allreduce"):
one jitted train step = minibatch forward/backward via jax.grad + optimizer
update; gradients over the row-sharded batch are reduced by XLA collectives —
the Hogwild races and periodic model-averaging disappear because synchronous
data-parallel SGD on ICI is strictly stronger hardware-wise. Weights are
replicated; batch dim is sharded.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.model import ModelBase
from h2o3_tpu.parallel import compat as _compat


def _activation(name: str):
    name = (name or "Rectifier").lower()
    if "rectifier" in name:
        return jax.nn.relu
    if "tanh" in name:
        return jnp.tanh
    if "maxout" in name:
        return None  # handled specially (pairs of units)
    raise ValueError(name)


class H2ODeepLearningEstimator(ModelBase):
    algo = "deeplearning"
    # mesh-sharded serving: the net's (W, b) list as shared device args.
    # Weight matrices shard their OUT-FEATURE axis over the optional
    # "model" mesh axis (tensor parallelism for wide layers; the
    # contracting axis stays whole, so reduction order — and therefore
    # every bit of the result — is unchanged); biases shard to match.
    # On the default rows-only mesh both specs degenerate to replication.
    _serving_param_attrs = ("_params_net",)
    _partition_rules = (
        (r"^_params_net/\d+/0$", jax.sharding.PartitionSpec(None, "model")),
        (r"^_params_net/\d+/1$", jax.sharding.PartitionSpec("model")),
    )
    _defaults = {
        "hidden": None, "epochs": 10.0, "activation": "Rectifier",
        "adaptive_rate": True, "rho": 0.99, "epsilon": 1e-8,
        "rate": 0.005, "rate_annealing": 1e-6, "rate_decay": 1.0,
        "momentum_start": 0.0, "momentum_ramp": 1e6, "momentum_stable": 0.0,
        "input_dropout_ratio": 0.0, "hidden_dropout_ratios": None,
        "l1": 0.0, "l2": 0.0, "loss": "Automatic", "mini_batch_size": 1,
        "autoencoder": False, "train_samples_per_iteration": -2,
        "score_interval": 5.0, "initial_weight_distribution": "UniformAdaptive",
        "initial_weight_scale": 1.0, "stopping_rounds": 5,
        "stopping_metric": "AUTO", "stopping_tolerance": 0.0,
        "max_w2": float("inf"), "standardize": True, "reproducible": False,
        "export_weights_and_biases": False, "shuffle_training_data": False,
    }
    supervised = True

    def train(self, x=None, y=None, training_frame=None, **kw):
        self.supervised = not bool(self.params.get("autoencoder") or
                                   kw.get("autoencoder"))
        if not self.supervised:
            # autoencoder: unsupervised — no response needed
            return ModelBase.train(self, x=x, y=None,
                                   training_frame=training_frame, **kw)
        return ModelBase.train(self, x=x, y=y, training_frame=training_frame,
                               **kw)

    # ------------------------------------------------------------------
    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        autoenc = bool(self.params.get("autoencoder"))
        if autoenc:
            Y = Xz
            out_dim = X.shape[1]
            loss_kind = "quadratic"
        else:
            yv = di.response(frame)
            w = jnp.where(jnp.isnan(yv), 0.0, w)
            yz = jnp.where(jnp.isnan(yv), 0.0, yv)
            if self._is_classifier:
                out_dim = self.nclasses
                Y = yz.astype(jnp.int32)
                loss_kind = "ce"
            else:
                out_dim = 1
                Y = yz
                loss_kind = "quadratic"
        hidden = list(self.params.get("hidden") or [200, 200])
        act = _activation(self.params.get("activation"))
        maxout = act is None
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 0)
        dims = [X.shape[1]] + hidden + [out_dim]
        params = []
        for i in range(len(dims) - 1):
            key, k1 = jax.random.split(key)
            fan_in, fan_out = dims[i], dims[i + 1]
            if maxout and i < len(dims) - 2:
                fan_out *= 2
            # UniformAdaptive init (Neurons.java): U(±√(6/(fi+fo)))
            lim = math.sqrt(6.0 / (dims[i] + dims[i + 1]))
            W = jax.random.uniform(k1, (fan_in, fan_out), jnp.float32,
                                   -lim, lim)
            b = jnp.zeros(fan_out, jnp.float32)
            params.append((W, b))
        in_drop = float(self.params.get("input_dropout_ratio") or 0.0)
        hid_drop = self.params.get("hidden_dropout_ratios")
        l1 = float(self.params.get("l1") or 0.0)
        l2 = float(self.params.get("l2") or 0.0)
        nh = len(hidden)

        def forward(params, xb, rng=None, train=False):
            h = xb
            if train and in_drop > 0 and rng is not None:
                rng, k = jax.random.split(rng)
                h = h * (jax.random.uniform(k, h.shape) > in_drop)
            for i, (W, b) in enumerate(params[:-1]):
                z = h @ W + b
                if maxout:
                    z = z.reshape(z.shape[0], -1, 2).max(axis=2)
                else:
                    z = act(z)
                if train and hid_drop and rng is not None:
                    d = float(hid_drop[i]) if i < len(hid_drop) else 0.0
                    if d > 0:
                        rng, k = jax.random.split(rng)
                        z = z * (jax.random.uniform(k, z.shape) > d) / (1 - d)
                h = z
            W, b = params[-1]
            return h @ W + b

        def loss_fn(params, xb, yb, wb, rng):
            out = forward(params, xb, rng, train=True)
            if loss_kind == "ce":
                ll = optax.softmax_cross_entropy_with_integer_labels(out, yb)
            else:
                tgt = yb if autoenc else yb[:, None]
                pred = out if autoenc else out
                ll = ((pred - tgt) ** 2).mean(axis=-1) if autoenc \
                    else ((out[:, 0] - yb) ** 2)
            base = (wb * ll).sum() / jnp.maximum(wb.sum(), 1e-8)
            reg = sum(jnp.abs(W).sum() for W, _ in params) * l1 \
                + sum((W * W).sum() for W, _ in params) * l2
            return base + reg

        if self.params.get("adaptive_rate", True):
            opt = optax.adadelta(learning_rate=1.0,
                                 rho=float(self.params["rho"]),
                                 eps=float(self.params["epsilon"]))
        else:
            sched = optax.exponential_decay(
                float(self.params["rate"]), 1000,
                1.0 / (1.0 + float(self.params["rate_annealing"]) * 1000))
            opt = optax.sgd(sched,
                            momentum=float(self.params.get("momentum_stable"))
                            or None)
        opt_state = opt.init(params)

        @_compat.guard_collective

        @jax.jit
        def step(params, opt_state, xb, yb, wb, rng):
            l, g = jax.value_and_grad(loss_fn)(params, xb, yb, wb, rng)
            updates, opt_state = opt.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l

        n = frame.nrows
        pad = X.shape[0]
        epochs = float(self.params.get("epochs") or 10.0)
        mb = int(self.params.get("mini_batch_size") or 1)
        if mb <= 1:
            mb = min(256, max(32, n // 16 or 32))  # sync-SGD friendly batch
        nsteps = max(1, int(epochs * n / mb))
        rng_np = np.random.default_rng(seed if seed > 0 else 0)
        history = []
        for s in range(nsteps):
            idx = rng_np.integers(0, n, size=mb)
            xb = jnp.take(Xz, jnp.asarray(idx), axis=0)
            yb = jnp.take(Y, jnp.asarray(idx), axis=0)
            wb = jnp.take(w, jnp.asarray(idx), axis=0)
            key, k = jax.random.split(key)
            params, opt_state, l = step(params, opt_state, xb, yb, wb, k)
            if s % max(1, nsteps // 10) == 0 or s == nsteps - 1:
                history.append({"samples": (s + 1) * mb,
                                "epochs": (s + 1) * mb / n,
                                "training_loss": float(l)})
                if job.budget_exhausted:
                    break
                job.update(0.1 + 0.8 * (s + 1) / nsteps,
                           f"epoch {(s+1)*mb/n:.2f}")
        self._params_net = params
        self._forward = forward
        self._loss_kind = loss_kind
        self._output.scoring_history = history
        self._output.model_summary = {
            "hidden": hidden, "activation": self.params.get("activation"),
            "epochs_trained": nsteps * mb / n,
            "weights": [list(W.shape) for W, _ in params],
        }

    # ------------------------------------------------------------------
    def __getstate__(self):
        # derived jit wrapper is rebuilt on demand; never pickled
        state = dict(self.__dict__)
        state.pop("_forward_jit", None)
        return state

    def _score_matrix(self, X):
        # one jit wrapper PER MODEL, cached on the instance: the old
        # jit(lambda) had a fresh identity per call and recompiled on
        # every predict. Under the serving scorer cache this inlines into
        # the outer program; the legacy big-batch path still runs fused.
        fwd = self.__dict__.get("_forward_jit")
        if fwd is None:
            fwd = self._forward_jit = _compat.guard_collective(
                jax.jit(self._forward))
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        out = fwd(self._params_net, Xz)
        if self.params.get("autoencoder"):
            return out
        if self._is_classifier:
            return jax.nn.softmax(out, axis=1)
        return out[:, 0]

    def anomaly(self, test_data: Frame) -> Frame:
        """Autoencoder per-row reconstruction MSE (H2O h2o.anomaly)."""
        X = self._dinfo.matrix(test_data)
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        rec = self._score_matrix(X)
        mse = np.asarray(((rec - Xz) ** 2).mean(axis=1))[: test_data.nrows]
        return Frame(["Reconstruction.MSE"],
                     [Vec.from_numpy(mse.astype(np.float64))])

    def _score_train_valid(self, frame, valid):
        if self.params.get("autoencoder"):
            return
        ModelBase._score_train_valid(self, frame, valid)
