"""Model metrics — hex/ModelMetrics* rebuilt as fused device passes.

Reference: hex/ModelMetrics.java (+~30 subclasses), hex/AUC2.java (streaming
400-bin threshold histogram), hex/ConfusionMatrix.java, hex/GainsLift.java.
H2O computes metrics inside the BigScore MRTask pass (hex/Model.java:2077) —
one sweep over rows, small reduced state.

TPU-native design: same one-sweep structure: each metric family is a single
jitted function of (actual, predicted, weight) row-sharded arrays returning a
small replicated state (histograms / sums), finished on the host. The AUC
follows AUC2's histogram method but with 4096 score bins (still one psum-able
histogram; finer than the reference's 400, so closer to the exact AUC).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from h2o3_tpu.parallel import compat as _compat

NBINS_AUC = 4096
GAINS_GROUPS = 16


def _wmask(y, w):
    """Fold NaN rows (padding / missing response) into the weight vector."""
    valid = ~jnp.isnan(y)
    w = jnp.where(valid, w, 0.0)
    y = jnp.where(valid, 0.0, 0.0) + jnp.where(valid, y, 0.0)
    return y, w


# ===========================================================================
# Regression (hex/ModelMetricsRegression.java)
@_compat.guard_collective
@jax.jit
def _regression_pass(y, p, w):
    y, w = _wmask(y, w)
    p = jnp.where(w > 0, p, 0.0)
    n = w.sum()
    err = y - p
    sse = (w * err * err).sum()
    sae = (w * jnp.abs(err)).sum()
    # RMSLE guard: only valid when y,p >= 0
    sle = jnp.log1p(jnp.clip(p, 0.0)) - jnp.log1p(jnp.clip(y, 0.0))
    ssle = (w * sle * sle).sum()
    neg = ((w > 0) & ((y < 0) | (p < 0))).sum()
    sy = (w * y).sum()
    syy = (w * y * y).sum()
    return n, sse, sae, ssle, neg, sy, syy


@dataclass
class RegressionMetrics:
    mse: float
    rmse: float
    mae: float
    rmsle: float
    mean_residual_deviance: float
    r2: float
    nobs: int

    def to_dict(self):
        return {"MSE": self.mse, "RMSE": self.rmse, "MAE": self.mae,
                "RMSLE": self.rmsle,
                "mean_residual_deviance": self.mean_residual_deviance,
                "r2": self.r2, "nobs": self.nobs}


def regression_metrics(y, p, w=None) -> RegressionMetrics:
    w = jnp.ones_like(y) if w is None else w
    n, sse, sae, ssle, neg, sy, syy = (float(v) for v in _regression_pass(y, p, w))
    mse = sse / n if n else math.nan
    var_y = syy / n - (sy / n) ** 2 if n else math.nan
    return RegressionMetrics(
        mse=mse, rmse=math.sqrt(mse) if mse == mse else math.nan,
        mae=sae / n if n else math.nan,
        rmsle=math.sqrt(ssle / n) if n and neg == 0 else math.nan,
        mean_residual_deviance=mse,
        r2=1.0 - mse / var_y if n and var_y > 0 else math.nan,
        nobs=int(n))


# ===========================================================================
# Binomial (hex/ModelMetricsBinomial.java + hex/AUC2.java)
@_compat.guard_collective
@jax.jit
def _binomial_pass(y, p, w):
    """One sweep → logloss sum + per-score-bin pos/neg weight histograms."""
    y, w = _wmask(y, w)
    p = jnp.clip(jnp.where(w > 0, p, 0.5), 1e-15, 1 - 1e-15)
    n = w.sum()
    ll = -(w * (y * jnp.log(p) + (1 - y) * jnp.log(1 - p))).sum()
    bins = jnp.clip((p * NBINS_AUC).astype(jnp.int32), 0, NBINS_AUC - 1)
    pos = jax.ops.segment_sum(w * y, bins, NBINS_AUC)
    neg = jax.ops.segment_sum(w * (1.0 - y), bins, NBINS_AUC)
    sse = (w * (y - p) ** 2).sum()
    return n, ll, sse, pos, neg


@dataclass
class BinomialMetrics:
    auc: float
    pr_auc: float
    gini: float
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    f1: float
    f2: float
    f0point5: float
    accuracy: float
    precision: float
    recall: float
    specificity: float
    mcc: float
    max_f1_threshold: float
    confusion_matrix: np.ndarray  # 2x2 at max-F1 threshold [ [tn, fp], [fn, tp] ]
    gains_lift: Optional[dict] = None
    nobs: int = 0
    domain: Optional[list] = None

    def to_dict(self):
        d = {k: getattr(self, k) for k in
             ("auc", "pr_auc", "gini", "logloss", "mse", "rmse",
              "mean_per_class_error", "f1", "accuracy", "precision", "recall",
              "mcc", "max_f1_threshold", "nobs")}
        d["confusion_matrix"] = self.confusion_matrix.tolist()
        return d


def binomial_metrics(y, p, w=None, domain=None) -> BinomialMetrics:
    w = jnp.ones_like(y) if w is None else w
    n, ll, sse, pos, neg = _binomial_pass(y, p, w)
    n, ll, sse = float(n), float(ll), float(sse)
    pos = np.asarray(pos, np.float64)   # bin b ≈ score (b+.5)/NBINS
    neg = np.asarray(neg, np.float64)
    P, N = pos.sum(), neg.sum()
    # sweep thresholds high→low: cumulative TP/FP above each bin boundary
    tp = np.cumsum(pos[::-1])[::-1]     # predicted positive at thr = bin edge
    fp = np.cumsum(neg[::-1])[::-1]
    # prepend "predict nothing positive" point
    tp_all = np.concatenate([tp, [0.0]])
    fp_all = np.concatenate([fp, [0.0]])
    tpr = tp_all / P if P else np.zeros_like(tp_all)
    fpr = fp_all / N if N else np.zeros_like(fp_all)
    auc = float(np.trapezoid(tpr[::-1], fpr[::-1])) if P and N else math.nan
    # PR-AUC (ModelMetricsBinomial._pr_auc): precision vs recall
    with np.errstate(invalid="ignore", divide="ignore"):
        prec = np.where(tp_all + fp_all > 0, tp_all / (tp_all + fp_all), 1.0)
    pr_auc = float(np.trapezoid(prec[::-1], tpr[::-1])) if P else math.nan
    # threshold metrics at max F1 (H2O's default CM threshold)
    fn = P - tp_all
    tn = N - fp_all
    with np.errstate(invalid="ignore", divide="ignore"):
        f1 = 2 * tp_all / (2 * tp_all + fp_all + fn)
        f1 = np.nan_to_num(f1)
    bi = int(np.argmax(f1))
    thr = bi / NBINS_AUC
    TP, FP, FN, TN = tp_all[bi], fp_all[bi], fn[bi], tn[bi]
    precision = TP / (TP + FP) if TP + FP else 0.0
    recall = TP / (TP + FN) if TP + FN else 0.0
    spec = TN / (TN + FP) if TN + FP else 0.0
    acc = (TP + TN) / n if n else math.nan
    beta2, beta05 = 4.0, 0.25
    f2 = (1 + beta2) * precision * recall / (beta2 * precision + recall) \
        if precision + recall else 0.0
    f05 = (1 + beta05) * precision * recall / (beta05 * precision + recall) \
        if precision + recall else 0.0
    mcc_den = math.sqrt((TP + FP) * (TP + FN) * (TN + FP) * (TN + FN))
    mcc = (TP * TN - FP * FN) / mcc_den if mcc_den else 0.0
    mpce = 0.5 * ((FN / P if P else 0.0) + (FP / N if N else 0.0))
    gl = _gains_lift(pos, neg)
    return BinomialMetrics(
        auc=auc, pr_auc=pr_auc, gini=2 * auc - 1 if auc == auc else math.nan,
        logloss=ll / n if n else math.nan,
        mse=sse / n if n else math.nan,
        rmse=math.sqrt(sse / n) if n else math.nan,
        mean_per_class_error=mpce,
        f1=float(f1[bi]), f2=f2, f0point5=f05, accuracy=acc,
        precision=precision, recall=recall, specificity=spec, mcc=mcc,
        max_f1_threshold=thr,
        confusion_matrix=np.array([[TN, FP], [FN, TP]]),
        gains_lift=gl, nobs=int(n), domain=domain)


def _gains_lift(pos, neg) -> dict:
    """hex/GainsLift.java — 16 quantile groups by predicted score."""
    P, N = pos.sum(), neg.sum()
    tot = P + N
    if tot == 0 or P == 0:
        return {}
    cum_w = np.cumsum((pos + neg)[::-1])  # from highest score down
    cum_p = np.cumsum(pos[::-1])
    edges = [tot * (g + 1) / GAINS_GROUPS for g in range(GAINS_GROUPS)]
    rows = []
    prev_w = prev_p = 0.0
    for g, e in enumerate(edges):
        i = int(np.searchsorted(cum_w, e))
        i = min(i, len(cum_w) - 1)
        cw, cp = cum_w[i], cum_p[i]
        grp_w, grp_p = cw - prev_w, cp - prev_p
        resp_rate = grp_p / grp_w if grp_w else 0.0
        lift = resp_rate / (P / tot)
        rows.append({"group": g + 1,
                     "cumulative_data_fraction": cw / tot,
                     "response_rate": resp_rate, "lift": lift,
                     "cumulative_lift": (cp / cw) / (P / tot) if cw else 0.0,
                     "capture_rate": grp_p / P,
                     "cumulative_capture_rate": cp / P})
        prev_w, prev_p = cw, cp
    return {"groups": rows}


# ===========================================================================
# Multinomial (hex/ModelMetricsMultinomial.java)
def _multinomial_pass(nclass):
    @_compat.guard_collective
    @jax.jit
    def f(y, probs, w):
        y, w = _wmask(y, w)
        yi = y.astype(jnp.int32)
        n = w.sum()
        py = jnp.take_along_axis(probs, yi[:, None], axis=1)[:, 0]
        ll = -(w * jnp.log(jnp.clip(py, 1e-15, 1.0))).sum()
        pred = jnp.argmax(probs, axis=1)
        cm = jax.ops.segment_sum(w, yi * nclass + pred.astype(jnp.int32),
                                 nclass * nclass).reshape(nclass, nclass)
        # top-k hit ratios, k up to min(10, K)
        kmax = min(10, nclass)
        _, topk = jax.lax.top_k(probs, kmax)
        hits = (topk == yi[:, None]).astype(jnp.float32)
        hit_cum = jnp.cumsum(hits, axis=1)
        hit_k = (w[:, None] * hit_cum).sum(axis=0)
        # MSE over the 1-vs-all encoding (H2O: 1 - p_actual squared + sum others)
        onehot = jax.nn.one_hot(yi, nclass)
        sse = (w[:, None] * (onehot - probs) ** 2).sum()
        return n, ll, cm, hit_k, sse
    return f


@dataclass
class MultinomialMetrics:
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    error: float                # overall classification error
    confusion_matrix: np.ndarray
    hit_ratios: list
    nobs: int
    domain: Optional[list] = None

    def to_dict(self):
        return {"logloss": self.logloss, "MSE": self.mse, "RMSE": self.rmse,
                "mean_per_class_error": self.mean_per_class_error,
                "error": self.error,
                "confusion_matrix": self.confusion_matrix.tolist(),
                "hit_ratios": self.hit_ratios, "nobs": self.nobs}


def multinomial_metrics(y, probs, w=None, domain=None) -> MultinomialMetrics:
    nclass = int(probs.shape[1])
    w = jnp.ones_like(y) if w is None else w
    n, ll, cm, hit_k, sse = _multinomial_pass(nclass)(y, probs, w)
    n, ll, sse = float(n), float(ll), float(sse)
    cm = np.asarray(cm, np.float64)
    row_tot = cm.sum(axis=1)
    with np.errstate(invalid="ignore", divide="ignore"):
        per_class_err = np.where(row_tot > 0, 1.0 - np.diag(cm) / row_tot, 0.0)
    seen = row_tot > 0
    mpce = float(per_class_err[seen].mean()) if seen.any() else math.nan
    err = 1.0 - np.diag(cm).sum() / n if n else math.nan
    return MultinomialMetrics(
        logloss=ll / n if n else math.nan,
        mse=sse / n if n else math.nan,
        rmse=math.sqrt(sse / n) if n else math.nan,
        mean_per_class_error=mpce, error=float(err),
        confusion_matrix=cm,
        hit_ratios=[float(h) / n for h in np.asarray(hit_k)] if n else [],
        nobs=int(n), domain=domain)


# ===========================================================================
# Clustering (hex/ModelMetricsClustering.java)
@dataclass
class ClusteringMetrics:
    tot_withinss: float
    totss: float
    betweenss: float
    size: list
    withinss: list
    nobs: int

    def to_dict(self):
        return {"tot_withinss": self.tot_withinss, "totss": self.totss,
                "betweenss": self.betweenss, "size": self.size,
                "withinss": self.withinss, "nobs": self.nobs}
