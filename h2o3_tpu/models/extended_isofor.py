"""Extended Isolation Forest — hex/tree/isoforextended/ExtendedIsolationForest.

Reference: like IsolationForest, but splits are random HYPERPLANES
(random normal vector n, random intercept point p inside the node's bounding
box; row goes left iff (x−p)·n ≤ 0) — removes axis-parallel artifacts.
`extension_level` = number of non-zero dimensions − 1 (0 ⇒ classic IF).

TPU-native: per level, node bounding boxes are segment reductions and the
hyperplane draw/test for all rows is fused into one jitted program; trees are
stored as dense heap-order (normal, point, value) arrays, and scoring is a
fixed-depth walk where each step is a gathered row·normal dot product.
Anomaly score uses the canonical 2^(−E[h]/c(ψ)) normalization.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.isofor import _avg_path_jnp
from h2o3_tpu.models.tree.shared_tree import SharedTreeEstimator
from h2o3_tpu.parallel import compat as _compat


@_compat.guard_collective


@functools.partial(jax.jit, static_argnames=("d", "ext"))
def _eif_level(X, w, leaf, active, normA, pointA, didA, valA, key, *, d, ext):
    L = 2 ** d
    C = X.shape[1]
    lv = jnp.where(active & (w > 0), leaf, L)
    mn, mx = E.leaf_ranges(X, lv, L)
    cnt = jax.ops.segment_sum(w, lv, num_segments=L + 1)[:L]
    kn = jax.random.fold_in(key, 3 * d)
    kp = jax.random.fold_in(key, 3 * d + 1)
    km = jax.random.fold_in(key, 3 * d + 2)
    normal = jax.random.normal(kn, (L, C))
    if ext + 1 < C:   # keep only (ext+1) random dims
        r = jax.random.uniform(km, (L, C))
        kth = jnp.sort(r, axis=1)[:, ext:ext + 1]
        normal = jnp.where(r <= kth, normal, 0.0)
    span = jnp.maximum(mx - mn, 0.0)
    point = mn + jax.random.uniform(kp, (L, C)) * span
    did = (cnt > 1.5) & (span.sum(axis=1) > 0)
    base = 2 ** d - 1
    normA = jax.lax.dynamic_update_slice(normA, normal.astype(jnp.float32),
                                         (base, 0))
    pointA = jax.lax.dynamic_update_slice(pointA, point.astype(jnp.float32),
                                          (base, 0))
    didA = jax.lax.dynamic_update_slice(didA, did, (base,))
    valA = jax.lax.dynamic_update_slice(
        valA, (d + _avg_path_jnp(cnt)).astype(jnp.float32), (base,))
    proj = ((X - point[leaf]) * normal[leaf]).sum(axis=1)
    go_right = jnp.where(jnp.isnan(proj), False, proj > 0)
    splits = did[leaf] & active
    leaf = jnp.where(splits, 2 * leaf + go_right.astype(jnp.int32), 0)
    return leaf, splits, normA, pointA, didA, valA


@_compat.guard_collective


@functools.partial(jax.jit, static_argnames=("D",))
def _eif_final(w, leaf, active, valA, *, D):
    L = 2 ** D
    lv = jnp.where(active & (w > 0), leaf, L)
    cnt = jax.ops.segment_sum(w, lv, num_segments=L + 1)[:L]
    vals = (D + _avg_path_jnp(cnt)).astype(jnp.float32)
    return jax.lax.dynamic_update_slice(valA, vals, (2 ** D - 1,))


def _eif_walk(X, norms, points, dids, vals, D):
    """Mean path length over hyperplane trees: fixed-depth gather walk."""

    @_compat.guard_collective

    @jax.jit
    def run(X, norms, points, dids, vals):
        n = X.shape[0]
        T = norms.shape[0]

        def per_tree(acc, t):
            node = jnp.zeros(n, jnp.int32)

            def step(d, node):
                nr = norms[t][node]              # (n, C)
                pt = points[t][node]
                proj = ((X - pt) * nr).sum(axis=1)
                right = jnp.where(jnp.isnan(proj), False, proj > 0)
                child = 2 * node + 1 + right.astype(jnp.int32)
                return jnp.where(dids[t][node], child, node)

            node = jax.lax.fori_loop(0, D, step, node)
            return acc + vals[t][node], None

        out, _ = jax.lax.scan(per_tree, jnp.zeros(n, jnp.float32),
                              jnp.arange(T))
        return out / T

    return run(X, norms, points, dids, vals)


class H2OExtendedIsolationForestEstimator(SharedTreeEstimator):
    algo = "extendedisolationforest"
    supervised = False
    # mesh-sharded serving: the EIF hyperplane ensemble as shared device
    # args (overrides the SharedTree `_trees` export — EIF scores through
    # its own walk). Tree axis shards over the optional "model" mesh axis.
    _serving_param_attrs = ("_norms", "_points", "_dids", "_vals")
    _partition_rules = (
        (r"^_(norms|points|dids|vals)$",
         jax.sharding.PartitionSpec("model")),
    )
    _defaults = dict(SharedTreeEstimator._tree_defaults)
    _defaults.update({"ntrees": 100, "sample_size": 256, "extension_level": 0})

    def _fit(self, frame: Frame, job):
        di = self._dinfo
        X = di.matrix(frame)
        w = di.weights(frame)
        n = frame.nrows
        C = X.shape[1]
        ntrees = int(self.params["ntrees"])
        psi = min(int(self.params.get("sample_size") or 256), n)
        ext = min(int(self.params.get("extension_level") or 0), C - 1)
        D = max(1, int(np.ceil(np.log2(max(psi, 2)))))
        seed = int(self.params.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed > 0 else 42)
        rate = psi / max(n, 1)
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        nodes = 2 ** (D + 1) - 1
        norms, points, dids, vals = [], [], [], []
        for t in range(ntrees):
            key, k1, k2 = jax.random.split(key, 3)
            wt = w * (jax.random.uniform(k1, w.shape) < rate)
            leaf = jnp.zeros(Xz.shape[0], jnp.int32)
            active = jnp.ones(Xz.shape[0], bool)
            normA = jnp.zeros((nodes, C), jnp.float32)
            pointA = jnp.zeros((nodes, C), jnp.float32)
            didA = jnp.zeros(nodes, bool)
            valA = jnp.zeros(nodes, jnp.float32)
            for d in range(D):
                leaf, active, normA, pointA, didA, valA = _eif_level(
                    Xz, wt, leaf, active, normA, pointA, didA, valA, k2,
                    d=d, ext=ext)
            valA = _eif_final(wt, leaf, active, valA, D=D)
            norms.append(normA)
            points.append(pointA)
            dids.append(didA)
            vals.append(valA)
            job.update(0.1 + 0.8 * (t + 1) / ntrees, f"tree {t+1}")
        self._norms = jnp.stack(norms)
        self._points = jnp.stack(points)
        self._dids = jnp.stack(dids)
        self._vals = jnp.stack(vals)
        self._D = D
        self._cn = float(np.asarray(_avg_path_jnp(jnp.float32(psi))))
        self._output.model_summary = {
            "number_of_trees": ntrees, "sample_size": psi,
            "extension_level": ext,
        }

    def _score_matrix(self, X):
        Xz = jnp.where(jnp.isnan(X), 0.0, X)
        return _eif_walk(Xz, self._norms, self._points, self._dids,
                         self._vals, self._D)

    def predict(self, test_data: Frame) -> Frame:
        # bucketed compiled-scorer cache via _score_host (legacy for big n)
        ml = np.asarray(self._score_host(test_data))[: test_data.nrows]
        score = 2.0 ** (-ml / self._cn)
        return Frame(["anomaly_score", "mean_length"],
                     [Vec.from_numpy(score.astype(np.float64)),
                      Vec.from_numpy(ml.astype(np.float64))])
