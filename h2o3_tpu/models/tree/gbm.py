"""GBM public module — driver lives in shared_tree.py (GBM/DRF share it,
mirroring hex/tree/SharedTree.java ownership of the build loop)."""

from h2o3_tpu.models.tree.shared_tree import H2OGradientBoostingEstimator

__all__ = ["H2OGradientBoostingEstimator"]
