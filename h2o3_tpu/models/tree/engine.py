"""Histogram tree-growing engine — the TPU rebuild of H2O's SharedTree core.

Reference hot path (SURVEY.md §3.3): hex/tree/ScoreBuildHistogram2.java
(2-phase: score rows→leaf, then per-(column,row-range) private histogram
accumulate), hex/tree/DHistogram.java:44 ({w,wY,wYY} bins packed in one
double[] :59-70, merged in reduce :338, uniform-adaptive binning :41),
hex/tree/DTree.java:514 (DecidedNode.bestCol — split scoring over bins),
hex/tree/SharedTree.java:507 (buildLayer).

TPU-native design — no CAS, no private copies, no reduce tree, and (critical
on real hardware) NO host↔device synchronization inside tree growth:
  * One tree level == ONE fused jitted program (`_level_step`): adaptive
    ranges → binning → histograms → split search → node-array writes → row
    routing. The controller dispatches D async programs per tree and never
    reads back until scoring time.
  * Uniform-adaptive bin ranges: per-(leaf,column) min/max are segment
    reductions over IN-SAMPLE rows; each row re-bins against ITS leaf's range
    each level — DHistogram's adaptive-range semantics, fully vectorized.
  * Histograms: hist[l,c,b,s] = Σ_r onehot_leaf[r,l]·stat_s[r]·onehot_bin[r,c,b].
    Shallow levels evaluate this as a dense matmul (leaf·stat panel)ᵀ @
    (bin one-hot) per column block — it rides the MXU, and the row
    contraction over the sharded dimension becomes one ICI all-reduce (the
    entire MRTask reduce tree collapses into a psum). Deep levels switch to
    segment-sum on a combined (leaf,bin) index.
  * Rows carry (leaf, heap-node) vectors; ALL rows are routed (so out-of-bag
    rows get tree predictions for the F update) while histogram contributions
    are weighted by the in-sample weights — H2O's sampling semantics.
  * Trees are dense heap-order DEVICE arrays (CompressedTree analog);
    training predictions are a gather val[heap] — no tree walk; ensemble
    scoring is a fixed-depth gather loop — static shapes, jit-friendly.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.parallel import compat as _compat
from h2o3_tpu.obs.timeline import span as _span

# Σ rows·trees processed — the headline GBM throughput numerator; bench.py
# and /metrics read the same counter (per-ensemble rate = Δcounter/Δt)
ROW_TREES = _om.counter("h2o3_gbm_row_trees_total",
                        "rows x trees processed by the tree engines")
_LEVEL_SECONDS = _om.histogram(
    "h2o3_tree_level_seconds",
    "per-level wall time of the tree engines, labeled by engine "
    "(adaptive = per-level dispatch enqueue; binned = the eager "
    "instrumented pass of binned.measure_level_seconds, synced per "
    "level) and by level index — the bench per-level cost arbiter")

# Dense-matmul histogram path is used while (leaves × 3 stats) stays MXU-sized.
# Measured on v5e: the one-hot matmul beats segment-sum scatter ~3× even at
# L=256 (scatter serializes on TPU); the threshold is a memory guard, not a
# FLOPs one.
_MATMUL_MAX_LEAVES = 256
_COL_BLOCK = 8


# ===========================================================================
# Building blocks (called inside the fused level step; individually jitted
# only for unit tests — nested jit inlines).
def leaf_ranges(X, lv, L):
    """Per-(leaf,col) min/max over in-sample rows (lv==L → excluded)."""
    big = jnp.float32(3.0e38)
    xmin = jnp.where(jnp.isnan(X), big, X)
    xmax = jnp.where(jnp.isnan(X), -big, X)
    mn = jax.ops.segment_min(xmin, lv, num_segments=L + 1)[:L]
    mx = jax.ops.segment_max(xmax, lv, num_segments=L + 1)[:L]
    return mn, mx


def bin_rows(X, lv, mn, mx, B):
    """Adaptive binning: row r, col c → bin in [0,B); NA → bin B."""
    safe = jnp.minimum(lv, mn.shape[0] - 1)
    lm = mn[safe]
    lM = mx[safe]
    span = jnp.maximum(lM - lm, 1e-30)
    b = jnp.floor((X - lm) / span * B).astype(jnp.int32)
    b = jnp.clip(b, 0, B - 1)
    return jnp.where(jnp.isnan(X), B, b)


def histogram_matmul(bins, lv, stats, L, B):
    """hist (L, C, B+1, 3) via MXU: (n,L·3)ᵀ @ (n,CB·(B+1)) per column block."""
    n, C = bins.shape
    oh_leaf = jax.nn.one_hot(lv, L, dtype=jnp.float32)            # (n, L)
    W3 = (oh_leaf[:, :, None] * stats[:, None, :]).reshape(n, L * 3)
    nb = B + 1
    pad_c = (-C) % _COL_BLOCK
    binsp = jnp.pad(bins, ((0, 0), (0, pad_c)), constant_values=B)
    nblk = binsp.shape[1] // _COL_BLOCK

    def block(carry, cb):
        blk = jax.lax.dynamic_slice(binsp, (0, cb * _COL_BLOCK),
                                    (n, _COL_BLOCK))
        oh = jax.nn.one_hot(blk, nb, dtype=jnp.float32)           # (n,CB,nb)
        h = jnp.einsum("nk,ncb->kcb", W3, oh,
                       preferred_element_type=jnp.float32)        # (L3,CB,nb)
        return carry, h

    _, hs = jax.lax.scan(block, 0, jnp.arange(nblk))   # (nblk, L3, CB, nb)
    h = hs.transpose(1, 0, 2, 3).reshape(L * 3, nblk * _COL_BLOCK, nb)[:, :C]
    return h.reshape(L, 3, C, nb).transpose(0, 2, 3, 1)


def histogram_scatter(bins, lv, stats, L, B):
    """Deep-tree path: segment-sum on combined (leaf·(B+1)+bin) per column."""
    n, C = bins.shape
    nb = B + 1
    base = lv * nb

    def one_col(c):
        idx = base + bins[:, c]
        return jax.ops.segment_sum(stats, idx,
                                   num_segments=(L + 1) * nb)[: L * nb]

    hs = jax.lax.map(one_col, jnp.arange(C))                      # (C, L·nb, 3)
    return hs.reshape(C, L, nb, 3).transpose(1, 0, 2, 3)


def build_histograms(bins, lv, stats, L, B):
    if L <= _MATMUL_MAX_LEAVES:
        return histogram_matmul(bins, lv, stats, L, B)
    return histogram_scatter(bins, lv, stats, L, B)


def find_best_splits(hist, mn, mx, min_rows, min_split_improvement,
                     col_mask, B, reg_lambda=0.0):
    """Vectorized DecidedNode.bestCol over every (leaf, col, threshold,
    NA-dir). col_mask: (L, C) bool — per-leaf column availability (mtries).

    hist: (L, C, B+1, 3); slot B is the NA bucket. Returns per-leaf arrays:
      did, gain, col, thr, na_left, leaf_w, leaf_wy.
    Split at t ∈ [0,B-1): left = bins ≤ t (+NA if na_left), right = rest.

    reg_lambda > 0 turns the SE reduction into the XGBoost regularized
    structure score: se = wyy - wy²/(w+λ). Since wyy is additive over a
    leaf's children it cancels in the gain difference, so the argmax is
    EXACTLY hist-mode XGBoost's Σ G²/(H+λ) split objective when the caller
    feeds hessian-weighted stats (w = Σh, wy = Σg).
    """
    w = hist[..., 0]
    wy = hist[..., 1]
    wyy = hist[..., 2]
    main_w, na_w = w[..., :B], w[..., B]
    main_wy, na_wy = wy[..., :B], wy[..., B]
    main_wyy, na_wyy = wyy[..., :B], wyy[..., B]

    def se(w_, wy_, wyy_):
        den = jnp.maximum(w_ + reg_lambda, 1e-30)
        return wyy_ - jnp.where(w_ > 0, wy_ * wy_ / den, 0.0)

    tot_w = main_w.sum(-1) + na_w                      # (L, C) — same ∀ c
    tot_wy = main_wy.sum(-1) + na_wy
    tot_wyy = main_wyy.sum(-1) + na_wyy
    se_parent = se(tot_w, tot_wy, tot_wyy)

    cl_w = jnp.cumsum(main_w, -1)[..., :-1]            # (L, C, B-1) left sums
    cl_wy = jnp.cumsum(main_wy, -1)[..., :-1]
    cl_wyy = jnp.cumsum(main_wyy, -1)[..., :-1]

    def gains(nal):
        lw = cl_w + (na_w[..., None] if nal else 0.0)
        lwy = cl_wy + (na_wy[..., None] if nal else 0.0)
        lwyy = cl_wyy + (na_wyy[..., None] if nal else 0.0)
        rw = tot_w[..., None] - lw
        rwy = tot_wy[..., None] - lwy
        rwyy = tot_wyy[..., None] - lwyy
        g = se_parent[..., None] - se(lw, lwy, lwyy) - se(rw, rwy, rwyy)
        ok = (lw >= min_rows) & (rw >= min_rows)
        return jnp.where(ok, g, -jnp.inf)

    g_right = gains(False)                             # (L, C, B-1)
    g_left = gains(True)
    g = jnp.maximum(g_right, g_left)
    na_left = g_left > g_right
    g = jnp.where(col_mask[:, :, None], g, -jnp.inf)

    L, C = tot_w.shape
    flat = g.reshape(L, C * (B - 1))
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    best_col = (best // (B - 1)).astype(jnp.int32)
    best_bin = (best % (B - 1)).astype(jnp.int32)
    best_nal = jnp.take_along_axis(
        na_left.reshape(L, C * (B - 1)), best[:, None], 1)[:, 0]
    # threshold value: upper edge of bin t in the leaf's adaptive range
    lmn = jnp.take_along_axis(mn, best_col[:, None], 1)[:, 0]
    lmx = jnp.take_along_axis(mx, best_col[:, None], 1)[:, 0]
    thr = lmn + (lmx - lmn) * (best_bin + 1).astype(jnp.float32) / B
    did = jnp.isfinite(best_gain) & \
        (best_gain > jnp.maximum(min_split_improvement, 0.0))
    leaf_w = tot_w[:, 0]
    leaf_wy = tot_wy[:, 0]
    return did, best_gain, best_col, thr, best_nal, leaf_w, leaf_wy


# ===========================================================================
# The fused per-level program — zero host syncs.
@_compat.guard_collective
@functools.partial(jax.jit, static_argnames=("d", "B", "mtries"))
def _level_step(X, stats, w_in, leaf, heap, active, colA, thrA, nalA, valA,
                gains, col_mask, key, *, d, B, mtries,
                min_rows, min_split_improvement, reg_lambda=0.0):
    L = 2 ** d
    C = X.shape[1]
    in_sample = active & (w_in > 0)
    lv = jnp.where(in_sample, leaf, L)
    mn, mx = leaf_ranges(X, lv, L)
    bins = bin_rows(X, lv, mn, mx, B)
    hist = build_histograms(bins, lv, stats, L, B)
    if mtries > 0 and mtries < C:
        # per-leaf mtries column sampling (DRF per-node semantics)
        r = jax.random.uniform(jax.random.fold_in(key, d), (L, C))
        kth = jnp.sort(r, axis=1)[:, mtries - 1:mtries]
        cmask = (r <= kth) & col_mask[None, :]
    else:
        cmask = jnp.broadcast_to(col_mask[None, :], (L, C))
    did, gain, bcol, thr, nal, lw, lwy = find_best_splits(
        hist, mn, mx, min_rows, min_split_improvement, cmask, B,
        reg_lambda=reg_lambda)
    base = 2 ** d - 1
    lvl_val = jnp.where(lw > 0, lwy / jnp.maximum(lw, 1e-30), 0.0)
    colA = jax.lax.dynamic_update_slice(
        colA, jnp.where(did, bcol, -1).astype(jnp.int32), (base,))
    thrA = jax.lax.dynamic_update_slice(thrA, thr, (base,))
    nalA = jax.lax.dynamic_update_slice(nalA, nal, (base,))
    valA = jax.lax.dynamic_update_slice(valA, lvl_val.astype(jnp.float32),
                                        (base,))
    gains = gains.at[bcol].add(jnp.where(did, jnp.maximum(gain, 0.0), 0.0))
    # route ALL rows in split nodes (OOB rows included — they need the tree's
    # prediction), freeze rows in terminal nodes
    c = bcol[leaf]
    t = thr[leaf]
    x = jnp.take_along_axis(X, c[:, None], axis=1)[:, 0]
    isna = jnp.isnan(x)
    go_right = jnp.where(isna, ~nal[leaf], x > t)
    splits = did[leaf] & active
    leaf = jnp.where(splits, 2 * leaf + go_right.astype(jnp.int32), 0)
    heap = jnp.where(splits, 2 * heap + 1 + go_right.astype(jnp.int32), heap)
    active = splits
    return leaf, heap, active, colA, thrA, nalA, valA, gains


@_compat.guard_collective
@functools.partial(jax.jit, static_argnames=("D",))
def _final_leaves(stats, leaf, active, w_in, valA, *, D):
    L = 2 ** D
    lv = jnp.where(active & (w_in > 0), leaf, L)
    sums = jax.ops.segment_sum(stats[:, :2], lv, num_segments=L + 1)[:L]
    vals = jnp.where(sums[:, 0] > 0,
                     sums[:, 1] / jnp.maximum(sums[:, 0], 1e-30),
                     0.0).astype(jnp.float32)
    return jax.lax.dynamic_update_slice(valA, vals, (2 ** D - 1,))


def gamma_pass(heap, w, res, hess, val, *, nodes, scale=1.0,
               reg_lambda=0.0, reg_alpha=0.0):
    """GammaPass (GBM.java:1235) on device: Newton leaf Σw·res / Σw·hess.
    With reg_lambda/reg_alpha this is the XGBoost leaf weight
    sign(G)·max(|G|−α, 0)/(H+λ)."""
    with _span("tree.gamma", nodes=nodes):
        return _gamma_pass_jit(heap, w, res, hess, val, nodes=nodes,
                               scale=scale, reg_lambda=reg_lambda,
                               reg_alpha=reg_alpha)


@_compat.guard_collective
@functools.partial(jax.jit,
                   static_argnames=("nodes", "scale", "reg_lambda",
                                    "reg_alpha"))
def _gamma_pass_jit(heap, w, res, hess, val, *, nodes, scale=1.0,
                    reg_lambda=0.0, reg_alpha=0.0):
    num = jax.ops.segment_sum(w * res, heap, num_segments=nodes)
    den = jax.ops.segment_sum(w * hess, heap, num_segments=nodes)
    if reg_alpha:
        num = jnp.sign(num) * jnp.maximum(jnp.abs(num) - reg_alpha, 0.0)
    den = den + reg_lambda
    return jnp.where(den > 1e-10,
                     jnp.clip(scale * num / jnp.maximum(den, 1e-10), -19, 19),
                     val).astype(jnp.float32)


@_compat.guard_collective
@functools.partial(jax.jit, static_argnames=("nodes", "D"))
def _node_covers_jit(heap, w, *, nodes, D):
    cov = jax.ops.segment_sum(w, heap, num_segments=nodes)
    for d in range(D - 1, -1, -1):
        lo, hi = 2 ** d - 1, 2 ** (d + 1) - 1
        kids = cov[2 * lo + 1: 2 * hi + 1].reshape(hi - lo, 2).sum(axis=1)
        cov = cov.at[lo:hi].add(kids)
    return cov.astype(jnp.float32)


def node_covers(heap, w, *, nodes, D):
    """Per-node training weight R_j (MOJO node-weight analog, used by
    TreeSHAP): terminal weights from the row router, then children sums
    propagate up the heap level by level."""
    cov = _node_covers_jit(heap, w, nodes=nodes, D=D)
    if _cpu_backend():
        # same flaky-CPU-collective guard as TreeGrower.grow: this program
        # contains a psum over the sharded row axis — drain before piling on
        jax.block_until_ready(cov)
    return cov


# ===========================================================================
# Dense heap-order tree storage (hex/tree/CompressedTree analog)
@dataclass
class TreeArrays:
    """One ensemble's trees as stacked dense arrays, heap node order:
    node 0 = root; children of i are 2i+1 / 2i+2. Leaves carry values.
    Arrays may live on device (jnp) or host (np)."""
    col: object       # (T, nodes) int32, -1 = leaf
    thr: object       # (T, nodes) f32
    na_left: object   # (T, nodes) bool
    value: object     # (T, nodes) f32 — prediction if stopped here
    depth: int
    cover: object = None   # (T, nodes) f32 training weight per node (SHAP)
    # categorical SET splits (water/util/IcedBitSet.java analog): per-node
    # go-right bitset over level ids, plus which columns are categorical
    catbits: object = None      # (T, nodes, W) uint32 or None
    col_is_cat: object = None   # (C,) bool or None

    @property
    def ntrees(self):
        return self.col.shape[0]


def stack_trees(tree_list, depth) -> TreeArrays:
    """Stack per-tree device arrays into one ensemble — stays on device.
    Accepts (col, thr, nal, val) or (col, thr, nal, val, cover) tuples."""
    cover = None
    if len(tree_list[0]) >= 5:
        cover = jnp.stack([t[4] for t in tree_list])
    return TreeArrays(
        col=jnp.stack([t[0] for t in tree_list]),
        thr=jnp.stack([t[1] for t in tree_list]),
        na_left=jnp.stack([t[2] for t in tree_list]),
        value=jnp.stack([t[3] for t in tree_list]),
        depth=depth, cover=cover)


# TreeArrays is a pytree: the mesh-sharded serving fast path passes
# whole ensembles as SHARED DEVICE ARGUMENTS into pjit'd scorer programs
# (one HBM copy per model, every row-bucket program reuses it) instead
# of baking them in as closure constants. Children are the per-node
# arrays; `depth` is static trace structure, and `col_is_cat` stays HOST
# data (aux) because predict_ensemble resolves the has-categoricals
# branch with `np.any` at trace time.
def _trees_flatten(t: TreeArrays):
    aux = (t.depth,
           None if t.col_is_cat is None
           else tuple(bool(b) for b in np.asarray(t.col_is_cat)))
    return (t.col, t.thr, t.na_left, t.value, t.cover, t.catbits), aux


def _trees_unflatten(aux, children):
    depth, cat = aux
    col, thr, nal, val, cover, catbits = children
    return TreeArrays(col=col, thr=thr, na_left=nal, value=val,
                      depth=depth, cover=cover, catbits=catbits,
                      col_is_cat=None if cat is None
                      else np.asarray(cat, bool))


jax.tree_util.register_pytree_node(TreeArrays, _trees_flatten,
                                   _trees_unflatten)


@_compat.guard_collective
@functools.partial(jax.jit, static_argnames=("depth", "has_cat"))
def _ensemble_walk(X, col, thr, nal, val, tw, catbits, iscat, *, depth,
                   has_cat):
    """Module-level jitted gather walk: cached per (shapes, depth, has_cat)
    signature. Defining this as a closure inside predict_ensemble gave the
    jit a fresh function identity per call — every single ensemble predict
    retraced AND recompiled, which dominated serving latency."""
    n = X.shape[0]
    if has_cat:
        nb = catbits.shape[-1] * 32

    def per_tree(acc, t):
        node = jnp.zeros(n, jnp.int32)

        def step(d, node):
            c = col[t][node]
            leafish = c < 0
            cc = jnp.maximum(c, 0)
            x = jnp.take_along_axis(X, cc[:, None], axis=1)[:, 0]
            isna = jnp.isnan(x)
            right = x > thr[t][node]
            if has_cat:
                code = jnp.clip(jnp.nan_to_num(x).astype(jnp.int32),
                                0, nb - 1)
                word = catbits[t][node, code // 32]
                bit = (word >> (code % 32).astype(jnp.uint32)) & 1
                right = jnp.where(iscat[cc], bit == 1, right)
            right = jnp.where(isna, ~nal[t][node], right)
            child = 2 * node + 1 + right.astype(jnp.int32)
            return jnp.where(leafish, node, child)

        node = jax.lax.fori_loop(0, depth, step, node)
        return acc + tw[t] * val[t][node], None

    out, _ = jax.lax.scan(per_tree, jnp.zeros(n, jnp.float32),
                          jnp.arange(col.shape[0]))
    return out


def predict_ensemble(X, trees: TreeArrays, weights=None):
    """Σ_t value[t, leaf_t(row)] — fixed-depth gather walk per tree.
    Categorical SET-split nodes route by bitset membership of the level id
    (hex/genmodel GenModel.bitSetContains analog)."""
    col = jnp.asarray(trees.col)
    thr = jnp.asarray(trees.thr)
    nal = jnp.asarray(trees.na_left)
    val = jnp.asarray(trees.value)
    tw = (jnp.asarray(weights, jnp.float32) if weights is not None
          else jnp.ones(trees.ntrees, jnp.float32))
    has_cat = trees.catbits is not None and trees.col_is_cat is not None \
        and bool(np.any(np.asarray(trees.col_is_cat)))  # h2o3-ok: R025 col_is_cat is host numpy model metadata excluded from the serving params pytree — static per artifact; the export PR hoists has_cat into artifact metadata (covers the if below)
    if has_cat:
        catbits = jnp.asarray(trees.catbits)
        iscat = jnp.asarray(np.asarray(trees.col_is_cat))
    else:
        # fixed dummy shapes so the no-cat program signature is stable
        catbits = jnp.zeros((1, 1, 1), jnp.uint32)
        iscat = jnp.zeros(1, bool)
    return _ensemble_walk(X, col, thr, nal, val, tw, catbits, iscat,
                          depth=trees.depth, has_cat=has_cat)


@_compat.guard_collective
@functools.partial(jax.jit, static_argnames=("depth",))
def _leaf_id_walk(X, col, thr, nal, *, depth):
    """Module-level (cached) version of the leaf-id walk — same per-call
    recompile hazard as _ensemble_walk."""
    n = X.shape[0]

    def per_tree(_, t):
        node = jnp.zeros(n, jnp.int32)
        dep = jnp.zeros(n, jnp.int32)

        def step(d, carry):
            node, dep = carry
            c = col[t][node]
            leafish = c < 0
            cc = jnp.maximum(c, 0)
            x = jnp.take_along_axis(X, cc[:, None], axis=1)[:, 0]
            isna = jnp.isnan(x)
            right = jnp.where(isna, ~nal[t][node], x > thr[t][node])
            child = 2 * node + 1 + right.astype(jnp.int32)
            return (jnp.where(leafish, node, child),
                    jnp.where(leafish, dep, dep + 1))

        node, dep = jax.lax.fori_loop(0, depth, step, (node, dep))
        return None, (node, dep)

    _, (nodes, deps) = jax.lax.scan(per_tree, None,
                                    jnp.arange(col.shape[0]))
    return nodes, deps


def predict_leaf_ids(X, trees: TreeArrays):
    """Per-(row, tree) terminal node ids and depths (IF path length, SHAP)."""
    return _leaf_id_walk(X, jnp.asarray(trees.col), jnp.asarray(trees.thr),
                         jnp.asarray(trees.na_left), depth=trees.depth)


# ===========================================================================
class TreeGrower:
    """Grows ONE tree level-by-level with D async device programs and no host
    round-trips. Returns device arrays; used by the GBM/DRF/IF drivers."""

    def __init__(self, nbins: int, max_depth: int, min_rows: float,
                 min_split_improvement: float, reg_lambda: float = 0.0):
        self.B = int(nbins)
        self.D = int(max_depth)
        self.min_rows = float(min_rows)
        self.msi = float(min_split_improvement)
        self.reg_lambda = float(reg_lambda)
        self.nodes = 2 ** (self.D + 1) - 1

    def grow(self, X, w, grad, col_mask=None, key=None, mtries: int = 0):
        """X: (n,C) f32 NaN-NA; w: (n,) in-sample weights (0 = out-of-bag);
        grad: (n,) regression target (residual/gradient).

        Returns device arrays (col, thr, na_left, value, heap, gains):
        heap = per-row terminal node id (val[heap] = this tree's prediction).
        """
        n, C = X.shape
        stats = jnp.stack([w, w * grad, w * grad * grad], axis=1)
        leaf = jnp.zeros(n, jnp.int32)
        heap = jnp.zeros(n, jnp.int32)
        active = jnp.ones(n, bool)
        colA = jnp.full(self.nodes, -1, jnp.int32)
        thrA = jnp.zeros(self.nodes, jnp.float32)
        nalA = jnp.zeros(self.nodes, bool)
        valA = jnp.zeros(self.nodes, jnp.float32)
        gains = jnp.zeros(C, jnp.float32)
        if col_mask is None:
            col_mask = jnp.ones(C, bool)
        if key is None:
            key = jax.random.PRNGKey(0)
        ROW_TREES.inc(n, engine="adaptive")
        with _span("tree.grow", rows=n, cols=C, depth=self.D):
            for d in range(self.D):
                # span covers the level DISPATCH (histogram + split search
                # + routing are one fused async program; on TPU the enqueue
                # returns before the device finishes)
                with _span("tree.level", depth=d), \
                        _LEVEL_SECONDS.time(engine="adaptive",
                                            level=str(d)):
                    leaf, heap, active, colA, thrA, nalA, valA, gains = \
                        _level_step(
                            X, stats, w, leaf, heap, active, colA, thrA,
                            nalA, valA, gains, col_mask, key, d=d, B=self.B,
                            mtries=int(mtries), min_rows=self.min_rows,
                            min_split_improvement=self.msi,
                            reg_lambda=self.reg_lambda)
                if _cpu_backend():
                    # XLA CPU collectives abort flakily when programs
                    # containing all-reduces pile up in the async queue
                    # (virtual-device test mesh only): drain per level. And
                    # since the controller is synchronous here anyway, stop
                    # growing once every row is frozen — deep levels of
                    # unbalanced limits (max_depth 15+ on small data) would
                    # otherwise compile and run for nothing. TPU stays
                    # fully async at fixed depth.
                    # h2o3-ok: R002 intentional per-level drain barrier (CPU collective flakiness), gated to the CPU backend
                    jax.block_until_ready(valA)
                    # the early-exit probe is an EAGER cross-shard reduce:
                    # it must take the same collective guard as the level
                    # programs or a concurrent build can rendezvous-starve
                    # against it on the host mesh
                    if not _compat.run_host_serialized(
                            lambda: bool(jnp.any(active))):
                        return colA, thrA, nalA, valA, heap, gains
            valA = _final_leaves(stats, leaf, active, w, valA, D=self.D)
            if _cpu_backend():
                # h2o3-ok: R002 same intentional CPU-only drain barrier as above
                jax.block_until_ready(valA)
        return colA, thrA, nalA, valA, heap, gains


_CPU_BACKEND_CACHE: bool | None = None


def _cpu_backend() -> bool:
    """Lazy, memoized backend probe.

    Probing ``jax.default_backend()`` at module import initializes the
    backend eagerly; when the TPU relay is down that raised (or hung) in
    *import*, taking down every consumer including bench.py before it
    could emit a structured record (BENCH_r03 lesson). Defer until the
    first tree actually trains.
    """
    global _CPU_BACKEND_CACHE
    if _CPU_BACKEND_CACHE is None:
        _CPU_BACKEND_CACHE = jax.default_backend() == "cpu"
    return _CPU_BACKEND_CACHE
