"""Histogram tree-growing engine — the TPU rebuild of H2O's SharedTree core.

Reference hot path (SURVEY.md §3.3): hex/tree/ScoreBuildHistogram2.java
(2-phase: score rows→leaf, then per-(column,row-range) private histogram
accumulate), hex/tree/DHistogram.java:44 ({w,wY,wYY} bins packed in one
double[] :59-70, merged in reduce :338, uniform-adaptive binning :41),
hex/tree/DTree.java:514 (DecidedNode.bestCol — split scoring over bins),
hex/tree/SharedTree.java:507 (buildLayer).

TPU-native design — no CAS, no private copies, no reduce tree:
  * Leaf assignment is a per-row int vector updated level-by-level
    (phase-1 "score" fused into the previous level's split application).
  * Uniform-adaptive bin ranges: per-(leaf,column) min/max are segment
    reductions; each row re-bins against ITS leaf's range each level —
    exactly DHistogram's adaptive-range semantics, fully vectorized.
  * Histograms: hist[l,c,b,s] = Σ_r onehot_leaf[r,l]·stat_s[r]·onehot_bin[r,c,b].
    For shallow levels this is evaluated as a dense matmul
    (leaf·stat panel)ᵀ @ (bin one-hot) per column block — it rides the MXU,
    and the row-contraction over the sharded dimension becomes one ICI
    all-reduce (the entire MRTask reduce tree collapses into a psum).
    For deep levels (many leaves) it switches to segment-sum (scatter-add)
    on a combined (leaf,bin) index.
  * Split search is one vectorized pass over (leaf, col, bin, na-dir) on
    device — DecidedNode.bestCol without the per-node loop.
  * Trees are dense heap-order arrays (CompressedTree analog), so ensemble
    prediction is a fixed-depth gather loop — static shapes, jit-friendly.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Dense-matmul histogram path is used while (leaves × 3 stats) stays MXU-sized.
_MATMUL_MAX_LEAVES = 64
_COL_BLOCK = 8


# ===========================================================================
# Per-level kernels (static over L=leaves-at-level, B=nbins, C=ncols)
@functools.partial(jax.jit, static_argnames=("L",))
def leaf_ranges(X, leaf, L):
    """Per-(leaf,col) min/max over active rows → uniform-adaptive bin ranges.

    X: (n, C) f32 with NaN for NA; leaf: (n,) int32 in [0,L), L = inactive.
    """
    big = jnp.float32(3.0e38)
    xmin = jnp.where(jnp.isnan(X), big, X)
    xmax = jnp.where(jnp.isnan(X), -big, X)
    mn = jax.ops.segment_min(xmin, leaf, num_segments=L + 1)[:L]
    mx = jax.ops.segment_max(xmax, leaf, num_segments=L + 1)[:L]
    return mn, mx


@functools.partial(jax.jit, static_argnames=("B",))
def bin_rows(X, leaf, mn, mx, B):
    """Adaptive binning: row r, col c → bin in [0,B); NA → bin B."""
    lm = mn[leaf]                      # (n, C) gather of own-leaf ranges
    lM = mx[leaf]
    span = jnp.maximum(lM - lm, 1e-30)
    b = jnp.floor((X - lm) / span * B).astype(jnp.int32)
    b = jnp.clip(b, 0, B - 1)
    return jnp.where(jnp.isnan(X), B, b)


@functools.partial(jax.jit, static_argnames=("L", "B"))
def histogram_matmul(bins, leaf, stats, L, B):
    """hist (L, C, B+1, 3) via MXU: (n,L·3)ᵀ @ (n,CB·(B+1)) per column block."""
    n, C = bins.shape
    oh_leaf = jax.nn.one_hot(leaf, L, dtype=jnp.float32)          # (n, L)
    W3 = (oh_leaf[:, :, None] * stats[:, None, :]).reshape(n, L * 3)
    nb = B + 1
    pad_c = (-C) % _COL_BLOCK
    binsp = jnp.pad(bins, ((0, 0), (0, pad_c)), constant_values=B)
    nblk = binsp.shape[1] // _COL_BLOCK

    def block(carry, cb):
        blk = jax.lax.dynamic_slice(binsp, (0, cb * _COL_BLOCK),
                                    (n, _COL_BLOCK))
        oh = jax.nn.one_hot(blk, nb, dtype=jnp.float32)           # (n,CB,nb)
        h = jnp.einsum("nk,ncb->kcb", W3, oh,
                       preferred_element_type=jnp.float32)        # (L3,CB,nb)
        return carry, h

    _, hs = jax.lax.scan(block, 0, jnp.arange(nblk))   # (nblk, L3, CB, nb)
    h = hs.transpose(1, 0, 2, 3).reshape(L * 3, nblk * _COL_BLOCK, nb)[:, :C]
    return h.reshape(L, 3, C, nb).transpose(0, 2, 3, 1)


@functools.partial(jax.jit, static_argnames=("L", "B"))
def histogram_scatter(bins, leaf, stats, L, B):
    """Deep-tree path: segment-sum on combined (leaf·(B+1)+bin) per column."""
    n, C = bins.shape
    nb = B + 1
    base = leaf * nb

    def one_col(c):
        idx = base + bins[:, c]
        return jax.ops.segment_sum(stats, idx, num_segments=(L + 1) * nb)[: L * nb]

    hs = jax.lax.map(one_col, jnp.arange(C))                      # (C, L·nb, 3)
    return hs.reshape(C, L, nb, 3).transpose(1, 0, 2, 3)


def build_histograms(bins, leaf, stats, L, B):
    if L * 3 <= _MATMUL_MAX_LEAVES * 3:
        return histogram_matmul(bins, leaf, stats, L, B)
    return histogram_scatter(bins, leaf, stats, L, B)


# ===========================================================================
@functools.partial(jax.jit, static_argnames=("B",))
def find_best_splits(hist, mn, mx, min_rows, min_split_improvement,
                     col_mask, B):
    """Vectorized DecidedNode.bestCol over every (leaf, col, threshold, NA-dir).

    hist: (L, C, B+1, 3); slot B is the NA bucket. Returns per-leaf arrays:
      gain (L,), col (L,), thr_bin (L,), na_left (L,), plus child stat sums.
    Split at t ∈ [0,B-1): left = bins ≤ t (+NA if na_left), right = rest.
    """
    w = hist[..., 0]
    wy = hist[..., 1]
    wyy = hist[..., 2]
    main_w, na_w = w[..., :B], w[..., B]
    main_wy, na_wy = wy[..., :B], wy[..., B]
    main_wyy, na_wyy = wyy[..., :B], wyy[..., B]

    def se(w_, wy_, wyy_):
        return wyy_ - jnp.where(w_ > 0, wy_ * wy_ / jnp.maximum(w_, 1e-30), 0.0)

    tot_w = main_w.sum(-1) + na_w                      # (L, C) — same ∀ c
    tot_wy = main_wy.sum(-1) + na_wy
    tot_wyy = main_wyy.sum(-1) + na_wyy
    se_parent = se(tot_w, tot_wy, tot_wyy)

    cl_w = jnp.cumsum(main_w, -1)[..., :-1]            # (L, C, B-1) left sums
    cl_wy = jnp.cumsum(main_wy, -1)[..., :-1]
    cl_wyy = jnp.cumsum(main_wyy, -1)[..., :-1]

    def gains(nal):
        lw = cl_w + (na_w[..., None] if nal else 0.0)
        lwy = cl_wy + (na_wy[..., None] if nal else 0.0)
        lwyy = cl_wyy + (na_wyy[..., None] if nal else 0.0)
        rw = tot_w[..., None] - lw
        rwy = tot_wy[..., None] - lwy
        rwyy = tot_wyy[..., None] - lwyy
        g = se_parent[..., None] - se(lw, lwy, lwyy) - se(rw, rwy, rwyy)
        ok = (lw >= min_rows) & (rw >= min_rows)
        return jnp.where(ok, g, -jnp.inf)

    g_right = gains(False)                             # (L, C, B-1)
    g_left = gains(True)
    g = jnp.maximum(g_right, g_left)
    na_left = g_left > g_right
    g = jnp.where(col_mask[None, :, None], g, -jnp.inf)

    L, C = tot_w.shape
    flat = g.reshape(L, C * (B - 1))
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    best_col = (best // (B - 1)).astype(jnp.int32)
    best_bin = (best % (B - 1)).astype(jnp.int32)
    best_nal = jnp.take_along_axis(
        na_left.reshape(L, C * (B - 1)), best[:, None], 1)[:, 0]
    # threshold value: upper edge of bin t in the leaf's adaptive range
    lmn = jnp.take_along_axis(mn, best_col[:, None], 1)[:, 0]
    lmx = jnp.take_along_axis(mx, best_col[:, None], 1)[:, 0]
    thr = lmn + (lmx - lmn) * (best_bin + 1).astype(jnp.float32) / B
    did = best_gain > jnp.maximum(min_split_improvement, 0.0)
    # leaf prediction stats (for terminal value): parent mean = Σwy/Σw
    leaf_w = tot_w[:, 0]
    leaf_wy = tot_wy[:, 0]
    return did, best_gain, best_col, thr, best_nal, leaf_w, leaf_wy


@jax.jit
def apply_splits(X, leaf, active, did, col, thr, na_left):
    """Phase-1 "score": route rows to child leaves; freeze terminal rows."""
    c = col[leaf]
    t = thr[leaf]
    x = jnp.take_along_axis(X, c[:, None], axis=1)[:, 0]
    isna = jnp.isnan(x)
    go_right = jnp.where(isna, ~na_left[leaf], x > t)
    new_leaf = 2 * leaf + go_right.astype(jnp.int32)
    splits = did[leaf] & active
    return jnp.where(splits, new_leaf, 0), active & did[leaf]


# ===========================================================================
# Dense heap-order tree storage (hex/tree/CompressedTree analog)
@dataclass
class TreeArrays:
    """One ensemble's trees as stacked dense arrays, heap node order:
    node 0 = root; children of i are 2i+1 / 2i+2. Leaves carry values."""
    col: np.ndarray       # (T, nodes) int32, -1 = leaf
    thr: np.ndarray       # (T, nodes) f32
    na_left: np.ndarray   # (T, nodes) bool
    value: np.ndarray     # (T, nodes) f32 — prediction if stopped here
    depth: int

    @property
    def ntrees(self):
        return self.col.shape[0]


def predict_ensemble(X, trees: TreeArrays, weights=None):
    """Σ_t value[t, leaf_t(row)] — fixed-depth gather walk per tree.

    X: (n, C) f32 (NaN = NA). Returns (n,) f32. `weights`: per-tree scale.
    """
    col = jnp.asarray(trees.col)
    thr = jnp.asarray(trees.thr)
    nal = jnp.asarray(trees.na_left)
    val = jnp.asarray(trees.value)
    tw = (jnp.asarray(weights, jnp.float32) if weights is not None
          else jnp.ones(trees.ntrees, jnp.float32))
    depth = trees.depth

    @jax.jit
    def run(X):
        n = X.shape[0]

        def per_tree(acc, t):
            node = jnp.zeros(n, jnp.int32)

            def step(d, node):
                c = col[t][node]
                leafish = c < 0
                cc = jnp.maximum(c, 0)
                x = jnp.take_along_axis(X, cc[:, None], axis=1)[:, 0]
                isna = jnp.isnan(x)
                right = jnp.where(isna, ~nal[t][node], x > thr[t][node])
                child = 2 * node + 1 + right.astype(jnp.int32)
                return jnp.where(leafish, node, child)

            node = jax.lax.fori_loop(0, depth, step, node)
            return acc + tw[t] * val[t][node], None

        out, _ = jax.lax.scan(per_tree, jnp.zeros(n, jnp.float32),
                              jnp.arange(trees.ntrees))
        return out

    return run(X)


def predict_leaf_ids(X, trees: TreeArrays):
    """Per-(row, tree) terminal node ids and depths (isolation forest path
    length; also SHAP later)."""
    col = jnp.asarray(trees.col)
    thr = jnp.asarray(trees.thr)
    nal = jnp.asarray(trees.na_left)
    depth = trees.depth

    @jax.jit
    def run(X):
        n = X.shape[0]

        def per_tree(_, t):
            node = jnp.zeros(n, jnp.int32)
            dep = jnp.zeros(n, jnp.int32)

            def step(d, carry):
                node, dep = carry
                c = col[t][node]
                leafish = c < 0
                cc = jnp.maximum(c, 0)
                x = jnp.take_along_axis(X, cc[:, None], axis=1)[:, 0]
                isna = jnp.isnan(x)
                right = jnp.where(isna, ~nal[t][node], x > thr[t][node])
                child = 2 * node + 1 + right.astype(jnp.int32)
                return (jnp.where(leafish, node, child),
                        jnp.where(leafish, dep, dep + 1))

            node, dep = jax.lax.fori_loop(0, depth, step, (node, dep))
            return None, (node, dep)

        _, (nodes, deps) = jax.lax.scan(per_tree, None,
                                        jnp.arange(trees.ntrees))
        return nodes, deps

    return run(X)


# ===========================================================================
class TreeGrower:
    """Grows ONE tree level-by-level; used by GBM/DRF/IF drivers.

    The driver supplies per-row gradient stats each tree; the grower returns
    heap-order arrays plus per-row final leaf ids (for leaf-value fitting à la
    GBM's GammaPass).
    """

    def __init__(self, nbins: int, max_depth: int, min_rows: float,
                 min_split_improvement: float):
        self.B = int(nbins)
        self.D = int(max_depth)
        self.min_rows = float(min_rows)
        self.msi = float(min_split_improvement)
        self.nodes = 2 ** (self.D + 1) - 1

    def grow(self, X, w, grad, col_mask=None, rng=None, mtries: int = 0):
        """X: (n,C) f32 NaN-NA; w: (n,) sample weights (0 = not in tree);
        grad: (n,) target the tree regresses on (residual/gradient).
        Returns (col, thr, na_left, value, leaf_final, gain_by_col)."""
        n, C = X.shape
        B, D = self.B, self.D
        stats = jnp.stack([w, w * grad, w * grad * grad], axis=1)
        leaf = jnp.zeros(n, jnp.int32)
        active = w > 0
        col_arr = np.full(self.nodes, -1, np.int32)
        thr_arr = np.zeros(self.nodes, np.float32)
        nal_arr = np.zeros(self.nodes, bool)
        val_arr = np.zeros(self.nodes, np.float32)
        gain_by_col = np.zeros(C, np.float64)
        if col_mask is None:
            col_mask = jnp.ones(C, bool)
        for d in range(D):
            L = 2 ** d
            lv = jnp.where(active, leaf, L)
            mn, mx = leaf_ranges(X, lv, L)
            bins = bin_rows(X, lv, mn, mx, B)
            hist = build_histograms(bins, lv, stats, L, B)
            cmask = col_mask
            if mtries and mtries < C and rng is not None:
                # per-leaf mtries is emulated per-level (DRF col sampling)
                r = rng.random(C)
                k = np.partition(r, mtries - 1)[mtries - 1]
                cmask = jnp.asarray(r <= k) & col_mask
            did, gain, bcol, thr, nal, lw, lwy = find_best_splits(
                hist, mn, mx, self.min_rows, self.msi, cmask, B)
            did_np = np.asarray(did)
            gain_np = np.asarray(gain)
            col_np = np.asarray(bcol)
            base = 2 ** d - 1
            lw_np = np.asarray(lw)
            lwy_np = np.asarray(lwy)
            ids = base + np.arange(L)
            # record this level's decisions + fallback leaf means
            val_arr[ids] = np.where(lw_np > 0, lwy_np / np.maximum(lw_np, 1e-30), 0.0)
            col_arr[ids] = np.where(did_np, col_np, -1)
            thr_arr[ids] = np.asarray(thr)
            nal_arr[ids] = np.asarray(nal)
            for l in np.nonzero(did_np)[0]:
                gain_by_col[col_np[l]] += max(gain_np[l], 0.0)
            if not did_np.any():
                break
            leaf, active = apply_splits(X, leaf, active, did, bcol,
                                        jnp.asarray(thr), nal)
        else:
            # reached depth D: fit leaf means for the deepest layer
            L = 2 ** D
            lv = jnp.where(active, leaf, L)
            sums = jax.ops.segment_sum(stats[:, :2], lv, num_segments=L + 1)[:L]
            sums_np = np.asarray(sums)
            ids = 2 ** D - 1 + np.arange(L)
            val_arr[ids] = np.where(sums_np[:, 0] > 0,
                                    sums_np[:, 1] / np.maximum(sums_np[:, 0], 1e-30),
                                    0.0)
        return col_arr, thr_arr, nal_arr, val_arr, gain_by_col
